"""Training-dynamics aggregation — staleness, elastic distance, quality.

The post-mortem half of the dynamics plane (docs/OBSERVABILITY.md
"dynamics"). Three journal record kinds feed it:

- ``dynamics`` (client ranks, one per exchange, written by
  ``parallel/ps_roles._record_dynamics``): elastic distance
  ‖x_local − x̃‖, push/fetch-delta norms, param norm, update/param
  ratio;
- ``push_stale`` (server ranks, one per applied versioned push):
  ``staleness`` = center updates applied between the pushing client's
  fetch and its push landing, attributed per source rank;
- ``param_version`` (server ranks, one per PARAM reply): the center
  version stamped into the reply — the monotonicity evidence
  conformance rule TC204 replays.

:func:`aggregate_dynamics` reduces them into per-client elastic
trajectories with a monotone-growth divergence verdict, per-source
staleness percentiles (exact — journals carry exact integer staleness,
no bucketing), per-server version progressions, and a run roll-up whose
scalars (``staleness_p99``, ``elastic_dist_final``, ``norm_ratio``)
ride in every ``bench.py`` mnist-ps JSON line next to ``samples/s`` —
the before/after quality instrument for the ROADMAP fast-wire item.

:func:`check_dynamics_gate` turns the roll-up into a CI verdict against
a small JSON gate file (the ``obs slo`` pattern)::

    {"staleness_p99_max": 8, "elastic_dist_final_max": 50.0,
     "norm_ratio_max": 0.5, "allow_diverging": false}

Unknown gate keys fail loudly — a typo'd threshold must not silently
gate nothing. Like the rest of the reader side this module is
stdlib-only: no jax, no transport imports; safe for the lint.sh gate.
"""

from __future__ import annotations

import json
from typing import Iterable, Mapping, Optional

from mpit_tpu.obs.merge import (
    _rec_rank,
    expand_journal_paths,
    read_journal,
)

# post-mortem divergence verdict — deliberately the same shape as the
# live AlertEngine rule (strictly increasing across N observations AND
# overall growth beyond a factor), so the dashboard and the report agree
DIVERGENCE_WINDOWS = 4
DIVERGENCE_FACTOR = 2.0

# trajectory points carried in the report per client (the verdict uses
# the full series; the report tail is for humans and plots)
_TRAJECTORY_TAIL = 64

_GATE_KEYS = {
    "staleness_p99_max": (int, float),
    "elastic_dist_final_max": (int, float),
    "norm_ratio_max": (int, float),
    "allow_diverging": (bool,),
}


def _exact_percentile(counts: Mapping[int, int], q: float) -> Optional[int]:
    """q-th percentile (0..1) of a ``{value: count}`` tally — exact, the
    journals carry exact integer staleness (no geometric bucketing)."""
    total = sum(counts.values())
    if total == 0:
        return None
    need = q * total
    seen = 0
    for v in sorted(counts):
        seen += counts[v]
        if seen >= need:
            return v
    return max(counts)


def diverging(
    trajectory: list,
    windows: int = DIVERGENCE_WINDOWS,
    factor: float = DIVERGENCE_FACTOR,
) -> bool:
    """Monotone-growth verdict over an elastic-distance series: the last
    ``windows`` points are strictly increasing AND grew by more than
    ``factor`` overall. A healthy EASGD run's elastic distance
    equilibrates (the center keeps pulling workers back); sustained
    strict growth is the exploration term winning — divergence."""
    tail = trajectory[-windows:]
    if len(tail) < windows or tail[0] <= 0:
        return False
    return all(b > a for a, b in zip(tail, tail[1:])) and (
        tail[-1] / tail[0] > factor
    )


def aggregate_dynamics(journal_paths: Iterable[str]) -> dict:
    """Cross-rank dynamics report from obs journals (files or dirs of
    ``obs_rank*.jsonl``). Empty journals (a run with the dynamics plane
    never armed, or pre-dynamics journals) yield ``run: None`` — the CLI
    maps that to exit 2, distinct from a gate violation."""
    clients: dict[int, dict] = {}
    trajectories: dict[int, list] = {}
    staleness: dict[int, dict] = {}
    servers: dict[int, dict] = {}
    last_gv: dict[int, tuple] = {}  # per-server (gen, version) high-water

    for path in expand_journal_paths(journal_paths):
        for rec in read_journal(path):
            ev = rec.get("ev")
            rank = _rec_rank(rec)
            if ev == "dynamics":
                row = clients.setdefault(rank, {
                    "rounds": 0, "algo": rec.get("algo"),
                    "push_norm": None, "param_norm": None,
                    "fetch_delta": None, "norm_ratio": None,
                })
                row["rounds"] += 1
                # journals are per-rank monotone, so last write wins =
                # final exchange
                for k in ("push_norm", "param_norm", "fetch_delta"):
                    if k in rec:
                        row[k] = rec[k]
                if "ratio" in rec:
                    row["norm_ratio"] = rec["ratio"]
                if "elastic" in rec:
                    trajectories.setdefault(rank, []).append(
                        rec["elastic"]
                    )
            elif ev == "push_stale":
                src = rec.get("src")
                s = rec.get("staleness")
                if src is None or not isinstance(s, (int, float)):
                    continue
                st = staleness.setdefault(
                    src, {"pushes": 0, "sum": 0, "counts": {}}
                )
                st["pushes"] += 1
                st["sum"] += s
                st["counts"][int(s)] = st["counts"].get(int(s), 0) + 1
            elif ev == "param_version":
                v = rec.get("version")
                if not isinstance(v, int):
                    continue
                # restart generation (elastic runs): a restored server
                # resumes from its last snapshot, so versions may step
                # back across a gen bump — monotonicity is per (gen,
                # version) lexicographic order, mirroring TC204
                g = rec.get("gen", 0)
                if not isinstance(g, int):
                    g = 0
                srv = servers.setdefault(rank, {
                    "param_replies": 0, "first_version": v,
                    "final_version": v, "monotonic": True,
                    "restores": 0,
                })
                srv["param_replies"] += 1
                pg, pv = last_gv.get(rank, (g, v))
                if (g, v) < (pg, pv):
                    srv["monotonic"] = False
                if g > pg:
                    srv["restores"] += g - pg
                last_gv[rank] = max(last_gv.get(rank, (g, v)), (g, v))
                srv["final_version"] = max(srv["final_version"], v)

    for rank, traj in trajectories.items():
        row = clients[rank]
        row["elastic"] = {
            "first": traj[0],
            "final": traj[-1],
            "max": max(traj),
            "mean": sum(traj) / len(traj),
        }
        row["diverging"] = diverging(traj)
        row["trajectory"] = traj[-_TRAJECTORY_TAIL:]

    stal_rows: dict[int, dict] = {}
    for src, st in sorted(staleness.items()):
        stal_rows[src] = {
            "pushes": st["pushes"],
            "mean": st["sum"] / st["pushes"],
            "p50": _exact_percentile(st["counts"], 0.50),
            "p99": _exact_percentile(st["counts"], 0.99),
            "max": max(st["counts"]),
        }

    run = None
    if clients or stal_rows or servers:
        finals = [
            c["elastic"]["final"] for c in clients.values()
            if "elastic" in c
        ]
        ratios = [
            c["norm_ratio"] for c in clients.values()
            if c.get("norm_ratio") is not None
        ]
        p99s = [r["p99"] for r in stal_rows.values() if r["p99"] is not None]
        run = {
            "clients": len(clients),
            "servers": len(servers),
            "staleness_p99": max(p99s) if p99s else None,
            "elastic_dist_final": max(finals) if finals else None,
            "norm_ratio": max(ratios) if ratios else None,
            "diverging": any(
                c.get("diverging") for c in clients.values()
            ),
            "versions_monotonic": all(
                s["monotonic"] for s in servers.values()
            ) if servers else None,
        }

    return {
        "clients": {r: clients[r] for r in sorted(clients)},
        "staleness": stal_rows,
        "servers": {r: servers[r] for r in sorted(servers)},
        "run": run,
    }


def load_gate(path: str) -> dict:
    """Parse + validate a dynamics gate file. Raises ``ValueError`` for
    unknown keys or mistyped values (a typo'd threshold must fail the
    gate run loudly, not silently check nothing), ``OSError`` for an
    unreadable file."""
    with open(path) as f:
        gate = json.load(f)
    if not isinstance(gate, dict):
        raise ValueError("dynamics gate must be a JSON object")
    for key, value in gate.items():
        types = _GATE_KEYS.get(key)
        if types is None:
            raise ValueError(
                f"unknown dynamics gate key {key!r} "
                f"(known: {sorted(_GATE_KEYS)})"
            )
        if types == (bool,):
            ok = isinstance(value, bool)
        else:
            # bool is an int subclass — reject it for numeric thresholds
            ok = isinstance(value, types) and not isinstance(value, bool)
        if not ok:
            raise ValueError(
                f"dynamics gate key {key!r}: expected "
                f"{'/'.join(t.__name__ for t in types)}, got {value!r}"
            )
    return gate


def check_dynamics_gate(report: dict, gate: Mapping) -> list[str]:
    """Violation strings (empty = pass) for an aggregated report against
    a parsed gate. A threshold whose metric is absent from the report is
    a violation — a gate on staleness over journals that carry none
    means the instrumentation regressed, which is exactly what the gate
    exists to catch."""
    run = report.get("run") or {}
    out: list[str] = []

    def _bound(key: str, metric: str) -> None:
        if key not in gate:
            return
        value = run.get(metric)
        if value is None:
            out.append(
                f"{metric}: absent from the report but gated by {key}"
            )
        elif value > gate[key]:
            out.append(f"{metric}: {value} > {key}={gate[key]}")

    _bound("staleness_p99_max", "staleness_p99")
    _bound("elastic_dist_final_max", "elastic_dist_final")
    _bound("norm_ratio_max", "norm_ratio")
    if not gate.get("allow_diverging", False) and run.get("diverging"):
        ranks = [
            r for r, c in report.get("clients", {}).items()
            if c.get("diverging")
        ]
        out.append(f"diverging: client rank(s) {ranks} — elastic "
                   "distance growing monotonically beyond "
                   f"{DIVERGENCE_FACTOR}x over {DIVERGENCE_WINDOWS} "
                   "exchanges")
    return out
