"""Online health alerts over live telemetry snapshots.

The :class:`AlertEngine` evaluates the per-rank ``live/rank_<r>.json``
snapshots (written by :class:`mpit_tpu.obs.live.LiveExporter`) against
three conditions and appends structured records to ``alerts.jsonl``:

- **dead_rank** — a rank's heartbeat (wall-clock ``t`` of its freshest
  snapshot) is stale relative to the freshest rank in the world, beyond
  ``staleness_factor`` x that rank's own export interval. Staleness is
  judged *relative* (max ``t`` across ranks, not the reader's clock) so
  the check is meaningful both in-flight and post-mortem: when the
  launcher tears the whole world down, the rank that died *first* is
  still the stale one.
- **straggler** — one training rank's rolling compute fraction (the
  ``train.compute_s`` counter's rolling rate — seconds of compute per
  wall second) is an outlier: the min-max spread across ranks exceeds
  ``straggler_spread`` and the flagged rank is the farthest from the
  median. A rank starved by a slow wire computes less per second; this
  is the signal a group leader will use to route around it.
- **slo_burn** — a serving rank's rolling SLO miss fraction, normalized
  by the error budget ``(1 - slo_target)``, exceeds ``burn_threshold``.
  Burn 1.0 means the budget is being consumed exactly as fast as it
  accrues; >1 means the run will blow its SLO if the window persists.
- **staleness_runaway** — a server rank's rolling push-staleness p99
  (the ``train.staleness`` histogram, docs/OBSERVABILITY.md "dynamics")
  jumps past ``staleness_runaway_factor`` x its OWN baseline (the
  median of its prior observations, floored at ``staleness_floor``
  units). Relative-to-self, so a topology whose steady state is 3
  updates of staleness doesn't false-positive where one whose steady
  state is 0.2 would.
- **divergence** — a client rank's elastic distance ‖x_local − x̃‖
  gauge grows strictly monotonically across ``divergence_windows``
  consecutive exports AND by more than ``divergence_factor`` overall —
  the EASGD exploration term failing to pull workers back to the
  center (unstable alpha/lr), caught while the run still has something
  to save. Histories advance only when a rank's snapshot ``seq``
  advances, so re-reading an unchanged snapshot set is idempotent.

Alerts deduplicate per ``(kind, rank)`` while the condition holds and
re-arm on recovery; existing ``alerts.jsonl`` content seeds the active
set so ``--once`` re-runs don't duplicate. Like the rest of the reader
side this module is stdlib-only — no jax, no transport imports.
"""

from __future__ import annotations

import dataclasses
import json
import os
import statistics
from typing import Mapping, Optional

from mpit_tpu.obs.live import (
    M_ELASTIC_DIST,
    M_REQ_FINISHED,
    M_SLO_MISSES,
    M_STALENESS,
    compute_fraction,
    percentile_ms,
)

# per-rank dynamics histories are capped — the engine may outlive a long
# run and the conditions only ever look at a recent suffix
_HISTORY_CAP = 64


@dataclasses.dataclass(frozen=True)
class AlertConfig:
    """Thresholds for the three alert conditions.

    ``staleness_factor`` is multiplied by each rank's own export
    interval (with ``min_staleness_s`` as a floor) — one number that
    stays correct when ranks export at different rates."""

    staleness_factor: float = 3.0
    min_staleness_s: float = 1.0
    straggler_spread: float = 0.25
    min_compute_fraction: float = 0.02
    min_uptime_s: float = 1.0
    burn_threshold: float = 1.0
    slo_target: float = 0.95
    min_finished_rate: float = 0.5
    # training-dynamics rules (docs/OBSERVABILITY.md "dynamics")
    divergence_windows: int = 4
    divergence_factor: float = 2.0
    staleness_runaway_factor: float = 3.0
    staleness_floor: float = 1.0
    staleness_baseline_len: int = 3

    def __post_init__(self):
        if self.staleness_factor <= 0:
            raise ValueError("staleness_factor must be > 0")
        if not 0 < self.slo_target < 1:
            raise ValueError("slo_target must be in (0, 1)")
        if self.divergence_windows < 2:
            raise ValueError("divergence_windows must be >= 2")
        if self.divergence_factor <= 1.0:
            raise ValueError("divergence_factor must be > 1")
        if self.staleness_runaway_factor <= 1.0:
            raise ValueError("staleness_runaway_factor must be > 1")
        if self.staleness_floor <= 0:
            raise ValueError("staleness_floor must be > 0")
        if self.staleness_baseline_len < 1:
            raise ValueError("staleness_baseline_len must be >= 1")


def staleness_s(snap: dict, config: AlertConfig) -> float:
    interval = snap.get("interval_s") or 1.0
    return max(config.min_staleness_s, config.staleness_factor * interval)


class AlertEngine:
    """Evaluate snapshots, append newly-firing alerts to ``path``.

    ``path=None`` keeps the engine in-memory (tests, dashboards that
    only display). ``evaluate`` returns the records that fired *this*
    pass; an alert stays suppressed while its condition persists and
    re-arms once the condition clears.

    ``on_fire`` (optional) is called once per newly-fired record —
    the black-box trigger hook: ``obs live`` wires it to
    :func:`mpit_tpu.obs.blackbox.request_dump` so a dead_rank /
    straggler / slo_burn / divergence firing freezes the incident
    window on every rank of the fleet. A callback that raises never
    takes the alert loop down."""

    def __init__(
        self,
        path: Optional[str],
        config: AlertConfig = AlertConfig(),
        on_fire=None,
    ):
        self.path = path
        self.config = config
        self.on_fire = on_fire
        self._active: set = set()  # (kind, rank) currently firing
        # dynamics histories: rank -> [(seq, value), ...] capped at
        # _HISTORY_CAP; advanced once per NEW snapshot seq (see
        # _observe_dynamics) — the memory behind staleness_runaway and
        # divergence
        self._elastic_hist: dict = {}
        self._staleness_hist: dict = {}
        if path is not None and os.path.exists(path):
            for rec in _read_jsonl(path):
                if rec.get("ev") == "alert":
                    self._active.add((rec.get("kind"), rec.get("rank")))

    # -- conditions -------------------------------------------------------

    def _dead_ranks(self, snapshots: Mapping[int, dict], now: float) -> list:
        out = []
        for rank, snap in snapshots.items():
            limit = staleness_s(snap, self.config)
            age = now - snap["t"]
            if age > limit:
                out.append((
                    "dead_rank", rank,
                    {
                        "age_s": round(age, 3),
                        "staleness_s": round(limit, 3),
                        "last_seq": snap.get("seq"),
                    },
                ))
        return out

    def _stragglers(self, snapshots: Mapping[int, dict]) -> list:
        cfg = self.config
        fracs = {}
        for rank, snap in snapshots.items():
            f = compute_fraction(snap)
            if f is None or (snap.get("uptime_s") or 0.0) < cfg.min_uptime_s:
                continue
            fracs[rank] = f
        if len(fracs) < 2 or max(fracs.values()) < cfg.min_compute_fraction:
            return []
        spread = max(fracs.values()) - min(fracs.values())
        if spread <= cfg.straggler_spread:
            return []
        med = statistics.median(fracs.values())
        rank = max(fracs, key=lambda r: abs(fracs[r] - med))
        return [(
            "straggler", rank,
            {
                "compute_fraction": round(fracs[rank], 4),
                "median": round(med, 4),
                "spread": round(spread, 4),
                "fractions": {str(r): round(f, 4) for r, f in sorted(fracs.items())},
            },
        )]

    def _slo_burns(self, snapshots: Mapping[int, dict]) -> list:
        cfg = self.config
        out = []
        for rank, snap in snapshots.items():
            counters = snap.get("counters", {})
            finished = counters.get(M_REQ_FINISHED)
            if finished is None or finished["rate"] < cfg.min_finished_rate:
                continue
            misses = counters.get(M_SLO_MISSES, {"rate": 0.0})
            miss_frac = misses["rate"] / finished["rate"]
            burn = miss_frac / (1.0 - cfg.slo_target)
            if burn > cfg.burn_threshold:
                out.append((
                    "slo_burn", rank,
                    {
                        "burn": round(burn, 3),
                        "miss_fraction": round(miss_frac, 4),
                        "slo_target": cfg.slo_target,
                        "finished_rate": round(finished["rate"], 3),
                    },
                ))
        return out

    def _observe_dynamics(self, snapshots: Mapping[int, dict]) -> None:
        """Advance the per-rank dynamics histories — one observation per
        new snapshot ``seq``, so evaluating an unchanged snapshot set
        twice (``--once`` re-runs, slow pollers) never manufactures a
        trend that isn't there."""
        for rank, snap in snapshots.items():
            seq = snap.get("seq")
            elastic = snap.get("gauges", {}).get(M_ELASTIC_DIST)
            if elastic is not None:
                hist = self._elastic_hist.setdefault(rank, [])
                if not hist or hist[-1][0] != seq:
                    hist.append((seq, float(elastic)))
                    del hist[:-_HISTORY_CAP]
            h = snap.get("hists", {}).get(M_STALENESS)
            if h is not None:
                buckets = h.get("rolling") or h.get("buckets") or {}
                p99 = percentile_ms(buckets, 0.99)
                if p99 is not None:
                    hist = self._staleness_hist.setdefault(rank, [])
                    if not hist or hist[-1][0] != seq:
                        # /1e3 undoes percentile_ms's ms scaling — the
                        # staleness hist is in units, not time
                        hist.append((seq, p99 / 1e3))
                        del hist[:-_HISTORY_CAP]

    def _divergences(self) -> list:
        cfg = self.config
        out = []
        for rank, hist in sorted(self._elastic_hist.items()):
            vals = [v for _, v in hist][-cfg.divergence_windows:]
            if len(vals) < cfg.divergence_windows or vals[0] <= 0:
                continue
            if all(b > a for a, b in zip(vals, vals[1:])) and (
                vals[-1] / vals[0] > cfg.divergence_factor
            ):
                out.append((
                    "divergence", rank,
                    {
                        "elastic_dist": round(vals[-1], 6),
                        "growth": round(vals[-1] / vals[0], 3),
                        "windows": cfg.divergence_windows,
                        "trajectory": [round(v, 6) for v in vals],
                    },
                ))
        return out

    def _staleness_runaways(self) -> list:
        cfg = self.config
        out = []
        for rank, hist in sorted(self._staleness_hist.items()):
            vals = [v for _, v in hist]
            if len(vals) < cfg.staleness_baseline_len + 1:
                continue
            newest = vals[-1]
            baseline = max(statistics.median(vals[:-1]), cfg.staleness_floor)
            if newest > cfg.staleness_runaway_factor * baseline:
                out.append((
                    "staleness_runaway", rank,
                    {
                        "staleness_p99": round(newest, 3),
                        "baseline": round(baseline, 3),
                        "factor": round(newest / baseline, 3),
                    },
                ))
        return out

    # -- driver -----------------------------------------------------------

    def evaluate(
        self,
        snapshots: Mapping[int, dict],
        now: Optional[float] = None,
    ) -> list:
        """One pass over the current snapshots. ``now`` defaults to the
        freshest snapshot's wall-clock (relative staleness — see module
        docstring); pass ``time.time()`` to also catch *all* ranks going
        silent at once while the run should still be alive."""
        if not snapshots:
            return []
        if now is None:
            now = max(s["t"] for s in snapshots.values())
        self._observe_dynamics(snapshots)
        found = (
            self._dead_ranks(snapshots, now)
            + self._stragglers(snapshots)
            + self._slo_burns(snapshots)
            + self._staleness_runaways()
            + self._divergences()
        )
        condition_keys = {(kind, rank) for kind, rank, _ in found}
        fired = []
        for kind, rank, detail in found:
            if (kind, rank) in self._active:
                continue
            self._active.add((kind, rank))
            fired.append({
                "ev": "alert",
                "kind": kind,
                "rank": rank,
                "t": now,
                "detail": detail,
            })
        # re-arm alerts whose condition cleared
        self._active &= condition_keys
        self._active |= {(f["kind"], f["rank"]) for f in fired}
        if fired and self.path is not None:
            with open(self.path, "a") as f:
                for rec in fired:
                    f.write(json.dumps(rec) + "\n")
        if fired and self.on_fire is not None:
            for rec in fired:
                try:
                    self.on_fire(rec)
                except Exception:
                    pass
        return fired


def _read_jsonl(path: str) -> list:
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    except OSError:
        pass
    return out


def read_alerts(path: str) -> list:
    """Parsed ``alerts.jsonl`` records (tolerant of partial lines)."""
    return [r for r in _read_jsonl(path) if r.get("ev") == "alert"]
