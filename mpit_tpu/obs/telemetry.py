"""TelemetryTransport — wire telemetry + trace propagation over any Transport.

The observability twin of :class:`~mpit_tpu.transport.chaos.ChaosTransport`
(same ``wrap_transports`` idiom, composable with it): wrap a rank's
transport and every send/recv is

- **counted** — per-(peer, tag) message/byte counters, error and timeout
  totals, power-of-two latency histograms, and a send-queue-depth gauge
  (sampled off the socket transport's per-dst queues when present);
- **journaled** — one JSONL record per wire event in the rank's
  :class:`~mpit_tpu.obs.core.Journal` (sampled every Nth per stream via
  ``ObsConfig.sample``), which is what the Perfetto merger consumes;
- **traced** — when ``ObsConfig.trace`` is on, the payload rides inside a
  small envelope carrying ``(trace_id, span_id, lamport)``; the receiving
  wrapper strips it, advances its Lamport clock, and parks the context as
  the receiving thread's *remote parent* so the next send from that thread
  (a server's PARAM reply) lands in the same trace.

Composition order with chaos: wrap telemetry OUTERMOST
(``TelemetryTransport(ChaosTransport(inner))``) — the counters then see
every *attempted* send (what the application experienced, injected faults
included), latency includes injected delay, and the per-(dst, tag) stream
index ``n`` stays in lockstep with the chaos schedule's, which is the join
key the merger uses to place a replayed FaultLog on the timeline.

Overhead contract: when obs is not armed there is no wrapper at all
(:func:`maybe_wrap` returns the transport unchanged) and the protocol-side
hooks reduce to a getattr (:func:`mpit_tpu.obs.core.span`); both are pinned
by the micro-benchmark in tests/test_obs.py.
"""

from __future__ import annotations

import os
import time
from typing import Any, Optional, Sequence

from mpit_tpu.analysis.runtime import make_lock
from mpit_tpu.obs.blackbox import BlackBox, arm_process_triggers
from mpit_tpu.obs.live import LiveExporter, MetricsRegistry
from mpit_tpu.obs.core import (
    _ENVELOPE_MARK,
    Journal,
    LogicalClock,
    ObsConfig,
    SpanContext,
    Tracer,
    _new_id,
    arm_faulthandler,
    config_from_env,
)
from mpit_tpu.transport.base import RecvTimeout, Transport


def _approx_nbytes(obj: Any) -> int:
    """Cheap payload size estimate — NEVER serializes (a pickle.dumps per
    message would dwarf the send itself for inproc reference-passing).
    EXACT for ndarrays/bytes and for the PS chunked scatter envelopes
    ``(epoch, seq, chunk)`` — tuple members sum, the chunk contributes its
    true ``nbytes`` — so the per-(peer, tag) byte counters are a
    trustworthy baseline for the quantized-wire work. Flat guesses remain
    only for scalars and unknown objects."""
    if obj is None:
        return 0
    nb = getattr(obj, "nbytes", None)
    if nb is not None:
        kind = getattr(getattr(obj, "dtype", None), "kind", "")
        if kind == "O":
            # object-dtype ndarray: nbytes counts POINTERS, not contents —
            # recurse over the elements for the real payload size
            try:
                return sum(_approx_nbytes(o) for o in obj.flat)
            except Exception:
                return int(nb)
        return int(nb)
    if isinstance(obj, (bytes, bytearray, memoryview)):
        return len(obj)
    if isinstance(obj, (tuple, list)):
        return sum(_approx_nbytes(o) for o in obj)
    if isinstance(obj, str):
        return len(obj)
    if isinstance(obj, (int, float, bool)):
        return 8
    if isinstance(obj, dict):
        return sum(
            _approx_nbytes(k) + _approx_nbytes(v) for k, v in obj.items()
        )
    return 64


def _lat_bucket(seconds: float) -> int:
    """Histogram bucket: ceil(log2(µs)) — bucket ``b`` holds latencies in
    (2^(b-1), 2^b] µs; sub-µs lands in bucket 0."""
    return max(0, int(seconds * 1e6)).bit_length()


class _PeerTagStats:
    """Counters for one (peer, tag) direction; mutated under the owning
    transport's stats lock."""

    __slots__ = ("msgs", "bytes", "errs", "timeouts", "hist", "n", "phases")

    def __init__(self):
        self.msgs = 0
        self.bytes = 0
        self.errs = 0
        self.timeouts = 0
        self.hist: dict[int, int] = {}
        self.n = 0  # next stream index (pre-incremented on use)
        # wire-phase seconds (serialize / queue_wait / write) accumulated
        # from phase-aware transports' SendHandles; empty when the inner
        # stack measures no split (inproc, native, through chaos)
        self.phases: dict[str, float] = {}

    def to_dict(self) -> dict:
        out = {"msgs": self.msgs, "bytes": self.bytes}
        if self.errs:
            out["errs"] = self.errs
        if self.timeouts:
            out["timeouts"] = self.timeouts
        if self.hist:
            out["lat_hist_log2us"] = {
                str(k): v for k, v in sorted(self.hist.items())
            }
        if self.phases:
            out["phase_s"] = {
                k: round(v, 6) for k, v in sorted(self.phases.items())
            }
        return out


class TelemetryTransport(Transport):
    """Telemetry/tracing wrapper: accounting on both paths, passthrough
    semantics. The wrapped rank keeps its identity; protocol code finds
    the tracer via the ``obs_tracer`` attribute (the
    :func:`mpit_tpu.obs.core.span` hook's contract)."""

    def __init__(
        self,
        inner: Transport,
        config: ObsConfig,
        journal: Optional[Journal] = None,
    ):
        self.inner = inner
        self.rank = inner.rank
        self.size = inner.size
        self.config = config
        self.journal = journal
        self.obs_tracer = Tracer(
            inner.rank, clock=LogicalClock(), journal=journal
        )
        self.clock = self.obs_tracer.clock
        self._stats_lock = make_lock("TelemetryTransport._stats_lock")
        self._send_stats: dict[tuple[int, int], _PeerTagStats] = {}
        self._recv_stats: dict[tuple[int, int], _PeerTagStats] = {}
        self._max_queue_depth = 0
        # live telemetry plane (MPIT_OBS_LIVE): a registry protocol code
        # publishes into via live_registry(transport), fed the aggregated
        # wire counters via a pull collector, exported by a background
        # thread when a run dir exists (registry only otherwise)
        self.obs_registry: Optional[MetricsRegistry] = None
        self._live_exporter: Optional[LiveExporter] = None
        if config.live:
            self.obs_registry = MetricsRegistry(inner.rank)
            self.obs_registry.add_collector("wire", self._live_wire_fragment)
            if journal is not None and journal.blackbox is not None:
                self.obs_registry.add_collector(
                    "blackbox", journal.blackbox.stats
                )
            if config.dir is not None:
                self._live_exporter = LiveExporter(
                    self.obs_registry,
                    os.path.join(config.dir, "live"),
                    interval_s=config.live_interval,
                )

    # -- accounting -------------------------------------------------------

    def _stat(self, table: dict, peer: int, tag: int) -> _PeerTagStats:
        s = table.get((peer, tag))
        if s is None:
            s = table[(peer, tag)] = _PeerTagStats()
        return s

    def _queue_depth(self) -> Optional[int]:
        """Outbound backlog, when the inner chain ends in a transport with
        per-dst send queues (SocketTransport); None otherwise. Reads the
        deque length without the queue's condition — a monitoring gauge
        may be momentarily stale, it must never contend with the drainer."""
        t: Any = self.inner
        for _ in range(4):  # telemetry -> chaos -> ... -> socket
            qs = getattr(t, "_send_queues", None)
            if qs is not None:
                return sum(len(q._items) for q in list(qs.values()))
            t = getattr(t, "inner", None)
            if t is None:
                return None
        return None

    # -- send path --------------------------------------------------------

    def _send_common(self, dst: int, tag: int, payload: Any, async_: bool):
        cfg = self.config
        clk = self.clock.tick()
        ctx = None
        parent_id = None
        wire = payload
        if cfg.trace:
            parent = self.obs_tracer.current_context()
            trace_id = parent.trace_id if parent is not None else _new_id()
            parent_id = parent.span_id if parent is not None else None
            ctx = SpanContext(trace_id, _new_id())
            wire = (_ENVELOPE_MARK, trace_id, ctx.span_id, clk, payload)
        nbytes = _approx_nbytes(payload)
        t0 = time.perf_counter()
        err: Optional[BaseException] = None
        handle = None
        try:
            # the sync path ALSO goes through isend: for SocketTransport
            # send() is literally isend().wait(), and the base Transport
            # defines isend as send + set_done — identical semantics either
            # way, but the returned handle carries the wire-phase split
            # (serialize / queue_wait / write) when the stack measures one
            handle = self.inner.isend(dst, tag, wire)
            if not async_:
                handle.wait()
        except BaseException as e:
            err = e
            raise
        finally:
            dt = time.perf_counter() - t0
            # a completed handle's phases are stable; an in-flight async
            # handle is left alone (its split lands in later sends' stats
            # only if still unread — phases are best-effort for isend)
            phases = (
                getattr(handle, "phases", None)
                if handle is not None and handle.done() and err is None
                else None
            )
            if phases is not None:
                # byte-counting transports (SocketTransport) stamp the
                # exact on-wire frame length next to the phase split —
                # prefer it over the estimate, so summary bytes equal
                # socket-level bytes (asserted by tests/test_obs.py)
                exact = getattr(handle, "wire_nbytes", None)
                if exact is not None:
                    nbytes = exact
            depth = None
            with self._stats_lock:
                s = self._stat(self._send_stats, dst, tag)
                s.n += 1
                n = s.n - 1
                s.msgs += 1
                s.bytes += nbytes
                if err is not None:
                    s.errs += 1
                bucket = _lat_bucket(dt)
                s.hist[bucket] = s.hist.get(bucket, 0) + 1
                if phases:
                    for k, v in phases.items():
                        s.phases[k] = s.phases.get(k, 0.0) + v
                sampled = n % cfg.sample == 0
            if sampled:
                depth = self._queue_depth()
                if depth is not None:
                    with self._stats_lock:
                        if depth > self._max_queue_depth:
                            self._max_queue_depth = depth
            if self.journal is not None and sampled:
                # "mtag" not "tag": MetricsLogger's record schema already
                # uses "tag" for the run identifier ("obs")
                fields: dict[str, Any] = {
                    "dst": dst, "mtag": tag, "n": n,
                    "bytes": nbytes, "dur": dt,
                }
                if ctx is not None:
                    fields["trace"] = ctx.trace_id
                    fields["span"] = ctx.span_id
                    if parent_id is not None:
                        fields["parent"] = parent_id
                if depth is not None:
                    fields["qdepth"] = depth
                if phases:
                    # short keys, journal-budget style: serialize /
                    # queue_wait / write wall-clock for THIS send
                    if "serialize" in phases:
                        fields["ser"] = phases["serialize"]
                    if "queue_wait" in phases:
                        fields["qw"] = phases["queue_wait"]
                    if "write" in phases:
                        fields["wr"] = phases["write"]
                if err is not None:
                    fields["err"] = type(err).__name__
                self.journal.event(
                    "isend" if async_ else "send", clk, **fields
                )
        return handle

    def send(self, dst: int, tag: int, payload: Any) -> None:
        self._send_common(dst, tag, payload, async_=False)

    def isend(self, dst: int, tag: int, payload: Any):
        return self._send_common(dst, tag, payload, async_=True)

    # -- recv path --------------------------------------------------------

    def recv(self, src: int = -1, tag: int = -1,
             timeout: Optional[float] = None):
        t0 = time.perf_counter()
        try:
            msg = self.inner.recv(src, tag, timeout)
        except RecvTimeout:
            # counted, never journaled: a watchdog's poll loop would spam
            # one record per poll interval
            with self._stats_lock:
                self._stat(self._recv_stats, src, tag).timeouts += 1
            raise
        wait = time.perf_counter() - t0
        payload = msg.payload
        ctx: Optional[SpanContext] = None
        remote_clk: Optional[int] = None
        if (
            type(payload) is tuple
            and len(payload) == 5
            and payload[0] == _ENVELOPE_MARK
        ):
            _, trace_id, span_id, remote_clk, payload = payload
            msg.payload = payload
            ctx = SpanContext(trace_id, span_id)
            clk = self.clock.observe(remote_clk)
        else:
            clk = self.clock.tick()
        if self.config.trace:
            # parent the receiving thread's NEXT sends on this message
            # (None clears a stale parent when the sender wasn't tracing)
            self.obs_tracer.set_remote_parent(ctx)
        # exact on-wire frame length when the inner stack counted it
        # (SocketTransport stamps every delivered message); the estimate
        # remains for reference-passing transports
        nbytes = getattr(msg, "wire_nbytes", None)
        if nbytes is None:
            nbytes = _approx_nbytes(payload)
        with self._stats_lock:
            s = self._stat(self._recv_stats, msg.src, msg.tag)
            s.n += 1
            n = s.n - 1
            s.msgs += 1
            s.bytes += nbytes
            bucket = _lat_bucket(wait)
            s.hist[bucket] = s.hist.get(bucket, 0) + 1
            sampled = n % self.config.sample == 0
        if self.journal is not None and sampled:
            fields = {
                "src": msg.src, "mtag": msg.tag, "n": n,
                "bytes": nbytes, "wait": wait,
            }
            if ctx is not None:
                fields["trace"] = ctx.trace_id
                fields["from_span"] = ctx.span_id
            if remote_clk is not None:
                # the sender's Lamport stamp: the post-mortem analyzer's
                # cross-rank alignment key (pairs this recv with the
                # sender's journal record carrying the same clock)
                fields["rclk"] = remote_clk
            self.journal.event("recv", clk, **fields)
        return msg

    # -- passthrough ------------------------------------------------------

    def probe(self, src: int = -1, tag: int = -1,
              timeout: Optional[float] = 0) -> bool:
        return self.inner.probe(src, tag, timeout)

    def close(self) -> None:
        try:
            self.inner.close()
        finally:
            try:
                self.obs_tracer.close()
            finally:
                self.close_live()

    def close_live(self) -> None:
        """Stop the live exporter (final snapshot lands on disk);
        idempotent, a no-op when live telemetry is not armed. Called from
        :meth:`close` and from the trainer teardown, which closes tracers
        explicitly rather than closing wrappers."""
        if self._live_exporter is not None:
            self._live_exporter.close()
            self._live_exporter = None

    def _live_wire_fragment(self) -> dict:
        """Live-snapshot collector: the per-(peer, tag) tables aggregated
        to rank totals (the dashboard wants a health line per rank, not
        the full matrix — ``summary()`` still has the split), plus the
        queue-depth gauge. Pulled at export time so the send/recv hot
        path pays nothing for the live plane."""
        tx = {"msgs": 0, "bytes": 0, "errs": 0}
        rx = {"msgs": 0, "bytes": 0, "errs": 0, "timeouts": 0}
        lat: dict[str, int] = {}
        with self._stats_lock:
            for s in self._send_stats.values():
                tx["msgs"] += s.msgs
                tx["bytes"] += s.bytes
                tx["errs"] += s.errs
                for b, c in s.hist.items():
                    lat[str(b)] = lat.get(str(b), 0) + c
            for s in self._recv_stats.values():
                rx["msgs"] += s.msgs
                rx["bytes"] += s.bytes
                rx["errs"] += s.errs
                rx["timeouts"] += s.timeouts
        out: dict[str, Any] = {"tx": tx, "rx": rx}
        if lat:
            out["send_lat_hist_log2us"] = lat
        depth = self._queue_depth()
        if depth is not None:
            out["queue_depth"] = depth
        return out

    # -- reporting --------------------------------------------------------

    def summary(self) -> dict:
        """JSON-able counters snapshot, folded into ``trainer.stats()``."""
        with self._stats_lock:
            out = {
                "rank": self.rank,
                "send": {
                    f"{dst}:{tag}": s.to_dict()
                    for (dst, tag), s in sorted(self._send_stats.items())
                },
                "recv": {
                    f"{src}:{tag}": s.to_dict()
                    for (src, tag), s in sorted(self._recv_stats.items())
                },
            }
            if self._max_queue_depth:
                out["max_queue_depth"] = self._max_queue_depth
        # receive-side phase split (transfer / deserialize per src:tag)
        # lives in the socket transport's read loop, not in this wrapper —
        # walk the inner chain for it, same depth bound as _queue_depth
        t: Any = self.inner
        for _ in range(4):  # telemetry -> chaos -> ... -> socket
            rx = getattr(t, "rx_phases", None)
            if callable(rx):
                snap = rx()
                if snap:
                    out["rx_phase_s"] = snap
                break
            t = getattr(t, "inner", None)
            if t is None:
                break
        return out


def _journal_for(config: ObsConfig, rank: int) -> Optional[Journal]:
    if config.dir is None:
        return None
    import os

    box = None
    if config.blackbox:
        box = BlackBox(
            config.dir, rank,
            max_records=config.blackbox_records,
            max_seconds=config.blackbox_seconds,
            gen=int(os.environ.get("MPIT_RESPAWN_GEN", "0") or 0),
        )
    return Journal(
        os.path.join(config.dir, f"obs_rank{rank}.jsonl"), rank,
        max_records=config.max_records,
        mode="ring" if config.ring else "cap",
        blackbox=box,
    )


def wrap_obs_transports(
    transports: Sequence[Transport], config: ObsConfig
) -> list[TelemetryTransport]:
    """Wrap a whole world (the chaos ``wrap_transports`` idiom); each rank
    gets its own journal file under ``config.dir`` (None = counters only).
    """
    return [
        TelemetryTransport(t, config, _journal_for(config, t.rank))
        for t in transports
    ]


def maybe_wrap(
    transport: Transport, config: Optional[ObsConfig]
) -> Transport:
    """The disabled fast path: with no config there is no wrapper — the
    transport is returned UNCHANGED (identity, pinned by tests)."""
    if config is None:
        return transport
    return TelemetryTransport(
        transport, config, _journal_for(config, transport.rank)
    )


def wrap_from_env(transport: Transport) -> Transport:
    """Process-mode hook (examples/ptest_proc.py): wrap iff ``MPIT_OBS_*``
    is armed in the environment — one line in a launch script instruments
    a whole run without code changes anywhere else. MPIT_OBS_FAULTHANDLER
    additionally arms periodic all-thread stack dumps for this process
    (``stacks_rank<r>.txt`` next to the journal) — hung-job forensics."""
    config = config_from_env()
    if config is not None:
        arm_faulthandler(config, f"rank{transport.rank}")
        if config.blackbox:
            # process mode owns its main thread: install the SIGTERM /
            # dump-signal triggers here (thread-mode worlds rely on the
            # atexit + dump-request triggers instead)
            arm_process_triggers(config.blackbox_dump_signal)
    return maybe_wrap(transport, config)
