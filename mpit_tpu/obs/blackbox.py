"""Black-box flight recorder: a bounded in-memory ring of the last few
seconds of a rank's observability stream, dumped atomically on trigger.

The live plane (``MPIT_OBS_LIVE``) answers "how is the run doing *now*";
the journals answer "what happened" — but only for ranks that exited
cleanly (a SIGKILLed process flushes nothing) and only within the
journal cap, which keeps the *head* of a run. This module is the
aviation-style third leg: every :class:`~mpit_tpu.obs.core.Journal`
tees its records (spans, wire telemetry, dynamics, serve lifecycle —
including records the cap drops) into a :class:`BlackBox`, a ring
bounded by BOTH record count and wall-clock horizon, that costs a list
append while healthy and writes ``<dir>/blackbox/rank_<r>.jsonl``
when something goes wrong.

Dump triggers (all with per-incident dedup):

- **close** — a cleanly-finished rank leaves its final window, so a
  post-mortem covers the whole fleet, not just the ranks that died;
- **atexit** — interpreter teardown catches ranks that never reached
  ``close()`` (an uncaught exception, ``sys.exit``);
- **SIGTERM** — the dump runs before the default handler re-raises, so
  a polite kill (the launcher's ``terminate()``, a scheduler's
  preemption warning) still captures the window. SIGKILL cannot be
  caught — that gap is exactly what the *cross-rank* triggers cover:
- **dump request** — any process may call :func:`request_dump` to write
  ``<dir>/blackbox/dump_request.json``; a per-process watcher thread
  (one poll every ~0.3 s) sees it and dumps EVERY local box, so one
  observer (the alert engine in ``obs live``, the elastic supervisor in
  ``mpit_tpu.launch`` observing a kill) freezes the incident window on
  every surviving rank of the fleet;
- **signal** — ``MPIT_OBS_BLACKBOX_DUMP_SIGNAL=USR1`` arms an explicit
  dump-and-continue signal for interactive forensics.

Dumps are atomic (tmp + ``os.replace``) and *accumulate*: each dump
appends one segment — a ``blackbox`` header record (rank, gen, trigger,
incident, window, eviction counters) followed by the ring's records in
journal format — to the rank's file, so an incident dump is never
overwritten by the quieter close dump that follows it. The analyzer
(``python -m mpit_tpu.obs postmortem``, :mod:`mpit_tpu.obs.postmortem`)
reassembles the segments into a cross-rank incident report.

Like the rest of the reader/boundary surface this module is
stdlib-only — it must be importable from the launcher and the CLI
without jax or the transport stack.
"""

from __future__ import annotations

import atexit
import json
import os
import signal as signal_mod
import threading
import time
from typing import Any, Callable, Optional

from mpit_tpu.analysis.runtime import make_lock

#: dump_request.json poll cadence for the watcher thread — fast enough
#: that survivors freeze their windows while the incident is still in
#: the ring horizon, slow enough to be free (one stat per poll)
_WATCH_INTERVAL_S = 0.3

REQUEST_FILE = "dump_request.json"


def _blackbox_dir(obs_dir: str) -> str:
    return os.path.join(obs_dir, "blackbox")


class BlackBox:
    """One rank's flight recorder: a ring bounded by record count AND
    wall-clock horizon, teed from the rank's Journal (see
    :meth:`~mpit_tpu.obs.core.Journal.event`).

    ``record`` is the hot path — a list append plus an amortized
    head-trim, pinned by the micro-benchmark in tests/test_blackbox.py.
    ``dump`` is the cold path — it snapshots the ring under the lock
    and does all formatting/IO outside it."""

    def __init__(
        self,
        obs_dir: str,
        rank: int,
        max_records: int = 2048,
        max_seconds: float = 30.0,
        gen: int = 0,
    ):
        if max_records < 1:
            raise ValueError("max_records must be >= 1")
        if max_seconds <= 0:
            raise ValueError("max_seconds must be > 0")
        self.dir = _blackbox_dir(obs_dir)
        self.rank = rank
        self.gen = gen
        self.max_records = max_records
        self.max_seconds = max_seconds
        self.path = os.path.join(self.dir, f"rank_{rank}.jsonl")
        self.evicted = 0
        self.dumps = 0
        self.last_trigger: Optional[str] = None
        self._ring: list = []  # (t, clk, ev, fields)
        self._lock = make_lock(f"obs.BlackBox._lock[{rank}]")
        self._closed = False
        self._seen_incidents: set = set()
        self._sources: list = []  # (name, callable) extra dump content
        _register(self)

    # -- hot path ---------------------------------------------------------

    def record(self, t: float, clk: int, ev: str, fields: dict) -> None:
        """Tee one journal record into the ring. Caller (Journal.event)
        already holds ITS lock; this takes the box's own so signal/
        watcher-thread dumps stay safe against concurrent writers."""
        with self._lock:
            if self._closed:
                return
            ring = self._ring
            ring.append((t, clk, ev, fields))
            if len(ring) > self.max_records:
                del ring[0]
                self.evicted += 1
            # horizon trim: amortized O(1) — each record is appended
            # once and evicted at most once
            horizon = t - self.max_seconds
            n = 0
            while n < len(ring) and ring[n][0] < horizon:
                n += 1
            if n:
                del ring[:n]
                self.evicted += n

    # -- dump path --------------------------------------------------------

    def add_source(self, name: str, fn: Callable[[], list]) -> None:
        """Register extra dump-time content: ``fn`` returns a list of
        JSON-able dicts appended to every dump segment under
        ``x_source: name`` (the chaos FaultLog's schedule rides along
        this way — see examples/ptest_proc.py)."""
        with self._lock:
            self._sources.append((name, fn))

    def stats(self) -> dict:
        """Live-plane collector fragment: the recorder's own health."""
        with self._lock:
            return {
                "records": len(self._ring),
                "evicted": self.evicted,
                "dumps": self.dumps,
                "last_trigger": self.last_trigger,
            }

    def dump(
        self, trigger: str, incident: Optional[str] = None
    ) -> Optional[str]:
        """Write one dump segment; returns the file path, or None when
        this incident was already dumped (per-incident dedup) or the
        ring is empty. Never raises — a flight recorder that can crash
        the plane is worse than none."""
        try:
            return self._dump(trigger, incident)
        except Exception:
            return None

    def _dump(self, trigger: str, incident: Optional[str]) -> Optional[str]:
        with self._lock:
            if incident is not None:
                if incident in self._seen_incidents:
                    return None
                self._seen_incidents.add(incident)
            ring = list(self._ring)
            sources = list(self._sources)
            self.dumps += 1
            self.last_trigger = trigger
        if not ring and trigger in ("atexit", "close"):
            return None
        header = {
            "ts": round(time.time(), 3),
            "tag": "obs",
            "process": 0,
            "step": ring[-1][1] if ring else 0,
            "rank": self.rank,
            "ev": "blackbox",
            "t": time.time(),
            "gen": self.gen,
            "trigger": trigger,
            "records": len(ring),
            "evicted": self.evicted,
            "cap": self.max_records,
            "horizon_s": self.max_seconds,
        }
        if incident is not None:
            header["incident"] = incident
        if ring:
            header["t_first"] = ring[0][0]
            header["t_last"] = ring[-1][0]
        lines = [json.dumps(header)]
        for t, clk, ev, fields in ring:
            rec = {
                "ts": round(t, 3), "tag": "obs", "process": 0,
                "step": clk, "rank": self.rank, "ev": ev, "t": t,
            }
            for k, v in fields.items():
                rec[k] = _jsonable(v)
            lines.append(json.dumps(rec))
        for name, fn in sources:
            try:
                extra = fn()
            except Exception:
                continue
            for item in extra:
                rec = dict(item)
                rec.setdefault("rank", self.rank)
                rec["x_source"] = name
                lines.append(json.dumps(rec))
        os.makedirs(self.dir, exist_ok=True)
        # accumulate-atomically: new file = old segments + this one,
        # swapped in with os.replace — an earlier incident segment is
        # never clobbered by the close dump that follows it, and a
        # reader never sees a torn file
        prev = b""
        try:
            with open(self.path, "rb") as f:
                prev = f.read()
        except OSError:
            pass
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(prev)
            f.write(("\n".join(lines) + "\n").encode())
        os.replace(tmp, self.path)
        return self.path

    def close(self) -> None:
        """Stop recording and leave the registry (the Journal dumps a
        final ``close`` segment *before* calling this)."""
        with self._lock:
            self._closed = True
            self._ring = []
        _unregister(self)


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, bool, int, float, type(None), list, dict)):
        return v
    if hasattr(v, "tolist"):
        return v.tolist()
    try:
        return float(v)
    except (TypeError, ValueError):
        return repr(v)


# -- process-wide trigger plumbing ------------------------------------------
# One registry of live boxes per process (thread mode has one box per
# rank in a single process; process mode has one per OS process), one
# watcher thread, one atexit hook, at most one handler per signal.

_REG_LOCK = make_lock("obs.blackbox._REG_LOCK")
_BOXES: list = []
_WATCHER: Optional[threading.Thread] = None
_WATCHER_STOP = threading.Event()
_ATEXIT_ARMED = False
_SIGTERM_ARMED = False
_DUMP_SIGNALS: set = set()


def _register(box: BlackBox) -> None:
    global _WATCHER, _ATEXIT_ARMED
    with _REG_LOCK:
        _BOXES.append(box)
        if not _ATEXIT_ARMED:
            atexit.register(_dump_all, "atexit")
            _ATEXIT_ARMED = True
        if _WATCHER is None:
            _WATCHER_STOP.clear()
            _WATCHER = threading.Thread(
                target=_watch, daemon=True, name="mpit-blackbox-watch"
            )
            _WATCHER.start()


def _unregister(box: BlackBox) -> None:
    global _WATCHER
    with _REG_LOCK:
        try:
            _BOXES.remove(box)
        except ValueError:
            pass
        if not _BOXES:
            # park the watcher when the last box leaves; a fresh box
            # restarts it (tests create/destroy many worlds per process)
            _WATCHER_STOP.set()
            _WATCHER = None


def _boxes() -> list:
    with _REG_LOCK:
        return list(_BOXES)


def _dump_all(trigger: str, incident: Optional[str] = None) -> list:
    return [
        p for b in _boxes()
        if (p := b.dump(trigger, incident)) is not None
    ]


def _watch() -> None:
    """Poll each live box's ``dump_request.json`` (watcher thread). One
    request file per obs dir; the incident id dedups per box, so every
    box dumps exactly once per incident however often the file is
    re-read."""
    stop = _WATCHER_STOP
    while not stop.wait(_WATCH_INTERVAL_S):
        boxes = _boxes()
        if not boxes:
            continue
        by_dir: dict[str, list] = {}
        for b in boxes:
            by_dir.setdefault(b.dir, []).append(b)
        for d, group in by_dir.items():
            req = _read_request(os.path.join(d, REQUEST_FILE))
            if req is None:
                continue
            incident = req.get("incident") or "request"
            for b in group:
                b.dump("request", incident)


def _read_request(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            req = json.load(f)
    except (OSError, ValueError):
        return None
    return req if isinstance(req, dict) else None


def request_dump(
    obs_dir: str, reason: str, incident: Optional[str] = None
) -> str:
    """Ask every rank of the run under ``obs_dir`` to freeze its window:
    writes ``<dir>/blackbox/dump_request.json`` atomically; each rank's
    watcher thread sees it within ~{interval} and dumps (deduped per
    ``incident``). Callable from any process that can see the obs dir —
    the alert engine, the elastic supervisor, a human. Returns the
    incident id (auto-derived from the reason + wall-clock when not
    given)."""
    if incident is None:
        incident = f"{reason}@{int(time.time() * 1000)}"
    d = _blackbox_dir(obs_dir)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, REQUEST_FILE)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(
            {"incident": incident, "reason": reason, "t": time.time()}, f
        )
    os.replace(tmp, path)
    # the requester's own boxes (thread mode: observer == observed
    # process) dump immediately rather than waiting out the poll
    for b in _boxes():
        if b.dir == d:
            b.dump("request", incident)
    return incident


def arm_process_triggers(
    dump_signal: Optional[str] = None,
) -> None:
    """Install the process-level dump triggers: a chaining SIGTERM
    handler (dump all boxes, restore the previous handler, re-raise so
    the exit status still says SIGTERM), and optionally an explicit
    dump-and-continue signal (``MPIT_OBS_BLACKBOX_DUMP_SIGNAL`` — name
    with or without the SIG prefix, or a number). Idempotent; silently
    a no-op off the main thread (signal() would raise) — the atexit and
    dump-request triggers still cover such worlds."""
    global _SIGTERM_ARMED
    with _REG_LOCK:
        want_sigterm = not _SIGTERM_ARMED
        _SIGTERM_ARMED = True
    if want_sigterm:
        try:
            prev = signal_mod.getsignal(signal_mod.SIGTERM)

            def _on_term(signum, frame):
                _dump_all("sigterm")
                if callable(prev) and prev not in (
                    signal_mod.SIG_IGN, signal_mod.SIG_DFL
                ):
                    prev(signum, frame)
                else:
                    signal_mod.signal(signum, signal_mod.SIG_DFL)
                    signal_mod.raise_signal(signum)

            signal_mod.signal(signal_mod.SIGTERM, _on_term)
        except (ValueError, OSError):
            with _REG_LOCK:
                _SIGTERM_ARMED = False
    if dump_signal:
        signum = _parse_signal(dump_signal)
        if signum is not None and signum not in _DUMP_SIGNALS:
            try:
                signal_mod.signal(
                    signum,
                    lambda s, f: _dump_all(
                        "signal", f"signal-{s}@{int(time.time())}"
                    ),
                )
                _DUMP_SIGNALS.add(signum)
            except (ValueError, OSError):
                pass


def _parse_signal(name: str) -> Optional[int]:
    try:
        return int(name)
    except ValueError:
        pass
    key = name.upper()
    if not key.startswith("SIG"):
        key = "SIG" + key
    return getattr(signal_mod, key, None)


def box_for(transport) -> Optional[BlackBox]:
    """The flight recorder behind an obs-wrapped transport (None when
    obs or the black box is unarmed) — how protocol-adjacent code (e.g.
    the chaos fault-log source in examples/ptest_proc.py) reaches it
    without knowing the wrapper layout."""
    journal = getattr(transport, "journal", None)
    return getattr(journal, "blackbox", None)
