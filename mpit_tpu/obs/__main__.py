"""CLI: merge per-rank obs journals into a Perfetto-loadable trace.

    python -m mpit_tpu.obs merge RUN_DIR [-o trace.json] [--faults f.jsonl]
    python -m mpit_tpu.obs summary RUN_DIR
    python -m mpit_tpu.obs summary --diff RUN_A RUN_B
    python -m mpit_tpu.obs roofline RUN_DIR [--json]
    python -m mpit_tpu.obs slo RUN_DIR [--gate slo.json] [--json]
    python -m mpit_tpu.obs dynamics RUN_DIR [--gate dynamics.json] [--json]
    python -m mpit_tpu.obs live RUN_DIR [--once] [--json] [--validate]
    python -m mpit_tpu.obs postmortem RUN_DIR [--json] [--perfetto t.json]

``RUN_DIR`` is the ``MPIT_OBS_DIR`` of the run (or explicit journal
files). ``merge`` writes Chrome-trace JSON — open it at
https://ui.perfetto.dev (or chrome://tracing). With ``--faults`` (or a
``faults.jsonl`` sitting in the run dir) chaos faults render as instant
events on the rank that suffered them; live-plane alerts
(``live/alerts.jsonl``) render the same way. ``summary --diff`` compares
two runs stream by stream — per-(peer, tag) message/byte counters and
the median log2-µs latency bucket — and prints only the streams that
moved. ``roofline`` joins the journals into a per-rank and per-run
compute/wire/idle/overhead breakdown (fractions sum to 1.0; the slowest
client is flagged as straggler). ``slo`` reduces the serving lifecycle
events (``models/serving.py`` under the loadgen harness — see
docs/SERVING.md) to TTFT/TPOT/e2e percentiles, goodput, queue depth and
occupancy; ``--gate slo.json`` checks them against ceilings/floors.
``dynamics`` reduces the training-dynamics records
(docs/OBSERVABILITY.md "dynamics") to per-client staleness percentiles,
elastic-distance trajectories with a monotone-growth divergence
verdict, and update/param norm ratios; ``--gate dynamics.json`` checks
the run roll-up (``staleness_p99_max``, ``elastic_dist_final_max``,
``norm_ratio_max``, ``allow_diverging``).
``live`` reads the in-run snapshots a ``MPIT_OBS_LIVE=1`` run exports
(``live/rank_<r>.json``), renders a refreshing cross-rank dashboard
(``--once --json`` for scripting), and runs the online alert engine
(dead-rank, straggler, SLO burn) appending ``live/alerts.jsonl`` —
each firing also requests a black-box dump on every rank of the run
(``--no-dump`` to observe without touching the run dir).
``postmortem`` assembles the cross-rank incident report from the
black-box dumps (``blackbox/rank_*.jsonl``): first-mover, last
exchange rounds acked/dropped, staleness/elastic/wire-phase overlays,
membership + chaos churn — see docs/OBSERVABILITY.md "Black box".
Exit codes: 0 ok, 1 gate violation / new alerts / invalid snapshot /
incident found, 2 usage/empty.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from mpit_tpu.obs.merge import (
    diff_summaries,
    expand_journal_paths,
    merge_to_chrome_trace,
    roofline,
    summarize,
    trace_ids_by_rank,
)


def _print_roofline(report: dict) -> None:
    hdr = (
        f"{'rank':>4} {'role':>6} {'window':>9} {'compute':>8} "
        f"{'wire':>8} {'idle':>8} {'ovhd':>8} {'exch':>5} {'bytes':>10}"
    )
    print(hdr)
    for rank, row in report["ranks"].items():
        ph = row["phases"]
        mark = " <- straggler" if rank == report["straggler"] else ""
        print(
            f"{rank:>4} {row['role']:>6} {row['window_s']:>8.3f}s "
            f"{ph['compute']:>7.1%} {ph['wire']:>7.1%} "
            f"{ph['idle']:>7.1%} {ph['overhead']:>7.1%} "
            f"{row['exchanges']:>5} {row['bytes']:>10}{mark}"
        )
    run = report["run"]
    ph = run["phases"]
    print(
        f" run: {run['clients']} client(s) / "
        f"{run['ranks'] - run['clients']} server(s), "
        f"window {run['window_s']:.3f}s — compute {ph['compute']:.1%}, "
        f"wire {ph['wire']:.1%}, idle {ph['idle']:.1%}, "
        f"overhead {ph['overhead']:.1%}"
    )


def _print_diff(rows) -> None:
    moved = [r for r in rows if not r["same"]]
    for r in moved:
        lat = ""
        if r["delta_p50_bucket"] is not None:
            lat = (
                f", p50 bucket {r['p50_bucket_a']} -> "
                f"{r['p50_bucket_b']}"
            )
        print(
            f"rank {r['rank']} {r['dir']} "
            f"{'->' if r['dir'] == 'send' else '<-'} peer {r['peer']} "
            f"{r['tag_name']}: msgs {r['msgs_a']} -> {r['msgs_b']} "
            f"({r['delta_msgs']:+d}), bytes {r['bytes_a']} -> "
            f"{r['bytes_b']} ({r['delta_bytes']:+d}){lat}"
        )
    print(
        f"{len(moved)} stream(s) changed, "
        f"{len(rows) - len(moved)} unchanged"
    )


def _fmt_ms(v) -> str:
    return "-" if v is None else f"{v:.1f}"


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _print_dynamics(report: dict) -> None:
    run = report["run"]
    verdict = "DIVERGING" if run["diverging"] else "stable"
    print(
        f"dynamics: {run['clients']} client(s) / {run['servers']} "
        f"server(s) — staleness p99 {_fmt(run['staleness_p99'])}, "
        f"elastic final {_fmt(run['elastic_dist_final'])}, "
        f"norm ratio {_fmt(run['norm_ratio'])} — {verdict}"
    )
    if report["staleness"]:
        print(f"{'src':>4} {'pushes':>7} {'p50':>5} {'p99':>5} "
              f"{'max':>5} {'mean':>7}")
        for src, row in report["staleness"].items():
            print(
                f"{src:>4} {row['pushes']:>7} {_fmt(row['p50']):>5} "
                f"{_fmt(row['p99']):>5} {_fmt(row['max']):>5} "
                f"{row['mean']:>7.2f}"
            )
    if report["clients"]:
        print(f"{'rank':>4} {'algo':>8} {'rounds':>7} {'elastic':>9} "
              f"{'(first->final)':>16} {'push':>9} {'ratio':>7}  verdict")
        for rank, row in report["clients"].items():
            el = row.get("elastic")
            span = (
                f"{_fmt(el['first'])}->{_fmt(el['final'])}"
                if el is not None else "-"
            )
            print(
                f"{rank:>4} {str(row.get('algo')):>8} "
                f"{row['rounds']:>7} "
                f"{_fmt(el['final'] if el else None):>9} {span:>16} "
                f"{_fmt(row.get('push_norm')):>9} "
                f"{_fmt(row.get('norm_ratio')):>7}  "
                + ("DIVERGING" if row.get("diverging") else "stable")
            )
    for rank, row in report["servers"].items():
        mono = "monotonic" if row["monotonic"] else "NON-MONOTONIC"
        print(
            f" server rank {rank}: {row['param_replies']} PARAM "
            f"replies, version {row['first_version']} -> "
            f"{row['final_version']} ({mono})"
        )


def _print_live(report: dict, live_dir: str, fired: list) -> None:
    run = report["run"]
    print(
        f"live: {run['ranks']} rank(s) under {live_dir} — "
        f"throughput {run['throughput']:.1f} samples/s, "
        f"max heartbeat age {run['max_age_s']:.1f}s"
    )
    hdr = (
        f"{'rank':>4} {'role':>6} {'age':>6} {'seq':>5} {'thr/s':>8} "
        f"{'queue':>5} {'compute':>8} {'wire':>6} {'other':>6} "
        f"{'exch p50/p90/p99 ms':>20}  faults"
    )
    print(hdr)
    for rank, row in report["ranks"].items():
        ph = row.get("phases")
        exch = row.get("exchange_ms")
        q = row.get("queue_depth")
        faults = ",".join(
            f"{k}:{v}" for k, v in sorted(row.get("faults", {}).items())
        ) or "-"
        print(
            f"{rank:>4} {row['role']:>6} {row['age_s']:>5.1f}s "
            f"{row['seq']:>5} {row['throughput']:>8.1f} "
            f"{'-' if q is None else q:>5} "
            + (
                f"{ph['compute']:>7.1%} {ph['wire']:>5.1%} "
                f"{ph['other']:>5.1%} "
                if ph is not None else f"{'-':>7} {'-':>5} {'-':>5} "
            )
            + (
                f"{_fmt_ms(exch['p50']):>6}/{_fmt_ms(exch['p90'])}"
                f"/{_fmt_ms(exch['p99']):<7}"
                if exch is not None else f"{'-':>20}"
            )
            + f"  {faults}"
        )
        srow = row.get("serve")
        if srow is not None:
            print(
                f"     serve: waiting {srow['waiting']} "
                f"occupied {srow['occupied']} rps {srow['rps']:.1f} "
                f"tokens/s {srow['tokens_per_s']:.1f} "
                f"slo-miss {srow['slo_miss_fraction']:.1%} "
                f"ttft p50 {_fmt_ms(srow.get('ttft_p50_ms'))}ms "
                f"p99 {_fmt_ms(srow.get('ttft_p99_ms'))}ms"
            )
        stal = row.get("staleness")
        dyn = row.get("dynamics")
        if stal is not None or dyn is not None:
            parts = []
            if stal is not None:
                parts.append("staleness p50/p99 "
                             f"{_fmt(stal['p50'])}/{_fmt(stal['p99'])}")
            if dyn is not None:
                parts.append(f"elastic {_fmt(dyn['elastic_dist'])}")
                parts.append(f"push {_fmt(dyn['push_norm'])}")
                parts.append(f"ratio {_fmt(dyn['norm_ratio'])}")
            print("     dynamics: " + "  ".join(parts))
    for rec in fired:
        print(
            f"ALERT {rec['kind']} rank {rec['rank']}: "
            f"{json.dumps(rec['detail'])}"
        )


def _cmd_live(ns) -> int:
    import time as _time

    from mpit_tpu.obs import alerts as alerts_mod
    from mpit_tpu.obs import live as live_mod

    live_dir = live_mod.find_live_dir(ns.path)

    if ns.validate:
        paths = sorted(glob.glob(os.path.join(live_dir, "rank_*.json")))
        if not paths:
            print(f"no rank_*.json snapshots under {live_dir}",
                  file=sys.stderr)
            return 2
        bad = 0
        for path in paths:
            try:
                with open(path) as f:
                    snap = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                print(f"{path}: unreadable: {e}", file=sys.stderr)
                bad += 1
                continue
            problems = live_mod.validate_snapshot(snap)
            for prob in problems:
                print(f"{path}: {prob}", file=sys.stderr)
            bad += bool(problems)
        print(f"validated {len(paths)} snapshot(s), {bad} invalid")
        return 1 if bad else 0

    engine = None
    if not ns.no_alerts:
        kwargs = {
            k: v for k, v in (
                ("staleness_factor", ns.staleness_factor),
                ("straggler_spread", ns.straggler_spread),
                ("burn_threshold", ns.burn_threshold),
                ("slo_target", ns.slo_target),
            ) if v is not None
        }
        on_fire = None
        if not ns.no_dump:
            from mpit_tpu.obs import blackbox as blackbox_mod

            # live_dir is <run dir>/live — the dump request goes in the
            # run dir, where every rank's watcher thread polls for it
            run_dir = os.path.dirname(os.path.abspath(live_dir))

            def on_fire(rec):
                blackbox_mod.request_dump(
                    run_dir,
                    f"alert:{rec.get('kind')}",
                    f"{rec.get('kind')}-rank{rec.get('rank')}",
                )

        engine = alerts_mod.AlertEngine(
            os.path.join(live_dir, "alerts.jsonl"),
            alerts_mod.AlertConfig(**kwargs),
            on_fire=on_fire,
        )

    deadline = (
        _time.monotonic() + ns.max_seconds
        if ns.max_seconds is not None else None
    )
    try:
        while True:
            snaps = live_mod.read_snapshots(live_dir)
            if not snaps:
                if ns.once:
                    print(f"no rank_*.json snapshots under {live_dir} "
                          "(is MPIT_OBS_LIVE armed?)", file=sys.stderr)
                    return 2
                print(f"waiting for snapshots under {live_dir} ...",
                      file=sys.stderr)
            else:
                fired = engine.evaluate(snaps) if engine is not None else []
                report = live_mod.aggregate(snaps)
                report["alerts_fired"] = fired
                if ns.json:
                    json.dump(report, sys.stdout)
                    print()
                else:
                    if not ns.once:
                        # clear + home, full-refresh dashboard
                        sys.stdout.write("\x1b[2J\x1b[H")
                    _print_live(report, live_dir, fired)
                    sys.stdout.flush()
                if ns.once:
                    return 1 if fired else 0
            if deadline is not None and _time.monotonic() >= deadline:
                return 0
            _time.sleep(ns.refresh)
    except KeyboardInterrupt:
        return 0


def _cmd_postmortem(ns) -> int:
    from mpit_tpu.obs import postmortem as pm

    report = pm.analyze(ns.path, k_rounds=ns.rounds)
    if report is None:
        print(f"no black-box dumps under {ns.path} (expected "
              "blackbox/rank_*.jsonl — did any trigger fire?)",
              file=sys.stderr)
        return 2
    if ns.json:
        json.dump(report, sys.stdout)
        print()
    else:
        print(pm.format_report(report))
    if ns.perfetto is not None:
        faults = None
        if glob.glob(os.path.join(ns.path, "faults*.jsonl")):
            faults = ns.path
        alerts = None
        for cand in (
            os.path.join(ns.path, "live", "alerts.jsonl"),
            os.path.join(ns.path, "alerts.jsonl"),
        ):
            if os.path.exists(cand):
                alerts = cand
                break
        trace = merge_to_chrome_trace(
            pm.dump_paths(ns.path), faults_path=faults, alerts_path=alerts
        )
        with open(ns.perfetto, "w") as f:
            json.dump(trace, f)
        print(f"wrote {ns.perfetto}: {len(trace['traceEvents'])} "
              "incident-window events", file=sys.stderr)
    return 1 if report["verdict"] == "incident" else 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mpit_tpu.obs", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    mp = sub.add_parser("merge", help="journals -> Chrome-trace JSON")
    mp.add_argument("paths", nargs="+",
                    help="run dir (MPIT_OBS_DIR) or journal files")
    mp.add_argument("-o", "--out", default=None,
                    help="output file (default: <first dir>/trace.json)")
    mp.add_argument("--faults", default=None,
                    help="chaos fault log JSONL (or a directory of "
                         "faults*.jsonl, process mode) to overlay "
                         "(default: <run dir>/faults*.jsonl when present)")
    mp.add_argument("--alerts", default=None,
                    help="live-plane alerts.jsonl to overlay as instant "
                         "markers (default: <run dir>/live/alerts.jsonl "
                         "or <run dir>/alerts.jsonl when present)")

    sp = sub.add_parser("summary", help="per-rank event tallies")
    sp.add_argument("paths", nargs="+")
    sp.add_argument(
        "--diff",
        action="store_true",
        help="compare exactly two runs stream-by-stream (per-(peer, tag) "
        "counters + median latency bucket)",
    )

    rp = sub.add_parser(
        "roofline",
        help="per-rank compute/wire/idle/overhead attribution",
    )
    rp.add_argument("paths", nargs="+",
                    help="run dir (MPIT_OBS_DIR) or journal files")
    rp.add_argument("--json", action="store_true",
                    help="emit the full report as JSON instead of a table")

    lp = sub.add_parser(
        "slo",
        help="serving scorecard: TTFT/TPOT/e2e percentiles, goodput",
    )
    lp.add_argument("paths", nargs="+",
                    help="run dir (the server's ObsConfig.dir) or "
                         "journal files")
    lp.add_argument("--gate", default=None,
                    help="JSON gate file of ceilings/floors (e.g. "
                         '{"ttft_p99_ms": 250, "goodput_min": 0.95}); '
                         "violations exit 1")
    lp.add_argument("--json", action="store_true",
                    help="emit the report (plus any violations) as JSON")
    lp.add_argument("--default-slo-ms", type=float, default=None,
                    help="e2e SLO applied to requests submitted without "
                         "one (default: such requests meet vacuously)")

    dp = sub.add_parser(
        "dynamics",
        help="training-dynamics report: staleness, elastic distance, "
             "update/param norm ratios, divergence verdict",
    )
    dp.add_argument("paths", nargs="+",
                    help="run dir (MPIT_OBS_DIR) or journal files")
    dp.add_argument("--gate", default=None,
                    help="JSON gate file (keys: staleness_p99_max, "
                         "elastic_dist_final_max, norm_ratio_max, "
                         "allow_diverging); violations exit 1")
    dp.add_argument("--json", action="store_true",
                    help="emit the report (plus any violations) as JSON")

    vp = sub.add_parser(
        "live",
        help="live dashboard + alerts over live/rank_*.json snapshots",
    )
    vp.add_argument("path",
                    help="run dir (MPIT_OBS_DIR) or its live/ subdir")
    vp.add_argument("--once", action="store_true",
                    help="one pass instead of a refreshing dashboard "
                         "(exit 1 if new alerts fired)")
    vp.add_argument("--json", action="store_true",
                    help="emit the aggregate report as JSON (implies "
                         "machine-readable; pairs with --once)")
    vp.add_argument("--refresh", type=float, default=2.0,
                    help="dashboard refresh interval, seconds (default 2)")
    vp.add_argument("--max-seconds", type=float, default=None,
                    help="stop the refreshing dashboard after this long")
    vp.add_argument("--no-alerts", action="store_true",
                    help="display only: skip the alert engine (nothing "
                         "appended to alerts.jsonl)")
    vp.add_argument("--no-dump", action="store_true",
                    help="alerts fire without requesting black-box dumps "
                         "(observe without writing into the run dir)")
    vp.add_argument("--staleness-factor", type=float, default=None,
                    help="dead-rank threshold as a multiple of each "
                         "rank's export interval (default 3)")
    vp.add_argument("--straggler-spread", type=float, default=None,
                    help="compute-fraction min-max spread that flags a "
                         "straggler (default 0.25)")
    vp.add_argument("--burn-threshold", type=float, default=None,
                    help="SLO burn rate that alerts (default 1.0 = "
                         "error budget consumed as fast as it accrues)")
    vp.add_argument("--slo-target", type=float, default=None,
                    help="SLO attainment target the burn rate is "
                         "normalized against (default 0.95)")
    vp.add_argument("--validate", action="store_true",
                    help="strict-validate every snapshot against the "
                         "versioned schema and exit (the lint.sh golden "
                         "gate)")

    pp = sub.add_parser(
        "postmortem",
        help="cross-rank incident report from black-box dumps",
    )
    pp.add_argument("path",
                    help="run dir (MPIT_OBS_DIR) holding "
                         "blackbox/rank_*.jsonl, or a dump dir itself")
    pp.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")
    pp.add_argument("--rounds", type=int, default=5,
                    help="exchange rounds reconstructed per rank "
                         "(default 5)")
    pp.add_argument("--perfetto", default=None, metavar="OUT",
                    help="also write an incident-window Chrome trace of "
                         "the dumps (open at https://ui.perfetto.dev)")

    ns = p.parse_args(argv)

    # live and postmortem read their own layouts (rank_*.json snapshots
    # / blackbox dumps), not obs_rank*.jsonl journals — dispatch them
    # before the journal-expansion gate below
    if ns.cmd == "live":
        return _cmd_live(ns)
    if ns.cmd == "postmortem":
        return _cmd_postmortem(ns)

    if ns.cmd == "summary" and ns.diff:
        if len(ns.paths) != 2:
            print("summary --diff takes exactly two run dirs",
                  file=sys.stderr)
            return 2
        a, b = ns.paths
        if not expand_journal_paths([a]) or not expand_journal_paths([b]):
            print(f"no obs_rank*.jsonl journals under {a} or {b}",
                  file=sys.stderr)
            return 2
        _print_diff(diff_summaries([a], [b]))
        return 0

    journals = expand_journal_paths(ns.paths)
    if not journals:
        print(f"no obs_rank*.jsonl journals under {ns.paths}",
              file=sys.stderr)
        return 2

    if ns.cmd == "slo":
        from mpit_tpu.loadgen.slo import (
            aggregate_paths, evaluate_gate, format_report, load_gate,
        )

        report = aggregate_paths(
            journals, default_slo_ms=ns.default_slo_ms
        )
        if report["requests"]["submitted"] == 0:
            print("journals carry no request lifecycle events "
                  "(serve with obs=ObsConfig(dir=...))", file=sys.stderr)
            return 2
        violations = []
        if ns.gate is not None:
            try:
                gate = load_gate(ns.gate)
            except (OSError, ValueError) as e:
                print(f"bad gate file {ns.gate}: {e}", file=sys.stderr)
                return 2
            violations = evaluate_gate(report, gate)
        if ns.json:
            json.dump({**report, "violations": violations}, sys.stdout)
            print()
        else:
            print(format_report(report))
            for v in violations:
                print(f"SLO VIOLATION: {v}")
        if violations:
            return 1
        return 0

    if ns.cmd == "dynamics":
        from mpit_tpu.obs.dynamics import (
            aggregate_dynamics, check_dynamics_gate, load_gate,
        )

        report = aggregate_dynamics(journals)
        if report["run"] is None:
            print("journals carry no training-dynamics records "
                  "(train with obs armed — docs/OBSERVABILITY.md "
                  "\"dynamics\")", file=sys.stderr)
            return 2
        violations = []
        if ns.gate is not None:
            try:
                gate = load_gate(ns.gate)
            except (OSError, ValueError) as e:
                print(f"bad gate file {ns.gate}: {e}", file=sys.stderr)
                return 2
            violations = check_dynamics_gate(report, gate)
        if ns.json:
            json.dump({**report, "violations": violations}, sys.stdout)
            print()
        else:
            _print_dynamics(report)
            for v in violations:
                print(f"DYNAMICS VIOLATION: {v}")
        if violations:
            return 1
        return 0

    if ns.cmd == "roofline":
        report = roofline(journals)
        if report["run"] is None:
            print("journals carry no timed events", file=sys.stderr)
            return 2
        if ns.json:
            json.dump(report, sys.stdout, indent=2, default=str)
            print()
        else:
            _print_roofline(report)
        return 0

    if ns.cmd == "summary":
        for rank, row in summarize(journals).items():
            print(
                f"rank {rank}: {row['events']} events "
                f"({row['sends']} sends / {row['recvs']} recvs, "
                f"{row['bytes']} bytes, {row['traces']} traces)"
            )
        return 0

    first_dir = next((q for q in ns.paths if os.path.isdir(q)), None)
    faults = ns.faults
    if faults is None and first_dir is not None:
        candidate = os.path.join(first_dir, "faults.jsonl")
        if os.path.exists(candidate):
            faults = candidate
        elif glob.glob(os.path.join(first_dir, "faults*.jsonl")):
            # process-mode runs write one fault log per rank; the dir
            # form hands all of them to read_fault_log
            faults = first_dir
    alerts = ns.alerts
    if alerts is None and first_dir is not None:
        for candidate in (
            os.path.join(first_dir, "live", "alerts.jsonl"),
            os.path.join(first_dir, "alerts.jsonl"),
        ):
            if os.path.exists(candidate):
                alerts = candidate
                break
    out_path = ns.out or os.path.join(first_dir or ".", "trace.json")

    trace = merge_to_chrome_trace(
        journals, faults_path=faults, alerts_path=alerts
    )
    with open(out_path, "w") as f:
        json.dump(trace, f)

    by_rank = trace_ids_by_rank(journals)
    all_traces = set().union(*by_rank.values()) if by_rank else set()
    cross = sum(
        1 for t in all_traces
        if sum(1 for ids in by_rank.values() if t in ids) >= 2
    )
    n_faults = sum(1 for e in trace["traceEvents"] if e.get("cat") == "chaos")
    n_alerts = sum(1 for e in trace["traceEvents"] if e.get("cat") == "alert")
    print(
        f"wrote {out_path}: {len(trace['traceEvents'])} events from "
        f"{len(by_rank) or len(journals)} rank(s), {len(all_traces)} "
        f"trace(s) ({cross} cross-rank), {n_faults} fault marker(s), "
        f"{n_alerts} alert marker(s) — open in https://ui.perfetto.dev"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
