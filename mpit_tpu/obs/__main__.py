"""CLI: merge per-rank obs journals into a Perfetto-loadable trace.

    python -m mpit_tpu.obs merge RUN_DIR [-o trace.json] [--faults f.jsonl]
    python -m mpit_tpu.obs summary RUN_DIR
    python -m mpit_tpu.obs summary --diff RUN_A RUN_B
    python -m mpit_tpu.obs roofline RUN_DIR [--json]
    python -m mpit_tpu.obs slo RUN_DIR [--gate slo.json] [--json]

``RUN_DIR`` is the ``MPIT_OBS_DIR`` of the run (or explicit journal
files). ``merge`` writes Chrome-trace JSON — open it at
https://ui.perfetto.dev (or chrome://tracing). With ``--faults`` (or a
``faults.jsonl`` sitting in the run dir) chaos faults render as instant
events on the rank that suffered them. ``summary --diff`` compares two
runs stream by stream — per-(peer, tag) message/byte counters and the
median log2-µs latency bucket — and prints only the streams that moved.
``roofline`` joins the journals into a per-rank and per-run
compute/wire/idle/overhead breakdown (fractions sum to 1.0; the slowest
client is flagged as straggler). ``slo`` reduces the serving lifecycle
events (``models/serving.py`` under the loadgen harness — see
docs/SERVING.md) to TTFT/TPOT/e2e percentiles, goodput, queue depth and
occupancy; ``--gate slo.json`` checks them against ceilings/floors.
Exit codes: 0 ok, 1 gate violation, 2 usage/empty.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from mpit_tpu.obs.merge import (
    diff_summaries,
    expand_journal_paths,
    merge_to_chrome_trace,
    roofline,
    summarize,
    trace_ids_by_rank,
)


def _print_roofline(report: dict) -> None:
    hdr = (
        f"{'rank':>4} {'role':>6} {'window':>9} {'compute':>8} "
        f"{'wire':>8} {'idle':>8} {'ovhd':>8} {'exch':>5} {'bytes':>10}"
    )
    print(hdr)
    for rank, row in report["ranks"].items():
        ph = row["phases"]
        mark = " <- straggler" if rank == report["straggler"] else ""
        print(
            f"{rank:>4} {row['role']:>6} {row['window_s']:>8.3f}s "
            f"{ph['compute']:>7.1%} {ph['wire']:>7.1%} "
            f"{ph['idle']:>7.1%} {ph['overhead']:>7.1%} "
            f"{row['exchanges']:>5} {row['bytes']:>10}{mark}"
        )
    run = report["run"]
    ph = run["phases"]
    print(
        f" run: {run['clients']} client(s) / "
        f"{run['ranks'] - run['clients']} server(s), "
        f"window {run['window_s']:.3f}s — compute {ph['compute']:.1%}, "
        f"wire {ph['wire']:.1%}, idle {ph['idle']:.1%}, "
        f"overhead {ph['overhead']:.1%}"
    )


def _print_diff(rows) -> None:
    moved = [r for r in rows if not r["same"]]
    for r in moved:
        lat = ""
        if r["delta_p50_bucket"] is not None:
            lat = (
                f", p50 bucket {r['p50_bucket_a']} -> "
                f"{r['p50_bucket_b']}"
            )
        print(
            f"rank {r['rank']} {r['dir']} "
            f"{'->' if r['dir'] == 'send' else '<-'} peer {r['peer']} "
            f"{r['tag_name']}: msgs {r['msgs_a']} -> {r['msgs_b']} "
            f"({r['delta_msgs']:+d}), bytes {r['bytes_a']} -> "
            f"{r['bytes_b']} ({r['delta_bytes']:+d}){lat}"
        )
    print(
        f"{len(moved)} stream(s) changed, "
        f"{len(rows) - len(moved)} unchanged"
    )


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mpit_tpu.obs", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    mp = sub.add_parser("merge", help="journals -> Chrome-trace JSON")
    mp.add_argument("paths", nargs="+",
                    help="run dir (MPIT_OBS_DIR) or journal files")
    mp.add_argument("-o", "--out", default=None,
                    help="output file (default: <first dir>/trace.json)")
    mp.add_argument("--faults", default=None,
                    help="chaos fault log JSONL (or a directory of "
                         "faults*.jsonl, process mode) to overlay "
                         "(default: <run dir>/faults*.jsonl when present)")

    sp = sub.add_parser("summary", help="per-rank event tallies")
    sp.add_argument("paths", nargs="+")
    sp.add_argument(
        "--diff",
        action="store_true",
        help="compare exactly two runs stream-by-stream (per-(peer, tag) "
        "counters + median latency bucket)",
    )

    rp = sub.add_parser(
        "roofline",
        help="per-rank compute/wire/idle/overhead attribution",
    )
    rp.add_argument("paths", nargs="+",
                    help="run dir (MPIT_OBS_DIR) or journal files")
    rp.add_argument("--json", action="store_true",
                    help="emit the full report as JSON instead of a table")

    lp = sub.add_parser(
        "slo",
        help="serving scorecard: TTFT/TPOT/e2e percentiles, goodput",
    )
    lp.add_argument("paths", nargs="+",
                    help="run dir (the server's ObsConfig.dir) or "
                         "journal files")
    lp.add_argument("--gate", default=None,
                    help="JSON gate file of ceilings/floors (e.g. "
                         '{"ttft_p99_ms": 250, "goodput_min": 0.95}); '
                         "violations exit 1")
    lp.add_argument("--json", action="store_true",
                    help="emit the report (plus any violations) as JSON")
    lp.add_argument("--default-slo-ms", type=float, default=None,
                    help="e2e SLO applied to requests submitted without "
                         "one (default: such requests meet vacuously)")

    ns = p.parse_args(argv)

    if ns.cmd == "summary" and ns.diff:
        if len(ns.paths) != 2:
            print("summary --diff takes exactly two run dirs",
                  file=sys.stderr)
            return 2
        a, b = ns.paths
        if not expand_journal_paths([a]) or not expand_journal_paths([b]):
            print(f"no obs_rank*.jsonl journals under {a} or {b}",
                  file=sys.stderr)
            return 2
        _print_diff(diff_summaries([a], [b]))
        return 0

    journals = expand_journal_paths(ns.paths)
    if not journals:
        print(f"no obs_rank*.jsonl journals under {ns.paths}",
              file=sys.stderr)
        return 2

    if ns.cmd == "slo":
        from mpit_tpu.loadgen.slo import (
            aggregate_paths, evaluate_gate, format_report, load_gate,
        )

        report = aggregate_paths(
            journals, default_slo_ms=ns.default_slo_ms
        )
        if report["requests"]["submitted"] == 0:
            print("journals carry no request lifecycle events "
                  "(serve with obs=ObsConfig(dir=...))", file=sys.stderr)
            return 2
        violations = []
        if ns.gate is not None:
            try:
                gate = load_gate(ns.gate)
            except (OSError, ValueError) as e:
                print(f"bad gate file {ns.gate}: {e}", file=sys.stderr)
                return 2
            violations = evaluate_gate(report, gate)
        if ns.json:
            json.dump({**report, "violations": violations}, sys.stdout)
            print()
        else:
            print(format_report(report))
            for v in violations:
                print(f"SLO VIOLATION: {v}")
        if violations:
            return 1
        return 0

    if ns.cmd == "roofline":
        report = roofline(journals)
        if report["run"] is None:
            print("journals carry no timed events", file=sys.stderr)
            return 2
        if ns.json:
            json.dump(report, sys.stdout, indent=2, default=str)
            print()
        else:
            _print_roofline(report)
        return 0

    if ns.cmd == "summary":
        for rank, row in summarize(journals).items():
            print(
                f"rank {rank}: {row['events']} events "
                f"({row['sends']} sends / {row['recvs']} recvs, "
                f"{row['bytes']} bytes, {row['traces']} traces)"
            )
        return 0

    first_dir = next((q for q in ns.paths if os.path.isdir(q)), None)
    faults = ns.faults
    if faults is None and first_dir is not None:
        candidate = os.path.join(first_dir, "faults.jsonl")
        if os.path.exists(candidate):
            faults = candidate
        elif glob.glob(os.path.join(first_dir, "faults*.jsonl")):
            # process-mode runs write one fault log per rank; the dir
            # form hands all of them to read_fault_log
            faults = first_dir
    out_path = ns.out or os.path.join(first_dir or ".", "trace.json")

    trace = merge_to_chrome_trace(journals, faults_path=faults)
    with open(out_path, "w") as f:
        json.dump(trace, f)

    by_rank = trace_ids_by_rank(journals)
    all_traces = set().union(*by_rank.values()) if by_rank else set()
    cross = sum(
        1 for t in all_traces
        if sum(1 for ids in by_rank.values() if t in ids) >= 2
    )
    n_faults = sum(1 for e in trace["traceEvents"] if e.get("cat") == "chaos")
    print(
        f"wrote {out_path}: {len(trace['traceEvents'])} events from "
        f"{len(by_rank) or len(journals)} rank(s), {len(all_traces)} "
        f"trace(s) ({cross} cross-rank), {n_faults} fault marker(s) — "
        "open in https://ui.perfetto.dev"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
