"""Cross-rank post-mortem over black-box flight-recorder dumps.

``python -m mpit_tpu.obs postmortem <dir>`` assembles the incident
report a human would otherwise stitch together by hand from N per-rank
dump files:

- **aligns** the per-rank dump windows on a shared timeline (relative
  wall offsets from the earliest dumped record) and cross-checks the
  alignment with Lamport clocks — every traced ``recv`` carries the
  sender's clock (``rclk``), which pairs it with the send record
  bearing the same stamp;
- **names the first-mover**: who stalled or died first. Membership
  events (``launch.py`` records the kill signal / child exit code) are
  the primary citation; absent those, the dead-rank staleness idea from
  the alert engine is applied *retrospectively* — each rank's
  "last heard from" is the freshest record it dumped OR any other rank
  received from it, and the rank that went silent earliest (relative to
  the freshest rank, beyond the median-gap threshold) is named;
- **reconstructs the last K exchange rounds** per client: each PUSH
  send (stream index ``n``) is joined against the server dumps' recvs
  of the same stream — acked vs dropped — and overlaid with the
  staleness the server measured for that client, the client's own
  elastic distance / norm-ratio dynamics, and the wire phase split
  (serialize / queue-wait / write) of each push;
- **overlays** chaos faults (dump-embedded fault schedules and
  ``faults*.jsonl``), live-plane alerts, and membership churn.

Exit codes: 0 clean, 1 incident found, 2 no dumps. ``--json`` emits the
full report; ``--perfetto`` additionally writes an incident-window
Chrome trace of the dumps via :mod:`mpit_tpu.obs.merge`.

``<dir>`` is the run dir (``MPIT_OBS_DIR``) — dumps are read from its
``blackbox/`` subdir, or from ``<dir>`` itself when it directly holds
``rank_*.jsonl`` (the golden-fixture layout). Stdlib-only, like every
reader in this package.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Optional

from mpit_tpu.obs.merge import TAG_NAMES, read_fault_log

#: PUSH streams (client -> server parameter updates) — the exchange
#: rounds the report reconstructs
_PUSH_TAGS = (2, 3)
#: staleness threshold for the retrospective first-mover call: a rank
#: is "gone" when its silence exceeds this multiple of the median
#: cross-rank record gap (mirrors AlertConfig.staleness_factor)
_SILENCE_FACTOR = 3.0
_SILENCE_FLOOR_S = 0.05


def _read_jsonl(path: str) -> list:
    out = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail of a killed writer
    except OSError:
        pass
    return out


def dump_paths(path: str) -> list:
    """The dump files for a run dir: ``<dir>/blackbox/rank_*.jsonl``
    (current + per-generation archives), or ``<dir>/rank_*.jsonl`` when
    the dir itself is a dump dir."""
    for d in (os.path.join(path, "blackbox"), path):
        found = sorted(glob.glob(os.path.join(d, "rank_*.jsonl")))
        if found:
            return found
    return []


def load_dumps(path: str) -> dict:
    """Parse dump files into per-(rank, gen) streams. Each file holds
    one or more segments (``ev: "blackbox"`` header, then records);
    overlapping segments (an incident dump followed by the close dump
    of the same window) are deduplicated on (clk, ev, t)."""
    ranks: dict = {}
    for p in dump_paths(path):
        for rec in _read_jsonl(p):
            rank = rec.get("rank")
            if rank is None:
                continue
            if rec.get("ev") == "blackbox":
                key = (rank, rec.get("gen", 0))
                slot = ranks.setdefault(
                    key, {"headers": [], "records": [], "_seen": set()}
                )
                slot["headers"].append(rec)
                continue
            gen = rec.get("gen", None)
            # records don't carry gen; attach to the rank's latest
            # opened segment (dump files are written header-first)
            key = None
            for k in ranks:
                if k[0] == rank and (gen is None or k[1] == gen):
                    key = k
            if key is None:
                key = (rank, 0)
                ranks[key] = {"headers": [], "records": [], "_seen": set()}
            slot = ranks[key]
            sig = (rec.get("step"), rec.get("ev"), rec.get("t"))
            if sig in slot["_seen"]:
                continue
            slot["_seen"].add(sig)
            slot["records"].append(rec)
    for slot in ranks.values():
        slot.pop("_seen")
        slot["records"].sort(key=lambda r: (r.get("t") or 0.0))
    return ranks


def _membership(path: str) -> list:
    """Supervisor membership transitions (``ev: "membership"``, with the
    transition in ``kind``: spawn/kill/exit/respawn/done). ``t`` is
    monotonic-relative (ordering within the file); ``wt`` is the wall
    clock stamp that joins the dump timeline."""
    return [
        r for r in _read_jsonl(os.path.join(path, "membership.jsonl"))
        if r.get("ev") == "membership"
    ]


def _alerts(path: str) -> list:
    for cand in (
        os.path.join(path, "live", "alerts.jsonl"),
        os.path.join(path, "alerts.jsonl"),
    ):
        recs = _read_jsonl(cand)
        if recs:
            return [r for r in recs if r.get("ev") == "alert"]
    return []


def _median(vals: list) -> float:
    s = sorted(vals)
    return s[len(s) // 2] if s else 0.0


def _last_heard(ranks: dict) -> dict:
    """rank -> latest wall-clock anyone (itself included) has evidence
    of it being alive: its own dumped records, plus recvs FROM it in
    other ranks' dumps."""
    heard: dict = {}

    def _note(rank, t):
        if rank is None or t is None:
            return
        if rank not in heard or t > heard[rank]:
            heard[rank] = t

    for (rank, _gen), slot in ranks.items():
        for rec in slot["records"]:
            _note(rank, rec.get("t"))
            if rec.get("ev") == "recv":
                _note(rec.get("src"), rec.get("t"))
    return heard


def _first_mover(ranks: dict, membership: list, alerts: list) -> dict:
    """Name who moved first, best evidence wins: a supervisor-recorded
    kill/abnormal-exit, else the earliest dead_rank alert, else the
    retrospective staleness call over the dumps."""
    churn = [
        m for m in membership
        if m.get("kind") in ("kill", "leave")
        or (m.get("kind") == "exit" and m.get("code", 0) != 0)
    ]
    if churn:
        first = min(churn, key=lambda m: m.get("t", 0.0))
        why = f"membership: {first['kind']}"
        if first.get("signal"):
            why += f" by {first['signal']}"
        if first.get("code") is not None:
            why += f" (exit code {first['code']})"
        return {
            "rank": first.get("rank"),
            "gen": first.get("gen"),
            "source": "membership",
            "why": why,
            "event": first,
        }
    dead = [a for a in alerts if a.get("kind") == "dead_rank"]
    if dead:
        first = min(dead, key=lambda a: a.get("t", 0.0))
        return {
            "rank": first.get("rank"),
            "source": "alert",
            "why": "earliest dead_rank alert",
            "event": first,
        }
    heard = _last_heard(ranks)
    if len(heard) < 2:
        return {"rank": None, "source": None, "why": "no cross-rank evidence"}
    now = max(heard.values())
    # threshold from the observed record cadence, alert-engine style
    ts = sorted(
        t for slot in ranks.values() for t in
        (r.get("t") for r in slot["records"]) if t is not None
    )
    gaps = [b - a for a, b in zip(ts, ts[1:]) if b > a]
    limit = max(_SILENCE_FLOOR_S, _SILENCE_FACTOR * _median(gaps))
    rank, t = min(heard.items(), key=lambda kv: kv[1])
    silence = now - t
    if silence <= limit:
        return {
            "rank": None, "source": None,
            "why": f"no rank silent beyond {limit:.3f}s",
        }
    return {
        "rank": rank,
        "source": "staleness",
        "why": (
            f"silent {silence:.3f}s before the freshest rank "
            f"(threshold {limit:.3f}s)"
        ),
        "silence_s": round(silence, 3),
        "threshold_s": round(limit, 3),
    }


def _exchange_rounds(ranks: dict, k: int) -> dict:
    """Per client rank: the last ``k`` PUSH rounds, each send joined
    (by per-stream index ``n``) against the destination server's
    dumped recvs — acked / dropped / unknown (no server dump)."""
    # (src, dst, tag) -> set of n the server actually received
    acked: dict = {}
    server_dumped: set = set()
    # (src, server) -> that server's recv records from src, in order —
    # the ONLY surviving view of a SIGKILLed client's final pushes
    recv_view: dict = {}
    # (src, server) -> server-side staleness sequence for that client
    staleness: dict = {}
    for (rank, _gen), slot in ranks.items():
        for rec in slot["records"]:
            ev = rec.get("ev")
            if ev == "recv" and rec.get("mtag") in _PUSH_TAGS:
                server_dumped.add(rank)
                key = (rec.get("src"), rank, rec.get("mtag"))
                acked.setdefault(key, set()).add(rec.get("n"))
                recv_view.setdefault(
                    (rec.get("src"), rank), []
                ).append(rec)
            elif ev == "push_stale":
                staleness.setdefault(
                    (rec.get("src"), rank), []
                ).append({
                    "t": rec.get("t"),
                    "staleness": rec.get("staleness"),
                    "version": rec.get("version"),
                    "epoch": rec.get("epoch"),
                })
    out: dict = {}
    for (rank, gen), slot in ranks.items():
        pushes = []
        dyn = []
        for rec in slot["records"]:
            ev = rec.get("ev")
            if ev in ("send", "isend") and rec.get("mtag") in _PUSH_TAGS:
                dst = rec.get("dst")
                n = rec.get("n")
                row = {
                    "n": n,
                    "dst": dst,
                    "tag": TAG_NAMES.get(rec.get("mtag"), rec.get("mtag")),
                    "t": rec.get("t"),
                    "clk": rec.get("step"),
                    "bytes": rec.get("bytes"),
                    "dur_ms": (
                        round(rec["dur"] * 1e3, 3)
                        if rec.get("dur") is not None else None
                    ),
                }
                phases = {
                    key: round(rec[f] * 1e3, 3)
                    for key, f in (
                        ("ser_ms", "ser"), ("qw_ms", "qw"), ("wr_ms", "wr"),
                    ) if rec.get(f) is not None
                }
                if phases:
                    row["phases"] = phases
                if dst in server_dumped:
                    row["acked"] = (
                        n in acked.get((rank, dst, rec.get("mtag")), set())
                    )
                else:
                    row["acked"] = None  # server window not captured
                pushes.append(row)
            elif ev == "dynamics":
                dyn.append({
                    "round": rec.get("round"),
                    "t": rec.get("t"),
                    "elastic": rec.get("elastic"),
                    "ratio": rec.get("ratio"),
                    "push_norm": rec.get("push_norm"),
                })
        if not pushes:
            continue
        pushes = pushes[-k:]
        entry: dict = {"gen": gen, "pushes": pushes}
        if dyn:
            entry["dynamics"] = dyn[-k:]
        seen = {
            str(server): seq[-k:]
            for (src, server), seq in staleness.items() if src == rank
        }
        if seen:
            entry["staleness_at_server"] = seen
        out.setdefault(str(rank), entry)
    # a SIGKILLed client leaves no dump of its own — reconstruct its
    # final rounds from the SURVIVING servers' recv windows (received
    # by definition; what it sent-but-lost died with it)
    dumped = {rank for (rank, _gen) in ranks}
    for (src, server), recs in sorted(recv_view.items(), key=str):
        if src is None or src in dumped:
            continue
        entry = out.setdefault(
            str(src), {"gen": None, "view": "server", "pushes": []}
        )
        if entry.get("view") != "server":
            continue
        for rec in recs:
            entry["pushes"].append({
                "n": rec.get("n"),
                "dst": server,
                "tag": TAG_NAMES.get(rec.get("mtag"), rec.get("mtag")),
                "t": rec.get("t"),
                "clk": rec.get("rclk"),
                "bytes": rec.get("bytes"),
                "dur_ms": None,
                "acked": True,
            })
        seen = {
            str(sv): seq[-k:]
            for (s, sv), seq in staleness.items() if s == src
        }
        if seen:
            entry["staleness_at_server"] = seen
    for entry in out.values():
        if entry.get("view") == "server":
            entry["pushes"].sort(key=lambda p: (p["t"] or 0.0))
            entry["pushes"] = entry["pushes"][-k:]
    return out


def analyze(path: str, k_rounds: int = 5) -> Optional[dict]:
    """Build the full post-mortem report for a run dir; None when no
    dump records exist (exit 2 at the CLI)."""
    ranks = load_dumps(path)
    if not any(slot["records"] for slot in ranks.values()):
        return None
    membership = _membership(path)
    alerts = _alerts(path)
    all_ts = [
        r["t"] for slot in ranks.values()
        for r in slot["records"] if r.get("t") is not None
    ]
    t0 = min(all_ts)

    windows: dict = {}
    for (rank, gen), slot in sorted(ranks.items()):
        recs = slot["records"]
        hdr = slot["headers"][-1] if slot["headers"] else {}
        triggers = sorted({
            h.get("trigger") for h in slot["headers"] if h.get("trigger")
        })
        incidents = sorted({
            h["incident"] for h in slot["headers"] if h.get("incident")
        })
        win = {
            "gen": gen,
            "records": len(recs),
            "evicted": hdr.get("evicted", 0),
            "triggers": triggers,
            "window_s": [
                round(recs[0]["t"] - t0, 3),
                round(recs[-1]["t"] - t0, 3),
            ] if recs else None,
            "last_clk": max(
                (r.get("step", 0) for r in recs), default=0
            ),
        }
        if incidents:
            win["incidents"] = incidents
        slo_misses = sum(
            1 for r in recs
            if r.get("ev") == "req_finish" and r.get("slo_miss")
        )
        if slo_misses:
            win["slo_misses"] = slo_misses
        windows[str(rank)] = win

    heard = _last_heard(ranks)
    mover = _first_mover(ranks, membership, alerts)
    exchanges = _exchange_rounds(ranks, k_rounds)

    # clock alignment check: recv records pair with their send via the
    # sender's Lamport stamp; the wall offset of each pair bounds the
    # cross-rank clock skew (one machine → ~µs; it is evidence either way)
    sends: dict = {}
    for (rank, _gen), slot in ranks.items():
        for r in slot["records"]:
            if r.get("ev") in ("send", "isend"):
                sends[(rank, r.get("step"))] = r.get("t")
    skews = []
    for (rank, _gen), slot in ranks.items():
        for r in slot["records"]:
            if r.get("ev") == "recv" and r.get("rclk") is not None:
                st = sends.get((r.get("src"), r.get("rclk")))
                if st is not None and r.get("t") is not None:
                    skews.append(r["t"] - st)
    clock = {
        "paired_messages": len(skews),
        "skew_median_ms": (
            round(_median(skews) * 1e3, 3) if skews else None
        ),
    }

    churn = [
        m for m in membership
        if m.get("kind") in ("kill", "exit", "respawn", "leave", "join")
    ]
    faults = [
        r for slot in ranks.values() for r in slot["records"]
        if r.get("x_source") == "faults" or r.get("ev") == "fault"
    ]
    if not faults:
        faults = read_fault_log(path) or []
    dropped = sum(
        1 for entry in exchanges.values()
        for p in entry["pushes"] if p.get("acked") is False
    )

    findings = []
    if mover.get("rank") is not None:
        findings.append(
            f"first-mover: rank {mover['rank']} ({mover['why']})"
        )
    if dropped:
        findings.append(
            f"{dropped} push(es) sent but never received by a dumped "
            "server window"
        )
    for a in alerts:
        findings.append(f"alert {a.get('kind')} on rank {a.get('rank')}")
    for m in churn:
        if m.get("kind") in ("kill", "exit", "leave"):
            note = f"membership: rank {m.get('rank')} {m['kind']}"
            if m.get("signal"):
                note += f" ({m['signal']})"
            if m.get("kind") == "exit" and m.get("code") is not None:
                note += f" code {m['code']}"
            findings.append(note)
    if faults:
        findings.append(f"{len(faults)} chaos fault(s) in the window")

    incident = bool(
        mover.get("rank") is not None
        or dropped
        or alerts
        or any(
            m.get("kind") in ("kill", "leave")
            or (m.get("kind") == "exit" and m.get("code", 0) != 0)
            for m in churn
        )
    )
    return {
        "dir": path,
        "t0": t0,
        "verdict": "incident" if incident else "clean",
        "ranks": windows,
        "last_heard_s": {
            str(r): round(t - t0, 3) for r, t in sorted(heard.items())
        },
        "first_mover": mover,
        "exchanges": exchanges,
        "clock": clock,
        "membership": churn,
        "alerts": alerts,
        "faults_n": len(faults),
        "findings": findings,
    }


def format_report(report: dict) -> str:
    """The human rendering (the --json shape is the report itself)."""
    lines = []
    verdict = report["verdict"].upper()
    mover = report["first_mover"]
    lines.append(
        f"post-mortem: {verdict} — {len(report['ranks'])} dumped "
        f"window(s) under {report['dir']}"
    )
    if mover.get("rank") is not None:
        lines.append(f"first-mover: rank {mover['rank']} — {mover['why']}")
    else:
        lines.append(f"first-mover: none ({mover['why']})")
    lines.append(f"{'rank':>4} {'gen':>3} {'recs':>5} {'evict':>5} "
                 f"{'window (rel s)':>16} {'clk':>6}  triggers")
    for rank, w in sorted(report["ranks"].items(), key=lambda kv: kv[0]):
        win = (
            f"{w['window_s'][0]:.3f}..{w['window_s'][1]:.3f}"
            if w.get("window_s") else "-"
        )
        lines.append(
            f"{rank:>4} {w['gen']:>3} {w['records']:>5} "
            f"{w['evicted']:>5} {win:>16} {w['last_clk']:>6}  "
            + ",".join(w["triggers"] or ["-"])
        )
    for rank, entry in sorted(report["exchanges"].items()):
        via = (
            " (server view — its own window died with it)"
            if entry.get("view") == "server" else ""
        )
        lines.append(f"rank {rank} — last {len(entry['pushes'])} "
                     f"push round(s){via}:")
        for p in entry["pushes"]:
            ack = {True: "acked", False: "DROPPED", None: "unknown"}[
                p["acked"]
            ]
            ph = p.get("phases")
            phs = (
                " ser/qw/wr "
                + "/".join(
                    str(ph.get(k, "-"))
                    for k in ("ser_ms", "qw_ms", "wr_ms")
                ) + "ms"
                if ph else ""
            )
            dur = f"{p['dur_ms']}ms" if p.get("dur_ms") is not None else "-"
            lines.append(
                f"   n={p['n']} -> rank {p['dst']} {p['tag']} "
                f"{p['bytes']}B {dur} {ack}{phs}"
            )
        for server, seq in sorted(
            entry.get("staleness_at_server", {}).items()
        ):
            vals = ",".join(str(s["staleness"]) for s in seq)
            lines.append(
                f"   staleness at server {server}: [{vals}] "
                f"(version {seq[-1]['version']})"
            )
        dyn = entry.get("dynamics")
        if dyn:
            d = dyn[-1]
            lines.append(
                f"   dynamics @round {d['round']}: elastic "
                f"{d['elastic']} ratio {d['ratio']}"
            )
    clock = report["clock"]
    if clock["paired_messages"]:
        lines.append(
            f"clock: {clock['paired_messages']} send/recv pair(s) "
            f"aligned via Lamport stamps, median wall skew "
            f"{clock['skew_median_ms']}ms"
        )
    for f in report["findings"]:
        lines.append(f"finding: {f}")
    return "\n".join(lines)
