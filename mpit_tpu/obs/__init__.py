"""mpit_tpu.obs — distributed tracing + wire telemetry for the PS protocol.

The third subsystem next to ``analysis`` (static/runtime correctness) and
``transport.chaos`` (fault injection): cross-rank trace/span context
propagated through the transport (docs/OBSERVABILITY.md), per-(peer, tag)
wire telemetry, per-rank JSONL event journals, and a merger CLI
(``python -m mpit_tpu.obs``) that joins them — optionally overlaying a
chaos FaultLog — into one Perfetto timeline.

Activation: ``AsyncPSTrainer(obs=ObsConfig(...))`` in code, or any
``MPIT_OBS_*`` env knob for launcher-driven runs (no code changes).

The live plane (``MPIT_OBS_LIVE=1`` / ``ObsConfig(live=True)``) adds
in-run snapshots: a per-rank :class:`~mpit_tpu.obs.live.MetricsRegistry`
exported atomically to ``<dir>/live/rank_<r>.json``, aggregated by
``python -m mpit_tpu.obs live <dir>`` into a dashboard with online
health alerts (:mod:`mpit_tpu.obs.alerts`).

The dynamics plane (:mod:`mpit_tpu.obs.dynamics`) reduces the same
journals to update-quality evidence — per-source push staleness,
per-client elastic-distance trajectories with a divergence verdict,
update/param norm ratios — via ``python -m mpit_tpu.obs dynamics
<dir> [--gate dynamics.json]``.

The black box (:mod:`mpit_tpu.obs.blackbox`, on by default whenever a
journal dir is armed) keeps a bounded ring of each rank's last records
and dumps it to ``<dir>/blackbox/rank_<r>.jsonl`` on SIGTERM/atexit/
alert/supervisor request; ``python -m mpit_tpu.obs postmortem <dir>``
(:mod:`mpit_tpu.obs.postmortem`) assembles the dumps into a cross-rank
incident report — first-mover, final exchange rounds acked/dropped,
staleness/elastic/wire-phase overlays.
"""

from mpit_tpu.obs.alerts import (  # noqa: F401
    AlertConfig,
    AlertEngine,
    read_alerts,
)
from mpit_tpu.obs.blackbox import (  # noqa: F401
    BlackBox,
    arm_process_triggers,
    box_for,
    request_dump,
)
from mpit_tpu.obs.core import (  # noqa: F401
    Journal,
    LogicalClock,
    NULL_SPAN,
    ObsConfig,
    SpanContext,
    Tracer,
    arm_faulthandler,
    config_from_env,
    disarm_faulthandler,
    span,
    write_fault_log,
)
from mpit_tpu.obs.dynamics import (  # noqa: F401
    aggregate_dynamics,
    check_dynamics_gate,
    diverging,
    load_gate,
)
from mpit_tpu.obs.live import (  # noqa: F401
    LiveExporter,
    MetricsRegistry,
    NULL_REGISTRY,
    aggregate,
    live_registry,
    read_snapshots,
    validate_snapshot,
)
from mpit_tpu.obs.merge import (  # noqa: F401
    diff_summaries,
    merge_to_chrome_trace,
    read_journal,
    roofline,
    summarize,
    trace_ids_by_rank,
)
from mpit_tpu.obs.postmortem import (  # noqa: F401
    analyze as analyze_postmortem,
    format_report as format_postmortem,
    load_dumps,
)
from mpit_tpu.obs.telemetry import (  # noqa: F401
    TelemetryTransport,
    maybe_wrap,
    wrap_from_env,
    wrap_obs_transports,
)
