"""Merge per-rank obs journals into one Chrome-trace/Perfetto timeline.

Input: the ``obs_rank<r>.jsonl`` journals that
:class:`~mpit_tpu.obs.telemetry.TelemetryTransport` writes, optionally plus
a chaos fault log persisted by :func:`mpit_tpu.obs.core.write_fault_log`.
Output: the Chrome Trace Event JSON object format (``{"traceEvents":
[...]}``), which https://ui.perfetto.dev opens directly.

Rendering:

- each transport rank is one Perfetto *process* track (``pid`` = rank);
- ``send``/``isend`` and ``recv`` become complete (``ph: "X"``) slices —
  a send's duration is its time in the transport call, a recv's slice
  spans the receiver's blocked wait;
- every traced send emits a *flow* (``ph: "s"`` → ``ph: "f"``, id = the
  send's span id) that Perfetto draws as an arrow from the send slice to
  the matching recv slice on the destination rank — the cross-rank trace
  made visible;
- ``span_b``/``span_e`` regions (the trainer's per-exchange spans) become
  nested B/E slices on their rank's track;
- chaos faults become instant events (``ph: "i"``) on the track of the
  rank that suffered them (the sending rank — every injected fault is
  sender-side, docs/ROBUSTNESS.md). FaultEvents deliberately carry no
  timestamp (replay-comparability), so placement joins the fault's
  ``(src, dst, tag, n)`` stream coordinates against the telemetry send
  events, whose stream index is in lockstep with the chaos schedule's;
- serving journals (``models/serving.py`` under load, docs/SERVING.md)
  add two thread tracks: ``tid 1`` holds the scheduler's ``prefill``/
  ``segment`` work slices (events carry end time + ``dur``, like recv)
  and ``serve_fault`` instants, ``tid 2`` holds one async span per
  request (``ph: "b"/"n"/"e"``, id = rid) from enqueue through admit /
  first token to finish or cancel — queueing time visible per request;
- training-dynamics records (docs/OBSERVABILITY.md "dynamics") become
  Perfetto counter tracks (``ph: "C"``): an ``elastic_dist`` lane per
  client rank and one ``staleness src <r>`` lane per pushing client on
  each server rank — update quality rendered on the same timeline as
  the wire traffic that caused it.

This module reads only files — it must import neither jax nor the
transport stack, so the CLI stays fast and safe to run anywhere.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Iterable, Optional

# protocol tag names for display; sources of truth are
# mpit_tpu/parallel/pserver.py (1-10) and mpit_tpu/fleet/replica.py
# (11-15) — kept literal here so the merger imports nothing heavier
# than the standard library
TAG_NAMES = {
    1: "FETCH",
    2: "PUSH_EASGD",
    3: "PUSH_DELTA",
    4: "PARAM",
    5: "STOP",
    6: "HEARTBEAT",
    7: "JOIN",
    8: "LEAVE",
    9: "SHARD_MAP",
    10: "RESHARD",
    11: "ROUTE",
    12: "REPLY",
    13: "WEIGHT_SUB",
    14: "WEIGHT_PUSH",
    15: "FLEET_STOP",
}


def _tag_name(tag) -> str:
    return TAG_NAMES.get(tag, str(tag))


def read_journal(path: str) -> list[dict]:
    """Records of one JSONL journal (malformed lines are skipped — a
    journal truncated by a killed rank must not sink the whole merge;
    a directory — e.g. the ``blackbox/`` or ``live/`` subdir a listing
    of the run dir sweeps up — reads as empty)."""
    out = []
    if os.path.isdir(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def expand_journal_paths(paths: Iterable[str]) -> list[str]:
    """Each path may be a journal file or a directory of
    ``obs_rank*.jsonl``; returns the flat sorted file list."""
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "obs_rank*.jsonl"))))
        else:
            out.append(p)
    return out


def read_fault_log(path: str) -> list[dict]:
    """Fault records from one JSONL file, or from every ``faults*.jsonl``
    in a directory (process-mode runs write one fault log per rank —
    faults are recorded sender-side, so the per-rank union is the whole
    schedule)."""
    if os.path.isdir(path):
        paths = sorted(glob.glob(os.path.join(path, "faults*.jsonl")))
    else:
        paths = [path]
    return [
        r for p in paths for r in read_journal(p) if r.get("ev") == "fault"
    ]


def _rec_rank(rec: dict):
    return rec.get("rank", rec.get("process", 0))


def _rec_time(rec: dict) -> Optional[float]:
    # precise "t" preferred; "ts" (1 ms resolution) is the fallback for
    # hand-written or foreign MetricsLogger streams
    return rec.get("t", rec.get("ts"))


def merge_to_chrome_trace(
    journal_paths: Iterable[str],
    faults_path: Optional[str] = None,
    alerts_path: Optional[str] = None,
) -> dict:
    """Chrome-trace JSON object from per-rank journals (+ optional chaos
    fault log and live-plane ``alerts.jsonl``). Wall-clock timestamps
    are rebased to the earliest event; events within a rank keep journal
    order (monotonic per rank by the Journal's construction)."""
    journal_paths = expand_journal_paths(journal_paths)
    per_rank: dict[int, list[dict]] = {}
    for path in journal_paths:
        for rec in read_journal(path):
            if _rec_time(rec) is None or "ev" not in rec:
                continue
            per_rank.setdefault(_rec_rank(rec), []).append(rec)

    t0 = min(
        (_rec_time(r) for recs in per_rank.values() for r in recs),
        default=0.0,
    )

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 1)

    events: list[dict] = []
    # (src_rank, dst, tag, n) -> send timestamp in µs, the fault join key
    send_index: dict[tuple, float] = {}

    for rank in sorted(per_rank):
        events.append({
            "ph": "M", "name": "process_name", "pid": rank, "tid": 0,
            "args": {"name": f"rank {rank}"},
        })
        for rec in per_rank[rank]:
            t = _rec_time(rec)
            ev = rec["ev"]
            if ev in ("send", "isend"):
                ts = us(t)
                dur = max(rec.get("dur", 0.0) * 1e6, 1.0)
                args = {
                    k: rec[k]
                    for k in ("dst", "n", "bytes", "qdepth", "err", "trace")
                    if k in rec
                }
                args["clk"] = rec.get("step")
                name = f"{ev} {_tag_name(rec.get('mtag'))}"
                events.append({
                    "ph": "X", "name": name, "cat": "wire",
                    "pid": rank, "tid": 0, "ts": ts, "dur": dur,
                    "args": args,
                })
                if "span" in rec:
                    events.append({
                        "ph": "s", "id": f"{rec['span']:x}", "name": "msg",
                        "cat": "flow", "pid": rank, "tid": 0, "ts": ts,
                    })
                key = (rank, rec.get("dst"), rec.get("mtag"), rec.get("n"))
                send_index.setdefault(key, ts)
            elif ev == "recv":
                wait = rec.get("wait", 0.0)
                end = us(t)
                ts = max(us(t - wait), 0.0)
                args = {
                    k: rec[k]
                    for k in ("src", "n", "bytes", "trace")
                    if k in rec
                }
                args["clk"] = rec.get("step")
                events.append({
                    "ph": "X",
                    "name": f"recv {_tag_name(rec.get('mtag'))}",
                    "cat": "wire", "pid": rank, "tid": 0, "ts": ts,
                    "dur": max(end - ts, 1.0), "args": args,
                })
                if "from_span" in rec:
                    # bind to the enclosing recv slice: arrow head lands
                    # where the wait ended
                    events.append({
                        "ph": "f", "bp": "e", "id": f"{rec['from_span']:x}",
                        "name": "msg", "cat": "flow", "pid": rank,
                        "tid": 0, "ts": end,
                    })
            elif ev == "span_b":
                events.append({
                    "ph": "B", "name": str(rec.get("name", "span")),
                    "cat": "span", "pid": rank, "tid": 0, "ts": us(t),
                    "args": {
                        k: rec[k]
                        for k in ("trace", "span", "parent", "step")
                        if k in rec
                    },
                })
            elif ev == "span_e":
                events.append({
                    "ph": "E", "name": str(rec.get("name", "span")),
                    "cat": "span", "pid": rank, "tid": 0, "ts": us(t),
                })
            elif ev in ("prefill", "segment"):
                # serving work slices: t is stamped at END of the
                # operation, dur carries its extent (the recv idiom)
                dur = max(rec.get("dur", 0.0) * 1e6, 1.0)
                ts = max(us(t) - dur, 0.0)
                if ev == "prefill":
                    name = f"prefill x{rec.get('k', '?')}"
                    keys = ("k", "bucket")
                else:
                    name = (
                        "spec segment" if rec.get("spec") else "segment"
                    )
                    keys = ("seg", "occupied", "nslots", "waiting")
                events.append({
                    "ph": "X", "name": name, "cat": "serve",
                    "pid": rank, "tid": 1, "ts": ts, "dur": dur,
                    "args": {k: rec[k] for k in keys if k in rec},
                })
            elif ev == "req_enqueue":
                # request lifecycles as async spans keyed by rid: one
                # lane per in-flight request in Perfetto, enqueue ->
                # admit -> first token -> finish/cancel
                rid = rec.get("rid")
                events.append({
                    "ph": "b", "name": f"req {rid}", "cat": "request",
                    "id": str(rid), "pid": rank, "tid": 2, "ts": us(t),
                    "args": {
                        k: rec[k]
                        for k in ("p_len", "max_new", "slo_ms")
                        if k in rec
                    },
                })
            elif ev in ("req_admit", "req_first_token"):
                rid = rec.get("rid")
                events.append({
                    "ph": "n", "name": ev[4:], "cat": "request",
                    "id": str(rid), "pid": rank, "tid": 2, "ts": us(t),
                    "args": (
                        {"slot": rec["slot"]} if "slot" in rec else {}
                    ),
                })
            elif ev in ("req_finish", "req_cancel"):
                rid = rec.get("rid")
                events.append({
                    "ph": "e", "name": f"req {rid}", "cat": "request",
                    "id": str(rid), "pid": rank, "tid": 2, "ts": us(t),
                    "args": {
                        k: rec[k]
                        for k in ("reason", "gen", "where")
                        if k in rec
                    },
                })
            elif ev == "serve_fault":
                events.append({
                    "ph": "i", "s": "p",
                    "name": f"fault {rec.get('kind', '?')}",
                    "cat": "chaos", "pid": rank, "tid": 1, "ts": us(t),
                    "args": {
                        k: rec[k]
                        for k in ("boundary", "delay")
                        if k in rec
                    },
                })
            elif ev == "dynamics":
                # training-dynamics counter track (per client rank):
                # Perfetto renders ph "C" as a value-over-time lane, so
                # the elastic distance ‖x_local − x̃‖ trajectory sits
                # directly under the rank's wire/span slices
                events.append({
                    "ph": "C", "name": "elastic_dist", "cat": "dynamics",
                    "pid": rank, "tid": 0, "ts": us(t),
                    "args": {"value": rec.get("elastic", 0.0)},
                })
            elif ev == "push_stale":
                # per-source staleness counter track on the server rank:
                # one lane per pushing client, so a delayed client's
                # elevated staleness is visually attributable
                events.append({
                    "ph": "C",
                    "name": f"staleness src {rec.get('src')}",
                    "cat": "dynamics", "pid": rank, "tid": 0,
                    "ts": us(t),
                    "args": {"value": rec.get("staleness", 0)},
                })
            elif ev == "journal_cap":
                # truncation evidence (cap footer, written incrementally):
                # where the journal stopped/evicted is itself a clue
                events.append({
                    "ph": "i", "s": "p", "name": "journal truncated",
                    "cat": "obs", "pid": rank, "tid": 0, "ts": us(t),
                    "args": {
                        k: rec[k]
                        for k in (
                            "cap", "dropped_records", "mode",
                            "evicted_records",
                        ) if k in rec
                    },
                })
            elif ev == "blackbox":
                # flight-recorder dump header — marks where a window was
                # frozen and why (merging dump files gives the incident
                # trace the postmortem --perfetto flag asks for)
                events.append({
                    "ph": "i", "s": "p",
                    "name": f"blackbox dump ({rec.get('trigger', '?')})",
                    "cat": "obs", "pid": rank, "tid": 0, "ts": us(t),
                    "args": {
                        k: rec[k]
                        for k in (
                            "trigger", "incident", "records", "evicted",
                            "gen", "t_first", "t_last",
                        ) if k in rec
                    },
                })

    if faults_path is not None:
        for fault in read_fault_log(faults_path):
            key = (fault["src"], fault["dst"], fault["tag"], fault["n"])
            ts = send_index.get(key)
            args = {
                "dst": fault["dst"],
                "mtag": _tag_name(fault["tag"]),
                "n": fault["n"],
            }
            if ts is None:
                # no matching telemetry send (sampled out, or the journal
                # died first): pin at the timeline origin, visibly marked
                ts = 0.0
                args["unplaced"] = True
            events.append({
                "ph": "i", "s": "p", "name": f"fault {fault['kind']}",
                "cat": "chaos", "pid": fault["src"], "tid": 0, "ts": ts,
                "args": args,
            })

    if alerts_path is not None:
        # live-plane alerts join by (rank, wall-clock): unlike chaos
        # faults (no timestamp — joined through the send stream index)
        # an alert record carries the aggregator's wall-clock `t`, which
        # shares the journals' timebase, so it places directly. Alerts
        # raised after the last journal event (a dead rank is noticed
        # only once its journal went quiet) land past the timeline end —
        # that is the honest position, not an artifact.
        for rec in read_journal(alerts_path):
            if rec.get("ev") != "alert" or rec.get("t") is None:
                continue
            events.append({
                "ph": "i", "s": "p",
                "name": f"alert {rec.get('kind', '?')}",
                "cat": "alert", "pid": rec.get("rank", 0), "tid": 0,
                "ts": max(us(rec["t"]), 0.0),
                "args": rec.get("detail", {}),
            })

    events.sort(key=lambda e: (e.get("ts", 0.0), e["pid"]))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def trace_ids_by_rank(journal_paths: Iterable[str]) -> dict[int, set]:
    """trace-id sets per rank — the cross-rank assertion helper (a trace
    spanning client and server appears in >= 2 ranks' sets)."""
    out: dict[int, set] = {}
    for path in expand_journal_paths(journal_paths):
        for rec in read_journal(path):
            if "trace" in rec:
                out.setdefault(_rec_rank(rec), set()).add(rec["trace"])
    return out


def _lat_bucket(seconds: float) -> int:
    # ceil(log2(µs)); bucket b holds (2^(b-1), 2^b] µs — kept in lockstep
    # with mpit_tpu.obs.telemetry._lat_bucket, replicated here so the
    # merger stays importable without the transport stack
    return max(0, int(seconds * 1e6)).bit_length()


def _stream_stats(journal_paths: Iterable[str]) -> dict:
    """(rank, dir, peer, tag) -> {msgs, bytes, hist} from the journals:
    ``dir`` is "send"/"recv", ``peer`` the remote rank, ``hist`` the
    log2-µs latency histogram (send duration / recv blocked wait)."""
    out: dict[tuple, dict] = {}
    for path in expand_journal_paths(journal_paths):
        for rec in read_journal(path):
            ev = rec.get("ev")
            if ev in ("send", "isend"):
                key = (_rec_rank(rec), "send", rec.get("dst"),
                       rec.get("mtag"))
                lat = rec.get("dur")
            elif ev == "recv":
                key = (_rec_rank(rec), "recv", rec.get("src"),
                       rec.get("mtag"))
                lat = rec.get("wait")
            else:
                continue
            s = out.setdefault(key, {"msgs": 0, "bytes": 0, "hist": {}})
            s["msgs"] += 1
            s["bytes"] += rec.get("bytes", 0)
            if lat is not None:
                b = _lat_bucket(lat)
                s["hist"][b] = s["hist"].get(b, 0) + 1
    return out


def _hist_p50(hist: dict) -> Optional[int]:
    """Median latency bucket — the scalar each stream's histograms are
    compared by (a whole-bucket shift = a 2x latency regression)."""
    total = sum(hist.values())
    if not total:
        return None
    seen = 0
    for b in sorted(hist):
        seen += hist[b]
        if 2 * seen >= total:
            return b
    return max(hist)


def diff_summaries(
    run_a: Iterable[str], run_b: Iterable[str]
) -> list[dict]:
    """Per-(rank, dir, peer, tag) stream comparison of two runs — message
    and byte counts plus the median latency bucket. One row per stream
    present in either run, sorted; ``delta_*`` is b - a (missing stream =
    zeros/None). Rows where nothing moved carry ``same: True`` so callers
    can filter to the interesting ones."""
    a, b = _stream_stats(run_a), _stream_stats(run_b)
    rows = []
    for key in sorted(set(a) | set(b), key=str):
        rank, direction, peer, tag = key
        sa = a.get(key, {"msgs": 0, "bytes": 0, "hist": {}})
        sb = b.get(key, {"msgs": 0, "bytes": 0, "hist": {}})
        pa, pb = _hist_p50(sa["hist"]), _hist_p50(sb["hist"])
        rows.append({
            "rank": rank,
            "dir": direction,
            "peer": peer,
            "tag": tag,
            "tag_name": _tag_name(tag),
            "msgs_a": sa["msgs"], "msgs_b": sb["msgs"],
            "delta_msgs": sb["msgs"] - sa["msgs"],
            "bytes_a": sa["bytes"], "bytes_b": sb["bytes"],
            "delta_bytes": sb["bytes"] - sa["bytes"],
            "p50_bucket_a": pa, "p50_bucket_b": pb,
            "delta_p50_bucket": (
                pb - pa if pa is not None and pb is not None else None
            ),
            "same": sa["msgs"] == sb["msgs"]
            and sa["bytes"] == sb["bytes"]
            and pa == pb,
        })
    return rows


def summarize(journal_paths: Iterable[str]) -> dict:
    """Per-rank event/byte tallies for the ``summary`` subcommand."""
    out: dict[int, dict] = {}
    for path in expand_journal_paths(journal_paths):
        for rec in read_journal(path):
            if "ev" not in rec:
                continue
            r = out.setdefault(
                _rec_rank(rec),
                {"events": 0, "sends": 0, "recvs": 0, "bytes": 0,
                 "traces": set()},
            )
            r["events"] += 1
            if rec["ev"] in ("send", "isend"):
                r["sends"] += 1
                r["bytes"] += rec.get("bytes", 0)
            elif rec["ev"] == "recv":
                r["recvs"] += 1
            if "trace" in rec:
                r["traces"].add(rec["trace"])
    return {
        rank: {**v, "traces": len(v["traces"])}
        for rank, v in sorted(out.items())
    }


def _merge_intervals(intervals: list) -> list:
    """Sorted disjoint union of (start, end) intervals."""
    merged: list = []
    for b, e in sorted(i for i in intervals if i[1] > i[0]):
        if merged and b <= merged[-1][1]:
            if e > merged[-1][1]:
                merged[-1] = (merged[-1][0], e)
        else:
            merged.append((b, e))
    return merged


def _overlap(start: float, end: float, merged: list) -> float:
    """Length of [start, end] covered by the sorted disjoint intervals."""
    total = 0.0
    for b, e in merged:
        if e <= start:
            continue
        if b >= end:
            break
        total += min(e, end) - max(b, start)
    return total


def roofline(journal_paths: Iterable[str]) -> dict:
    """Per-rank and per-run compute/wire/idle/overhead attribution.

    The roofline join (docs/OBSERVABILITY.md), in the spirit of the
    MVAPICH DNN-training characterization (PAPERS.md, arXiv:1810.11112):

    - **compute** — wall-clock inside ``"compute"`` spans, which the
      training loop closes with proof-of-completion blocking so the
      figure is real device time, not dispatch time;
    - **wire** — every journaled send's in-transport duration, plus recv
      waits that fall *inside* one of the rank's spans (a client blocked
      in ``fetch()`` mid-exchange is waiting on the wire);
    - **idle** — recv waits *outside* any span: a server parked in its
      dispatch loop, or a client between protocol phases;
    - **overhead** — the remainder of the rank's observation window
      (Python, journaling, untraced host work).

    Fractions are normalized by ``max(window, compute + wire + idle)`` so
    they sum to exactly 1.0 even when sampled intervals overlap. Ranks
    that never open a span are reported as role ``"server"`` (the PS
    servers run no local step); span-opening ranks are ``"clients"`` and
    the slowest of them (by compute seconds, when the spread is > 5%) is
    flagged as the straggler.
    """
    per_rank: dict[int, list[dict]] = {}
    for path in expand_journal_paths(journal_paths):
        for rec in read_journal(path):
            if "ev" not in rec or _rec_time(rec) is None:
                continue
            per_rank.setdefault(_rec_rank(rec), []).append(rec)

    ranks: dict[int, dict] = {}
    for rank, recs in sorted(per_rank.items()):
        times = [_rec_time(r) for r in recs]
        window = max(times) - min(times) if len(times) > 1 else 0.0
        open_spans: dict = {}  # span id -> (name, t_begin)
        spans: list = []  # (begin, end) of every closed span
        compute_s = 0.0
        exch: list = []
        sends = recvs = nbytes = 0
        wire_s = idle_s = 0.0
        waits: list = []  # (begin, end) recv waits, classified below
        for rec in recs:
            ev, t = rec["ev"], _rec_time(rec)
            if ev == "span_b":
                open_spans[rec.get("span")] = (rec.get("name"), t)
            elif ev == "span_e":
                opened = open_spans.pop(rec.get("span"), None)
                if opened is None:
                    continue
                name, t_b = opened
                spans.append((t_b, t))
                if name == "compute":
                    compute_s += t - t_b
                elif name == "exchange":
                    exch.append(t - t_b)
            elif ev in ("send", "isend"):
                sends += 1
                nbytes += rec.get("bytes", 0)
                wire_s += rec.get("dur", 0.0)
            elif ev == "recv":
                recvs += 1
                nbytes += rec.get("bytes", 0)
                wait = rec.get("wait", 0.0)
                if wait > 0:
                    waits.append((t - wait, t))
        merged = _merge_intervals(spans)
        for b, e in waits:
            in_span = _overlap(b, e, merged)
            wire_s += in_span
            idle_s += (e - b) - in_span
        denom = max(window, compute_s + wire_s + idle_s)
        overhead_s = denom - (compute_s + wire_s + idle_s)
        ranks[rank] = {
            "role": "client" if spans or open_spans else "server",
            "window_s": window,
            "compute_s": compute_s,
            "wire_s": wire_s,
            "idle_s": idle_s,
            "overhead_s": overhead_s,
            "phases": {
                "compute": compute_s / denom if denom else 0.0,
                "wire": wire_s / denom if denom else 0.0,
                "idle": idle_s / denom if denom else 0.0,
                "overhead": overhead_s / denom if denom else 1.0,
            },
            "sends": sends,
            "recvs": recvs,
            "bytes": nbytes,
            "exchanges": len(exch),
            "exchange_mean_s": sum(exch) / len(exch) if exch else None,
        }

    if not ranks:
        return {"ranks": {}, "run": None, "straggler": None}

    tot = {
        k: sum(r[k] for r in ranks.values())
        for k in ("compute_s", "wire_s", "idle_s", "overhead_s")
    }
    denom = sum(
        max(r["window_s"], r["compute_s"] + r["wire_s"] + r["idle_s"])
        for r in ranks.values()
    )
    run = {
        **tot,
        "window_s": max(r["window_s"] for r in ranks.values()),
        "phases": {
            "compute": tot["compute_s"] / denom if denom else 0.0,
            "wire": tot["wire_s"] / denom if denom else 0.0,
            "idle": tot["idle_s"] / denom if denom else 0.0,
            "overhead": tot["overhead_s"] / denom if denom else 1.0,
        },
        "ranks": len(ranks),
        "clients": sum(1 for r in ranks.values() if r["role"] == "client"),
        "bytes": sum(r["bytes"] for r in ranks.values()),
    }

    straggler = None
    clients = {
        rk: r["compute_s"] for rk, r in ranks.items()
        if r["role"] == "client" and r["compute_s"] > 0
    }
    if len(clients) >= 2:
        lo, hi = min(clients.values()), max(clients.values())
        if hi > 1.05 * lo:
            straggler = max(clients, key=lambda rk: clients[rk])

    return {"ranks": ranks, "run": run, "straggler": straggler}
