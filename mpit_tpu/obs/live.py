"""Live telemetry plane — in-run metrics registry + atomic snapshot export.

Everything obs built so far (tracing, roofline, SLO) is post-mortem:
JSONL journals reduced after the run ends. This module is the in-flight
half: a per-rank :class:`MetricsRegistry` that instrumentation publishes
into (counters, gauges, rolling-window geometric histograms), and a
:class:`LiveExporter` background thread that atomically snapshots the
registry to ``<MPIT_OBS_DIR>/live/rank_<r>.json`` on a configurable
interval. ``python -m mpit_tpu.obs live <dir>`` aggregates the snapshots
across ranks into a dashboard and runs the online alert engine
(:mod:`mpit_tpu.obs.alerts`) — the signals a replica router or elastic
scheduler will consume (ROADMAP: serving replicas, elastic membership).

Design rules:

- **Names are a registry.** Every metric name published here is an
  ``M_*`` module constant below — the one registered namespace. Lint rule
  MPT012 flags publishes that bypass it (a typo'd key otherwise just
  splits a series silently).
- **Two publish paths.** Per-round / per-request events push directly
  (``inc``/``set_gauge``/``observe`` — cheap at that frequency);
  per-message wire counters are *pulled* at export time via
  ``add_collector`` (the TelemetryTransport already counts every message
  under its own lock — re-counting per send would tax the hot path for a
  1 Hz consumer).
- **Disabled cost is a getattr.** When live export is not armed there is
  no registry; :func:`live_registry` returns the shared
  :data:`NULL_REGISTRY` whose methods are no-ops — the ``NULL_SPAN``
  idiom, pinned by the micro-benchmark in tests/test_live.py.
- **Snapshots are atomic and versioned.** Write-to-temp + ``os.replace``
  so a reader never sees a torn file; ``schema`` guards parsing across
  versions; ``seq`` is a monotonic heartbeat (a stuck exporter is
  distinguishable from a slow one), and staleness is judged *relative*
  to the freshest rank so post-mortem aggregation still identifies which
  rank died first.

This module reads/writes only files and must import neither jax nor the
transport stack (the ``obs.merge`` contract) — the CLI stays fast and
safe to run anywhere, including the lint.sh schema gate.
"""

from __future__ import annotations

import glob
import json
import math
import os
import threading
import time
from typing import Any, Callable, Mapping, Optional

from mpit_tpu.analysis.runtime import make_lock

SNAPSHOT_SCHEMA = 1

# ---------------------------------------------------------------------------
# The registered metric namespace (lint rule MPT012's source of truth):
# module-level M_* string constants, one per published series. Publishing
# code imports these by name — never inlines the string.

# PS training plane (published by parallel/ps_roles.py per round)
M_STEPS = "train.steps"
M_SAMPLES = "train.samples"
M_COMPUTE_S = "train.compute_s"
M_EXCHANGE_S = "train.exchange_s"
M_EXCHANGE_LAT = "train.exchange_lat"
M_ROUNDS = "train.rounds"
M_PUSHES = "train.pushes"
M_SKIPPED_ROUNDS = "train.skipped_rounds"
M_EXCHANGE_FAILURES = "train.exchange_failures"
M_STALE_PARAMS = "train.stale_params_dropped"
M_REPAIRED_CHUNKS = "train.repaired_chunks"

# training-dynamics plane (docs/OBSERVABILITY.md "dynamics"):
# M_STALENESS is a histogram published by the SERVER per applied
# versioned push — one staleness unit recorded as one "second", so the
# unit-agnostic geometric buckets apply and percentile_ms/1000 recovers
# staleness units within one ~10% bucket step. The rest are per-round
# client gauges from parallel/ps_roles._record_dynamics.
M_STALENESS = "train.staleness"
M_ELASTIC_DIST = "train.elastic_dist"
M_PUSH_NORM = "train.push_norm"
M_PARAM_NORM = "train.param_norm"
M_NORM_RATIO = "train.norm_ratio"

# serving plane (published by models/serving.py lifecycle events)
M_REQ_SUBMITTED = "serve.submitted"
M_REQ_FINISHED = "serve.finished"
M_REQ_CANCELLED = "serve.cancelled"
M_SLO_MISSES = "serve.slo_misses"
M_TOKENS = "serve.tokens"
M_TTFT = "serve.ttft"
M_E2E = "serve.e2e"
M_SEGMENTS = "serve.segments"
M_WAITING = "serve.waiting"
M_OCCUPIED = "serve.occupied"
M_SERVE_FAULTS = "serve.faults"

# load-harness plane (published by loadgen/harness.py per boundary)
M_LOAD_PENDING = "load.pending"
M_LOAD_LATENESS_S = "load.submit_lateness_s"

# serving-fleet plane (published by fleet/router.py and fleet/replica.py)
M_FLEET_ROUTED = "fleet.routed"
M_FLEET_REDISPATCHED = "fleet.redispatched"
M_FLEET_SHED = "fleet.shed"
M_FLEET_REPLICAS = "fleet.replicas"
M_FLEET_OUTSTANDING = "fleet.outstanding"
M_FLEET_WEIGHTS_VERSION = "fleet.weights_version"

# base-1.1 geometric buckets on microseconds — kept in lockstep with
# mpit_tpu.loadgen.slo (bucket b covers (1.1^(b-1), 1.1^b] µs, any
# percentile within one ~10% step); replicated here so this module stays
# importable without the loadgen package (which pulls the transport
# stack through its chaos module)
_BASE = 1.1
_LOG_BASE = math.log(_BASE)


def _bucket(seconds: float) -> int:
    us = seconds * 1e6
    if us <= 1.0:
        return 0
    return int(math.ceil(math.log(us) / _LOG_BASE))


def _bucket_ms(b: int) -> float:
    return _BASE ** b / 1e3


def percentile_ms(counts: Mapping, q: float) -> Optional[float]:
    """q-th percentile (0..1) of a ``{bucket: count}`` histogram, in ms.

    Bucket keys may be ints or their str forms (JSON round-trip)."""
    total = sum(counts.values())
    if total == 0:
        return None
    need = q * total
    seen = 0
    for b in sorted(counts, key=int):
        seen += counts[b]
        if seen >= need:
            return _bucket_ms(int(b))
    return _bucket_ms(max(int(b) for b in counts))


class _RollingSum:
    """Time-sliced rolling accumulator: the window is ``nslices`` fixed
    slices; expired slices are dropped on read/write. O(nslices) memory,
    no per-sample timestamps."""

    __slots__ = ("slice_s", "nslices", "slices")

    def __init__(self, window_s: float, nslices: int):
        self.slice_s = window_s / nslices
        self.nslices = nslices
        self.slices: list = []  # [[slice_idx, value], ...] ascending

    def _prune(self, idx: int) -> None:
        lo = idx - self.nslices + 1
        while self.slices and self.slices[0][0] < lo:
            self.slices.pop(0)

    def add(self, now: float, value: float) -> None:
        idx = int(now / self.slice_s)
        if self.slices and self.slices[-1][0] == idx:
            self.slices[-1][1] += value
        else:
            self.slices.append([idx, value])
            self._prune(idx)

    def value(self, now: float) -> float:
        self._prune(int(now / self.slice_s))
        return sum(v for _, v in self.slices)


class _RollingHist:
    """Rolling ``{bucket: count}`` histogram, same slice scheme."""

    __slots__ = ("slice_s", "nslices", "slices")

    def __init__(self, window_s: float, nslices: int):
        self.slice_s = window_s / nslices
        self.nslices = nslices
        self.slices: list = []  # [[slice_idx, {bucket: count}], ...]

    def _prune(self, idx: int) -> None:
        lo = idx - self.nslices + 1
        while self.slices and self.slices[0][0] < lo:
            self.slices.pop(0)

    def add(self, now: float, bucket: int) -> None:
        idx = int(now / self.slice_s)
        if not self.slices or self.slices[-1][0] != idx:
            self.slices.append([idx, {}])
            self._prune(idx)
        counts = self.slices[-1][1]
        counts[bucket] = counts.get(bucket, 0) + 1

    def counts(self, now: float) -> dict:
        self._prune(int(now / self.slice_s))
        out: dict = {}
        for _, counts in self.slices:
            for b, c in counts.items():
                out[b] = out.get(b, 0) + c
        return out


class MetricsRegistry:
    """Thread-safe per-rank metric store: monotonically increasing
    counters (cumulative total + rolling-window sum), last-write gauges,
    and base-1.1 geometric histograms (cumulative + rolling buckets).

    ``clock`` is the monotonic time source for the rolling windows
    (injectable for tests); wall-clock stamps in snapshots come from
    ``time.time`` so cross-rank staleness can be compared.

    Collectors (``add_collector``) are sampled at snapshot time OUTSIDE
    the registry lock — they may take their own locks (the telemetry
    stats lock) and must never publish back into the registry from
    inside the callback."""

    def __init__(
        self,
        rank: int,
        role: str = "ps",
        window_s: float = 30.0,
        slices: int = 6,
        clock: Callable[[], float] = time.monotonic,
    ):
        if window_s <= 0 or slices < 1:
            raise ValueError("window_s must be > 0 and slices >= 1")
        self.rank = rank
        self.role = role
        self.window_s = float(window_s)
        self.slices = int(slices)
        self._clock = clock
        self._lock = make_lock("obs.MetricsRegistry._lock")
        self._counters: dict = {}  # name -> [total, _RollingSum]
        self._gauges: dict = {}    # name -> value
        self._hists: dict = {}     # name -> [counts, total, sum_s, _RollingHist]
        self._collectors: list = []  # (name, fn)
        self._t0_wall = time.time()
        self._t0 = clock()

    # -- publish ----------------------------------------------------------

    def inc(self, name: str, value: float = 1.0) -> None:
        now = self._clock()
        with self._lock:
            entry = self._counters.get(name)
            if entry is None:
                entry = self._counters[name] = [
                    0.0, _RollingSum(self.window_s, self.slices)
                ]
            entry[0] += value
            entry[1].add(now, value)

    def set_gauge(self, name: str, value: float) -> None:
        # coercion lives here, not at call sites: publishers sit in hot
        # loops where a float() on the caller's side reads as (and is
        # linted as) a device sync
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, seconds: float) -> None:
        b = _bucket(seconds)
        now = self._clock()
        with self._lock:
            entry = self._hists.get(name)
            if entry is None:
                entry = self._hists[name] = [
                    {}, 0, 0.0, _RollingHist(self.window_s, self.slices)
                ]
            entry[0][b] = entry[0].get(b, 0) + 1
            entry[1] += 1
            entry[2] += seconds
            entry[3].add(now, b)

    def add_collector(self, name: str, fn: Callable[[], dict]) -> None:
        with self._lock:
            self._collectors.append((name, fn))

    # -- snapshot ---------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able versioned state. Counter ``rate`` is the rolling sum
        divided by the covered window (= the window once uptime exceeds
        it) — for a seconds-valued counter that rate IS the rolling phase
        fraction, which is what the dashboard and the straggler alert
        read."""
        now = self._clock()
        now_wall = time.time()
        uptime = now - self._t0
        covered = max(min(self.window_s, uptime), 1e-3)
        with self._lock:
            counters = {
                name: {
                    "total": entry[0],
                    "rate": entry[1].value(now) / covered,
                }
                for name, entry in sorted(self._counters.items())
            }
            gauges = dict(sorted(self._gauges.items()))
            hists = {}
            for name, entry in sorted(self._hists.items()):
                counts, total, sum_s, rolling = entry
                rcounts = rolling.counts(now)
                hists[name] = {
                    "count": total,
                    "sum_s": round(sum_s, 6),
                    "buckets": {str(b): c for b, c in sorted(counts.items())},
                    "rolling": {
                        str(b): c for b, c in sorted(rcounts.items())
                    },
                }
            collectors = list(self._collectors)
        collect = {}
        for name, fn in collectors:
            try:
                collect[name] = fn()
            except Exception as e:  # a broken collector must not kill export
                collect[name] = {"error": repr(e)}
        return {
            "schema": SNAPSHOT_SCHEMA,
            "rank": self.rank,
            "role": self.role,
            "pid": os.getpid(),
            "t": now_wall,
            "t0": self._t0_wall,
            "uptime_s": round(uptime, 6),
            "window_s": self.window_s,
            "covered_s": round(covered, 6),
            "counters": counters,
            "gauges": gauges,
            "hists": hists,
            "collect": collect,
        }


class _NullRegistry:
    """The disabled fast path: one shared no-op registry, so a publish
    site costs a getattr + an identity check + a no-op method call when
    live telemetry is off (the ``NULL_SPAN`` idiom; pinned by the
    micro-benchmark in tests/test_live.py)."""

    __slots__ = ()

    def inc(self, name: str, value: float = 1.0) -> None:
        pass

    def set_gauge(self, name: str, value: float) -> None:
        pass

    def observe(self, name: str, seconds: float) -> None:
        pass

    def add_collector(self, name: str, fn) -> None:
        pass


NULL_REGISTRY = _NullRegistry()


def live_registry(obj: Any):
    """Instrumentation hook: the live :class:`MetricsRegistry` when
    ``obj`` (a transport, a server — anything carrying ``obs_registry``)
    has one armed, the shared no-op otherwise. Safe to call in loops
    unconditionally — the disabled path is a getattr and a check."""
    reg = getattr(obj, "obs_registry", None)
    if reg is None:
        return NULL_REGISTRY
    return reg


class LiveExporter:
    """Background snapshot writer: every ``interval_s`` (and once at
    start and once at close, so even sub-interval runs leave a
    snapshot), the registry's state lands atomically in
    ``<live_dir>/rank_<r>.json`` — write-to-temp + ``os.replace``, a
    reader never sees a torn file. ``seq`` increments per write (the
    monotonic heartbeat the dead-rank alert watches, via the wall-clock
    ``t`` it stamps alongside). Write errors are counted, never raised —
    a full disk must not kill training."""

    def __init__(
        self,
        registry: MetricsRegistry,
        live_dir: str,
        interval_s: float = 1.0,
        start: bool = True,
    ):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        os.makedirs(live_dir, exist_ok=True)
        self.registry = registry
        self.interval_s = float(interval_s)
        self.path = os.path.join(live_dir, f"rank_{registry.rank}.json")
        self.write_errors = 0
        self._seq = 0
        # write() runs on the export thread AND on close()'s caller; the
        # join() in close() has a timeout, so it is not a guaranteed fence
        self._write_lock = make_lock(
            f"obs.LiveExporter._write_lock[{registry.rank}]"
        )
        self._stop = threading.Event()
        self._closed = False
        self._thread = threading.Thread(
            target=self._run,
            name=f"mpit-live-export-{registry.rank}",
            daemon=True,
        )
        if start:
            self._thread.start()

    def _run(self) -> None:
        self.write()  # first heartbeat immediately, not one interval in
        while not self._stop.wait(self.interval_s):
            self.write()

    def write(self) -> None:
        snap = self.registry.snapshot()
        with self._write_lock:
            self._seq += 1
            snap["seq"] = self._seq
            snap["interval_s"] = self.interval_s
            tmp = f"{self.path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump(snap, f)
                os.replace(tmp, self.path)
            except OSError:
                self.write_errors += 1
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    def close(self) -> None:
        """Stop the thread and write one final snapshot (the run's last
        state must be on disk even when the run was shorter than one
        interval). Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)
        self.write()


# ---------------------------------------------------------------------------
# Reading side: snapshot validation and cross-rank aggregation (the
# `python -m mpit_tpu.obs live` backend).


def validate_snapshot(snap: Any) -> list[str]:
    """Schema problems for one parsed snapshot (empty list = valid).
    This is the contract the checked-in golden snapshot is gated
    against in scripts/lint.sh."""
    problems: list[str] = []
    if not isinstance(snap, dict):
        return ["snapshot is not a JSON object"]

    def _num(key, minimum=None):
        v = snap.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            problems.append(f"{key}: missing or not a number")
            return
        if minimum is not None and v < minimum:
            problems.append(f"{key}: {v} < {minimum}")

    if snap.get("schema") != SNAPSHOT_SCHEMA:
        problems.append(
            f"schema: {snap.get('schema')!r} != {SNAPSHOT_SCHEMA}"
        )
    _num("rank", 0)
    if not isinstance(snap.get("role"), str):
        problems.append("role: missing or not a string")
    _num("t")
    _num("t0")
    _num("uptime_s", 0.0)
    _num("window_s", 1e-9)
    _num("seq", 1)
    _num("interval_s", 1e-9)
    for section, leaf in (
        ("counters", ("total", "rate")),
        ("hists", ("count", "sum_s", "buckets", "rolling")),
    ):
        table = snap.get(section)
        if not isinstance(table, dict):
            problems.append(f"{section}: missing or not an object")
            continue
        for name, entry in table.items():
            if not isinstance(entry, dict):
                problems.append(f"{section}[{name}]: not an object")
                continue
            for k in leaf:
                if k not in entry:
                    problems.append(f"{section}[{name}]: missing {k!r}")
    for section in ("gauges", "collect"):
        if not isinstance(snap.get(section), dict):
            problems.append(f"{section}: missing or not an object")
    return problems


def find_live_dir(path: str) -> str:
    """Accept either the run dir (``MPIT_OBS_DIR`` — snapshots under its
    ``live/``) or the live dir itself."""
    sub = os.path.join(path, "live")
    if os.path.isdir(sub):
        return sub
    return path


def read_snapshots(live_dir: str) -> dict[int, dict]:
    """rank -> parsed snapshot for every readable, schema-valid
    ``rank_*.json`` (torn/foreign files are skipped — one bad rank must
    not sink the dashboard)."""
    out: dict[int, dict] = {}
    for path in sorted(glob.glob(os.path.join(live_dir, "rank_*.json"))):
        try:
            with open(path) as f:
                snap = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if validate_snapshot(snap):
            continue
        out[int(snap["rank"])] = snap
    return out


def _counter(snap: dict, name: str) -> dict:
    return snap.get("counters", {}).get(name, {"total": 0.0, "rate": 0.0})


def _gauge(snap: dict, name: str):
    return snap.get("gauges", {}).get(name)


def compute_fraction(snap: dict) -> Optional[float]:
    """Rolling compute-seconds-per-second for a training rank (None when
    the rank publishes no compute) — the straggler alert's input."""
    c = snap.get("counters", {}).get(M_COMPUTE_S)
    if c is None:
        return None
    return float(c["rate"])


def aggregate(snapshots: Mapping[int, dict]) -> dict:
    """Cross-rank live report: per-rank health/throughput rows plus run
    totals. ``now`` is the freshest snapshot's wall-clock — staleness is
    *relative*, so a post-mortem aggregation still shows which rank fell
    silent first."""
    if not snapshots:
        return {"now": None, "ranks": {}, "run": None, "serve": None}
    now = max(s["t"] for s in snapshots.values())
    ranks: dict[int, dict] = {}
    serve_rows = []
    for rank, snap in sorted(snapshots.items()):
        wire = snap.get("collect", {}).get("wire", {})
        chaos = snap.get("collect", {}).get("chaos", {})
        cf = compute_fraction(snap)
        wf = _counter(snap, M_EXCHANGE_S)["rate"] if cf is not None else None
        row = {
            "role": snap.get("role", "?"),
            "age_s": round(now - snap["t"], 3),
            "seq": snap.get("seq"),
            "uptime_s": snap.get("uptime_s"),
            "interval_s": snap.get("interval_s"),
            "throughput": round(_counter(snap, M_SAMPLES)["rate"], 3),
            "samples": _counter(snap, M_SAMPLES)["total"],
            "rounds": _counter(snap, M_ROUNDS)["total"],
            "queue_depth": wire.get("queue_depth"),
            "faults": {
                k: v for k, v in chaos.items() if isinstance(v, int)
            },
            "serve_faults": _counter(snap, M_SERVE_FAULTS)["total"],
        }
        if cf is not None and wf is not None:
            row["phases"] = {
                "compute": round(cf, 4),
                "wire": round(wf, 4),
                "other": round(max(0.0, 1.0 - cf - wf), 4),
            }
        exch = snap.get("hists", {}).get(M_EXCHANGE_LAT)
        if exch is not None:
            buckets = exch["rolling"] or exch["buckets"]
            row["exchange_ms"] = {
                "p50": percentile_ms(buckets, 0.50),
                "p90": percentile_ms(buckets, 0.90),
                "p99": percentile_ms(buckets, 0.99),
            }
        # training-dynamics rows (docs/OBSERVABILITY.md "dynamics"):
        # server ranks publish the staleness hist (units, not time —
        # hence /1e3 undoing percentile_ms's ms scaling), client ranks
        # the per-round quality gauges
        stal = snap.get("hists", {}).get(M_STALENESS)
        if stal is not None:
            buckets = stal["rolling"] or stal["buckets"]
            p50 = percentile_ms(buckets, 0.50)
            p99 = percentile_ms(buckets, 0.99)
            row["staleness"] = {
                "p50": None if p50 is None else round(p50 / 1e3, 3),
                "p99": None if p99 is None else round(p99 / 1e3, 3),
            }
        elastic = _gauge(snap, M_ELASTIC_DIST)
        if elastic is not None:
            row["dynamics"] = {
                "elastic_dist": elastic,
                "push_norm": _gauge(snap, M_PUSH_NORM),
                "param_norm": _gauge(snap, M_PARAM_NORM),
                "norm_ratio": _gauge(snap, M_NORM_RATIO),
            }
        # flight-recorder health (collector fragment from the rank's
        # BlackBox): ring occupancy + dump count, so an operator can see
        # the recorder is armed — and that an incident already dumped —
        # without touching the run dir
        bb = snap.get("collect", {}).get("blackbox")
        if isinstance(bb, dict) and "records" in bb:
            row["blackbox"] = {
                "records": bb.get("records"),
                "dumps": bb.get("dumps"),
                "last_trigger": bb.get("last_trigger"),
            }
        if snap.get("role") == "serve":
            finished = _counter(snap, M_REQ_FINISHED)
            misses = _counter(snap, M_SLO_MISSES)
            miss_frac = (
                misses["rate"] / finished["rate"]
                if finished["rate"] > 0 else 0.0
            )
            srow = {
                "waiting": _gauge(snap, M_WAITING),
                "occupied": _gauge(snap, M_OCCUPIED),
                "rps": round(finished["rate"], 3),
                "tokens_per_s": round(_counter(snap, M_TOKENS)["rate"], 3),
                "finished": finished["total"],
                "cancelled": _counter(snap, M_REQ_CANCELLED)["total"],
                "slo_miss_fraction": round(miss_frac, 4),
            }
            ttft = snap.get("hists", {}).get(M_TTFT)
            if ttft is not None:
                buckets = ttft["rolling"] or ttft["buckets"]
                srow["ttft_p50_ms"] = percentile_ms(buckets, 0.50)
                srow["ttft_p99_ms"] = percentile_ms(buckets, 0.99)
            row["serve"] = srow
            serve_rows.append(srow)
        ranks[rank] = row
    fracs = [
        r["phases"]["compute"] for r in ranks.values() if "phases" in r
    ]
    run = {
        "ranks": len(ranks),
        "throughput": round(sum(r["throughput"] for r in ranks.values()), 3),
        "max_age_s": round(max(r["age_s"] for r in ranks.values()), 3),
        "compute_fraction_spread": (
            round(max(fracs) - min(fracs), 4) if len(fracs) >= 2 else None
        ),
    }
    serve = None
    if serve_rows:
        serve = {
            "rps": round(sum(r["rps"] for r in serve_rows), 3),
            "waiting": sum(r["waiting"] or 0 for r in serve_rows),
            "slo_miss_fraction": round(
                max(r["slo_miss_fraction"] for r in serve_rows), 4
            ),
        }
    return {"now": now, "ranks": ranks, "run": run, "serve": serve}
