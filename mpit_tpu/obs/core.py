"""obs core — span context, logical clock, per-rank event journal.

The reference debugged multi-rank training by reading interleaved per-rank
``print``s in the mpirun console (SURVEY.md §5); this package is the
do-better: every transport-level event (send, recv, span, fault) becomes
one JSONL record in a per-rank journal, causally linked across ranks by a
trace/span context that rides the wire inside a payload envelope
(:mod:`mpit_tpu.obs.telemetry`), and ``python -m mpit_tpu.obs merge``
joins the journals into one Chrome-trace/Perfetto timeline.

Span model
----------

- ``trace_id``  one logical *exchange* across ranks (a FETCH → PARAM
  round-trip, a push and its server-side apply). 64-bit random.
- ``span_id``   one timed operation inside a trace (a send, a recv wait,
  a ``span()`` region). Unique per process, also the flow-event id that
  draws the send→recv arrow in Perfetto.
- ``parent_id`` the enclosing span — a local ``span()`` region for sends
  made inside it, or the *remote* send span for operations a rank performs
  in response to a received message (the server's PARAM reply is parented
  by the client's FETCH send, which is what stitches one trace across the
  process boundary without the PS protocol code knowing).

Clocks: journals carry wall-clock ``t`` (merging assumes NTP-level skew —
single-host runs are exact) plus a Lamport logical clock ``clk`` that the
envelope propagates; ``clk`` gives a causal order that survives clock skew
and is what the merger validates cross-rank causality against.

Activation mirrors chaos (:func:`mpit_tpu.transport.chaos.config_from_env`):
obs must never arm implicitly — only recognized ``MPIT_OBS_*`` knobs count.

  MPIT_OBS_DIR          path journal directory (arms obs; one
                             obs_rank<r>.jsonl per transport rank)
  MPIT_OBS_TRACE        0|1  wire trace envelopes + flow linking (default 1)
  MPIT_OBS_TELEMETRY    0|1  per-(peer, tag) counters/histograms (default 1)
  MPIT_OBS_SAMPLE       int  journal every Nth wire event per stream
                             (default 1 = all; counters always see all)
  MPIT_OBS_MAX_RECORDS  int  per-journal record cap: writes past it are
                             dropped and counted, and a ``journal_cap``
                             footer carrying ``dropped_records`` is kept
                             current on disk (default: unbounded)
  MPIT_OBS_RING         0|1  ring journal mode: keep the LAST
                             ``max_records`` (default 4096) instead of
                             the first — a long soak preserves its crash,
                             not its boring start; the evicted head is
                             counted in the ``journal_cap`` footer
                             (``mode: "ring"``) and conformance licenses
                             it like a churned tail (default 0)
  MPIT_OBS_BLACKBOX     0|1  flight recorder (docs/OBSERVABILITY.md
                             "Black box"): every journal also tees into
                             a bounded in-memory ring that dumps to
                             ``<dir>/blackbox/rank_<r>.jsonl`` on
                             SIGTERM/atexit/close/alert/dump-request
                             (default 1 — armed whenever a dir is set)
  MPIT_OBS_BLACKBOX_RECORDS
                        int  black-box ring capacity, records (2048)
  MPIT_OBS_BLACKBOX_SECONDS
                        sec  black-box ring horizon: records older than
                             this are evicted regardless of count (30)
  MPIT_OBS_BLACKBOX_DUMP_SIGNAL
                        str  extra dump trigger: a signal name/number
                             (e.g. ``USR1``) that dumps the ring and
                             continues running (default: unset)
  MPIT_OBS_LIVE         0|1  live telemetry plane: per-rank metrics
                             registry + background snapshot exporter
                             writing ``<dir>/live/rank_<r>.json``
                             (:mod:`mpit_tpu.obs.live`; default 0)
  MPIT_OBS_LIVE_INTERVAL
                        sec  live snapshot export interval (default 1.0)
  MPIT_OBS_FAULTHANDLER 0|1|sec  hang forensics: arm
                             ``faulthandler.dump_traceback_later`` so a
                             wedged rank leaves an all-threads stack
                             dump in ``<dir>/stacks_rank<r>.txt`` (or
                             stderr with no dir) every interval instead
                             of nothing ("1" = 300 s default interval,
                             a number = that interval in seconds)
"""

from __future__ import annotations

import dataclasses
import os
import struct
import threading
import time
from typing import Any, Iterable, Mapping, Optional

from mpit_tpu.analysis.runtime import make_lock

# wire envelope marker (telemetry.py wraps payloads as
# (_ENVELOPE_MARK, trace_id, span_id, clk, payload)); versioned so a
# mixed-version world fails visibly rather than mis-parsing
_ENVELOPE_MARK = "__mpit_obs1__"


def _new_id() -> int:
    """Random 63-bit id (json-safe positive int; os.urandom, not
    ``random`` — ids must not perturb or depend on seeded streams like
    the chaos schedule's)."""
    return struct.unpack(">Q", os.urandom(8))[0] >> 1


@dataclasses.dataclass(frozen=True)
class SpanContext:
    """What crosses the wire: enough to parent the receiver's next ops."""

    trace_id: int
    span_id: int


class LogicalClock:
    """Thread-safe Lamport clock: ``tick`` before local events, ``observe``
    on message receipt (clk = max(local, remote) + 1)."""

    def __init__(self):
        self._lock = make_lock("obs.LogicalClock._lock")
        self._value = 0

    def tick(self) -> int:
        with self._lock:
            self._value += 1
            return self._value

    def observe(self, remote: int) -> int:
        with self._lock:
            self._value = max(self._value, int(remote)) + 1
            return self._value

    def peek(self) -> int:
        with self._lock:
            return self._value


class Journal:
    """Per-rank JSONL event stream, one record per line in
    :class:`mpit_tpu.utils.metrics.MetricsLogger`'s format (``ts``/``tag``/
    ``process``/``step`` plus event fields) so existing JSONL tooling reads
    it unchanged. ``step`` carries the Lamport clock; ``t`` is the precise
    wall-clock (MetricsLogger's ``ts`` is rounded to 1 ms — too coarse for
    a µs timeline). The lock serializes concurrent writers (a client
    thread and its heartbeat timer share one rank's journal) and ``t`` is
    stamped inside it, so per-rank journal timestamps are monotonically
    non-decreasing by construction — the property the merged timeline (and
    its test) relies on.

    ``max_records`` caps journal growth (a million-request load run must
    not fill the disk silently): writes past the cap are dropped and
    counted into a ``journal_cap`` footer record carrying the
    ``dropped_records`` total — readers see the loss explicitly instead
    of inferring it from absence. The footer is kept current on disk
    *incrementally* (appended on the first drop, rewritten in place
    every ``_FOOTER_EVERY`` drops and at close), so a SIGKILLed rank's
    journal still confesses its truncation to within ``_FOOTER_EVERY``
    drops — ``obs slo`` and conformance must not need a clean exit to
    learn that records are missing.

    ``mode="ring"`` inverts the cap: the journal buffers the LAST
    ``max_records`` in memory (evicting the oldest, counted as
    ``evicted_records``) and flushes the survivors at :meth:`close` —
    a week-long soak keeps its crash window, not its boring start. The
    flushed journal ends with the same ``journal_cap`` footer plus
    ``mode: "ring"`` so readers (and TC202's licensing) can tell an
    evicted head from lost messages. The memory-buffered tail is the
    honest cost: a SIGKILLed ring journal writes nothing — which is
    exactly the gap the black-box dump triggers exist to cover
    (:mod:`mpit_tpu.obs.blackbox`).

    ``blackbox`` tees every record (including ones the cap drops) into
    the rank's in-memory flight recorder; the tee is a deque append —
    its cost on the journal hot path is pinned by
    tests/test_blackbox.py."""

    #: rewrite the on-disk footer every this-many drops (kill-safety
    #: granularity vs. one extra seek+write per drop)
    _FOOTER_EVERY = 64
    _RING_DEFAULT_RECORDS = 4096

    def __init__(
        self, path: str, rank: int, max_records: Optional[int] = None,
        mode: str = "cap", blackbox: Optional[Any] = None,
    ):
        from mpit_tpu.utils.metrics import MetricsLogger

        if max_records is not None and max_records < 1:
            raise ValueError("max_records must be >= 1")
        if mode not in ("cap", "ring"):
            raise ValueError("mode must be 'cap' or 'ring'")
        if mode == "ring" and max_records is None:
            max_records = self._RING_DEFAULT_RECORDS
        self.path = path
        self.rank = rank
        self.mode = mode
        self.max_records = max_records
        self.dropped_records = 0
        self.evicted_records = 0
        self.blackbox = blackbox
        self._written = 0
        self._closed = False
        self._footer_off: Optional[int] = None
        self._lock = make_lock("obs.Journal._lock")
        self._ring: Optional[list] = [] if mode == "ring" else None
        self._m = MetricsLogger(
            path, tag="obs", echo=False, all_processes=True
        )

    # MetricsLogger owns these record keys; caller fields that collide
    # (e.g. a span arg named "step") are prefixed rather than rejected
    _RESERVED = ("step", "ts", "tag", "process", "rank", "ev", "t")

    def event(self, ev: str, clk: int, **fields: Any) -> None:
        for k in self._RESERVED:
            if k in fields:
                fields[f"x_{k}"] = fields.pop(k)
        t = time.time()
        with self._lock:
            if self._closed:
                return
            if self.blackbox is not None:
                # the tee sees EVERY record — including ones the cap is
                # about to drop; that inversion (cap keeps the head, the
                # flight recorder keeps the tail) is the black box's job
                self.blackbox.record(t, clk, ev, fields)
            if self._ring is not None:
                self._ring.append((t, clk, ev, fields))
                if len(self._ring) > self.max_records:
                    del self._ring[0]
                    self.evicted_records += 1
                return
            if (
                self.max_records is not None
                and self._written >= self.max_records
            ):
                self.dropped_records += 1
                if (
                    self.dropped_records == 1
                    or self.dropped_records % self._FOOTER_EVERY == 0
                ):
                    self._write_footer_locked()
                return
            self._written += 1
            self._m.log(clk, rank=self.rank, ev=ev, t=t, **fields)

    def _write_footer_locked(self) -> None:
        """Append-or-rewrite the ``journal_cap`` footer as the journal's
        last line. The stream is opened in append mode, so a rewrite is
        truncate-to-remembered-offset + append — after the cap no
        regular record ever follows the footer, so the offset stays
        valid for the journal's lifetime. Never raises: drop accounting
        must not kill the run it describes."""
        f = getattr(self._m, "_f", None)
        if f is None:
            return
        try:
            f.flush()
            if self._footer_off is None:
                self._footer_off = f.tell()
            else:
                f.truncate(self._footer_off)
            extra = {}
            if self.mode == "ring":
                extra["mode"] = "ring"
                extra["evicted_records"] = self.evicted_records
            self._m.log(
                self._written, rank=self.rank, ev="journal_cap",
                t=time.time(), cap=self.max_records,
                dropped_records=self.dropped_records, **extra,
            )
        except (OSError, ValueError):
            pass

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self._ring is not None:
                # flush the survivors in arrival order; their original
                # ``t`` stamps keep the per-rank monotonicity contract
                # (the footer's close-time t is >= all of them)
                for t, clk, ev, fields in self._ring:
                    self._written += 1
                    self._m.log(
                        clk, rank=self.rank, ev=ev, t=t, **fields
                    )
                self._ring = None
            if self.max_records is not None:
                # the footer rides OUTSIDE the cap (one fixed record),
                # and is written even at zero drops — "0 dropped" is an
                # assertion, absence is just a journal without a cap
                self._write_footer_locked()
            self._m.close()
        if self.blackbox is not None:
            # a cleanly-closed rank leaves its final window next to its
            # journal — post-mortems then cover the whole fleet, not
            # just the ranks something went wrong on
            self.blackbox.dump("close")
            self.blackbox.close()


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """Observability knobs; one frozen config shared by a world's wrappers
    (the :class:`mpit_tpu.transport.chaos.ChaosConfig` idiom).

    ``dir=None`` keeps counters/histograms but writes no journal (pure
    in-memory telemetry); ``trace=False`` drops the wire envelope (no
    cross-rank linking, zero payload growth); ``sample`` journals only
    every Nth send/recv per (peer, tag) stream — counters still see every
    message, so summaries stay exact while journal volume shrinks;
    ``max_records`` caps each journal's record count (drops are counted
    into the ``journal_cap`` footer — see :class:`Journal`);
    ``live=True`` arms the live telemetry plane — a per-rank
    :class:`mpit_tpu.obs.live.MetricsRegistry` plus a background
    exporter snapshotting ``<dir>/live/rank_<r>.json`` every
    ``live_interval`` seconds (registry only when ``dir`` is None);
    ``faulthandler`` > 0 arms hang forensics — a repeating
    :func:`faulthandler.dump_traceback_later` timer at that interval in
    seconds, dumping all threads' stacks to ``<dir>/stacks_<label>.txt``
    (stderr when ``dir`` is None) so a wedged rank leaves evidence next
    to its journal instead of nothing (0.0 = off);
    ``ring=True`` flips each journal to last-``max_records`` ring mode
    (see :class:`Journal` — a soak keeps its crash, not its start);
    ``blackbox`` (default True) arms the per-rank flight recorder
    whenever ``dir`` is set — a bounded in-memory ring of the last
    ``blackbox_records`` records / ``blackbox_seconds`` seconds, dumped
    to ``<dir>/blackbox/rank_<r>.jsonl`` on SIGTERM, atexit, clean
    close, an alert-driven dump request, or the explicit
    ``blackbox_dump_signal`` (:mod:`mpit_tpu.obs.blackbox`)."""

    dir: Optional[str] = None
    trace: bool = True
    telemetry: bool = True
    sample: int = 1
    max_records: Optional[int] = None
    live: bool = False
    live_interval: float = 1.0
    faulthandler: float = 0.0
    ring: bool = False
    blackbox: bool = True
    blackbox_records: int = 2048
    blackbox_seconds: float = 30.0
    blackbox_dump_signal: Optional[str] = None

    def __post_init__(self):
        if self.sample < 1:
            raise ValueError("sample must be >= 1")
        if self.max_records is not None and self.max_records < 1:
            raise ValueError("max_records must be >= 1")
        if self.live_interval <= 0:
            raise ValueError("live_interval must be > 0")
        if self.faulthandler < 0:
            raise ValueError("faulthandler must be >= 0 (0 = off)")
        if self.blackbox_records < 1:
            raise ValueError("blackbox_records must be >= 1")
        if self.blackbox_seconds <= 0:
            raise ValueError("blackbox_seconds must be > 0")


_ENV_KNOBS = frozenset(
    "MPIT_OBS_" + k
    for k in (
        "DIR", "TRACE", "TELEMETRY", "SAMPLE", "MAX_RECORDS",
        "LIVE", "LIVE_INTERVAL", "FAULTHANDLER", "RING",
        "BLACKBOX", "BLACKBOX_RECORDS", "BLACKBOX_SECONDS",
        "BLACKBOX_DUMP_SIGNAL",
    )
)

# MPIT_OBS_FAULTHANDLER=1 means "on, default cadence": dump every 5
# minutes — long enough that a healthy run never dumps (exchanges are
# sub-second), short enough that a wedged rank leaves evidence before
# anyone reaches for kill -9
_FAULTHANDLER_DEFAULT_S = 300.0


def _parse_faulthandler(raw: Optional[str]) -> float:
    if raw is None or raw in ("", "0", "false", "no"):
        return 0.0
    if raw in ("1", "true", "yes"):
        return _FAULTHANDLER_DEFAULT_S
    return float(raw)


def config_from_env(
    env: Mapping[str, str] = os.environ,
) -> Optional[ObsConfig]:
    """ObsConfig from ``MPIT_OBS_*`` knobs; None when none are set (obs
    never arms implicitly — same contract as chaos's env activation)."""
    if not any(k in _ENV_KNOBS for k in env):
        return None
    max_records = env.get("MPIT_OBS_MAX_RECORDS")
    return ObsConfig(
        dir=env.get("MPIT_OBS_DIR") or None,
        trace=env.get("MPIT_OBS_TRACE", "1") != "0",
        telemetry=env.get("MPIT_OBS_TELEMETRY", "1") != "0",
        sample=int(env.get("MPIT_OBS_SAMPLE", 1)),
        max_records=int(max_records) if max_records else None,
        live=env.get("MPIT_OBS_LIVE", "0") not in ("", "0"),
        live_interval=float(env.get("MPIT_OBS_LIVE_INTERVAL", 1.0)),
        faulthandler=_parse_faulthandler(env.get("MPIT_OBS_FAULTHANDLER")),
        ring=env.get("MPIT_OBS_RING", "0") not in ("", "0"),
        blackbox=env.get("MPIT_OBS_BLACKBOX", "1") != "0",
        blackbox_records=int(env.get("MPIT_OBS_BLACKBOX_RECORDS", 2048)),
        blackbox_seconds=float(env.get("MPIT_OBS_BLACKBOX_SECONDS", 30.0)),
        blackbox_dump_signal=env.get("MPIT_OBS_BLACKBOX_DUMP_SIGNAL")
        or None,
    )


# -- hang forensics ---------------------------------------------------------
# One arm per process: faulthandler.dump_traceback_later is process-global
# (a repeating timer over ALL threads), so the thread-mode trainer arms it
# once for the world and process mode arms it per rank. The dump file
# stays open for the process lifetime — faulthandler holds the fd.

_FAULTHANDLER_LOCK = make_lock("obs._FAULTHANDLER_LOCK")
_FAULTHANDLER_FILE = None


def arm_faulthandler(config: Optional["ObsConfig"], label: str) -> Optional[str]:
    """Arm the repeating all-threads stack dump when
    ``config.faulthandler`` > 0 — the MPIT_OBS_FAULTHANDLER knob's
    engine. Returns the dump path (``<dir>/stacks_<label>.txt``; None
    with the dump going to stderr, or when not armed). Idempotent per
    process: a second arm re-schedules the timer but keeps the first
    file. Never raises — forensics must not kill the run it exists to
    explain."""
    global _FAULTHANDLER_FILE
    if config is None or config.faulthandler <= 0:
        return None
    import faulthandler
    import sys

    # path work happens OUTSIDE the lock — only the file-slot check and
    # the (non-blocking) timer rearm sit in the critical section
    path = None
    if config.dir is not None:
        try:
            os.makedirs(config.dir, exist_ok=True)
        except OSError:
            return None
        path = os.path.join(config.dir, f"stacks_{label}.txt")
    with _FAULTHANDLER_LOCK:
        try:
            if path is not None:
                if _FAULTHANDLER_FILE is None:
                    _FAULTHANDLER_FILE = open(path, "w")
                else:
                    path = _FAULTHANDLER_FILE.name
            out = (
                _FAULTHANDLER_FILE if _FAULTHANDLER_FILE is not None
                else sys.stderr
            )
            faulthandler.dump_traceback_later(
                config.faulthandler, repeat=True, file=out
            )
        except (OSError, ValueError):
            return None
        return path


def disarm_faulthandler() -> None:
    """Cancel the pending dump timer (clean teardown: a finished run
    must not dump stacks from whatever outlives it). The dump file
    stays open — faulthandler may still hold it on some paths, and one
    fd per process is the documented cost."""
    import faulthandler

    faulthandler.cancel_dump_traceback_later()


class _NullSpan:
    """The disabled fast path: one shared no-op context manager, so an
    instrumentation site costs a getattr + an identity check when obs is
    off (pinned by the micro-benchmark in tests/test_obs.py)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    """An open ``span()`` region: journals B/E events and sits on the
    tracer's thread-local stack so sends made inside it inherit its
    trace."""

    __slots__ = ("tracer", "name", "ctx", "parent_id", "args")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.args = args
        self.ctx: Optional[SpanContext] = None
        self.parent_id: Optional[int] = None

    def __enter__(self) -> SpanContext:
        t = self.tracer
        # parent on the enclosing LOCAL span only — never on the thread's
        # remote parent. The remote parent exists to land a reply send in
        # the requester's trace (recv → handle → send); letting it parent
        # explicit spans would chain every exchange round into one
        # run-length trace via the previous round's PARAM recv.
        stack = t._stack()
        parent = stack[-1] if stack else None
        trace_id = parent.trace_id if parent is not None else _new_id()
        self.ctx = SpanContext(trace_id, _new_id())
        self.parent_id = parent.span_id if parent is not None else None
        t._stack().append(self.ctx)
        if t.journal is not None:
            t.journal.event(
                "span_b", t.clock.tick(), name=self.name,
                trace=self.ctx.trace_id, span=self.ctx.span_id,
                parent=self.parent_id, **self.args,
            )
        return self.ctx

    def __exit__(self, *exc):
        t = self.tracer
        stack = t._stack()
        if stack and stack[-1] is self.ctx:
            stack.pop()
        if t.journal is not None:
            t.journal.event(
                "span_e", t.clock.tick(), name=self.name,
                trace=self.ctx.trace_id, span=self.ctx.span_id,
            )
        return False


class Tracer:
    """Per-rank trace state: the logical clock, the journal, and the
    thread-local context stack + remote parent.

    Context resolution order for an outgoing send (``current_context``):

    1. the innermost open local ``span()`` on THIS thread, else
    2. the context of the last message THIS thread received (the remote
       parent — how a server's reply lands in the requester's trace), else
    3. nothing (the send starts a fresh single-span trace).

    Thread-locality is what makes 2 sound: the PS server is a recv →
    handle → reply loop on one thread, so "last received" is exactly the
    message being answered. Concurrent client threads each carry their
    own stack.
    """

    def __init__(self, rank: int, clock: Optional[LogicalClock] = None,
                 journal: Optional[Journal] = None):
        self.rank = rank
        self.clock = clock if clock is not None else LogicalClock()
        self.journal = journal
        self._tls = threading.local()

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current_context(self) -> Optional[SpanContext]:
        stack = getattr(self._tls, "stack", None)
        if stack:
            return stack[-1]
        return getattr(self._tls, "remote", None)

    def set_remote_parent(self, ctx: Optional[SpanContext]) -> None:
        self._tls.remote = ctx

    def span(self, name: str, **args: Any) -> _Span:
        return _Span(self, name, args)

    def close(self) -> None:
        if self.journal is not None:
            self.journal.close()


def span(transport, name: str, **args: Any):
    """Instrumentation hook for protocol code: a ``span()`` on the
    transport's tracer when the transport is obs-wrapped, the shared
    no-op otherwise. This getattr-and-check IS the guarded fast path —
    safe to leave in hot protocol loops unconditionally."""
    tracer = getattr(transport, "obs_tracer", None)
    if tracer is None:
        return NULL_SPAN
    return tracer.span(name, **args)


def write_fault_log(events: Iterable, path: str) -> int:
    """Persist a chaos :class:`~mpit_tpu.transport.chaos.FaultLog`'s
    events as JSONL for the merger (``--faults``). FaultEvents carry no
    timestamp by design (they must compare equal across replays); the
    merger recovers timeline placement by joining ``(src, dst, tag, n)``
    against the telemetry send events. Returns the event count."""
    import json

    n = 0
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps({
                "ev": "fault", "kind": e.kind, "src": e.src,
                "dst": e.dst, "tag": e.tag, "n": e.n,
            }) + "\n")
            n += 1
    return n
