"""mpit_tpu — a TPU-native distributed training framework.

A ground-up, jax/XLA-first rebuild of the capability surface of
``JiatianWu/mpiT`` (an MPI-for-Torch binding plus an asynchronous
parameter-server training harness; see SURVEY.md — the reference mount was
empty at survey time, so citations are to SURVEY.md/BASELINE.json rather than
reference file:line):

- ``mpit_tpu.comm``      — topology bootstrap + collectives. Replaces the
  reference's C MPI binding (SURVEY.md §2 comp. 1): ``MPI_Init/rank/size`` →
  TPU-slice discovery + ``jax.sharding.Mesh``; ``MPI_Allreduce/Bcast/Barrier``
  → ``jax.lax.psum``/friends over ICI.
- ``mpit_tpu.transport`` — tagged send/recv with ANY_SOURCE/ANY_TAG semantics
  for the host-async parameter-server protocol (the part of MPI that has no
  XLA analogue), over in-process queues or TCP sockets.
- ``mpit_tpu.goptim``    — distributed optimizers (EASGD/EAMSGD, Downpour)
  re-expressed as jit-compiled sharded update steps (SURVEY.md §2 comp. 5).
- ``mpit_tpu.parallel``  — trainers: sync allreduce DP (plus ZeRO-1
  sharded optimizer state and gradient accumulation), collective EASGD /
  Downpour, the host-async pserver/pclient fidelity mode
  (SURVEY.md §2 comps. 3, 4, 7), and the beyond-parity suite: sequence
  (ring or Ulysses), tensor (GSPMD), pipeline (GPipe/1F1B/interleaved),
  expert (top-k MoE), and the composed dp×tp×sp step.
- ``mpit_tpu.ops``       — pallas kernels (flash attention fwd+bwd,
  fused elastic update) and the sharded attention/MoE primitives.
- ``mpit_tpu.models``    — LeNet, VGG-small, AlexNet, ResNet-50, PTB
  LSTM (BASELINE.json configs 1–5), plus MLP and the transformer LM.
- ``mpit_tpu.data``      — dataset pipelines with deterministic synthetic
  fallbacks (no-network environments).
- ``mpit_tpu.utils``     — flat-parameter utilities (≡ Torch
  ``getParameters()``), config, logging, metrics, checkpointing.
"""

__version__ = "0.1.0"

import mpit_tpu.compat  # noqa: F401  (must precede any jax.shard_map use)
from mpit_tpu.comm import (  # noqa: F401
    Topology,
    init,
    finalize,
    is_initialized,
    topology,
    rank,
    size,
    process_rank,
    process_count,
    allreduce,
    allgather,
    bcast,
    barrier,
    device_barrier,
    psum,
    pmean,
    pmax,
    pmin,
    reduce_scatter,
    SUM,
    PROD,
    MAX,
    MIN,
    AVG,
)
from mpit_tpu.utils.params import (  # noqa: F401
    flatten_params,
    unflatten_params,
    FlatParamSpec,
)
