"""Tracing / profiling hooks.

Reference parity (SURVEY.md §5): the reference had only ad-hoc tic/toc timers
and prints. TPU plan from the survey: ``jax.profiler`` trace hooks plus
per-step wall-clock counters — a captured trace opens in
Perfetto/TensorBoard and shows the XLA op timeline, ICI collectives
included, which is the observability the MPI version never had.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax profiler trace into ``log_dir`` (no-op when None), so
    call sites can unconditionally wrap their hot loop."""
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named region on the host trace timeline (wrap a step or a phase)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Wall-clock timer for jitted step loops.

    Measures *completed* work: call ``stop()`` with (or after) a
    ``block_until_ready`` on the step output, otherwise async dispatch makes
    steps look free. Keeps a skip-count so compile steps don't pollute the
    stats."""

    def __init__(self, skip_first: int = 1):
        self.skip_first = skip_first
        self._times: list[float] = []
        self._seen = 0
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, result=None) -> float:
        """Blocks on ``result`` (if given), records the elapsed time.
        Returns the step's wall seconds."""
        if result is not None:
            jax.block_until_ready(result)
        if self._t0 is None:
            raise RuntimeError("StepTimer.stop() without start()")
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self._seen += 1
        if self._seen > self.skip_first:
            self._times.append(dt)
        return dt

    @property
    def mean(self) -> float:
        return sum(self._times) / len(self._times) if self._times else float("nan")

    @property
    def count(self) -> int:
        return len(self._times)

    def summary(self) -> dict:
        if not self._times:
            return {"steps": 0}
        ts = sorted(self._times)
        return {
            "steps": len(ts),
            "mean_s": self.mean,
            "p50_s": ts[len(ts) // 2],
            "max_s": ts[-1],
        }
