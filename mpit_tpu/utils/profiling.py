"""Tracing / profiling hooks.

Reference parity (SURVEY.md §5): the reference had only ad-hoc tic/toc timers
and prints. TPU plan from the survey: ``jax.profiler`` trace hooks plus
per-step wall-clock counters — a captured trace opens in
Perfetto/TensorBoard and shows the XLA op timeline, ICI collectives
included, which is the observability the MPI version never had.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax profiler trace into ``log_dir`` (no-op when None), so
    call sites can unconditionally wrap their hot loop."""
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


def annotate(name: str):
    """Named region on the host trace timeline (wrap a step or a phase)."""
    return jax.profiler.TraceAnnotation(name)


def force_completion(*results) -> float:  # mpit-analysis: host-sync-barrier
    """Proof of device execution, not just dispatch — THE one copy.

    On the axon tunnel platform ``jax.block_until_ready`` returns before
    device execution completes (round-1 bench finding: a LeNet step "timed"
    a flat ~115 µs at batch 256 AND 4096 — an impossible 2.5 PFLOP/s). The
    only trustworthy completion barrier is fetching a host value that
    data-depends on the computation's outputs.

    For EACH positional argument, the smallest floating-point leaf is
    reduced; the per-argument scalars are fused into ONE device scalar and
    fetched with a single transfer (each fetch pays a full tunnel
    round-trip). Pass the step's state and metrics as SEPARATE arguments so
    each gets its own proof leaf — a single pytree's smallest leaf is
    usually a loss scalar, which alone would not prove the state update
    finished. Non-floating leaves (ints, PRNG keys) are skipped; an
    argument with no floating leaf falls back to ``block_until_ready``
    (best effort — there is nothing fetchable to prove more).
    """
    import jax.numpy as jnp

    total = None
    for result in results:
        leaves = [
            leaf
            for leaf in jax.tree.leaves(result)
            if hasattr(leaf, "dtype")
            and jnp.issubdtype(leaf.dtype, jnp.floating)
        ]
        if not leaves:
            jax.block_until_ready(result)
            continue
        small = min(leaves, key=lambda leaf: leaf.size)
        term = jnp.sum(small).astype(jnp.float32)
        total = term if total is None else total + term
    return float(total) if total is not None else 0.0


class StepTimer:
    """Wall-clock timer for jitted step loops.

    Measures *completed* work: call ``stop()`` with (or after) a
    ``block_until_ready`` on the step output, otherwise async dispatch makes
    steps look free. Keeps a skip-count so compile steps don't pollute the
    stats."""

    def __init__(self, skip_first: int = 1):
        self.skip_first = skip_first
        self._times: list[float] = []
        self._seen = 0
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, result=None) -> float:
        """Proves completion of ``result`` (if given) via
        :func:`force_completion` — NOT ``block_until_ready``, which lies on
        this platform — then records the elapsed time. Returns the step's
        wall seconds. A tuple result (e.g. a ``(state, metrics)`` step
        output) is spread so each component gets its own proof leaf."""
        if result is not None:
            if isinstance(result, tuple):
                force_completion(*result)
            else:
                force_completion(result)
        if self._t0 is None:
            raise RuntimeError("StepTimer.stop() without start()")
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self._seen += 1
        if self._seen > self.skip_first:
            self._times.append(dt)
        return dt

    @property
    def mean(self) -> float:
        return sum(self._times) / len(self._times) if self._times else float("nan")

    @property
    def count(self) -> int:
        return len(self._times)

    def summary(self) -> dict:
        if not self._times:
            return {"steps": 0}
        ts = sorted(self._times)
        return {
            "steps": len(ts),
            "mean_s": self.mean,
            "p50_s": ts[len(ts) // 2],
            "max_s": ts[-1],
        }
