"""Virtual host-mesh provisioning — the ONE copy of the platform re-pin recipe.

A sitecustomize-registered hardware backend (axon) claims jax's platform at
interpreter start, so ``JAX_PLATFORMS``/``XLA_FLAGS`` set afterwards do not
stick on their own: the platform must be re-pinned through the config API
before the first computation, and the device-count flag must be in
``XLA_FLAGS`` before backend init.  This recipe was previously hand-rolled in
three places (tests/conftest.py, bench.py, __graft_entry__.py); any future
change to it belongs here only.

Importing this module is safe pre-backend-init: the package ``__init__`` pulls
in jax but runs no computation.
"""

import os
import re
import subprocess
import sys
from typing import Optional

_COUNT_FLAG = "--xla_force_host_platform_device_count"
# XLA:CPU collectives run one thread per virtual device and abort the whole
# process (SIGABRT, "Termination timeout ... Exiting to ensure a consistent
# program state", rendezvous.cc) if any participant misses the rendezvous
# within the default 40 s. On a loaded single-core box 8 device threads can
# legitimately take longer to all get scheduled — raise the ceiling so slow
# is slow, not dead. Observed crashing ~1/3 of suite runs on the 1-CPU rig.
_RENDEZVOUS_FLAGS = {
    # the matching warn_stuck flag is NOT registered in this jaxlib (an
    # unknown XLA_FLAGS entry is fatal), so only the termination ceiling is
    # raised. 120 s tolerates slow scheduling of N device threads on a
    # 1-core host without turning a genuine deadlock (see
    # parallel/common.bound_cpu_dispatch, the actual mitigation) into a
    # 15-minute hang.
    "--xla_cpu_collective_call_terminate_timeout_seconds": 120,
}
# Flag registration varies across jaxlib builds, and an unknown XLA_FLAGS
# entry is FATAL at backend init (parse_flags_from_env.cc aborts the
# process) — so the rendezvous flags are probed once in a throwaway
# subprocess before being adopted. The verdict is cached in the environment:
# child processes (mpit_tpu.launch ranks re-run this module) inherit it and
# skip the probe.
_PROBE_ENV = "MPIT_XLA_RENDEZVOUS_FLAGS_OK"


def _rendezvous_flags_supported() -> bool:
    cached = os.environ.get(_PROBE_ENV)
    if cached is not None:
        return cached == "1"
    flag_str = " ".join(f"{k}={v}" for k, v in _RENDEZVOUS_FLAGS.items())
    code = (
        "import os; "
        f"os.environ['XLA_FLAGS'] = {flag_str!r}; "
        "os.environ['JAX_PLATFORMS'] = 'cpu'; "
        "import jax; "
        "jax.config.update('jax_platforms', 'cpu'); "
        "jax.devices()"
    )
    rc = run_bounded(code, timeout=60, quiet=True)
    ok = rc == 0  # unknown flag -> SIGABRT; hang -> None; both mean "no"
    os.environ[_PROBE_ENV] = "1" if ok else "0"
    return ok


def run_bounded(
    code: str, timeout: float, quiet: bool = False, cwd: Optional[str] = None
) -> Optional[int]:
    """rc of ``python -c code`` bounded by ``timeout``; None on hang.

    The ONE copy of the kill-safe pattern for subprocesses that may touch a
    dead hardware backend (a child stuck in an uninterruptible syscall on
    the tunnel must not block the parent): after a kill, the reap wait is
    ALSO bounded, and an unkillable child is abandoned.
    """
    kw = (
        {"stdout": subprocess.DEVNULL, "stderr": subprocess.DEVNULL}
        if quiet
        else {}  # otherwise inherit streams: compile stalls stay visible
    )
    proc = subprocess.Popen([sys.executable, "-c", code], cwd=cwd, **kw)
    try:
        return proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass  # unkillable (D-state); abandon the zombie
        return None


def repin_platform(platform: str) -> None:
    """Re-pin jax's platform via the config API (env alone loses to a
    sitecustomize-registered backend).  Call before any jax computation —
    backend choice is sticky once initialized."""
    import jax

    os.environ["JAX_PLATFORMS"] = platform
    jax.config.update("jax_platforms", platform)


def force_virtual_devices(n: int, platform: str = "cpu") -> None:
    """Expose an ``n``-device virtual host mesh on ``platform``.

    Replaces any pre-existing device-count flag (CI images sometimes set
    one).  Call before backend init.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    for flag in (_COUNT_FLAG, *_RENDEZVOUS_FLAGS):
        flags = re.sub(flag + r"=\d+", "", flags)
    rendezvous = (
        [f"{k}={v}" for k, v in _RENDEZVOUS_FLAGS.items()]
        if _rendezvous_flags_supported()
        else []
    )
    extra = " ".join([f"{_COUNT_FLAG}={n}"] + rendezvous)
    os.environ["XLA_FLAGS"] = " ".join((flags + " " + extra).split())
    repin_platform(platform)
