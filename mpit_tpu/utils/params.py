"""Flat-parameter utilities: the TPU-native ``model:getParameters()``.

Reference parity (SURVEY.md §2 comp. 4, BASELINE.json:5): mpiT's pclient
flattened an ``nn.Module``'s parameters into one contiguous Torch storage so
the whole model moved as a single MPI buffer. The jax equivalent is
``jax.flatten_util.ravel_pytree``: one flat vector per model, with a cached
static unravel spec so flatten/unflatten round-trips stay out of the hot path
(the unravel closure is jit-traceable).

Unlike Torch's in-place storage aliasing, jax arrays are immutable — the flat
vector is a *copy*, and updates flow back through :func:`unflatten_params`.
Trainers that want zero-copy semantics simply keep the flat vector as the
source of truth and unflatten per step inside jit (XLA fuses the reshapes:
they are free at runtime).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


@dataclasses.dataclass(frozen=True)
class FlatParamSpec:
    """Static description of a flattened pytree: size + unravel closure."""

    size: int
    dtype: Any
    unravel: Callable[[jax.Array], Any]

    def __repr__(self) -> str:  # avoid printing the closure
        return f"FlatParamSpec(size={self.size}, dtype={self.dtype})"


def flatten_params(tree: Any) -> tuple[jax.Array, FlatParamSpec]:
    """Flatten a parameter pytree to one 1-D vector (≡ ``getParameters()``).

    Returns ``(flat, spec)``; ``spec.unravel(flat)`` reproduces the pytree
    with original shapes/dtypes. Safe under jit.
    """
    flat, unravel = ravel_pytree(tree)
    return flat, FlatParamSpec(size=flat.size, dtype=flat.dtype, unravel=unravel)


def unflatten_params(spec: FlatParamSpec, flat: jax.Array) -> Any:
    """Inverse of :func:`flatten_params`."""
    if flat.shape != (spec.size,):
        raise ValueError(
            f"flat vector shape {flat.shape} does not match spec ({spec.size},)"
        )
    return spec.unravel(flat)


def tree_zeros_like(tree: Any) -> Any:
    return jax.tree.map(jnp.zeros_like, tree)
