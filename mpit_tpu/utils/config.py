"""Run configuration: one dataclass + argparse, nothing heavier.

Reference parity (SURVEY.md §5): the reference's config system was a plain
Lua ``conf``/``opt`` table in ``ptest.lua`` (lr, τ, α, #servers, batch size).
Match that simplicity: a flat dataclass whose fields are the union of what
the five baseline configs need, an argparse bridge generated from the fields,
and JSON (de)serialization for reproducibility (the config is stamped into
checkpoints/metrics).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
from typing import Optional


@dataclasses.dataclass
class TrainConfig:
    # what to run
    preset: Optional[str] = None  # one of PRESETS, or None for flag-driven
    model: str = "lenet"
    dataset: str = "mnist"
    # easgd | eamsgd | downpour | sync | zero-sync | seq-sync | moe-sync |
    # pp-sync | ps-easgd | ps-eamsgd | ps-downpour (zero-sync = sync DP
    # with ZeRO-1 sharded optimizer state; eamsgd = EASGD with momentum in
    # the local optimizer, the paper's momentum variant — the alias
    # asserts momentum > 0; seq-sync = sync DP over a 2-D dp x sp mesh
    # with sequence-parallel ring attention; moe-sync = sync DP with the
    # transformer's MoE experts sharded over the worker axis; pp-sync =
    # pipeline parallelism over a dp x pp mesh, --pp-schedule
    # gpipe|1f1b|interleaved — all three transformer only)
    algo: str = "easgd"
    # optimization (reference conf table: lr, τ, α — SURVEY.md §5).
    # optimizer: sgd (the reference's; momentum applies) | adam | adamw
    # (weight_decay applies). lr_schedule: constant | cosine |
    # warmup-cosine (peak cfg.lr after warmup_steps, cosine to 0 over the
    # run's optimizer-update count). All elementwise — every trainer
    # (incl. ZeRO/MoE with their cross-leaf guards) accepts them.
    optimizer: str = "sgd"
    lr: float = 0.05
    momentum: float = 0.9
    # global-norm gradient clipping (None = off). Algos whose update runs
    # on consistent gradients get optax.clip_by_global_norm chained in;
    # moe-sync/zero-sync/pp-sync (device-varying grads inside shard_map,
    # where the chain would silently desync replicas) get the trainer's
    # mesh-correct clip_norm instead — same math, proven equal in tests
    clip_norm: Optional[float] = None
    lr_schedule: str = "constant"
    warmup_steps: int = 100
    weight_decay: float = 1e-4
    tau: int = 4
    alpha: Optional[float] = None  # None -> 0.9/W (EASGD paper rule)
    staleness: int = 0
    # exchange-collective compression for easgd/eamsgd: "none" (exact) or
    # "bf16" (halves ICI/DCN bytes per round; goptim.summed_client_diffs)
    exchange_dtype: str = "none"
    # input staging dtype: "float32" or "bf16" (halves host->device bytes
    # and first-layer HBM reads; models compute in bf16 anyway, so this
    # just moves their entry cast to the host — data.cast_input_dtype)
    input_dtype: str = "float32"
    # scale
    global_batch: int = 256
    epochs: int = 3
    train_size: int = 8192
    clients: int = 2  # ps-* algos
    servers: int = 1
    steps: int = 200  # ps-* algos: local steps per client
    transport: str = "auto"  # ps-* message plane: auto | native | inproc
    client_timeout: Optional[float] = None  # ps-* watchdog (None = hang,
    # matching the reference's dead-rank semantics)
    # stem for models with an MXU-hostile 3-channel first conv (resnet50,
    # alexnet): "conv" (textbook) or "space_to_depth" (same function,
    # MXU-friendlier input layout — mpit_tpu/ops/stem.py)
    stem: str = "conv"
    # rematerialize blocks on backward (resnet50, transformer): trades
    # ~1/3 extra FLOPs for O(1)-block activation memory — bigger batches
    # or longer sequences per chip (jax.checkpoint via flax nn.remat)
    remat: bool = False
    # sequence models
    seq_len: int = 32
    # seq-sync only: sequence-parallel extent (devices per ring; the mesh is
    # (num_devices // sp) x sp — batch axis "dp", sequence axis "sp") and
    # the scheme: "ring" (ppermute K/V rotation — extreme T) or "ulysses"
    # (all_to_all head<->sequence re-shard — moderate T, heads % sp == 0)
    sp: int = 1
    seq_impl: str = "ring"
    # pp-sync only: pipeline extent (stages; mesh (num_devices // pp) x pp),
    # microbatches per step, the schedule (gpipe | 1f1b | interleaved),
    # and virtual chunks per stage (interleaved only; layers must divide
    # by pp x pp-virtual)
    pp: int = 2
    n_micro: int = 4
    pp_schedule: str = "gpipe"
    pp_virtual: int = 2
    # transformer depth (pp-sync needs layers % pp == 0)
    layers: int = 2
    # transformer width: model dim, attention heads, FFN dim (0 -> 4x
    # d_model) — the knobs that set MXU fill; the tiny defaults match the
    # CPU-mesh tests, the ptb-transformer-large preset sets a
    # realistically-sized model (GPT-2-small shape)
    d_model: int = 128
    heads: int = 4
    d_ff: int = 0
    # sync/zero-sync: gradient accumulation — per-worker batch processed as
    # this many sequential slices, one optimizer update (exact math; no
    # model here has batch statistics). Memory knob for big batches.
    grad_accum: int = 1
    # transformer dense-attention implementation: "xla" (fused dense) or
    # "flash" (pallas tiled kernel on TPU; dense elsewhere) — the kernel
    # stays opt-in until its TPU measurement lands (ops/flash_attention)
    attn_impl: str = "xla"
    # moe-sync only: expert count (sharded over the worker axis; must be
    # divisible by it) and the GShard capacity factor
    moe_experts: int = 0
    moe_capacity_factor: float = 2.0
    # routing fidelity: top-k expert choice (1 = Switch, 2 = GShard),
    # auxiliary load-balance loss weight (GShard uses ~1e-2) and router
    # z-loss weight (ST-MoE uses ~1e-3); 0.0 = off
    moe_top_k: int = 1
    moe_balance_weight: float = 0.0
    moe_zloss_weight: float = 0.0
    # image models (ImageNet-shaped configs; smaller for CPU-mesh smoke runs)
    image_size: int = 224
    # plumbing
    seed: int = 0
    log_every: int = 0
    metrics_path: Optional[str] = None
    # input-pipeline depth: batches staged on device ahead of the running
    # step (async device_put overlaps transfer with compute); 0 = stage
    # synchronously — large-input configs (high tau x batch x resolution)
    # may need 0, since each staged group holds its full HBM footprint
    prefetch: int = 2
    ckpt_dir: Optional[str] = None
    ckpt_every: int = 0  # rounds/steps between checkpoints (0 = off)
    resume: bool = False
    profile_dir: Optional[str] = None

    def resolved_algo(self) -> str:
        """``algo`` with the eamsgd alias resolved to its protocol.

        EAMSGD is EASGD with momentum in the local optimizer (the paper's
        momentum variant; goptim.py module docstring) — same exchange
        protocol, so everything downstream dispatches on the resolved
        name. The alias's one job is asserting the momentum is actually
        on. The ONE place this rule lives; every algo consumer (run(),
        the PS path, the process examples) resolves through here.
        """
        if self.algo in ("eamsgd", "ps-eamsgd"):
            if self.momentum <= 0:
                raise ValueError(
                    f"algo={self.algo!r} requires momentum > 0 (EAMSGD is "
                    "EASGD with a momentum local optimizer); set "
                    "--momentum or use "
                    f"algo={self.algo.replace('eamsgd', 'easgd')!r}"
                )
            return self.algo.replace("eamsgd", "easgd")
        return self.algo

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "TrainConfig":
        return cls(**json.loads(s))

    @classmethod
    def parser(cls, description: str = "") -> argparse.ArgumentParser:
        """Argparse bridge: one ``--flag`` per field (underscores → dashes).

        Every flag defaults to ``argparse.SUPPRESS``, so the parsed namespace
        contains exactly the flags the user typed — "passed the default
        value" and "not passed" stay distinguishable for preset overlay."""
        p = argparse.ArgumentParser(description=description)
        for f in dataclasses.fields(cls):
            flag = "--" + f.name.replace("_", "-")
            if f.type == "bool" or isinstance(f.default, bool):
                p.add_argument(
                    flag, action="store_true", default=argparse.SUPPRESS
                )
            else:
                typ = {
                    "int": int, "float": float, "str": str,
                    "Optional[int]": int, "Optional[float]": float,
                    "Optional[str]": str,
                }.get(str(f.type), str)
                p.add_argument(flag, type=typ, default=argparse.SUPPRESS)
        return p

    @classmethod
    def from_args(cls, argv=None, description: str = "") -> "TrainConfig":
        """defaults < preset < explicitly-typed flags."""
        supplied = vars(cls.parser(description).parse_args(argv))
        cfg = cls()
        if "preset" in supplied:
            cfg = cfg.apply_preset(supplied["preset"])
        return dataclasses.replace(cfg, **supplied)

    def apply_preset(self, name: str):
        """Overlay a named baseline config on this config."""
        if name not in PRESETS:
            raise ValueError(
                f"unknown preset {name!r}; have {sorted(PRESETS)}"
            )
        return dataclasses.replace(self, preset=name, **PRESETS[name])


# The five driver-defined workload configs (BASELINE.md table; BASELINE.json
# lines 7-11). Scales are trimmed-down by default so every preset runs on the
# CPU-simulated mesh; pass bigger --train-size/--epochs on real hardware.
PRESETS: dict[str, dict] = {
    # 1: MNIST LeNet async-SGD — the reference's bundled ptest example
    "mnist-easgd": dict(
        model="lenet", dataset="mnist", algo="easgd",
        lr=0.05, momentum=0.9, tau=4, global_batch=256, epochs=3,
    ),
    # the literal 2-pclient + 1-pserver shape of the reference example
    "mnist-ps": dict(
        model="lenet", dataset="mnist", algo="ps-easgd",
        clients=2, servers=1, steps=200, tau=4, lr=0.05,
    ),
    # 2: CIFAR-10 VGG-small, sync allreduce DP, 8 workers
    "cifar-vgg-sync": dict(
        model="vgg", dataset="cifar10", algo="sync",
        lr=0.02, momentum=0.9, global_batch=256, epochs=3,
    ),
    # 3: ImageNet AlexNet, Downpour model-averaging
    "alexnet-downpour": dict(
        model="alexnet", dataset="imagenet", algo="downpour",
        lr=0.01, momentum=0.9, tau=4, staleness=1,
        global_batch=128, epochs=1, train_size=1024,
    ),
    # 4: ImageNet ResNet-50, sync allreduce (large-tensor collective stress)
    "resnet50-sync": dict(
        model="resnet50", dataset="imagenet", algo="sync",
        lr=0.1, momentum=0.9, global_batch=64, epochs=1, train_size=512,
    ),
    # 5: PTB LSTM EASGD (small frequent async updates, non-vision)
    "ptb-lstm-easgd": dict(
        model="lstm", dataset="ptb", algo="easgd",
        lr=1.0, momentum=0.0, tau=4, global_batch=128, epochs=1,
        seq_len=32,
    ),
    # beyond-parity: long-context transformer LM, sequence-parallel sync DP
    # over a dp x sp mesh (ring attention; --sp picks the ring width)
    "ptb-transformer-seq": dict(
        model="transformer", dataset="ptb", algo="seq-sync",
        lr=0.001, momentum=0.9, global_batch=32, epochs=1,
        seq_len=256, sp=1,
    ),
    # beyond-parity pipeline config: transformer over a dp x pp mesh
    # (pp=1 on one chip — staging/microbatching still exercised; the
    # multi-stage path is proven on the CPU mesh and in the dryrun)
    "ptb-transformer-pp": dict(
        model="transformer", dataset="ptb", algo="pp-sync",
        lr=0.001, momentum=0.9, global_batch=32, epochs=1,
        seq_len=256, pp=1, n_micro=4, layers=2,
    ),
    # beyond-parity MFU-ceiling config: a GPT-2-small-shaped LM whose
    # matmul dims (768/3072, T=512) actually fill the 128x128 MXU — the
    # tiny parity presets' low MFU is their 2015-era shapes, not the
    # framework; this preset is the evidence
    "ptb-transformer-large": dict(
        model="transformer", dataset="ptb", algo="seq-sync",
        optimizer="adamw", lr=3e-4, lr_schedule="warmup-cosine",
        global_batch=8, epochs=1, seq_len=512, sp=1,
        layers=6, d_model=768, heads=12,
    ),
}
