"""Checkpoint / resume for trainer states.

Reference parity (SURVEY.md §5): the reference did at most an ad-hoc
``torch.save`` of the model/center params in example scripts. This module does
the TPU-native equivalent properly: the whole trainer state pytree (params +
optimizer state + step/round counters + the EASGD center variable — resume
"must reproduce the center variable on the server role", SURVEY.md §5) is
serialized with flax's msgpack codec, written atomically (tmp + rename), with
retention of the last ``keep`` checkpoints.

Multi-host: only process 0 writes. Replicated leaves are fetched directly;
per-worker-sharded leaves are NOT fully addressable on a multi-host mesh
(``jax.device_get`` would raise), so they are explicitly all-gathered to every
process first — a collective, which is why ``save_checkpoint`` materializes
the host state on ALL processes before its process-0 gate. Every process
restores.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from typing import Any, Optional

import jax
from flax import serialization

_CKPT_RE = re.compile(r"^ckpt_(\d{8,})\.msgpack$")


def _ckpt_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"ckpt_{step:08d}.msgpack")


def _leaf_to_host(leaf):
    """Fetch one leaf to host memory.

    A worker-sharded leaf on a multi-host mesh spans devices this process
    cannot address, and ``jax.device_get`` raises on it; all-gather it to
    every process instead. The allgather is a COLLECTIVE — every process
    must reach it, so callers must map this over the full state on all
    processes before any process-0-only gating. On a single host
    (``is_fully_addressable``) it degrades to a plain ``device_get``.
    """
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return multihost_utils.process_allgather(leaf, tiled=True)
    return jax.device_get(leaf)


def state_to_host(state: Any) -> Any:
    """Materialize a (possibly sharded) state pytree as host numpy arrays.
    Collective on multi-host meshes — call from every process."""
    return jax.tree.map(_leaf_to_host, state)


def list_checkpoints(directory: str) -> list[int]:
    """Steps of all checkpoints in ``directory``, ascending."""
    if not os.path.isdir(directory):
        return []
    steps = []
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def latest_checkpoint(directory: str) -> Optional[int]:
    steps = list_checkpoints(directory)
    return steps[-1] if steps else None


def save_checkpoint(
    directory: str,
    state: Any,
    step: int,
    keep: int = 3,
    metadata: Optional[dict] = None,
) -> Optional[str]:
    """Write ``state`` (any pytree of arrays) at ``step``; prune to ``keep``.

    Returns the written path, or None on non-zero processes (which don't
    write — their state is a replica).
    """
    # collective (multi-host allgather of sharded leaves) — must precede the
    # process-0 gate or non-zero processes deadlock the gather
    host_state = state_to_host(state)
    path = None
    try:
        if jax.process_index() == 0:
            os.makedirs(directory, exist_ok=True)
            payload = serialization.to_bytes(host_state)
            path = _ckpt_path(directory, step)
            fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(payload)
                os.replace(tmp, path)  # atomic: never torn at `path`
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
            if metadata is not None:
                meta_path = os.path.join(directory, f"ckpt_{step:08d}.json")
                with open(meta_path, "w") as f:
                    json.dump({"step": step, **metadata}, f)
            for old in list_checkpoints(directory)[:-keep]:
                os.unlink(_ckpt_path(directory, old))
                meta = os.path.join(directory, f"ckpt_{old:08d}.json")
                if os.path.exists(meta):
                    os.unlink(meta)
    finally:
        # Returning before the write is globally visible would let a
        # non-zero process restore-immediately and race the file into
        # nonexistence (observed live in the 2-process integration drive):
        # the save is not "done" for ANY process until it is done for all.
        # In a finally so a process-0 write failure still releases the
        # peers (they would otherwise block in the barrier forever while
        # rank 0 raises). Assumes `directory` is on storage every process
        # can see (shared fs / GCS on a real pod).
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"mpit_ckpt_save_{step}")
    return path


def save_shard_state(path: str, state: dict) -> str:
    """Atomically write one PServer shard snapshot (msgpack dict).

    The elastic-membership recovery format (docs/ROBUSTNESS.md): the
    center array, the per-shard version counter, the ``(src, epoch)``
    dedup window, and the membership view are serialized TOGETHER, so a
    restore can never observe a center that disagrees with its dedup
    window — an applied-but-unpersisted push rolls back *with* the
    center it mutated, and its redelivery re-applies exactly once
    relative to the restored state. Same tmp+rename discipline as
    :func:`save_checkpoint`; no multi-host gating — each shard server
    is a single process writing its own file.

    Serialized with ``msgpack_serialize`` (not ``to_bytes``): the
    restore side is template-free ``msgpack_restore``, and ``to_bytes``
    first runs ``to_state_dict``, which rewrites nested lists into
    ``{"0": ...}`` index dicts that only a templated ``from_bytes``
    undoes — the dedup/membership entry lists must round-trip as lists.
    """
    payload = serialization.msgpack_serialize(state)
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        os.replace(tmp, path)  # atomic: never torn at `path`
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return path


def load_shard_state(path: str) -> dict:
    """Read a shard snapshot written by :func:`save_shard_state`."""
    with open(path, "rb") as f:
        payload = f.read()
    state = serialization.msgpack_restore(payload)
    if not isinstance(state, dict):
        raise ValueError(
            f"shard snapshot {path} is not a state dict "
            f"(got {type(state).__name__})"
        )
    return state


def restore_checkpoint(
    directory: str,
    template: Any,
    step: Optional[int] = None,
    shardings: Optional[Any] = None,
) -> tuple[Any, Optional[int]]:
    """Restore the latest (or ``step``-specific) checkpoint into the structure
    of ``template`` (the usual flax pattern: build a fresh state, then
    overwrite its leaves).

    Returns ``(state, step)``; ``(template, None)`` when no checkpoint
    exists — callers can branch on the second element to cold-start.
    ``shardings``: optional matching pytree of `jax.sharding.Sharding` to
    place restored leaves (pass the same shardings used at init so a resumed
    run keeps the worker-axis layout).
    """
    if step is None:
        step = latest_checkpoint(directory)
        if step is None:
            return template, None
    path = _ckpt_path(directory, step)
    with open(path, "rb") as f:
        payload = f.read()
    # from_bytes only needs a host pytree of the right SHAPES — the
    # template's values are discarded — so build it from leaf metadata
    # instead of state_to_host(template): that would run a whole-model
    # cross-host allgather per restore just to throw the result away.
    import numpy as np

    def _host_shaped(leaf):
        if isinstance(leaf, jax.Array):
            return np.zeros(leaf.shape, leaf.dtype)
        return leaf  # already host-side (np array / python scalar)

    state = serialization.from_bytes(
        jax.tree.map(_host_shaped, template), payload
    )
    if shardings is not None:
        state = jax.device_put(state, shardings)
    return state, step
