"""Structured per-process metrics & logging.

Reference parity (SURVEY.md §5): the reference's observability was ``print``
per rank, interleaved in the mpirun console. Here every record is one JSON
line tagged with the process index and wall-clock time, so multi-host runs
produce machine-mergeable streams (the benchmark harness consumes these), and
the console mirror keeps the reference's at-a-glance ergonomics.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Optional, TextIO

import jax


def _to_jsonable(v: Any) -> Any:
    # exact python types pass through untouched (a bool/str must not be
    # float()-coerced: float(True) and float("007") both "work")
    if isinstance(v, (str, bool, int, float, type(None), list, dict)):
        return v
    if hasattr(v, "tolist"):  # np/jax scalars and arrays, any rank
        return v.tolist()
    try:
        return float(v)  # other numeric scalar types
    except (TypeError, ValueError):
        return repr(v)


class MetricsLogger:
    """JSONL metrics stream (+ optional console mirror).

    Args:
      path: JSONL file to append to; parent dirs are created. When None,
        records go only to the console mirror.
      tag: short run identifier stamped on every record (e.g. "easgd").
      echo: also print a compact human-readable line to stderr.
      all_processes: by default only process 0 writes (replicated metrics are
        identical across processes); set True for genuinely per-process
        streams — each process should then use its own ``path``.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        tag: str = "train",
        echo: bool = True,
        all_processes: bool = False,
        _stream: Optional[TextIO] = None,
    ):
        self.tag = tag
        self.echo = echo
        self.process = jax.process_index()
        self._active = all_processes or self.process == 0
        self._f: Optional[TextIO] = _stream
        if path is not None and self._active and _stream is None:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            self._f = open(path, "a")

    def log(self, step: int, **metrics: Any) -> None:
        if not self._active:
            return
        rec = {
            "ts": round(time.time(), 3),
            "tag": self.tag,
            "process": self.process,
            "step": int(step),
            **{k: _to_jsonable(v) for k, v in metrics.items()},
        }
        if self._f is not None:
            self._f.write(json.dumps(rec) + "\n")
            self._f.flush()
        if self.echo:
            body = " ".join(
                f"{k}={v:.5g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in rec.items()
                if k not in ("ts", "tag", "process")
            )
            print(f"[{self.tag}] {body}", file=sys.stderr)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class Throughput:
    """Rolling samples/sec counter for the step loop (host-side, cheap)."""

    def __init__(self):
        self._t0: Optional[float] = None
        self._samples = 0

    def tick(self, samples: int) -> Optional[float]:
        """Record ``samples`` processed; returns current samples/sec (None on
        the first tick, which only starts the clock)."""
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = now
            return None
        self._samples += samples
        return self._samples / (now - self._t0)

    def reset(self) -> None:
        self._t0, self._samples = None, 0
