"""Utilities: flat-parameter handling, config, logging, metrics, checkpoint."""

from mpit_tpu.utils.params import (  # noqa: F401
    FlatParamSpec,
    flatten_params,
    unflatten_params,
    tree_zeros_like,
)
from mpit_tpu.utils.checkpoint import (  # noqa: F401
    save_checkpoint,
    restore_checkpoint,
    latest_checkpoint,
    list_checkpoints,
)
from mpit_tpu.utils.config import TrainConfig, PRESETS  # noqa: F401
from mpit_tpu.utils.metrics import MetricsLogger, Throughput  # noqa: F401
from mpit_tpu.utils.profiling import (  # noqa: F401
    StepTimer,
    annotate,
    force_completion,
    trace,
)
