"""Utilities: flat-parameter handling, config, logging, metrics, checkpoint."""

from mpit_tpu.utils.params import (  # noqa: F401
    FlatParamSpec,
    flatten_params,
    unflatten_params,
    tree_zeros_like,
)
