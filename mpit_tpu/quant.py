"""quant — the shared scalar quantization kernels, host and XLA paths.

One contract, two execution paths. The PS wire path
(:mod:`mpit_tpu.transport.wire`) quantizes numpy buffers on the host
before framing; the collective path (:mod:`mpit_tpu.comm.collectives`)
quantizes inside a jit'd ``shard_map`` program so the bytes that cross
the ICI/DCN links are the quantized codes, not float32. Both paths MUST
produce bit-identical codes and scales for the same input — the error-
feedback math (docs/WIRE.md) treats ``dequantize(quantize(x))`` as one
deterministic function, and a host/device disagreement would make the
residual wrong by exactly the disagreement. The equivalence is pinned in
``tests/test_wire.py`` (numpy-vs-jnp bit-equality for both modes).

Kernels (EQuARX-style, PAPERS.md arXiv:2506.17615):

- ``bf16``: round-to-nearest-even high halves of the float32 bits —
  pure bit arithmetic, scale-free, 2x byte drop;
- ``int8``: symmetric per-block absmax scaling, codes in [-127, 127],
  ``scale = absmax / 127`` computed in float32 on BOTH paths (a float64
  host division would double-round against XLA's f32), 4x byte drop.

This module imports numpy only at module scope; jax is imported lazily
inside the jnp kernels so the host wire path (and the stdlib-only
reader tools that sit behind it) never pays a jax import.
"""

from __future__ import annotations

import dataclasses
import sys

import numpy as np

_F32_SIZE = 4

QUANT_MODES = ("off", "bf16", "int8")

# on-wire bytes per quantized element (raw float32 = 4)
MODE_ITEMSIZE = {"off": 4, "bf16": 2, "int8": 1}


@dataclasses.dataclass(frozen=True)
class QuantArray:
    """A quantized float32 chunk in transit.

    ``mode`` is ``"bf16"`` (``data`` = uint16 high halves) or ``"int8"``
    (``data`` = symmetric codes in [-127, 127], ``scale`` = absmax/127).
    Pickles fine, so quantized exchange also works over the inproc
    broker and with pickle-only peers — quantization is a protocol-layer
    choice, independent of the framing."""

    mode: str
    scale: float
    data: np.ndarray

    @property
    def nbytes(self) -> int:
        """On-wire payload size (the telemetry byte counters read this
        via the same ``nbytes`` duck-type as real ndarrays): quantized
        buffer plus the header-resident scale."""
        return int(self.data.nbytes) + _F32_SIZE


# -- host (numpy) path ----------------------------------------------------


def _rt_numerics_checker():
    """The RT104 numerics sanitizer, IF some other code armed it.

    This module must stay importable with only numpy (lint.sh gate 6
    pins it jax- and analysis-free), so we never import the analysis
    package here: ``sys.modules`` is peeked for an already-imported
    ``analysis.runtime`` — exactly the processes that armed the checker
    (``MPIT_RT_NUMERICS=1`` ranks, ``checking(numerics=True)`` tests)
    have it loaded. Costs one dict lookup per quantize when unarmed."""
    rt = sys.modules.get("mpit_tpu.analysis.runtime")
    if rt is None:
        return None
    checker = rt.active_checker()
    if checker is not None and getattr(checker, "numerics", False):
        return checker
    return None


def quantize(arr: np.ndarray, mode: str) -> QuantArray:
    """Pack a float32 array into a :class:`QuantArray` (copies — the
    quantized buffer is new; the input is never aliased)."""
    a = np.ascontiguousarray(arr, dtype=np.float32)
    if mode == "bf16":
        u = a.view(np.uint32)
        # round-to-nearest-even on the dropped mantissa half; the +
        # carries into the exponent correctly for halfway cases
        data = ((u + 0x7FFF + ((u >> 16) & 1)) >> 16).astype(np.uint16)
        checker = _rt_numerics_checker()
        if checker is not None:
            checker.on_quantize("quantize", a, mode, None, data)
        return QuantArray("bf16", 1.0, data)
    if mode == "int8":
        # NaN/Inf never drive the block scale (an all-NaN chunk used to
        # poison amax and cast NaN to int8 — undefined codes); the scale
        # comes from the finite elements only, so it stays finite
        finite = np.isfinite(a)
        amax = (
            np.float32(np.max(np.where(finite, np.abs(a), np.float32(0))))
            if a.size
            else np.float32(0)
        )
        # f32 division, not float64-then-cast: the jnp path divides in
        # f32 and the two must agree to the bit (all-zero chunk: scale
        # is moot, pick 1)
        scale = amax / np.float32(127.0) if amax > 0 else np.float32(1.0)
        codes = np.clip(np.rint(a / scale), -127, 127)
        # ±Inf saturates to ±127 via the clip; NaN pins to code 0, so a
        # poisoned element dequantizes to 0 instead of garbage
        data = np.where(np.isnan(a), np.float32(0), codes).astype(np.int8)
        checker = _rt_numerics_checker()
        if checker is not None:
            checker.on_quantize("quantize", a, mode, scale, data)
        return QuantArray("int8", float(scale), data)
    raise ValueError(f"unknown quantization mode {mode!r}")


def dequantize(q: QuantArray) -> np.ndarray:
    """float32 reconstruction of a :class:`QuantArray`."""
    if q.mode == "bf16":
        data = np.ascontiguousarray(q.data, dtype=np.uint16)
        return (data.astype(np.uint32) << 16).view(np.float32)
    if q.mode == "int8":
        checker = _rt_numerics_checker()
        if checker is not None:
            checker.on_dequantize("dequantize", q.scale, q.mode)
        data = np.asarray(q.data, dtype=np.int8)
        return data.astype(np.float32) * np.float32(q.scale)
    raise ValueError(f"unknown quantization mode {q.mode!r}")


def quantize_rows(a: np.ndarray, mode: str):
    """Host twin of :func:`quantize_rows_jnp`: blockwise quantization of
    a 2-D float32 array, one absmax scale per row. Returns
    ``(codes (B, n), scales (B, 1))``, bit-identical to the jnp face on
    the same input (pinned in tests/test_wire.py) — the reference the
    RT104 sanitizer and the property suite probe without a jax import."""
    a = np.ascontiguousarray(a, dtype=np.float32)
    if a.ndim != 2:
        raise ValueError(f"quantize_rows wants a 2-D array, got {a.shape}")
    if mode == "bf16":
        return quantize(a, "bf16").data, np.ones(
            (a.shape[0], 1), np.float32
        )
    if mode == "int8":
        finite = np.isfinite(a)
        amax = np.max(
            np.where(finite, np.abs(a), np.float32(0)),
            axis=1,
            keepdims=True,
        ).astype(np.float32) if a.size else np.zeros(
            (a.shape[0], 1), np.float32
        )
        scales = np.where(
            amax > 0, amax / np.float32(127.0), np.float32(1.0)
        ).astype(np.float32)
        codes = np.clip(np.rint(a / scales), -127, 127)
        codes = np.where(np.isnan(a), np.float32(0), codes).astype(np.int8)
        checker = _rt_numerics_checker()
        if checker is not None:
            checker.on_quantize("quantize_rows", a, mode, scales, codes)
        return codes, scales
    raise ValueError(f"unknown quantization mode {mode!r}")


def dequantize_rows(codes: np.ndarray, scales, mode: str) -> np.ndarray:
    """Host twin of :func:`dequantize_rows_jnp` (scales broadcast over
    rows; ignored for bf16)."""
    if mode == "bf16":
        return dequantize(QuantArray("bf16", 1.0, codes))
    if mode == "int8":
        checker = _rt_numerics_checker()
        if checker is not None:
            checker.on_dequantize("dequantize_rows", scales, mode)
        data = np.asarray(codes, dtype=np.int8)
        return data.astype(np.float32) * np.asarray(scales, np.float32)
    raise ValueError(f"unknown quantization mode {mode!r}")


# -- device (jnp) path ----------------------------------------------------
#
# The jnp twins return (codes, scales) pairs instead of QuantArray —
# inside a traced program the scale is an array, and the collective path
# needs PER-BLOCK scales (one per destination row of the reduce-scatter)
# that a scalar-field dataclass cannot carry. ``quantize_jnp`` is the
# whole-array special case (scale shape ``()``); ``quantize_rows_jnp``
# quantizes each row of a 2-D array independently (scales ``(rows, 1)``).


def _jnp():
    import jax.numpy as jnp
    from jax import lax

    return jnp, lax


def quantize_jnp(x, mode: str):
    """jit-safe twin of :func:`quantize`: ``(codes, scale)`` for one
    array with ONE scale (f32 scalar; fixed 1.0 for bf16). Codes and
    scale are bit-identical to the numpy path on the same input."""
    jnp, lax = _jnp()
    a = jnp.asarray(x, jnp.float32)
    if mode == "bf16":
        u = lax.bitcast_convert_type(a, jnp.uint32)
        codes = ((u + 0x7FFF + ((u >> 16) & 1)) >> 16).astype(jnp.uint16)
        return codes, jnp.float32(1.0)
    if mode == "int8":
        # same NaN/Inf guards as the host path (scale from finite
        # elements only; Inf saturates, NaN pins to code 0) — the two
        # faces must stay bit-identical on ANY input, not just clean ones
        amax = (
            jnp.max(jnp.where(jnp.isfinite(a), jnp.abs(a), 0.0))
            if a.size
            else jnp.float32(0)
        )
        scale = jnp.where(amax > 0, amax / jnp.float32(127.0), 1.0)
        scale = scale.astype(jnp.float32)
        codes = jnp.clip(jnp.rint(a / scale), -127, 127)
        codes = jnp.where(jnp.isnan(a), 0.0, codes).astype(jnp.int8)
        return codes, scale
    raise ValueError(f"unknown quantization mode {mode!r}")


def dequantize_jnp(codes, scale, mode: str):
    """float32 reconstruction of a jnp ``(codes, scale)`` pair."""
    jnp, lax = _jnp()
    if mode == "bf16":
        u = codes.astype(jnp.uint32) << 16
        return lax.bitcast_convert_type(u, jnp.float32)
    if mode == "int8":
        return codes.astype(jnp.float32) * jnp.asarray(scale, jnp.float32)
    raise ValueError(f"unknown quantization mode {mode!r}")


def quantize_rows_jnp(x, mode: str):
    """Blockwise quantization of a 2-D array: each row gets its own
    absmax scale (the reduce-scatter layout — row j is the block bound
    for worker j). Returns ``(codes (B, n), scales (B, 1))``; bf16
    scales are ones (carried for shape uniformity, never sent)."""
    jnp, lax = _jnp()
    a = jnp.asarray(x, jnp.float32)
    if mode == "bf16":
        codes, _ = quantize_jnp(a, "bf16")
        return codes, jnp.ones((a.shape[0], 1), jnp.float32)
    if mode == "int8":
        amax = jnp.max(
            jnp.where(jnp.isfinite(a), jnp.abs(a), 0.0),
            axis=1,
            keepdims=True,
        )
        scale = jnp.where(amax > 0, amax / jnp.float32(127.0), 1.0)
        scale = scale.astype(jnp.float32)
        codes = jnp.clip(jnp.rint(a / scale), -127, 127)
        codes = jnp.where(jnp.isnan(a), 0.0, codes).astype(jnp.int8)
        return codes, scale
    raise ValueError(f"unknown quantization mode {mode!r}")


def dequantize_rows_jnp(codes, scales, mode: str):
    """float32 reconstruction of a blockwise pair (scales broadcast
    over rows)."""
    jnp, _ = _jnp()
    if mode == "bf16":
        return dequantize_jnp(codes, None, "bf16")
    if mode == "int8":
        return codes.astype(jnp.float32) * jnp.asarray(scales, jnp.float32)
    raise ValueError(f"unknown quantization mode {mode!r}")
