// tagged_broker — native message core for the host-async PS transport.
//
// Reference parity (SURVEY.md §2 comp. 1): the reference's only native
// component was a C binding exposing MPI's tagged send/recv surface to the
// training runtime. The TPU build's collective path needs no such shim (XLA
// *is* the native collective backend — SURVEY.md §2 native-component
// ledger), but the host-async parameter-server mode still moves tagged
// messages between actor threads; this library is that data plane in C++:
// per-rank mailboxes, MPI-style (src, tag) wildcard matching, and
// condition-variable blocking receives that run entirely outside the Python
// GIL (ctypes releases it for the duration of the call, so a blocked
// pserver recv costs the clients nothing).
//
// C ABI (for ctypes):
//   mpit_broker_create(size)                  -> handle
//   mpit_broker_send(h, src, dst, tag, p, n)  -> 0 / -1
//   mpit_broker_recv(h, rank, src, tag, t_s)  -> lease id >= 0 | -1 timeout
//                                                | -2 bad args | -3 closed
//   mpit_broker_probe(h, rank, src, tag)      -> 1 / 0 / -1
//   mpit_broker_probe_wait(h, rank, src, tag, t_s)
//                                             -> 1 found | 0 timeout
//                                                | -2 bad args | -3 closed
//   mpit_lease_info(h, lease, &src, &tag, &len)
//   mpit_lease_copy_free(h, lease, out)       -> copies payload, ends lease
//   mpit_lease_free(h, lease)                 -> drops payload, ends lease
//   mpit_broker_shutdown(h)                   -> refuse new work, wake waiters
//   mpit_broker_destroy(h)                    -> shutdown + drain + free
//
// A "lease" is a received message parked C-side until the caller has
// allocated a buffer of the right size; info -> copy_free is the two-phase
// read. Wildcards use -1 (ANY_SOURCE / ANY_TAG), matching
// mpit_tpu.transport.base.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <thread>
#include <utility>
#include <vector>

namespace {

constexpr int kAny = -1;

struct Msg {
  int src;
  int tag;
  std::vector<char> data;
};

bool Matches(const Msg& m, int src, int tag) {
  return (src == kAny || src == m.src) && (tag == kAny || tag == m.tag);
}

struct Mailbox {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<Msg> q;
};

struct Broker {
  explicit Broker(int n) : size(n), boxes(n) {}
  const int size;
  std::vector<Mailbox> boxes;  // constructed in place, never reallocated

  std::mutex lease_mu;
  int64_t next_lease = 0;
  std::map<int64_t, Msg> leases;

  // shutdown protocol: destroy() flips `shutting_down`, wakes every waiter,
  // and spins until `ops` (in-flight API calls) drains before deleting —
  // otherwise a thread parked in cv.wait would be left waiting on a freed
  // condvar (use-after-free). `ops` must be each call's LAST broker access.
  std::atomic<bool> shutting_down{false};
  std::atomic<int> ops{0};
};

// RAII in-flight-call marker; the destructor's decrement is the final
// touch of broker state on every API path.
struct OpGuard {
  explicit OpGuard(Broker* broker) : b(broker) { b->ops.fetch_add(1); }
  ~OpGuard() { b->ops.fetch_sub(1); }
  Broker* b;
};

// Pop the first message in arrival order matching (src, tag); caller holds
// box.mu. Returns true and moves the message out on a hit.
bool TakeMatch(Mailbox& box, int src, int tag, Msg* out) {
  for (auto it = box.q.begin(); it != box.q.end(); ++it) {
    if (Matches(*it, src, tag)) {
      *out = std::move(*it);
      box.q.erase(it);
      return true;
    }
  }
  return false;
}

}  // namespace

extern "C" {

void* mpit_broker_create(int size) {
  if (size < 1) return nullptr;
  return new Broker(size);
}

// Phase 1 of teardown: refuse new work and wake every parked receiver
// (they return -3). Does NOT free — the caller drains its in-flight calls
// first, then calls destroy. Splitting the phases lets the Python wrapper
// close the entry/increment race entirely on its side: it gates every API
// call behind its own counter, flips "closing" (no new entries), calls
// shutdown, waits for its counter to hit zero, and only then destroys.
void mpit_broker_shutdown(void* h) {
  auto* b = static_cast<Broker*>(h);
  if (b == nullptr) return;
  b->shutting_down.store(true);
  for (Mailbox& box : b->boxes) {
    // notify under the lock: a waiter between its predicate check and its
    // sleep would otherwise miss the wakeup forever
    std::lock_guard<std::mutex> g(box.mu);
    box.cv.notify_all();
  }
}

// Phase 2: free. The `ops` drain is defense in depth — the wrapper already
// guarantees quiescence (see shutdown above); `ops` alone cannot, since a
// caller holding the raw handle may sit between its null-check and its
// OpGuard increment when the spin loop reads zero.
void mpit_broker_destroy(void* h) {
  auto* b = static_cast<Broker*>(h);
  if (b == nullptr) return;
  mpit_broker_shutdown(h);
  while (b->ops.load() != 0) {
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
  delete b;
}

int mpit_broker_send(void* h, int src, int dst, int tag, const char* data,
                     uint64_t len) {
  auto* b = static_cast<Broker*>(h);
  if (b == nullptr || src < 0 || src >= b->size || dst < 0 || dst >= b->size)
    return -1;
  OpGuard op(b);
  if (b->shutting_down.load()) return -3;
  Msg m{src, tag, std::vector<char>(data, data + len)};
  Mailbox& box = b->boxes[dst];
  {
    std::lock_guard<std::mutex> g(box.mu);
    box.q.push_back(std::move(m));
  }
  // notify_all, not _one: concurrent receivers may wait on different
  // (src, tag) filters and the woken one is not necessarily the match
  box.cv.notify_all();
  return 0;
}

int64_t mpit_broker_recv(void* h, int rank, int src, int tag,
                         double timeout_s) {
  auto* b = static_cast<Broker*>(h);
  if (b == nullptr || rank < 0 || rank >= b->size) return -2;
  OpGuard op(b);
  Mailbox& box = b->boxes[rank];
  Msg m;
  bool got = false;
  {
    std::unique_lock<std::mutex> lk(box.mu);
    auto ready = [&] {
      return b->shutting_down.load() || (got = TakeMatch(box, src, tag, &m));
    };
    if (timeout_s < 0) {
      box.cv.wait(lk, ready);
    } else {
      auto dur = std::chrono::duration<double>(timeout_s);
      if (!box.cv.wait_for(lk, dur, ready)) return -1;
    }
  }
  if (!got) return -3;  // woken by shutdown
  std::lock_guard<std::mutex> g(b->lease_mu);
  int64_t id = b->next_lease++;
  b->leases.emplace(id, std::move(m));
  return id;
}

int mpit_broker_probe(void* h, int rank, int src, int tag) {
  auto* b = static_cast<Broker*>(h);
  if (b == nullptr || rank < 0 || rank >= b->size) return -1;
  OpGuard op(b);
  if (b->shutting_down.load()) return -1;
  Mailbox& box = b->boxes[rank];
  std::lock_guard<std::mutex> g(box.mu);
  for (const Msg& m : box.q) {
    if (Matches(m, src, tag)) return 1;
  }
  return 0;
}

// Blocking probe (MPI_Probe parity): park until a matching message is
// available WITHOUT consuming it. timeout_s < 0 blocks indefinitely.
// Returns 1 found, 0 timeout, -2 bad args, -3 woken by shutdown.
int mpit_broker_probe_wait(void* h, int rank, int src, int tag,
                           double timeout_s) {
  auto* b = static_cast<Broker*>(h);
  if (b == nullptr || rank < 0 || rank >= b->size) return -2;
  OpGuard op(b);
  Mailbox& box = b->boxes[rank];
  bool found = false;
  {
    std::unique_lock<std::mutex> lk(box.mu);
    auto ready = [&] {
      if (b->shutting_down.load()) return true;
      for (const Msg& m : box.q) {
        if (Matches(m, src, tag)) {
          found = true;
          return true;
        }
      }
      return false;
    };
    if (timeout_s < 0) {
      box.cv.wait(lk, ready);
    } else {
      auto dur = std::chrono::duration<double>(timeout_s);
      if (!box.cv.wait_for(lk, dur, ready)) return 0;
    }
  }
  return found ? 1 : -3;
}

// Drop a parked lease without copying its payload — the error-path cleanup
// for a receiver that failed between recv and copy_free (otherwise the
// message would sit in the lease map for the broker's lifetime).
int mpit_lease_free(void* h, int64_t lease) {
  auto* b = static_cast<Broker*>(h);
  if (b == nullptr) return -1;
  OpGuard op(b);
  std::lock_guard<std::mutex> g(b->lease_mu);
  return b->leases.erase(lease) != 0 ? 0 : -1;
}

int mpit_lease_info(void* h, int64_t lease, int* src, int* tag,
                    uint64_t* len) {
  auto* b = static_cast<Broker*>(h);
  if (b == nullptr) return -1;
  OpGuard op(b);
  std::lock_guard<std::mutex> g(b->lease_mu);
  auto it = b->leases.find(lease);
  if (it == b->leases.end()) return -1;
  *src = it->second.src;
  *tag = it->second.tag;
  *len = it->second.data.size();
  return 0;
}

int mpit_lease_copy_free(void* h, int64_t lease, char* out) {
  auto* b = static_cast<Broker*>(h);
  if (b == nullptr) return -1;
  OpGuard op(b);
  Msg m;
  {
    std::lock_guard<std::mutex> g(b->lease_mu);
    auto it = b->leases.find(lease);
    if (it == b->leases.end()) return -1;
    m = std::move(it->second);
    b->leases.erase(it);
  }
  if (!m.data.empty()) std::memcpy(out, m.data.data(), m.data.size());
  return 0;
}

}  // extern "C"
