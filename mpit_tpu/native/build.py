"""Build helper for the native library (g++ → shared object, cached).

The reference shipped its native binding through luarocks/CMake (SURVEY.md
§2 comp. 2). Here the native surface is small enough that the build is one
compiler invocation, done lazily on first import and cached next to the
source; ``make -C mpit_tpu/native`` (see Makefile) does the same thing
explicitly. No toolchain → ``NativeUnavailable``, and callers fall back to
the pure-Python broker.
"""

from __future__ import annotations

import os
import shutil
import subprocess

from mpit_tpu.analysis.runtime import make_lock

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "src", "tagged_broker.cpp")
LIB = os.path.join(_DIR, "_libmpit_native.so")

_build_lock = make_lock("native.build._build_lock")


class NativeUnavailable(RuntimeError):
    """No compiled library and no way to build one."""


def ensure_built(force: bool = False) -> str:
    """Return the path to the compiled library, building it if missing or
    older than the source. Raises :class:`NativeUnavailable` when neither a
    library nor a compiler is available."""
    with _build_lock:
        have_src = os.path.exists(SRC)
        have_lib = os.path.exists(LIB)
        if (
            not force
            and have_lib
            and (not have_src
                 or os.path.getmtime(LIB) >= os.path.getmtime(SRC))
        ):
            return LIB
        cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
        if cxx is not None and shutil.which(cxx) is None:
            cxx = None  # $CXX points at nothing runnable
        if cxx is None or not have_src:
            if have_lib:
                return LIB  # stale but present beats nothing
            if not have_src:
                raise NativeUnavailable(
                    f"missing source {SRC} and no prebuilt library"
                )
            raise NativeUnavailable(
                "no C++ compiler found (set $CXX) and no prebuilt "
                f"{os.path.basename(LIB)}"
            )
        # per-process tmp: two processes may build concurrently (the lock is
        # thread-local); each promotes atomically, last writer wins whole
        tmp = f"{LIB}.{os.getpid()}.tmp"
        cmd = [
            cxx, "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            "-o", tmp, SRC,
        ]
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, text=True, timeout=120
            )
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
                OSError) as e:
            stderr = getattr(e, "stderr", "") or ""
            raise NativeUnavailable(
                f"native build failed: {' '.join(cmd)}\n{stderr}"
            ) from e
        os.replace(tmp, LIB)
        return LIB
