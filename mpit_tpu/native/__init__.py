"""mpit_tpu.native — C++ message core for the host-async PS transport.

Reference parity (SURVEY.md §2 comps. 1-2 and the native-component ledger):
the reference's one native component was a C extension binding MPI's tagged
send/recv to the training runtime, built by rockspec/CMake. The TPU
collective path replaces that with XLA itself; *this* package is the native
equivalent for the part of the MPI surface XLA does not cover — the PS
protocol's tagged, wildcard-matched, blocking message exchange. C++ owns the
mailboxes, matching, and condvar blocking (`src/tagged_broker.cpp`); Python
binds it with ctypes (no pybind11 in this image) behind the exact
:class:`mpit_tpu.transport.Transport` interface, so ``PServer``/``PClient``
run unchanged on either broker. Blocking recvs release the GIL for their
full duration — concurrent pserver/pclient threads genuinely overlap.
"""

from __future__ import annotations

import contextlib
import ctypes
import pickle
from typing import Any, Optional

from mpit_tpu.analysis.runtime import make_condition
from mpit_tpu.native.build import LIB, NativeUnavailable, ensure_built
from mpit_tpu.transport.base import (
    ANY_SOURCE,
    ANY_TAG,
    Message,
    RecvTimeout,
    Transport,
)
from mpit_tpu.transport.socket_transport import WIRE_PICKLE_PROTOCOL

__all__ = [
    "NativeBroker",
    "NativeTransport",
    "NativeUnavailable",
    "is_available",
    "ensure_built",
    "LIB",
]

_lib: Optional[ctypes.CDLL] = None


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(ensure_built())
        lib.mpit_broker_create.argtypes = [ctypes.c_int]
        lib.mpit_broker_create.restype = ctypes.c_void_p
        lib.mpit_broker_shutdown.argtypes = [ctypes.c_void_p]
        lib.mpit_broker_destroy.argtypes = [ctypes.c_void_p]
        lib.mpit_broker_send.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_uint64,
        ]
        lib.mpit_broker_send.restype = ctypes.c_int
        lib.mpit_broker_recv.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_double,
        ]
        lib.mpit_broker_recv.restype = ctypes.c_int64
        lib.mpit_broker_probe.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.mpit_broker_probe.restype = ctypes.c_int
        lib.mpit_broker_probe_wait.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_double,
        ]
        lib.mpit_broker_probe_wait.restype = ctypes.c_int
        lib.mpit_lease_free.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.mpit_lease_free.restype = ctypes.c_int
        lib.mpit_lease_info.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.mpit_lease_info.restype = ctypes.c_int
        lib.mpit_lease_copy_free.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p,
        ]
        lib.mpit_lease_copy_free.restype = ctypes.c_int
        _lib = lib
    return _lib


def is_available() -> bool:
    """True when the native library exists (or can be built) AND loads.

    This is a capability probe feeding the transport="auto" fallback, so it
    swallows *any* failure — a wrong-arch prebuilt .so (OSError from CDLL),
    a broken $CXX, missing sources — not just NativeUnavailable."""
    try:
        _load()
        return True
    except Exception:
        return False


class NativeBroker:
    """size-rank broker backed by the C++ library (same surface as
    :class:`mpit_tpu.transport.Broker`)."""

    def __init__(self, size: int):
        if size < 1:
            raise ValueError("broker needs at least one rank")
        self._lib = _load()
        self.size = size
        self._h = self._lib.mpit_broker_create(size)
        if not self._h:
            raise RuntimeError("mpit_broker_create failed")
        # close() protocol: every C call runs inside _op(), counted under
        # _cv's lock; close() flips _closing (no new entries), wakes parked
        # receivers via C-side shutdown, waits for the count to drain, and
        # only then frees the C object — so no thread can ever touch a
        # dangling handle (the C-side ops counter alone cannot guarantee
        # that; see tagged_broker.cpp teardown comments).
        self._cv = make_condition("NativeBroker._cv")
        self._active = 0
        self._closing = False

    def transports(self) -> list["NativeTransport"]:
        return [NativeTransport(self, r) for r in range(self.size)]

    # internal ops used by NativeTransport ---------------------------------

    @contextlib.contextmanager
    def _op(self):
        with self._cv:
            if self._closing:
                raise RuntimeError("native broker closed")
            self._active += 1
        try:
            yield
        finally:
            with self._cv:
                self._active -= 1
                self._cv.notify_all()

    def _send(self, src: int, dst: int, tag: int, payload: Any) -> None:
        if not 0 <= dst < self.size:
            raise ValueError(f"dst {dst} out of range [0, {self.size})")
        # same pin as the socket wire: both brokers serve one protocol,
        # and a drifted writer corrupts frames for mixed-version peers
        blob = pickle.dumps(payload, protocol=WIRE_PICKLE_PROTOCOL)
        with self._op():
            rc = self._lib.mpit_broker_send(
                self._h, src, dst, tag, blob, len(blob)
            )
        if rc != 0:
            raise RuntimeError(f"native send failed (rc={rc})")

    def _recv(
        self, rank: int, src: int, tag: int, timeout: Optional[float]
    ) -> Message:
        t = -1.0 if timeout is None else float(timeout)
        with self._op():
            lease = self._lib.mpit_broker_recv(self._h, rank, src, tag, t)
            if lease >= 0:
                # any failure between acquiring the lease and copy_free must
                # drop the lease C-side, or the parked message leaks for the
                # broker's lifetime (copy_free is the only other release)
                try:
                    m_src = ctypes.c_int()
                    m_tag = ctypes.c_int()
                    m_len = ctypes.c_uint64()
                    if self._lib.mpit_lease_info(
                        self._h, lease, ctypes.byref(m_src),
                        ctypes.byref(m_tag), ctypes.byref(m_len),
                    ) != 0:
                        raise RuntimeError("native lease vanished")
                    buf = ctypes.create_string_buffer(max(m_len.value, 1))
                    if self._lib.mpit_lease_copy_free(
                        self._h, lease, buf
                    ) != 0:
                        raise RuntimeError("native lease copy failed")
                except BaseException:
                    self._lib.mpit_lease_free(self._h, lease)
                    raise
        if lease == -1:
            raise RecvTimeout(
                f"no message from src={src} tag={tag} within {timeout}s"
            )
        if lease == -3:
            raise RuntimeError("native broker closed during recv")
        if lease < 0:
            raise RuntimeError(f"native recv failed (rc={lease})")
        payload = (
            pickle.loads(buf.raw[: m_len.value]) if m_len.value else None
        )
        return Message(
            src=m_src.value, dst=rank, tag=m_tag.value, payload=payload
        )

    def _probe(
        self, rank: int, src: int, tag: int, timeout: Optional[float] = 0
    ) -> bool:
        if timeout == 0:
            with self._op():
                rc = self._lib.mpit_broker_probe(self._h, rank, src, tag)
            if rc < 0:
                raise RuntimeError(f"native probe failed (rc={rc})")
            return bool(rc)
        t = -1.0 if timeout is None else float(timeout)
        with self._op():
            rc = self._lib.mpit_broker_probe_wait(self._h, rank, src, tag, t)
        if rc == -3:
            raise RuntimeError("native broker closed during probe")
        if rc < 0:
            raise RuntimeError(f"native probe_wait failed (rc={rc})")
        return bool(rc)

    def close(self) -> None:
        """Idempotent; safe while receivers are parked in recv (they are
        woken and raise 'broker closed')."""
        with self._cv:
            if self._closing:
                return
            self._closing = True
            h = self._h
        if h:
            self._lib.mpit_broker_shutdown(h)
            with self._cv:
                while self._active:
                    self._cv.wait()
                self._h = None
            self._lib.mpit_broker_destroy(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeTransport(Transport):
    """One rank's endpoint on a :class:`NativeBroker` (drop-in for
    :class:`InProcTransport`)."""

    def __init__(self, broker: NativeBroker, rank: int):
        self._broker = broker
        self.rank = rank
        self.size = broker.size

    def send(self, dst: int, tag: int, payload: Any) -> None:
        self._broker._send(self.rank, dst, tag, payload)

    def recv(
        self,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = None,
    ) -> Message:
        return self._broker._recv(self.rank, src, tag, timeout)

    def probe(
        self,
        src: int = ANY_SOURCE,
        tag: int = ANY_TAG,
        timeout: Optional[float] = 0,
    ) -> bool:
        return self._broker._probe(self.rank, src, tag, timeout)
