"""pserver — host-async parameter-server actor (fidelity mode).

Reference parity (SURVEY.md §2 comp. 3, §3(c)): the reference's ``pserver``
held the center parameter vector as a flat tensor and ran a blocking
``Recv(ANY_SOURCE)`` loop, dispatching on message tag (fetch / push / stop).
This is that actor, TPU-style: the center lives in host memory as a numpy
chunk (device arrays would pin a chip per server for no benefit — the server
does O(bytes) axpy work, which is memory-bound host arithmetic), clients'
compute stays jit-compiled on device, and the protocol runs over
``mpit_tpu.transport`` (threads in-process, TCP across hosts).

Sharding: with S servers, the flat parameter vector is split into S
contiguous chunks (``np.array_split`` boundaries); server s owns chunk s —
the reference's worker→server mapping generalized to BASELINE.json:9's
"16 workers / 4 pservers" config.

Protocol tags (client → server unless noted):
  FETCH       (attempt_id|None)  server replies PARAM to requester
  PUSH_EASGD  (envelope)         center += alpha * (x_chunk - center)
  PUSH_DELTA  (envelope)         center += server_lr * delta_chunk
  PARAM       ((attempt_id, version, chunk) | chunk)  server → client reply
  STOP        ()                 client detaches; server exits when all did
  HEARTBEAT   ()                 liveness only (refreshes the watchdog)
  JOIN        ((attempt_id, epoch))  membership handshake; server registers
                                 the (rank, epoch) pair in its elastic
                                 membership view and replies PARAM exactly
                                 like a FETCH would
  LEAVE       ()                 planned departure (preemption notice) —
                                 the rank stops counting toward teardown
                                 without waiting for the watchdog
  SHARD_MAP   ((ring_version, members))  new ring view (sharded mode,
                                 docs/ROBUSTNESS.md "Shard ownership &
                                 resharding"): the server hands off shards
                                 it no longer owns and marks newly-owned
                                 ones pending; stale/duplicate views
                                 (ring_version <= current) are idempotently
                                 ignored
  RESHARD     ((ring_version, shard, shard_version, chunk, dedup))
                                 server -> server slice handoff: the new
                                 owner materializes the shard at its static
                                 layout slot and absorbs the sender's dedup
                                 window so exactly-once survives the move

Fault-tolerant envelopes (docs/ROBUSTNESS.md): a FETCH carrying an
``attempt_id`` gets it echoed in the PARAM reply, so a client whose
earlier attempt timed out can discard the stale reply instead of
mis-assembling chunks across attempts. A push envelope is ``(epoch, seq,
basis_version, chunk)``: ``seq`` is the client's per-push counter and
``epoch`` its per-instance identity, deduplicated server-side in a
sliding window so a duplicated/retransmitted push applies **exactly
once** (rejects counted in ``counts["dup_dropped"]``); a *replacement*
client on a reused rank has a fresh epoch, so its restarted seq stream
is not mistaken for replays of its predecessor's.
``basis_version`` is the training-dynamics plane
(docs/OBSERVABILITY.md "dynamics"): the server keeps a monotonic
``version`` counter over its center chunk, bumped once per applied
push and stamped into every attempt-id'd PARAM reply; the client
echoes the version it last fetched into its push envelopes, so the
server can journal per-push **staleness** — how many other updates
landed between this client's fetch and its push applying, the
asynchrony quantity the EASGD analysis bounds. Both the
``(epoch, seq, chunk)`` 3-tuple and bare payloads (no envelope) keep
working — legacy envelopes just carry no basis, so their pushes apply
without a staleness record. A frame
mangled on the wire (chaos ``corrupt``/``truncate`` — a
``CorruptedPayload`` marker or a wrong-shape chunk) is dropped whole and
counted in ``counts["malformed_dropped"]``; it never consumes a dedup
slot and never reaches the apply path.

Failure detection (a do-better over the reference — SURVEY.md §5: 'a dead
rank hangs the job'): with ``client_timeout`` set, the server runs a
watchdog over per-client last-activity times; a client silent for longer
than the timeout is declared dead and no longer blocks teardown. Any
message — including the zero-cost HEARTBEAT a PClient can emit from a timer
thread during long local compute — refreshes liveness, and a late message
from a declared-dead client revives it.

Elastic membership + checkpointed recovery (docs/ROBUSTNESS.md "Elastic
membership"): JOIN/REJOIN/LEAVE envelopes drive the
:class:`~mpit_tpu.parallel.elastic.ElasticMembership` view, so a
replacement process on a killed rank re-enters the run mid-flight
instead of staying in ``dead_clients`` forever. With a non-``.npy``
``ckpt_path``, :meth:`persist` writes a full shard snapshot (center +
version + restart generation + dedup window + membership, one atomic
msgpack file via ``utils/checkpoint.save_shard_state``) instead of the
legacy bare-center ``np.save``; a restarted server restores all of it,
so acked pushes are never double-applied across the restart (the dedup
window rolls back exactly as far as the center does) and the PARAM
version counter resumes monotone within the bumped generation ``gen``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Sequence

import numpy as np

from mpit_tpu.analysis.runtime import (
    make_lock,
    note as _rt_note,
    note_numeric_array as _rt_numeric,
)
from mpit_tpu.comm.topology import HashRing
from mpit_tpu.obs.live import M_STALENESS, live_registry
from mpit_tpu.parallel.elastic import ElasticMembership
from mpit_tpu.transport import (
    ANY_SOURCE,
    ANY_TAG,
    CorruptedPayload,
    RecvTimeout,
    Transport,
)
from mpit_tpu.transport.wire import (
    QuantArray,
    dequantize,
    quant_mode_from_env,
    quantize,
)

# mpit-analysis: protocol-role[server->client]
# (this module IS the server side of the PS wire protocol; the MPT008
# cross-module pass pairs every tag below against the client role's
# send/recv pattern in pclient.py / ps_roles.py)
TAG_FETCH = 1
TAG_PUSH_EASGD = 2
TAG_PUSH_DELTA = 3
TAG_PARAM = 4
TAG_STOP = 5
TAG_HEARTBEAT = 6
TAG_JOIN = 7
TAG_LEAVE = 8
TAG_SHARD_MAP = 9
TAG_RESHARD = 10


class _DedupWindow:
    """Per-(src, epoch) sliding window of seen push sequence numbers.

    ``admit`` is True exactly once per (src, epoch, seq): a retransmitted
    or chaos-duplicated push is rejected. A seq at or below ``high -
    size`` is *also* rejected — outside the window we can no longer tell
    a stale retransmit from a fresh push, and at-most-once is the safe
    side of that ambiguity (the client treats a lost push as a skipped
    round, never as corruption). Single-threaded by design: only the
    server's recv loop touches it."""

    def __init__(self, size: int = 1024):
        if size < 1:
            raise ValueError("dedup window size must be >= 1")
        self.size = size
        self._high: dict[tuple[int, int], int] = {}
        self._seen: dict[tuple[int, int], set[int]] = {}

    def admit(self, src: int, epoch: int, seq: int) -> bool:
        key = (src, epoch)
        high = self._high.get(key, 0)
        seen = self._seen.setdefault(key, set())
        if seq <= high - self.size or seq in seen:
            return False
        seen.add(seq)
        if seq > high:
            self._high[key] = seq
            if len(seen) > self.size:
                floor = seq - self.size
                self._seen[key] = {s for s in seen if s > floor}
        return True

    def absorb(self, entries) -> None:
        """Merge another window's :meth:`state` into this one (shard
        handoff): per (src, epoch) the high-water mark takes the max and
        the seen sets union, so a push the old owner already applied is
        still rejected by the new owner after the shard moves — the
        exactly-once guarantee travels WITH the shard, not with the
        server that happened to hold it."""
        for src, epoch, high, seen in entries:
            key = (int(src), int(epoch))  # mpit-analysis: ignore[MPT005]
            self._high[key] = max(self._high.get(key, 0), int(high))  # mpit-analysis: ignore[MPT005]
            s = self._seen.setdefault(key, set())
            s.update(int(x) for x in seen)  # mpit-analysis: ignore[MPT005]

    def state(self) -> list:
        """Snapshot as plain msgpack-friendly lists: one
        ``[src, epoch, high, sorted(seen)]`` entry per (src, epoch)."""
        return [
            [src, epoch, self._high.get((src, epoch), 0), sorted(seen)]
            for (src, epoch), seen in sorted(self._seen.items())
        ]

    def load_state(self, entries) -> None:
        """Restore from :meth:`state` output (int casts: msgpack hands
        back whatever width it stored)."""
        self._high.clear()
        self._seen.clear()
        # msgpack ints, not device scalars: cold restore path
        for src, epoch, high, seen in entries:
            key = (int(src), int(epoch))  # mpit-analysis: ignore[MPT005]
            self._high[key] = int(high)  # mpit-analysis: ignore[MPT005]
            self._seen[key] = {int(s) for s in seen}  # mpit-analysis: ignore[MPT005]


def partition_bounds(total: int, num_servers: int) -> list[tuple[int, int]]:
    """Contiguous chunk [start, end) per server (np.array_split boundaries:
    the first ``total % num_servers`` chunks get one extra element)."""
    q, r = divmod(total, num_servers)
    bounds, start = [], 0
    for i in range(num_servers):
        s = q + (1 if i < r else 0)
        bounds.append((start, start + s))
        start += s
    return bounds


class PServer:
    """One parameter-server actor owning a chunk of the flat center vector.

    Run ``start()`` in its own thread/process; it blocks in the recv loop
    until every expected client sent STOP (the reference's teardown,
    SURVEY.md §3(e)).
    """

    def __init__(
        self,
        transport: Transport,
        center_chunk: np.ndarray,
        num_clients: int,
        alpha: float = 0.5,
        server_lr: float = 1.0,
        client_ranks: Optional[Sequence[int]] = None,
        client_timeout: Optional[float] = None,
        ckpt_path: Optional[str] = None,
        ckpt_every: Optional[int] = 100,
        dedup_window: int = 1024,
        quant: Optional[str] = None,
        shard_map=None,
    ):
        """``client_timeout``: seconds of per-client silence before the
        watchdog declares it dead (requires ``client_ranks``); None keeps
        the reference's wait-forever semantics.

        ``ckpt_path``: elastic recovery (SURVEY.md §5 — optional
        do-better; the reference loses the center with the process).
        When set, the center chunk is persisted atomically every
        ``ckpt_every`` center updates (``None`` = only at clean
        teardown) and at clean teardown; a server constructed with an
        existing file RESTORES it (``self.restored``) instead of taking
        ``center_chunk``, so a restarted server resumes where the dead
        one left off. A shape mismatch (different model or server count)
        fails loudly — re-chunking across topologies is a layout change,
        not a resume.

        ``shard_map``: a :class:`~mpit_tpu.comm.topology.ShardMap` opts
        this server into consistent-hash sharded ownership
        (docs/ROBUSTNESS.md "Shard ownership & resharding"):
        ``center_chunk`` must be the ascending concatenation of the
        shards the map assigns to ``transport.rank``, pushes/fetches
        carry per-shard parts, and TAG_SHARD_MAP / TAG_RESHARD move
        ownership live. ``None`` keeps the legacy single contiguous
        chunk."""
        self.transport = transport
        self.center = np.array(center_chunk, dtype=np.float32, copy=True)
        self._shard_map = shard_map
        # sharded-ownership state: `_owned` is the ascending
        # (sid, start, end) list of MATERIALIZED shards backing
        # self.center; `_pending` are shards the current ring assigns
        # here whose data has not arrived yet (via TAG_RESHARD from the
        # old owner, or adopted from the first full EASGD push) — a
        # pending shard occupies no memory, which is what keeps the
        # reshard peak at old-slice + incoming-slice
        self._owned: list[tuple[int, int, int]] = []
        self._pending: dict[int, tuple[int, int]] = {}
        # per-shard monotonic update counters (dynamics plane): bumped
        # with every applied part, stamped into sharded PARAM replies so
        # staleness stays attributable per shard across ownership moves
        self.shard_versions: dict[int, int] = {}
        if shard_map is not None:
            self._owned = list(shard_map.ranges_for(transport.rank))
            owned_size = sum(e - s for _, s, e in self._owned)
            if self.center.size != owned_size:
                raise ValueError(
                    f"center_chunk has {self.center.size} elements but the "
                    f"shard map assigns {owned_size} to rank "
                    f"{transport.rank}"
                )
            self.shard_versions = {sid: 0 for sid, _, _ in self._owned}
        self.num_clients = num_clients
        self.alpha = float(alpha)
        self.server_lr = float(server_lr)
        self.client_ranks = (
            list(client_ranks) if client_ranks is not None else None
        )
        if client_timeout is not None:
            if self.client_ranks is None:
                raise ValueError("client_timeout requires client_ranks")
            if client_timeout <= 0:
                raise ValueError(
                    "client_timeout must be positive (use None to disable)"
                )
        self.client_timeout = client_timeout
        # opt-in quantized PARAM replies (MPIT_WIRE_QUANT, docs/WIRE.md):
        # only attempt-id'd fetches get a quantized snapshot — an un-id'd
        # FETCH is by definition a legacy client, which may predate
        # QuantArray entirely
        if quant is None:
            quant = quant_mode_from_env()
        elif quant not in ("off", "bf16", "int8"):
            raise ValueError(f"quant must be off|bf16|int8, got {quant!r}")
        self.quant = quant
        self.counts = {"fetch": 0, "push_easgd": 0, "push_delta": 0,
                       "heartbeat": 0, "join": 0, "leave": 0,
                       "dup_dropped": 0, "malformed_dropped": 0,
                       "shard_map": 0, "reshard": 0, "handoff_sent": 0,
                       "adopted_shards": 0, "misrouted_parts": 0}
        # training-dynamics plane (docs/OBSERVABILITY.md "dynamics"):
        # monotonic center-update version — bumped per applied push,
        # stamped into attempt-id'd PARAM replies, echoed back by
        # clients as the fetch basis of their push envelopes
        self.version = 0
        # restart generation: bumped on every snapshot restore; stamped
        # into param_version journal records so `obs dynamics` and TC204
        # judge version monotonicity within a generation (a restore may
        # legitimately roll the counter back to the persisted value)
        self.gen = 0
        # per-src staleness accounting {src: {pushes, sum, max}} for
        # versioned pushes only (legacy envelopes carry no basis)
        self.staleness_by_src: dict[int, dict[str, int]] = {}
        self._dedup = _DedupWindow(dedup_window)
        self._membership = ElasticMembership(num_clients, client_ranks)
        # aliases into the membership view: the watchdog, the STOP
        # branch, trainers, and tests all mutate/read these sets
        # directly, and membership keeps owning the same objects
        self.dead_clients = self._membership.dead
        self._stopped = self._membership.stopped
        self.error: Optional[BaseException] = None
        self._lock = make_lock("PServer._lock")
        if ckpt_every is not None and ckpt_every < 1:
            raise ValueError(
                "ckpt_every must be >= 1 (None = persist only at teardown)"
            )
        self.ckpt_path = ckpt_path
        self.ckpt_every = None if ckpt_every is None else int(ckpt_every)
        self._updates_since_save = 0
        self.restored = False
        if ckpt_path is not None and os.path.exists(ckpt_path):
            with open(ckpt_path, "rb") as f:
                magic = f.read(6)
            if magic == b"\x93NUMPY":
                # legacy bare-center snapshot (ps_trainer's center_<r>.npy)
                with open(ckpt_path, "rb") as f:
                    saved = np.load(f)
                if saved.shape != self.center.shape:
                    raise ValueError(
                        f"persisted center chunk {ckpt_path!r} has shape "
                        f"{saved.shape}, this server owns "
                        f"{self.center.shape} — resuming across a "
                        "model/server-count change is not supported"
                    )
                self.center = saved.astype(np.float32, copy=True)
            else:
                self._restore_shard(ckpt_path)
            self.restored = True

    def _restore_shard(self, ckpt_path: str) -> None:
        """Restore a full shard snapshot (elastic recovery format): the
        center + version + dedup window + membership come back as one
        consistent cut, so an acked push either survives with the center
        it mutated or rolls back with it — never half."""
        from mpit_tpu.utils.checkpoint import load_shard_state

        state = load_shard_state(ckpt_path)
        saved = np.asarray(state["center"], dtype=np.float32)
        shards = state.get("shards")
        if shards is None or self._shard_map is None:
            if saved.shape != self.center.shape:
                raise ValueError(
                    f"persisted shard snapshot {ckpt_path!r} has shape "
                    f"{saved.shape}, this server owns {self.center.shape} "
                    "— resuming across a model/server-count change is not "
                    "supported"
                )
        else:
            # sharded snapshot: the persisted ownership rows, not the
            # constructor's map, say what the center covers (ownership
            # may have moved between construction and the snapshot)
            owned = [
                (int(x[0]), int(x[1]), int(x[2]))  # mpit-analysis: ignore[MPT005]
                for x in shards
            ]
            if sum(e - s for _, s, e in owned) != saved.size:
                raise ValueError(
                    f"persisted shard snapshot {ckpt_path!r}: ownership "
                    "rows do not cover the persisted center"
                )
            self._owned = owned
            self._pending = {}
            self.shard_versions = {
                int(x[0]): int(x[3])  # mpit-analysis: ignore[MPT005]
                for x in shards
            }
        ring = state.get("ring")
        if ring is not None and self._shard_map is not None:
            rv = int(ring[0])  # mpit-analysis: ignore[MPT005]
            if rv > self._shard_map.ring.version:
                members = [int(m) for m in ring[1]]  # mpit-analysis: ignore[MPT005]
                self._shard_map = self._shard_map.with_ring(
                    HashRing(
                        members,
                        vnodes=self._shard_map.ring.vnodes,
                        version=rv,
                    )
                )
        self.center = saved.copy()
        self.version = int(state.get("version", 0))
        # a restore is a new generation: PARAM version records after the
        # restart carry gen+1 so monotonicity is judged per generation
        self.gen = int(state.get("gen", 0)) + 1
        dedup = state.get("dedup")
        if dedup is not None:
            self._dedup.load_state(dedup)
        membership = state.get("membership")
        if membership is not None:
            self._membership.load_state(membership)

    def _note(self, field: str, write: bool = True) -> None:
        """RT103 annotation: stamp an access to a shared field into the
        vector-clock sanitizer (no-op — one attr load — unless a
        race-mode runtime checker is armed, see MPIT_RT_RACE)."""
        _rt_note(f"PServer#{id(self)}.{field}", write)

    def start(self) -> None:
        """Recv loop; stores any exception in ``self.error`` (a daemon
        thread's traceback would otherwise vanish while clients block into
        RecvTimeout with the root cause lost)."""
        try:
            self._serve()
        except BaseException as e:
            self.error = e
            raise

    def _serve(self) -> None:
        watchdog = self.client_timeout is not None
        last_seen: dict[int, float] = {}
        if watchdog:
            now = time.monotonic()
            last_seen = {r: now for r in self.client_ranks}
        poll = self.client_timeout / 4 if watchdog else None

        # teardown when every expected rank is accounted for (stopped,
        # dead, or left) — equal to the seed's `len(stopped | dead) <
        # num_clients` loop when membership never changes, but correct
        # when ranks JOIN/LEAVE mid-run
        while not self._membership.teardown_complete():
            try:
                msg = self.transport.recv(ANY_SOURCE, ANY_TAG, timeout=poll)
            except RecvTimeout:
                self._expire(last_seen)
                continue
            if watchdog and msg.src in last_seen:
                last_seen[msg.src] = time.monotonic()
                # a late message from a declared-dead client revives it
                self._note("membership")
                self.dead_clients.discard(msg.src)
            if isinstance(msg.payload, CorruptedPayload):
                # an unparseable frame: in a real stack the tag itself
                # would be unreadable, so no dispatch — drop it (counted)
                # and let the sender's retry/timeout absorb the loss. It
                # still refreshed liveness above: garbage is a sign of
                # life.
                with self._lock:
                    self._note("counts")
                    self.counts["malformed_dropped"] += 1
                if watchdog:
                    self._expire(last_seen)
                continue
            if msg.tag == TAG_FETCH:
                with self._lock:
                    self._note("center", write=False)
                    self._note("version", write=False)
                    self._note("counts")
                    snapshot = self._reply_chunk()
                    version = self.version
                    self.counts["fetch"] += 1
                # echo the client's attempt id so a retrying fetch can
                # tell this reply from a stale one (None = legacy FETCH);
                # id'd replies also carry the center's update version —
                # the client echoes it back as its push basis so the
                # server can attribute per-push staleness
                if msg.payload is None:
                    reply = snapshot
                else:
                    reply = (msg.payload, version, self._quant_chunk(snapshot))
                self._journal_dynamics(
                    "param_version", dst=msg.src, version=version,
                    gen=self.gen,
                )
                self.transport.send(msg.src, TAG_PARAM, reply)
            elif msg.tag == TAG_PUSH_EASGD:
                if self._admit_push(msg):
                    # elastic move toward the client (SURVEY.md §3(c) push)
                    self._apply_update(msg, easgd=True)
                    self._maybe_persist()
            elif msg.tag == TAG_PUSH_DELTA:
                if self._admit_push(msg):
                    self._apply_update(msg, easgd=False)
                    self._maybe_persist()
            elif msg.tag == TAG_HEARTBEAT:
                with self._lock:
                    self._note("counts")
                    self.counts["heartbeat"] += 1
            elif msg.tag == TAG_JOIN:
                # membership handshake: register the (rank, epoch) pair
                # and answer with the same versioned PARAM a FETCH gets —
                # one reply tag keeps the wire protocol's single
                # request/reply shape (and the extracted model) intact
                parsed = self._parse_join(msg.payload)
                if parsed is None:
                    with self._lock:
                        self._note("counts")
                        self.counts["malformed_dropped"] += 1
                else:
                    attempt, client_epoch = parsed
                    self._note("membership")
                    kind = self._membership.register(msg.src, client_epoch)
                    with self._lock:
                        self._note("center", write=False)
                        self._note("version", write=False)
                        self._note("counts")
                        snapshot = self._reply_chunk()
                        version = self.version
                        self.counts["join"] += 1
                    if watchdog and msg.src not in last_seen:
                        # a brand-new rank: arm its watchdog slot
                        last_seen[msg.src] = time.monotonic()
                    reply = (attempt, version, self._quant_chunk(snapshot))
                    self._journal_dynamics(
                        "membership", src=msg.src, kind=kind,
                        view=self._membership.view_epoch, gen=self.gen,
                    )
                    self._journal_dynamics(
                        "param_version", dst=msg.src, version=version,
                        gen=self.gen,
                    )
                    self.transport.send(msg.src, TAG_PARAM, reply)
            elif msg.tag == TAG_LEAVE:
                self._note("membership")
                self._membership.leave(msg.src)
                with self._lock:
                    self._note("counts")
                    self.counts["leave"] += 1
                self._journal_dynamics(
                    "membership", src=msg.src, kind="leave",
                    view=self._membership.view_epoch, gen=self.gen,
                )
            elif msg.tag == TAG_STOP:
                self._note("membership")
                self._stopped.add(msg.src)
            elif msg.tag == TAG_SHARD_MAP:
                self._handle_shard_map(msg)
            elif msg.tag == TAG_RESHARD:
                self._handle_reshard(msg)
            else:
                raise ValueError(f"pserver: unknown tag {msg.tag}")
            if watchdog:
                self._expire(last_seen)
        self.persist()  # clean teardown: the final center is never lost

    def _parse_join(self, payload) -> Optional[tuple]:
        """``(attempt_id, epoch)`` from a JOIN envelope, or None for a
        malformed one (a chaos-mangled JOIN is dropped like any other
        unparseable frame; the client's join retry re-offers it)."""
        if (
            isinstance(payload, tuple)
            and len(payload) == 2
            and isinstance(payload[0], int)
            and isinstance(payload[1], int)
        ):
            return payload
        return None

    # ---- sharded ownership (docs/ROBUSTNESS.md "Shard ownership &
    # resharding"). All of the state below is confined to the server's
    # recv thread except `center`/`_owned`, which snapshot() readers see
    # under the lock.

    def _local_slices(self) -> list[tuple[int, int]]:
        """Local [start, end) into ``self.center`` per materialized
        shard, ascending (same order as ``self._owned``)."""
        out, off = [], 0
        for _, s, e in self._owned:
            out.append((off, off + (e - s)))
            off += e - s
        return out

    def _shard_slice(self, sid: int) -> Optional[tuple[int, int]]:
        for (osid, _, _), loc in zip(self._owned, self._local_slices()):
            if osid == sid:
                return loc
        return None

    def _materialize(self, sid: int, arr, version: int) -> None:
        """Install a pending shard's data at its static layout slot
        (caller holds the lock). The backing ``center`` array is rebuilt
        as the ascending concatenation — the only transient extra memory
        is the one incoming slice."""
        s, e = self._pending.pop(sid)
        pieces = [
            (gs, osid, ge, self.center[ls:le])
            for (osid, gs, ge), (ls, le) in zip(self._owned, self._local_slices())
        ]
        pieces.append((s, sid, e, np.asarray(arr, dtype=np.float32)))
        pieces.sort(key=lambda p: p[0])
        self._owned = [(p[1], p[0], p[2]) for p in pieces]
        self.center = np.concatenate([p[3] for p in pieces])
        self.shard_versions[sid] = int(version)

    def _drop_shard(self, sid: int) -> None:
        """Forget a handed-off shard (caller holds the lock): the slice
        leaves ``center`` immediately, so the old owner never holds a
        duplicate once the transfer is on the wire."""
        keep = [
            ((osid, s, e), self.center[ls:le])
            for (osid, s, e), (ls, le) in zip(self._owned, self._local_slices())
            if osid != sid
        ]
        self._owned = [k[0] for k in keep]
        self.center = (
            np.concatenate([k[1] for k in keep])
            if keep
            else np.zeros(0, dtype=np.float32)
        )
        self.shard_versions.pop(sid, None)

    def _reply_chunk(self):
        """PARAM reply body (caller holds the lock): the legacy
        contiguous copy, or — sharded — ``(sid, shard_version, slice)``
        parts the client places by the static layout, so a reply stays
        interpretable even when the client's ring view is behind."""
        if self._shard_map is None:
            return self.center.copy()
        return [
            (sid, int(self.shard_versions.get(sid, 0)), self.center[ls:le].copy())
            for (sid, _, _), (ls, le) in zip(self._owned, self._local_slices())
        ]

    def _quant_chunk(self, snapshot):
        if self.quant == "off":
            return snapshot
        # Param-fetch replies quantize a fresh center snapshot each
        # time, not an accumulating stream — no residual to carry.
        if isinstance(snapshot, list):
            return [
                # mpit-analysis: ef-off[fetch reply is a fresh snapshot]
                (sid, ver, quantize(arr, self.quant)) for sid, ver, arr in snapshot
            ]
        # mpit-analysis: ef-off[fetch reply is a fresh snapshot]
        return quantize(snapshot, self.quant)

    def _apply_update(self, msg, easgd: bool) -> None:
        """Apply an admitted push: the legacy whole-chunk axpy, or the
        per-shard parts of a sharded envelope."""
        with self._lock:
            self._note("center")
            self._note("version")
            self._note("counts")
            payload = msg.payload
            if isinstance(payload, list):
                self._apply_parts(payload, easgd)
            elif easgd:
                self.center += self.alpha * (np.asarray(payload) - self.center)
            else:
                self.center += self.server_lr * np.asarray(payload)
            self.counts["push_easgd" if easgd else "push_delta"] += 1
            self._updates_since_save += 1
            self.version += 1
            version = self.version
        self._record_push(msg, version)

    def _apply_parts(self, parts, easgd: bool) -> None:
        """Per-shard apply (caller holds the lock). An EASGD part for a
        *pending* shard seeds it (the payload IS the client's parameter
        values, so the first full push after a repair materializes the
        orphan slice — and the elastic pull below is then a no-op
        against an identical center). A DOWNPOUR delta cannot seed a
        shard and a part for a shard we do not own means the sender's
        ring view is behind; both are dropped and counted — the client
        re-offers to the current owner next round."""
        for sid, arr in parts:
            if sid in self._pending and easgd:
                self._materialize(sid, arr, self.shard_versions.get(sid, 0))
                self.counts["adopted_shards"] += 1
            loc = self._shard_slice(sid)
            if loc is None:
                self.counts["misrouted_parts"] += 1
                continue
            ls, le = loc
            if easgd:
                self.center[ls:le] += self.alpha * (arr - self.center[ls:le])
            else:
                self.center[ls:le] += self.server_lr * arr
            self.shard_versions[sid] = self.shard_versions.get(sid, 0) + 1

    def _parse_shard_map(self, payload) -> Optional[tuple]:
        """``(ring_version, members)`` from a SHARD_MAP envelope, or
        None for a malformed one."""
        if (
            isinstance(payload, (tuple, list))
            and len(payload) == 2
            and isinstance(payload[0], int)
            and isinstance(payload[1], (tuple, list))
            and len(payload[1]) > 0
            and all(isinstance(m, int) for m in payload[1])
        ):
            return int(payload[0]), tuple(int(m) for m in payload[1])
        return None

    def _handle_shard_map(self, msg) -> None:
        """Adopt a new ring view: hand off shards the new ring assigns
        elsewhere, mark newly-assigned ones pending. The ring version is
        the idempotency key — every repairing client derives the same
        ring from the same death, so the second and later announcements
        of one view are no-ops."""
        parsed = self._parse_shard_map(msg.payload)
        if parsed is None:
            with self._lock:
                self._note("counts")
                self.counts["malformed_dropped"] += 1
            return
        ring_version, members = parsed
        with self._lock:
            self._note("counts")
            self.counts["shard_map"] += 1
        if self._shard_map is None:
            return  # flat server: no ring to update
        if ring_version <= self._shard_map.ring.version:
            return  # stale or duplicate view
        new_ring = HashRing(
            members, vnodes=self._shard_map.ring.vnodes, version=ring_version
        )
        new_map = self._shard_map.with_ring(new_ring)
        mine = {sid for sid, _, _ in new_map.ranges_for(self.transport.rank)}
        held = {sid for sid, _, _ in self._owned}
        for sid in sorted(set(self._pending) - mine):
            del self._pending[sid]  # never arrived and no longer ours
        for sid in sorted(held - mine):
            self._handoff_shard(sid, new_map.assignment[sid], ring_version)
        for sid in sorted(mine - held - set(self._pending)):
            s, e = new_map.layout[sid]
            self._pending[sid] = (s, e)
        self._shard_map = new_map
        self._journal_dynamics(
            "shard_map", view=ring_version, src=msg.src,
            owned=len(self._owned), pending=len(self._pending), gen=self.gen,
        )

    def _handoff_shard(self, sid: int, dst: int, ring_version: int) -> None:
        """Graceful slice exchange to the shard's new owner: data +
        per-shard version + the dedup window travel together, so the new
        owner rejects replays of pushes the old owner already applied.
        The slice is dropped from ``center`` only after the transfer is
        accepted by the transport — a failed send keeps the shard here,
        and the next view announcement re-offers it (failure during
        failure-handling degrades to a retry, never to data loss)."""
        with self._lock:
            self._note("center", write=False)
            loc = self._shard_slice(sid)
            if loc is None:
                return
            ls, le = loc
            arr = self.center[ls:le].copy()
            ver = int(self.shard_versions.get(sid, 0))
            entries = self._dedup.state()
        payload = (ring_version, sid, ver, arr, entries)
        if not self._send_reshard(dst, payload):
            return
        with self._lock:
            self._note("center")
            self._note("counts")
            self._drop_shard(sid)
            self.counts["handoff_sent"] += 1
        self._journal_dynamics(
            "reshard", shard=sid, dst=dst, version=ver,
            view=ring_version, gen=self.gen,
        )

    def _send_reshard(self, dst: int, payload) -> bool:
        """Retry/backoff on the reshard transfer (the server-side twin
        of PClient._send_with_retry; the (ring_version, shard) pair in
        the payload plays the attempt-id role — the receiver ignores
        duplicates and stale versions)."""
        delay = 0.05
        for attempt in range(3):
            try:
                self.transport.send(dst, TAG_RESHARD, payload)
                return True
            except (ConnectionError, OSError):
                if attempt == 2:
                    return False
                time.sleep(delay)
                delay *= 2
        return False

    def _parse_reshard(self, payload) -> Optional[tuple]:
        """``(ring_version, shard, shard_version, chunk, dedup)`` from a
        RESHARD envelope, or None for a malformed one (a chaos-mangled
        transfer is dropped whole; the sender's re-offer repeats it)."""
        if not (
            isinstance(payload, (tuple, list))
            and len(payload) == 5
            and isinstance(payload[0], int)
            and isinstance(payload[1], int)
            and isinstance(payload[2], int)
            and isinstance(payload[4], (list, tuple))
        ):
            return None
        ring_version, sid, ver, chunk, entries = payload
        if self._shard_map is not None:
            if not (0 <= sid < self._shard_map.num_shards):
                return None
            try:
                arr = np.asarray(chunk, dtype=np.float32)
            except (TypeError, ValueError):
                return None
            s, e = self._shard_map.layout[sid]
            if arr.shape != (e - s,):
                return None
            chunk = arr
        return int(ring_version), int(sid), int(ver), chunk, entries

    def _handle_reshard(self, msg) -> None:
        """Install a handed-off shard: materialize the slice, take over
        its version counter, absorb the old owner's dedup window. A
        transfer for a shard that is not pending (duplicate, or a view
        we have since moved past) is idempotently ignored."""
        parsed = self._parse_reshard(msg.payload)
        if parsed is None:
            with self._lock:
                self._note("counts")
                self.counts["malformed_dropped"] += 1
            return
        ring_version, sid, ver, chunk, entries = parsed
        with self._lock:
            self._note("counts")
            self.counts["reshard"] += 1
        if self._shard_map is None or sid not in self._pending:
            return
        with self._lock:
            self._note("center")
            self._materialize(sid, chunk, ver)
            self.counts["adopted_shards"] += 1
        self._note("dedup")
        self._dedup.absorb(entries)
        self._journal_dynamics(
            "reshard", shard=sid, src=msg.src, version=ver,
            view=ring_version, gen=self.gen,
        )

    def owned_ranges(self) -> list:
        """Ascending ``(sid, start, end)`` of materialized shards
        (empty in legacy flat mode)."""
        with self._lock:
            return list(self._owned)

    def _admit_push(self, msg) -> bool:
        """Unwrap a push envelope, validate the chunk, and run the
        exactly-once check.

        ``(epoch, seq, basis_version, chunk)`` (and legacy ``(epoch,
        seq, chunk)``) envelopes are deduplicated per (src, epoch); the
        validated chunk is rebound onto ``msg.payload`` so the apply
        path below handles envelope and legacy bare-chunk pushes
        identically, and the basis version (when present) is stashed on
        the message for the post-apply staleness record. Returns False
        for a replay or a malformed chunk (both counted, never
        applied). Validation runs BEFORE the dedup admit: a
        chaos-truncated frame must not consume its (epoch, seq) slot —
        a clean retransmit of the same push should still be able to
        land."""
        payload = msg.payload
        basis: Optional[int] = None
        if (
            isinstance(payload, tuple)
            and len(payload) == 4
            and isinstance(payload[0], int)
            and isinstance(payload[1], int)
            and isinstance(payload[2], int)
        ):
            # versioned envelope: peel the fetch-basis version off and
            # fall through to the common (epoch, seq, chunk) handling —
            # dedup and validation are identical either way
            epoch, seq, basis, chunk = payload
            payload = (epoch, seq, chunk)
        if (
            isinstance(payload, tuple)
            and len(payload) == 3
            and isinstance(payload[0], int)
            and isinstance(payload[1], int)
        ):
            epoch, seq, chunk = payload
            arr = self._validate_chunk(chunk)
            if arr is None:
                with self._lock:
                    self._note("counts")
                    self.counts["malformed_dropped"] += 1
                return False
            msg.payload = arr
            # dedup is confined to the server thread — annotated so RT103
            # would catch any future second mutator
            self._note("dedup")
            if not self._dedup.admit(msg.src, epoch, seq):
                with self._lock:
                    self._note("counts")
                    self.counts["dup_dropped"] += 1
                return False
            msg.basis_version = basis
            msg.push_epoch = epoch
            return True
        arr = self._validate_chunk(payload)
        if arr is None:
            with self._lock:
                self._note("counts")
                self.counts["malformed_dropped"] += 1
            return False
        msg.payload = arr
        return True

    def _journal_dynamics(self, ev: str, **fields) -> None:
        """Write a training-dynamics record through the transport's obs
        tracer. No-op (one getattr) when the transport is not
        obs-wrapped or journaling is off — the disabled-cost contract
        of the rest of the obs plane."""
        tracer = getattr(self.transport, "obs_tracer", None)
        if tracer is None or tracer.journal is None:
            return
        tracer.journal.event(ev, tracer.clock.tick(), **fields)

    def _record_push(self, msg, version: int) -> None:
        """Account, journal, and live-publish an applied push's
        staleness when its envelope carried a fetch-basis version
        (legacy envelopes don't — they apply silently, as before).

        staleness = pre-apply version − basis version: the number of
        center updates that landed between this client's fetch and its
        push applying. 0 means the push coupled against exactly the
        center it fetched; under contention it grows with how many
        other clients' pushes raced in between — the per-(src, epoch)
        asynchrony signal ``obs dynamics`` aggregates."""
        basis = getattr(msg, "basis_version", None)
        if basis is None:
            return
        staleness = max(0, version - 1 - basis)
        with self._lock:
            self._note("staleness")
            st = self.staleness_by_src.setdefault(
                msg.src, {"pushes": 0, "sum": 0, "max": 0}
            )
            st["pushes"] += 1
            st["sum"] += staleness
            st["max"] = max(st["max"], staleness)
        self._journal_dynamics(
            "push_stale",
            src=msg.src,
            epoch=getattr(msg, "push_epoch", None),
            staleness=staleness,
            version=version,
        )
        # live histogram: one staleness unit recorded as one "second" —
        # the geometric buckets are unit-agnostic, so the dashboard's
        # percentile_ms/1000 recovers staleness units within bucket
        # resolution (~10%)
        live_registry(self.transport).observe(M_STALENESS, float(staleness))

    def _validate_chunk(self, chunk) -> Optional[np.ndarray]:
        """float32 view/copy of an update chunk, or None when the frame
        is malformed (chaos ``corrupt``/``truncate``, or just the wrong
        shape for this server's partition) — the safe side of
        at-most-once: an unparseable update is dropped whole, never
        partially or wrongly applied. Quantized chunks are dequantized
        here (a truncated QuantArray dequantizes to the wrong length and
        fails the shape check like any cut frame). Sharded-mode pushes
        carry ``(sid, chunk)`` parts instead of one contiguous chunk —
        each part is validated against its static layout slot."""
        if (
            self._shard_map is not None
            and isinstance(chunk, (list, tuple))
            and not isinstance(chunk, np.ndarray)
        ):
            return self._validate_parts(chunk)
        try:
            if isinstance(chunk, QuantArray):
                chunk = dequantize(chunk)
            arr = np.asarray(chunk, dtype=np.float32)
        except (TypeError, ValueError):
            return None
        if arr.shape != self.center.shape:
            return None
        # RT104: the server apply boundary — a NaN/Inf push admitted
        # here poisons the center for every subsequent fetch
        _rt_numeric("pserver.apply", arr)
        return arr

    def _validate_parts(self, parts) -> Optional[list]:
        """Validated ``[(sid, float32 array), ...]`` from a sharded push
        chunk, or None when any part is malformed — all-or-nothing, the
        same safe side of at-most-once as the contiguous path."""
        if len(parts) == 0:
            return None
        out = []
        for part in parts:
            if not (
                isinstance(part, (tuple, list))
                and len(part) == 2
                and isinstance(part[0], int)
            ):
                return None
            sid, chunk = part
            if not (0 <= sid < self._shard_map.num_shards):
                return None
            try:
                if isinstance(chunk, QuantArray):
                    chunk = dequantize(chunk)
                # wire payloads are host numpy (msgpack-decoded), never
                # device arrays — no host sync happens here
                arr = np.asarray(chunk, dtype=np.float32)  # mpit-analysis: ignore[MPT005]
            except (TypeError, ValueError):
                return None
            s, e = self._shard_map.layout[sid]
            if arr.shape != (e - s,):
                return None
            _rt_numeric("pserver.apply", arr)
            out.append((int(sid), arr))  # mpit-analysis: ignore[MPT005]
        return out

    def _maybe_persist(self) -> None:
        if (
            self.ckpt_path is None
            or self.ckpt_every is None  # teardown-only mode
            or self._updates_since_save < self.ckpt_every
        ):
            return
        self.persist()

    def _snapshot_state(self) -> dict:
        """One consistent cut of everything a restarted server needs:
        the keys below are the shard snapshot format — center, version,
        gen, dedup, and membership are persisted TOGETHER so a push that
        was applied but not yet persisted rolls back *with* the center
        it mutated (its redelivery then re-applies exactly once relative
        to the restored state)."""
        with self._lock:
            self._note("center", write=False)
            self._note("version", write=False)
            state = {
                "center": self.center.copy(),
                "version": int(self.version),
                "gen": int(self.gen),
                "dedup": self._dedup.state(),
                "membership": self._membership.state(),
                "shards": self._shards_state(),
                "ring": self._ring_state(),
            }
            self._updates_since_save = 0
        return state

    def _shards_state(self) -> Optional[list]:
        """Materialized shard ownership as ``[sid, start, end,
        shard_version]`` rows (None in legacy flat mode — the key is
        written either way so the snapshot schema has one shape)."""
        if self._shard_map is None:
            return None
        return [
            [int(sid), int(s), int(e), int(self.shard_versions.get(sid, 0))]
            for sid, s, e in self._owned
        ]

    def _ring_state(self) -> Optional[list]:
        if self._shard_map is None:
            return None
        return [
            int(self._shard_map.ring.version),
            list(self._shard_map.ring.members),
        ]

    def persist(self) -> None:
        """Atomically write the persistent snapshot (tmp + rename — a
        server killed mid-write leaves the previous snapshot intact).
        A ``.npy`` path keeps the legacy bare-center ``np.save`` format
        (ps_trainer's ``center_<rank>.npy`` resume contract); any other
        path gets the full shard snapshot, which is what elastic
        recovery restores from. Opened file handles keep ``np.save``
        from appending its own ``.npy``."""
        if self.ckpt_path is None:
            return
        if self.ckpt_path.endswith(".npy"):
            with self._lock:
                self._note("center", write=False)
                snap = self.center.copy()
                self._updates_since_save = 0
            tmp = self.ckpt_path + ".tmp"
            with open(tmp, "wb") as f:
                np.save(f, snap)
            os.replace(tmp, self.ckpt_path)
            return
        from mpit_tpu.utils.checkpoint import save_shard_state

        save_shard_state(self.ckpt_path, self._snapshot_state())

    def _expire(self, last_seen: dict) -> None:
        now = time.monotonic()
        for r, seen in last_seen.items():
            if (
                r not in self._stopped
                and r not in self.dead_clients
                and now - seen > self.client_timeout
            ):
                self._note("membership")
                self.dead_clients.add(r)

    def snapshot(self) -> np.ndarray:
        with self._lock:
            self._note("center", write=False)
            return self.center.copy()


def spawn_server_thread(server: PServer) -> threading.Thread:
    def run():
        try:
            server.start()
        except BaseException:
            # already recorded in server.error by start(); swallowing here
            # keeps the thread exit clean (re-raising from a thread only
            # feeds the default excepthook noise) — direct/synchronous
            # server.start() callers still get the raise
            pass

    t = threading.Thread(target=run, daemon=True, name="mpit-pserver")
    t.start()
    return t
