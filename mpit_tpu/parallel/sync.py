"""Synchronous allreduce data parallelism.

Reference parity (SURVEY.md §3(d), BASELINE.json:8): per step each worker
computes a gradient on its batch shard, ``mpiT.Allreduce(grad, SUM)`` then
``grad /= size``, and a replicated optimizer applies the averaged gradient.

TPU-native design: one jit-compiled ``shard_map`` step over the worker mesh
axis — the batch is sharded on the leading axis, params/optimizer state are
replicated, and the gradient average is a single ``lax.pmean`` that XLA lowers
to an ICI all-reduce fused into the step (no host round trip per step, unlike
the reference's per-step MPI call from the Lua loop).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu.comm.topology import Topology
from mpit_tpu.parallel import common


class DataParallelTrainer:
    """Sync allreduce DP trainer for a flax model.

    Usage::

        topo = mpit_tpu.init()
        trainer = DataParallelTrainer(model, optax.sgd(0.1), topo)
        state = trainer.init_state(jax.random.key(0), sample_batch_x)
        state, metrics = trainer.step(state, x_global, y_global)
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        topo: Optional[Topology] = None,
        loss_fn: Optional[Callable] = None,
        donate_state: bool = True,
        accum_steps: int = 1,
    ):
        """``accum_steps``: gradient accumulation — each step's local
        batch is processed as that many sequential slices (``lax.scan``)
        whose gradients average before the one optimizer update. The
        math is EXACTLY the full-batch step (equal slice sizes, mean
        losses, and no model here carries batch statistics — GroupNorm/
        LayerNorm only), so it trades step latency for peak activation
        memory: effective batch B needs only B/accum_steps of forward
        state in HBM at once."""
        self.model = model
        self.optimizer = optimizer
        self.topo = topo if topo is not None else _current_topology()
        self.loss_fn = (
            loss_fn
            if loss_fn is not None
            else common.default_loss_fn(model.apply)
        )
        self.accum_steps = accum = int(accum_steps)
        axis = self.topo.worker_axis
        mesh = self.topo.mesh
        local_vg = common.accumulated_value_and_grad(self.loss_fn, accum)

        def train_step(state: common.TrainState, x, y):
            loss, grads = local_vg(state.params, x, y)
            # the one collective of the step: grad average over workers
            grads = jax.lax.pmean(grads, axis)
            loss = jax.lax.pmean(loss, axis)
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            return (
                common.TrainState(
                    params=params, opt_state=opt_state, step=state.step + 1
                ),
                {"loss": loss},
            )

        self._step = jax.jit(
            jax.shard_map(
                train_step,
                mesh=mesh,
                in_specs=(P(), P(axis), P(axis)),
                out_specs=(P(), P()),
                check_vma=False,
            ),
            donate_argnums=(0,) if donate_state else (),
        )

        self._eval = common.build_count_loss_eval(model, self.topo)

    def init_state(self, rng, sample_x) -> common.TrainState:
        """Initialize replicated state. ``sample_x`` is a *per-worker* shaped
        batch (leading dim = per-worker batch); only shapes matter."""
        variables = self.model.init(rng, jnp.asarray(sample_x))
        state = common.TrainState.create(variables["params"], self.optimizer)
        return jax.device_put(
            state, self.topo.replicated_sharding()
        )

    def _check(self, x) -> None:
        common.check_accum_batch(
            len(x), self.topo.num_workers, self.accum_steps
        )

    def step(self, state, x_global, y_global):
        """One sync-DP step on a global batch (leading dim divisible by W,
        per-worker shard divisible by accum_steps)."""
        self._check(x_global)
        state, metrics = self._step(state, x_global, y_global)
        common.bound_cpu_dispatch(self.topo, metrics)
        return state, metrics

    def evaluate(self, state, x, y, batch: int = 1024):
        """Full-dataset eval; returns (accuracy, mean_loss)."""
        correct, loss_sum, n = common.batched_count_eval(
            self._eval, state.params, x, y, batch, self.topo.num_workers
        )
        return correct / n, loss_sum / n

    def fit(
        self,
        batches,
        state,
        epochs: int = 1,
        log_every: int = 0,
        start_epoch: int = 0,
        skip_steps: int = 0,
        on_step=None,
        prefetch: int = 2,
    ):
        """Epoch loop over a :class:`mpit_tpu.data.Batches` — the shared
        :func:`common.synced_fit_loop` with the sync-DP sharding/check.
        Returns (state, last_metrics)."""
        return common.synced_fit_loop(
            self.topo, self._step, batches, state,
            sharding=self.topo.worker_sharding(),
            check=self._check,
            log_tag="sync-dp",
            epochs=epochs, log_every=log_every, start_epoch=start_epoch,
            skip_steps=skip_steps, on_step=on_step, prefetch=prefetch,
        )
