"""Synchronous allreduce data parallelism.

Reference parity (SURVEY.md §3(d), BASELINE.json:8): per step each worker
computes a gradient on its batch shard, ``mpiT.Allreduce(grad, SUM)`` then
``grad /= size``, and a replicated optimizer applies the averaged gradient.

TPU-native design: one jit-compiled ``shard_map`` step over the worker mesh
axis — the batch is sharded on the leading axis, params/optimizer state are
replicated, and the gradient average is a single ``lax.pmean`` that XLA lowers
to an ICI all-reduce fused into the step (no host round trip per step, unlike
the reference's per-step MPI call from the Lua loop).

Bucketed / quantized gradient exchange (docs/PERF.md "overlapped DP
exchange"): when ``MPIT_DP_QUANT`` or ``MPIT_DP_BUCKET_BYTES`` engages it,
the step is restructured into a program pipeline — one backward program
that emits the gradient as size-targeted flat *buckets*, then per bucket a
staged reduce-scatter + all-gather exchange whose wire hops are separate
XLA programs from the (optional) quantize/dequantize math, and one apply
program that rebuilds the gradient tree and runs the optimizer. Separate
hop programs are what buys both halves of the ROADMAP fast-wire item:

- **overlap** — on a real accelerator the host dispatches every program
  asynchronously, so bucket k's all_to_all is in flight while bucket k+1's
  encode (and the next bucket's math) runs — double-buffering at program
  granularity without splitting the backward itself;
- **honest attribution** — when obs is armed each hop is timed and
  journaled as a ``send`` event while the quant math blocks inside
  ``compute`` spans, so ``obs roofline`` shows the wire *shrinking* under
  quantization rather than hiding quant compute inside the wire figure.

The quantized exchange (``comm.collectives.quantized_allreduce`` math, run
here as staged programs) carries two-level error-feedback residuals in
trainer state — level 1 on each worker's contribution, level 2 on its
owned reduced chunk — so the accumulated gradient stream stays unbiased
(docs/WIRE.md "Quantized collectives").

With both knobs off the trainer builds and runs EXACTLY the fused
single-program step above — bit-identical to the pre-bucketing trainer,
pinned by tests/test_perf_guards.py.
"""

from __future__ import annotations

import os
import time
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from mpit_tpu.analysis import runtime as _runtime

from mpit_tpu import quant as _quant
from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu.comm.topology import Topology
from mpit_tpu.obs import core as obs_core
from mpit_tpu.parallel import common

# bucket size target when bucketing is engaged without an explicit size:
# big enough that hop dispatch overhead amortizes, small enough that a
# ResNet-scale gradient still splits into several overlappable buckets
DEFAULT_DP_BUCKET_BYTES = 4 << 20


def dp_quant_from_env(env=None) -> str:
    """``MPIT_DP_QUANT`` (off|bf16|int8; default off) — the sync-DP
    gradient-exchange quantization mode."""
    env = os.environ if env is None else env
    mode = env.get("MPIT_DP_QUANT") or "off"
    if mode not in _quant.QUANT_MODES:
        raise ValueError(
            f"MPIT_DP_QUANT={mode!r}: expected one of {_quant.QUANT_MODES}"
        )
    return mode


def dp_bucket_bytes_from_env(env=None) -> Optional[int]:
    """``MPIT_DP_BUCKET_BYTES`` (positive int, f32 bytes per bucket) —
    setting it engages the bucketed exchange even unquantized. None when
    unset."""
    env = os.environ if env is None else env
    raw = env.get("MPIT_DP_BUCKET_BYTES")
    if raw is None or raw == "":
        return None
    b = int(raw)
    if b < 1:
        raise ValueError(f"MPIT_DP_BUCKET_BYTES={b} must be >= 1")
    return b


class _Bucket:
    """One gradient bucket: leaves ``[lo, hi)`` concatenated to a flat
    f32 vector of ``n`` elements, padded to ``n_pad`` (W-divisible; each
    worker owns a ``chunk``-element row of the reduce-scatter)."""

    __slots__ = ("lo", "hi", "n", "n_pad", "chunk", "hop_bytes")

    def __init__(self, lo: int, hi: int, n: int, w: int, mode: str):
        self.lo, self.hi, self.n = lo, hi, n
        self.n_pad = n + (-n % w)
        self.chunk = self.n_pad // w
        # per-worker wire volume of ONE hop (all_to_all out or all_gather
        # in are both the full padded bucket at wire width; int8 adds W
        # block scales)
        self.hop_bytes = self.n_pad * _quant.MODE_ITEMSIZE[mode] + (
            4 * w if mode == "int8" else 0
        )


class _BucketPlan:
    """Leaf layout + bucket partition for one parameter structure.

    Buckets are contiguous runs of flatten-order leaves closed once the
    accumulated f32 bytes reach the target (leaves are never split — a
    leaf larger than the target becomes its own bucket)."""

    def __init__(self, params, w: int, bucket_bytes: int, mode: str):
        leaves, self.treedef = jax.tree.flatten(params)
        self.shapes = [jnp.shape(l) for l in leaves]
        self.dtypes = [jnp.asarray(l).dtype for l in leaves]
        self.sizes = [int(np.prod(s, dtype=np.int64)) for s in self.shapes]
        self.buckets: List[_Bucket] = []
        lo, acc = 0, 0
        for i, sz in enumerate(self.sizes):
            acc += sz * 4
            if acc >= bucket_bytes:
                self.buckets.append(
                    _Bucket(lo, i + 1, sum(self.sizes[lo : i + 1]), w, mode)
                )
                lo, acc = i + 1, 0
        if lo < len(self.sizes):
            self.buckets.append(
                _Bucket(lo, len(self.sizes), sum(self.sizes[lo:]), w, mode)
            )

    def wire_bytes_per_step(self) -> int:
        """Per-worker bytes the exchange puts on the wire each step (two
        hops per bucket) — the bench.py A/B instrument."""
        return sum(2 * b.hop_bytes for b in self.buckets)


class DataParallelTrainer:
    """Sync allreduce DP trainer for a flax model.

    Usage::

        topo = mpit_tpu.init()
        trainer = DataParallelTrainer(model, optax.sgd(0.1), topo)
        state = trainer.init_state(jax.random.key(0), sample_batch_x)
        state, metrics = trainer.step(state, x_global, y_global)

    ``quant``/``bucket_bytes`` (default: the ``MPIT_DP_QUANT`` /
    ``MPIT_DP_BUCKET_BYTES`` knobs) select the bucketed exchange — see
    the module docstring. With both off the step is the fused
    single-program path, bit-identical to the pre-bucketing trainer.
    ``obs`` (default: :func:`mpit_tpu.obs.core.config_from_env`) arms
    per-step roofline + dynamics journaling on the bucketed path; call
    :meth:`close_obs` to flush the journal before reading it.
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        topo: Optional[Topology] = None,
        loss_fn: Optional[Callable] = None,
        donate_state: bool = True,
        accum_steps: int = 1,
        quant: Optional[str] = None,
        bucket_bytes: Optional[int] = None,
        obs: Optional[obs_core.ObsConfig] = None,
    ):
        """``accum_steps``: gradient accumulation — each step's local
        batch is processed as that many sequential slices (``lax.scan``)
        whose gradients average before the one optimizer update. The
        math is EXACTLY the full-batch step (equal slice sizes, mean
        losses, and no model here carries batch statistics — GroupNorm/
        LayerNorm only), so it trades step latency for peak activation
        memory: effective batch B needs only B/accum_steps of forward
        state in HBM at once."""
        self.model = model
        self.optimizer = optimizer
        self.topo = topo if topo is not None else _current_topology()
        self.loss_fn = (
            loss_fn
            if loss_fn is not None
            else common.default_loss_fn(model.apply)
        )
        self.accum_steps = accum = int(accum_steps)
        self.donate_state = donate_state
        self.quant = dp_quant_from_env() if quant is None else quant
        if self.quant not in _quant.QUANT_MODES:
            raise ValueError(
                f"quant={self.quant!r}: expected one of {_quant.QUANT_MODES}"
            )
        bb = (
            bucket_bytes
            if bucket_bytes is not None
            else dp_bucket_bytes_from_env()
        )
        self.bucketed = self.quant != "off" or bb is not None
        self.bucket_bytes = (
            int(bb) if bb is not None else DEFAULT_DP_BUCKET_BYTES
        )
        if self.bucket_bytes < 1:
            raise ValueError(
                f"bucket_bytes={self.bucket_bytes} must be >= 1"
            )
        self.obs = obs if obs is not None else obs_core.config_from_env()
        self._tracer: Optional[obs_core.Tracer] = None
        self._round = 0
        # bucketed-path machinery is shape-dependent; built on first step
        self._plan: Optional[_BucketPlan] = None

        axis = self.topo.worker_axis
        mesh = self.topo.mesh
        local_vg = common.accumulated_value_and_grad(self.loss_fn, accum)
        self._local_vg = local_vg

        def train_step(state: common.TrainState, x, y):
            loss, grads = local_vg(state.params, x, y)
            # the one collective of the step: grad average over workers
            grads = jax.lax.pmean(grads, axis)
            loss = jax.lax.pmean(loss, axis)
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            return (
                common.TrainState(
                    params=params, opt_state=opt_state, step=state.step + 1
                ),
                {"loss": loss},
            )

        self._step = jax.jit(
            jax.shard_map(
                train_step,
                mesh=mesh,
                in_specs=(P(), P(axis), P(axis)),
                out_specs=(P(), P()),
                check_vma=False,
            ),
            donate_argnums=(0,) if donate_state else (),
        )

        self._eval = common.build_count_loss_eval(model, self.topo)

    def init_state(self, rng, sample_x) -> common.TrainState:
        """Initialize replicated state. ``sample_x`` is a *per-worker* shaped
        batch (leading dim = per-worker batch); only shapes matter."""
        variables = self.model.init(rng, jnp.asarray(sample_x))
        state = common.TrainState.create(variables["params"], self.optimizer)
        return jax.device_put(
            state, self.topo.replicated_sharding()
        )

    def _check(self, x) -> None:
        common.check_accum_batch(
            len(x), self.topo.num_workers, self.accum_steps
        )

    # -- bucketed exchange machinery ------------------------------------

    def _ensure_buckets(self, params) -> None:
        if self._plan is not None:
            return
        w = self.topo.num_workers
        axis = self.topo.worker_axis
        mesh = self.topo.mesh
        mode = self.quant
        plan = _BucketPlan(params, w, self.bucket_bytes, mode)
        self._plan = plan
        nb = len(plan.buckets)
        local_vg = self._local_vg

        def _sm(fn, in_specs, out_specs, donate=()):
            return jax.jit(
                jax.shard_map(
                    fn,
                    mesh=mesh,
                    in_specs=in_specs,
                    out_specs=out_specs,
                    check_vma=False,
                ),
                donate_argnums=donate,
            )

        # program 1 — backward: local grads flattened into padded buckets
        # (pmean'd loss is the program's one collective; gradients leave
        # UNREDUCED, one (W, n_pad) row-block per bucket)
        def grads_step(params, x, y):
            loss, grads = local_vg(params, x, y)
            loss = lax.pmean(loss, axis)
            leaves = jax.tree.flatten(grads)[0]
            outs = [loss]
            for b in plan.buckets:
                parts = [
                    leaves[i].reshape(-1).astype(jnp.float32)
                    for i in range(b.lo, b.hi)
                ]
                flat = (
                    jnp.concatenate(parts) if len(parts) > 1 else parts[0]
                )
                if b.n_pad > b.n:
                    flat = jnp.pad(flat, (0, b.n_pad - b.n))
                outs.append(flat[None])
            return tuple(outs)

        self._grads_p = _sm(
            grads_step,
            (P(), P(axis), P(axis)),
            (P(), *[P(axis, None)] * nb),
        )

        if mode != "off":
            # program 2 — encode (math only, no collectives): level-1 EF
            # fold, blockwise quantize, new residual + its local sumsq
            def encode(row, r):
                c = row[0] + r[0]
                rows = c.reshape(w, -1)
                codes, scales = _quant.quantize_rows_jnp(rows, mode)
                deq = _quant.dequantize_rows_jnp(codes, scales, mode)
                new_r = c - deq.reshape(-1)
                return (
                    codes.reshape(1, -1),
                    scales.reshape(1, -1),
                    new_r[None],
                    jnp.sum(new_r * new_r)[None],
                )

            self._encode_p = _sm(
                encode,
                (P(axis, None), P(axis, None)),
                (P(axis, None), P(axis, None), P(axis, None), P(axis)),
                donate=(1,),
            )

            # program 3 — wire hop 1: the all_to_all of codes (+ scales
            # for int8; bf16 is scale-free). COLLECTIVE-ONLY by design:
            # its wall time is the journaled wire figure.
            def hop1(codes, scales):
                cx = lax.all_to_all(
                    codes[0].reshape(w, -1),
                    axis,
                    split_axis=0,
                    concat_axis=0,
                )
                if mode == "int8":
                    sx = lax.all_to_all(
                        scales.reshape(w, 1),
                        axis,
                        split_axis=0,
                        concat_axis=0,
                    ).reshape(1, -1)
                else:
                    sx = scales
                return cx.reshape(1, -1), sx

            self._hop1_p = _sm(
                hop1,
                (P(axis, None), P(axis, None)),
                (P(axis, None), P(axis, None)),
            )

            # program 4 — reduce (math only): dequantize received rows,
            # f32 mean, level-2 EF fold, requantize the owned chunk
            def reduce_q(cx, sx, r2):
                rows = _quant.dequantize_rows_jnp(
                    cx[0].reshape(w, -1), sx.reshape(w, 1), mode
                )
                red = jnp.sum(rows, axis=0) / w + r2[0]
                rcodes, rscale = _quant.quantize_jnp(red, mode)
                new_r2 = red - _quant.dequantize_jnp(rcodes, rscale, mode)
                return rcodes[None], rscale[None], new_r2[None]

            self._reduce_p = _sm(
                reduce_q,
                (P(axis, None), P(axis, None), P(axis, None)),
                (P(axis, None), P(axis), P(axis, None)),
                donate=(2,),
            )

            # program 5 — wire hop 2: all_gather of reduced codes
            def hop2(rcodes, rscale):
                g = lax.all_gather(rcodes[0], axis)
                if mode == "int8":
                    gs = lax.all_gather(rscale[0], axis)
                else:
                    gs = jnp.ones((w,), jnp.float32)
                return g, gs

            self._hop2_p = _sm(
                hop2, (P(axis, None), P(axis)), (P(), P())
            )

            # two-level EF residual state (module docstring / docs/WIRE.md)
            shard = self.topo.worker_sharding()
            self._residual = [
                jax.device_put(np.zeros((w, b.n_pad), np.float32), shard)
                for b in plan.buckets
            ]
            self._residual2 = [
                jax.device_put(np.zeros((w, b.chunk), np.float32), shard)
                for b in plan.buckets
            ]
        else:
            # raw buckets: same staged reduce-scatter + all-gather wire
            # pattern at full f32 width (the A/B baseline the quantized
            # path is measured against)
            def hop1_raw(row):
                return lax.all_to_all(
                    row[0].reshape(w, -1), axis, split_axis=0, concat_axis=0
                ).reshape(1, -1)

            def reduce_raw(xch):
                return (jnp.sum(xch[0].reshape(w, -1), axis=0) / w)[None]

            def hop2_raw(red):
                return lax.all_gather(red[0], axis)

            self._hop1_p = _sm(
                hop1_raw, (P(axis, None),), P(axis, None)
            )
            self._reduce_p = _sm(
                reduce_raw, (P(axis, None),), P(axis, None)
            )
            self._hop2_p = _sm(hop2_raw, (P(axis, None),), P())

        # final program — rebuild the gradient tree from gathered buckets
        # and run the (replicated) optimizer update
        def apply_fn(state, loss, gathered):
            flats = []
            for b, g in zip(plan.buckets, gathered):
                if mode == "off":
                    flat = g.reshape(-1)
                else:
                    codes, gs = g
                    flat = _quant.dequantize_rows_jnp(
                        codes, gs.reshape(-1, 1), mode
                    ).reshape(-1)
                flats.append(flat[: b.n])
            flat_all = (
                jnp.concatenate(flats) if len(flats) > 1 else flats[0]
            )
            leaves, off = [], 0
            for shape, dtype, sz in zip(
                plan.shapes, plan.dtypes, plan.sizes
            ):
                leaves.append(
                    flat_all[off : off + sz].reshape(shape).astype(dtype)
                )
                off += sz
            grads = jax.tree.unflatten(plan.treedef, leaves)
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            un = jnp.sqrt(
                sum(
                    jnp.sum(jnp.square(u.astype(jnp.float32)))
                    for u in jax.tree.leaves(updates)
                )
            )
            pn = jnp.sqrt(
                sum(
                    jnp.sum(jnp.square(p.astype(jnp.float32)))
                    for p in jax.tree.leaves(params)
                )
            )
            return (
                common.TrainState(
                    params=params, opt_state=opt_state, step=state.step + 1
                ),
                {"loss": loss, "param_norm": pn, "update_norm": un},
            )

        self._apply_p = jax.jit(
            apply_fn, donate_argnums=(0,) if self.donate_state else ()
        )

    def _armed_tracer(self) -> Optional[obs_core.Tracer]:
        """Build the journal/tracer lazily so ``trainer.obs`` can be set
        after warmup (the bench A/B pattern)."""
        if (
            self._tracer is None
            and self.obs is not None
            and self.obs.dir
        ):
            os.makedirs(self.obs.dir, exist_ok=True)
            journal = obs_core.Journal(
                os.path.join(self.obs.dir, "obs_rank0.jsonl"),
                rank=0,
                max_records=self.obs.max_records,
            )
            self._tracer = obs_core.Tracer(0, journal=journal)
        return self._tracer

    def close_obs(self) -> None:
        """Flush and close the trainer's obs journal (idempotent)."""
        if self._tracer is not None:
            self._tracer.close()
            self._tracer = None

    def wire_bytes_per_step(self) -> Optional[int]:
        """Per-worker exchange bytes per step (None until the first
        bucketed step has built the plan, or on the fused path)."""
        return (
            self._plan.wire_bytes_per_step()
            if self._plan is not None
            else None
        )

    def _timed_hop(self, prog, args, nbytes, tracer, settle):
        """Dispatch one wire-hop program. Armed: block and journal the
        wall wait as a ``send`` (dur + bytes — the roofline wire figure).
        Unarmed on the virtual CPU mesh: block without journaling (only
        one collective program may be in flight — see
        :func:`common.bound_cpu_dispatch`). On a real accelerator
        unarmed: fully async, which is where the overlap materializes."""
        if tracer is not None:
            t0 = time.perf_counter()
            out = prog(*args)
            jax.block_until_ready(out)
            tracer.journal.event(
                "send",
                tracer.clock.tick(),
                dur=time.perf_counter() - t0,
                bytes=nbytes,
            )
            return out
        out = prog(*args)
        if settle:
            jax.block_until_ready(out)
        return out

    def _bucketed_step(self, state, x, y):
        self._ensure_buckets(state.params)
        tracer = self._armed_tracer()
        armed = tracer is not None
        settle = (
            self.topo.platform == "cpu" and self.topo.num_devices > 1
        )

        def _span():
            return (
                tracer.span("compute") if armed else obs_core.NULL_SPAN
            )

        def _settle(out):
            # armed compute spans carry proof-of-completion blocking so
            # the roofline figure is device time, not dispatch time; the
            # CPU mesh additionally must not pipeline programs
            if armed or settle:
                jax.block_until_ready(out)

        with _span():
            loss, *rows = self._grads_p(state.params, x, y)
            _settle(rows)

        gathered, res_sq = [], []
        for k, row in enumerate(rows):
            b = self._plan.buckets[k]
            if self.quant != "off":
                with _span():
                    codes, scales, new_r, sq = self._encode_p(
                        row, self._residual[k]
                    )
                    _settle(codes)
                self._residual[k] = new_r
                res_sq.append(sq)
                cx, sx = self._timed_hop(
                    self._hop1_p, (codes, scales), b.hop_bytes,
                    tracer, settle,
                )
                with _span():
                    rcodes, rscale, new_r2 = self._reduce_p(
                        cx, sx, self._residual2[k]
                    )
                    _settle(rcodes)
                self._residual2[k] = new_r2
                gathered.append(
                    self._timed_hop(
                        self._hop2_p, (rcodes, rscale), b.hop_bytes,
                        tracer, settle,
                    )
                )
            else:
                xch = self._timed_hop(
                    self._hop1_p, (row,), b.hop_bytes, tracer, settle
                )
                with _span():
                    red = self._reduce_p(xch)
                    _settle(red)
                gathered.append(
                    self._timed_hop(
                        self._hop2_p, (red,), b.hop_bytes, tracer, settle
                    )
                )

        with _span():
            state, metrics = self._apply_p(state, loss, gathered)
            _settle(metrics)

        rt_numerics = (
            _runtime.active_checker() is not None
            and getattr(_runtime.active_checker(), "numerics", False)
        )
        if armed or rt_numerics:
            elastic = (
                float(
                    np.sqrt(
                        sum(
                            float(np.sum(np.asarray(s))) for s in res_sq
                        )
                    )
                )
                if res_sq
                else 0.0
            )
            # RT104 sees the SAME value the dynamics plane journals as
            # `elastic` — the sanitizer and the journal can never
            # disagree about what the EF residual norm was
            _runtime.note_residual_norm("sync-dp.elastic", elastic)
        if armed:
            self._round += 1
            pn = float(metrics["param_norm"])
            un = float(metrics["update_norm"])
            # dynamics plane (docs/OBSERVABILITY.md "dynamics"): elastic
            # = EF residual norm — bounded by the quantization grid, so a
            # healthy run equilibrates; sustained growth = the quantized
            # stream diverging from the raw one
            tracer.journal.event(
                "dynamics",
                tracer.clock.tick(),
                round=self._round,
                algo="sync-dp",
                elastic=elastic,
                push_norm=un,
                param_norm=pn,
                fetch_delta=0.0,
                ratio=un / pn if pn > 0 else 0.0,
            )
        return state, metrics

    def step(self, state, x_global, y_global):
        """One sync-DP step on a global batch (leading dim divisible by W,
        per-worker shard divisible by accum_steps)."""
        self._check(x_global)
        if self.bucketed:
            state, metrics = self._bucketed_step(state, x_global, y_global)
        else:
            tracer = self._armed_tracer()
            if tracer is not None:
                with tracer.span("compute"):
                    state, metrics = self._step(state, x_global, y_global)
                    jax.block_until_ready(metrics)
            else:
                state, metrics = self._step(state, x_global, y_global)
        common.bound_cpu_dispatch(self.topo, metrics)
        return state, metrics

    def evaluate(self, state, x, y, batch: int = 1024):
        """Full-dataset eval; returns (accuracy, mean_loss)."""
        correct, loss_sum, n = common.batched_count_eval(
            self._eval, state.params, x, y, batch, self.topo.num_workers
        )
        return correct / n, loss_sum / n

    def fit(
        self,
        batches,
        state,
        epochs: int = 1,
        log_every: int = 0,
        start_epoch: int = 0,
        skip_steps: int = 0,
        on_step=None,
        prefetch: int = 2,
    ):
        """Epoch loop over a :class:`mpit_tpu.data.Batches` — the shared
        :func:`common.synced_fit_loop` with the sync-DP sharding/check.
        Returns (state, last_metrics)."""
        step_fn = self._bucketed_step if self.bucketed else self._step
        return common.synced_fit_loop(
            self.topo, step_fn, batches, state,
            sharding=self.topo.worker_sharding(),
            check=self._check,
            log_tag="sync-dp",
            epochs=epochs, log_every=log_every, start_epoch=start_epoch,
            skip_steps=skip_steps, on_step=on_step, prefetch=prefetch,
        )
