"""Expert-parallel training: MoE transformer over the worker axis.

Beyond-parity extension making the GShard MoE op (``ops/moe.py``)
load-bearing: ``TransformerLM(moe_experts=E, moe_axis="dp")`` trains with
its experts sharded across the SAME axis the batch shards over (the
DeepSpeed-MoE arrangement — expert parallelism rides the data-parallel
group, tokens travel to their expert's device and back via
``lax.all_to_all`` inside the compiled step).

Gradient accounting: each device seeds the cotangent of its own LOCAL
mean loss, so after the all_to_all transposes an expert leaf holds
``∂(Σ_i local_loss_i)/∂expert = W · ∂(global mean)/∂expert`` — divided by
W here — while replicated leaves hold only their local term and are
``pmean``-ed as usual. Both end up as gradients of the same global-mean
objective (pinned by the W-invariance test).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu.comm.topology import Topology
from mpit_tpu.parallel import common


def _is_expert_leaf(path) -> bool:
    """Expert-sharded leaves carry the ``moe_`` name prefix, except the
    replicated router (Block._moe's naming contract)."""
    keys = [getattr(p, "key", getattr(p, "name", "")) for p in path]
    last = keys[-1] if keys else ""
    return (
        isinstance(last, str)
        and last.startswith("moe_")
        and last != "moe_router"
    )


class MoEParallelTrainer:
    """Expert-parallel sync trainer for an MoE :class:`TransformerLM`.

    Usage::

        topo = mpit_tpu.init()   # 1-D worker mesh
        model = TransformerLM(vocab_size=V, moe_experts=16, moe_axis="dp")
        trainer = MoEParallelTrainer(model, optax.adam(3e-4), topo)
        state = trainer.init_state(jax.random.key(0), x[:2])
        state, metrics = trainer.step(state, x_global, y_global)

    OPTIMIZER CONSTRAINT: ``optimizer.update`` runs inside shard_map where
    expert-leaf gradients are device-varying. ELEMENTWISE transforms (sgd,
    momentum, adam, adamw, ...) are safe — each leaf's update depends only
    on that leaf. Cross-leaf transforms (``clip_by_global_norm``,
    ``global_norm``-based schedules) would compute a different scalar per
    device and silently desynchronize the replicated leaves; use per-leaf
    clipping (``clip``, ``clip_by_block_rms``) instead. The constructor
    probes the optimizer behaviorally and REJECTS cross-leaf transforms
    (:func:`common.assert_elementwise_optimizer`). For global-norm
    clipping specifically, pass ``clip_norm=c`` — the trainer applies
    :func:`common.clip_by_global_norm_in_mesh` to the reduced gradients
    inside the step (expert shards psum their sum-of-squares, replicated
    leaves count once), which equals ``optax.clip_by_global_norm(c)`` on
    the dense model exactly.
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        topo: Optional[Topology] = None,
        donate_state: bool = True,
        clip_norm: Optional[float] = None,
    ):
        self.model = model
        self.optimizer = optimizer
        common.assert_elementwise_optimizer(optimizer, "MoEParallelTrainer")
        clip_norm = self.clip_norm = common.check_clip_norm(clip_norm)
        self.topo = topo if topo is not None else _current_topology()
        mesh = self.topo.mesh
        axis = self.topo.worker_axis
        if getattr(model, "moe_experts", 0) <= 0:
            raise ValueError(
                "MoEParallelTrainer needs a model with moe_experts > 0"
            )
        if getattr(model, "moe_axis", None) != axis:
            raise ValueError(
                f"model.moe_axis={getattr(model, 'moe_axis', None)!r} must "
                f"name the worker axis {axis!r}"
            )
        w = self.topo.num_workers
        if model.moe_experts % w:
            raise ValueError(
                f"moe_experts={model.moe_experts} not divisible by "
                f"{w} workers"
            )
        from mpit_tpu.models.transformer import aggregate_moe_losses

        w_bal = float(getattr(model, "moe_balance_weight", 0.0))
        w_z = float(getattr(model, "moe_zloss_weight", 0.0))

        def loss_fn(params, x, y):
            """CE + weighted aux losses; aux stats reported either way.

            The sown stats come out of the op already pmean-ed over the
            worker axis, so the aux terms are identical on every device —
            the local-grad-then-reduce accounting below stays exact (see
            the module docstring)."""
            logits, mut = model.apply(
                {"params": params}, x, mutable=["moe_losses"]
            )
            aux = aggregate_moe_losses(mut["moe_losses"])
            loss = common.cross_entropy_loss(logits, y)
            loss = loss + w_bal * aux["balance"] + w_z * aux["zloss"]
            return loss, aux

        self.loss_fn = loss_fn

        def spec_of(path, _):
            return P(axis) if _is_expert_leaf(path) else P()

        def train_step(state: common.TrainState, x, y):
            (loss, aux), grads = jax.value_and_grad(
                self.loss_fn, has_aux=True
            )(state.params, x, y)
            # expert leaves: the all_to_all transpose already delivered
            # every device's contribution (scaled W x, see module doc);
            # replicated leaves: average the local terms
            grads = jax.tree_util.tree_map_with_path(
                lambda path, g: g / w if _is_expert_leaf(path)
                else jax.lax.pmean(g, axis),
                grads,
            )
            loss = jax.lax.pmean(loss, axis)
            if clip_norm is not None:
                grads, _ = common.clip_by_global_norm_in_mesh(
                    grads, clip_norm, axis, is_sharded=_is_expert_leaf
                )
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            metrics = {"loss": loss}
            metrics.update(
                (f"moe_{k}", v) for k, v in aux.items()
            )
            return (
                common.TrainState(
                    params=params, opt_state=opt_state, step=state.step + 1
                ),
                metrics,
            )

        # per-leaf specs: the SAME rule tree for state-in and state-out
        # (optimizer state mirrors the param tree paths)
        def state_specs(state):
            return common.TrainState(
                params=jax.tree_util.tree_map_with_path(
                    spec_of, state.params
                ),
                opt_state=jax.tree_util.tree_map_with_path(
                    spec_of, state.opt_state
                ),
                step=P(),
            )

        self._spec_of = spec_of
        self._state_specs = state_specs
        self._axis = axis
        self._mesh = mesh
        self._donate = donate_state
        self._train_step = train_step
        self._step = None  # built on first step (needs the state template)

        def eval_step(params, x, y):
            logits = self.model.apply({"params": params}, x)
            correct = jnp.sum(jnp.argmax(logits, -1) == y)
            loss_sum = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).sum()
            return jax.lax.psum(correct, axis), jax.lax.psum(loss_sum, axis)

        self._eval_fn = eval_step
        self._eval = None

    def _build(self, state):
        specs = self._state_specs(state)
        self._step = jax.jit(
            jax.shard_map(
                self._train_step,
                mesh=self._mesh,
                in_specs=(specs, P(self._axis), P(self._axis)),
                out_specs=(specs, P()),
                check_vma=False,
            ),
            donate_argnums=(0,) if self._donate else (),
        )
        self._eval = jax.jit(
            jax.shard_map(
                self._eval_fn,
                mesh=self._mesh,
                in_specs=(specs.params, P(self._axis), P(self._axis)),
                out_specs=(P(), P()),
                check_vma=False,
            )
        )

    def init_state(self, rng, sample_x) -> common.TrainState:
        """Init on the dense clone (global expert leaves), then commit
        each leaf to its expert-sharded or replicated placement."""
        dense = self.model.clone(moe_axis=None)
        variables = dense.init(rng, jnp.asarray(sample_x))
        state = common.TrainState.create(variables["params"], self.optimizer)
        specs = self._state_specs(state)
        shardings = jax.tree.map(
            lambda s: NamedSharding(self._mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        state = jax.device_put(state, shardings)
        if self._step is None:
            self._build(state)
        return state

    def step(self, state, x_global, y_global):
        """One expert-parallel step on a global batch."""
        common.check_global_batch(len(x_global), self.topo.num_workers)
        if self._step is None:
            self._build(state)
        state, metrics = self._step(state, x_global, y_global)
        common.bound_cpu_dispatch(self.topo, metrics)
        return state, metrics

    def fit(
        self,
        batches,
        state,
        epochs: int = 1,
        log_every: int = 0,
        start_epoch: int = 0,
        skip_steps: int = 0,
        on_step=None,
        prefetch: int = 2,
    ):
        """Epoch loop — the shared :func:`common.synced_fit_loop` with the
        worker-axis batch sharding."""
        if self._step is None:
            self._build(state)
        w = self.topo.num_workers
        return common.synced_fit_loop(
            self.topo, self._step, batches, state,
            sharding=self.topo.worker_sharding(),
            check=lambda x: common.check_global_batch(len(x), w),
            log_tag="moe-sync",
            epochs=epochs, log_every=log_every, start_epoch=start_epoch,
            skip_steps=skip_steps, on_step=on_step, prefetch=prefetch,
        )

    def evaluate(self, state, x, y, batch: int = 512):
        """Token-level accuracy and mean loss."""
        if self._eval is None:
            self._build(state)
        correct, loss_sum, n = common.batched_count_eval(
            self._eval, state.params, x, y, batch, self.topo.num_workers
        )
        tokens = n * x.shape[1]
        return correct / tokens, loss_sum / tokens
