"""pclient — worker-side stub for the host-async parameter server.

Reference parity (SURVEY.md §2 comp. 4): the reference's ``pclient`` owned
the worker→server mapping, flattened the model (``getParameters()``), and
exposed async fetch/push used by goptim every τ steps. Same role here: it
splits the flat vector across the server partition (``partition_bounds``),
talks the tag protocol over ``mpit_tpu.transport``, and leaves all actual
training math to the caller — compute stays jit-compiled on device, only
flat numpy chunks cross the transport.

Fault tolerance (docs/ROBUSTNESS.md; the reference would simply hang):

- :meth:`fetch` retries with exponential backoff, and every FETCH carries
  a fresh *attempt id* that the server echoes in its PARAM reply — a
  stale reply belonging to a timed-out earlier attempt (or a
  chaos-duplicated one) is discarded instead of being mis-assembled into
  the wrong chunk slot.
- pushes carry an ``(epoch, seq, basis_version, chunk)`` envelope; the
  server's dedup window applies each (epoch, seq) exactly once, so send
  retries after a connection reset (and duplicated frames) can never
  double-apply. ``basis_version`` echoes the center version stamped
  into the last PARAM reply this client accepted from that server
  (``server_version``), which lets the server journal per-push
  staleness — the training-dynamics plane of docs/OBSERVABILITY.md.
- transient send failures (``ConnectionError``/``OSError``) are retried
  with the same backoff schedule before surfacing to the caller.
- a PARAM reply mangled on the wire (chaos ``corrupt``/``truncate``) is
  validated against the expected partition length and discarded
  (``corrupt_params_dropped``); the attempt's timeout then re-issues the
  FETCH — corruption degrades to the already-handled lost-reply case.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Optional, Sequence

import numpy as np

from mpit_tpu.parallel.pserver import (
    TAG_FETCH,
    TAG_HEARTBEAT,
    TAG_JOIN,
    TAG_LEAVE,
    TAG_PARAM,
    TAG_PUSH_DELTA,
    TAG_PUSH_EASGD,
    TAG_STOP,
    partition_bounds,
)
from mpit_tpu.transport import RecvTimeout, Transport
from mpit_tpu.transport.wire import (
    QuantArray,
    dequantize,
    quant_mode_from_env,
    quantize,
)

# mpit-analysis: protocol-role[client->server]
# (the client side of the PS wire protocol — MPT008 pairs every send/recv
# here against the dispatch loop in pserver.py)


class PClient:
    """Client stub: fetch / push against a set of sharded pservers.

    ``server_ranks[s]`` owns flat chunk s of a ``param_size`` vector.

    ``heartbeat_interval``: when set, a daemon timer thread sends
    zero-payload HEARTBEATs to every server so the server watchdog
    (``PServer(client_timeout=...)``) doesn't declare this client dead
    during long local compute between exchanges. Stopped by :meth:`stop`.

    Retry knobs: ``timeout`` is the *per-attempt* PARAM wait;
    ``max_retries`` extra attempts follow the first, each preceded by an
    exponential backoff (``backoff_base * 2**k``, capped at
    ``backoff_max``). Worst-case fetch latency per server is therefore
    ``(max_retries + 1) * timeout`` plus the backoff sum.

    Accounting: ``push_sent[rank]`` counts chunks *successfully handed to
    the transport* per server — under fault injection that excludes
    resets (never delivered), so it is exactly the number the server
    should have applied (drops/blackholes excepted); the chaos acceptance
    test pins ``server.counts == client sends`` on it.
    """

    def __init__(
        self,
        transport: Transport,
        server_ranks: Sequence[int],
        param_size: int,
        timeout: Optional[float] = 60.0,
        heartbeat_interval: Optional[float] = None,
        max_retries: int = 3,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
        quant: Optional[str] = None,
    ):
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        self.transport = transport
        self.server_ranks = list(server_ranks)
        self.param_size = int(param_size)
        self.bounds = partition_bounds(self.param_size, len(self.server_ranks))
        # coalescing: a rank appearing k times in server_ranks owns k
        # adjacent chunks — merge them so each round sends ONE message per
        # distinct server (one framed scatter instead of k sends, one
        # FETCH/PARAM round trip instead of k). Non-adjacent repeats would
        # make the merged chunk non-contiguous; reject them.
        self.ranks: list[int] = []
        self.rank_bounds: list[tuple[int, int]] = []
        for rank, (start, end) in zip(self.server_ranks, self.bounds):
            if self.ranks and rank == self.ranks[-1]:
                self.rank_bounds[-1] = (self.rank_bounds[-1][0], end)
            elif rank in self.ranks:
                raise ValueError(
                    f"server rank {rank} repeats non-adjacently in "
                    f"{self.server_ranks} — its chunks would not be "
                    "contiguous, so they cannot coalesce"
                )
            else:
                self.ranks.append(rank)
                self.rank_bounds.append((start, end))
        if quant is None:
            quant = quant_mode_from_env()
        elif quant not in ("off", "bf16", "int8"):
            raise ValueError(f"quant must be off|bf16|int8, got {quant!r}")
        self.quant = quant
        # error feedback (EF/EF21 shape): the quantization residual of
        # each push is carried into the next one, so the quantizer's bias
        # cancels over rounds instead of accumulating into the center.
        # Keyed per (tag, rank): EASGD pushes params, Downpour pushes
        # deltas — different quantities, separate residual streams.
        self._residual: dict[tuple[int, int], np.ndarray] = {}
        self.timeout = timeout
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        # identity for the server-side dedup window: a replacement client
        # on a reused rank must not look like replays of its predecessor
        self._epoch = int.from_bytes(os.urandom(8), "big")
        # attempt ids are seeded from the epoch so a replacement process
        # on a reused rank can never match a PARAM reply parked in the
        # transport for its predecessor's attempt — same disjointness
        # the epoch gives the push dedup window, applied to fetches
        self._attempt_ids = itertools.count(((self._epoch & 0xFFFFFF) << 24) + 1)
        self._push_seq = itertools.count(1)
        self.push_sent: dict[int, int] = {r: 0 for r in self.server_ranks}
        # center version last seen per server (stamped into attempt-id'd
        # PARAM replies) — echoed as the fetch basis in push envelopes
        # so the server can attribute per-push staleness
        self.server_version: dict[int, int] = {}
        self.stale_params_dropped = 0
        self.corrupt_params_dropped = 0
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if heartbeat_interval is not None:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                args=(float(heartbeat_interval),),
                daemon=True,
                name="mpit-pclient-heartbeat",
            )
            self._hb_thread.start()

    def _heartbeat_loop(self, interval: float) -> None:
        while not self._hb_stop.wait(interval):
            for rank in self.server_ranks:
                try:
                    self.transport.send(rank, TAG_HEARTBEAT, None)
                except Exception:
                    # transient (e.g. a TCP blip mid-reconnect): liveness
                    # resumes next tick — one bad send must NOT silently
                    # kill the heartbeat and get a healthy client declared
                    # dead later. The interval bounds the retry rate; the
                    # thread exits only via stop().
                    pass

    # -- retry plumbing ---------------------------------------------------

    def _backoff(self, attempt: int) -> None:
        time.sleep(min(self.backoff_base * (2 ** attempt), self.backoff_max))

    def _send_with_retry(self, rank: int, tag: int, payload) -> None:
        """Send, absorbing up to ``max_retries`` transient transport
        failures with backoff. Safe for at-most-once payloads only when
        the receiver deduplicates (push envelopes) or the message is
        idempotent (FETCH, STOP)."""
        for attempt in range(self.max_retries + 1):
            try:
                self.transport.send(rank, tag, payload)
                return
            except (ConnectionError, OSError):
                if attempt == self.max_retries:
                    raise
                self._backoff(attempt)

    def _send_fetch(self, rank: int) -> int:
        attempt_id = next(self._attempt_ids)
        self.transport.send(rank, TAG_FETCH, attempt_id)
        return attempt_id

    def _send_join(self, rank: int) -> int:
        attempt_id = next(self._attempt_ids)
        self.transport.send(rank, TAG_JOIN, (attempt_id, self._epoch))
        return attempt_id

    def _chunk_ok(self, chunk, expected: int) -> Optional[np.ndarray]:
        """float32 view of a PARAM chunk, or None when the reply is
        malformed (chaos ``corrupt`` replaced the frame, ``truncate`` cut
        the array short, or the shape just doesn't match this server's
        partition). Accepts, beyond a bare ndarray: a quantized
        :class:`QuantArray` (dequantized here) and a multi-chunk reply —
        a list of ndarray/QuantArray parts that concatenate to this
        server's merged partition (a sharded server answering one
        coalesced FETCH with its per-shard chunks in one message)."""
        try:
            if isinstance(chunk, QuantArray):
                arr = dequantize(chunk)
            elif isinstance(chunk, list):
                if not chunk:
                    return None
                arr = np.concatenate([
                    dequantize(p) if isinstance(p, QuantArray)
                    else np.asarray(p, dtype=np.float32)
                    for p in chunk
                ])
            else:
                arr = np.asarray(chunk, dtype=np.float32)
            arr = np.asarray(arr, dtype=np.float32)
        except (TypeError, ValueError):
            return None
        if arr.shape != (expected,):
            return None
        return arr

    def _await_param(
        self, rank: int, attempt_id: Optional[int], expected: int,
        resend=None,
    ) -> np.ndarray:
        """Collect one server's PARAM chunk, retrying the whole
        FETCH→PARAM attempt on timeout or send failure. Replies tagged
        with an attempt id other than the live one are stale — consumed
        and discarded so they can never be assembled into this (or a
        later) fetch. Malformed replies (chaos corrupt/truncate) are
        likewise discarded — the wait continues and the per-attempt
        timeout re-issues the FETCH, so a mangled reply is a retriable
        failure, never a crash or a junk-assembled vector."""
        if resend is None:
            resend = self._send_fetch
        last_exc: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            if attempt > 0:
                self._backoff(attempt - 1)
            if attempt_id is None:  # (re)issue this attempt's request
                try:
                    attempt_id = resend(rank)
                except (ConnectionError, OSError) as e:
                    last_exc = e
                    continue
            deadline = (
                None if self.timeout is None
                else time.monotonic() + self.timeout
            )
            while True:
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    last_exc = RecvTimeout(
                        f"PARAM from server {rank} not received within "
                        f"{self.timeout}s (attempt {attempt + 1})"
                    )
                    break
                try:
                    msg = self.transport.recv(
                        rank, TAG_PARAM, timeout=remaining
                    )
                except RecvTimeout as e:
                    last_exc = e
                    break
                payload = msg.payload
                if isinstance(payload, tuple) and len(payload) == 3:
                    # versioned reply (attempt_id, version, chunk) — the
                    # only shape today's server emits for id'd fetches
                    got_id, version, chunk = payload
                    if got_id != attempt_id:
                        self.stale_params_dropped += 1
                        continue  # a timed-out attempt's late reply
                    arr = self._chunk_ok(chunk, expected)
                    if arr is None:
                        # mangled on the wire: keep waiting; the timeout
                        # re-fetches (the server won't resend on its own)
                        self.corrupt_params_dropped += 1
                        continue
                    if isinstance(version, int):
                        # basis for this client's next push envelopes; a
                        # chaos-mangled non-int version just leaves the
                        # previous basis in place (staleness degrades to
                        # an overestimate, never a crash)
                        self.server_version[rank] = version
                    return arr
                if isinstance(payload, tuple) and len(payload) == 2:
                    # pre-version (attempt_id, chunk) reply — kept for
                    # hand-rolled protocol tests and mixed-version runs
                    got_id, chunk = payload
                    if got_id != attempt_id:
                        self.stale_params_dropped += 1
                        continue
                    arr = self._chunk_ok(chunk, expected)
                    if arr is None:
                        self.corrupt_params_dropped += 1
                        continue
                    return arr
                arr = self._chunk_ok(payload, expected)  # legacy un-id'd
                if arr is None:
                    self.corrupt_params_dropped += 1
                    continue
                return arr
            attempt_id = None  # attempt dead: the next one re-sends
        raise RecvTimeout(
            f"fetch from server {rank} failed after "
            f"{self.max_retries + 1} attempts"
        ) from last_exc

    # -- protocol ---------------------------------------------------------

    def fetch(self) -> np.ndarray:
        """Gather the full flat center from all servers (async fan-out:
        request every chunk before waiting on any — the reference's
        ``async_fetch_param`` shape, SURVEY.md §3(b)); per-server
        retry-with-backoff on timeout, attempt-id'd against stale
        replies."""
        attempts: dict[int, Optional[int]] = {}
        for rank in self.ranks:
            try:
                attempts[rank] = self._send_fetch(rank)
            except (ConnectionError, OSError):
                attempts[rank] = None  # the retry path re-sends
        out = np.empty(self.param_size, np.float32)
        for rank, (start, end) in zip(self.ranks, self.rank_bounds):
            out[start:end] = self._await_param(
                rank, attempts[rank], end - start
            )
        return out

    def join(self) -> np.ndarray:
        """Announce this client's (rank, epoch) to every server and
        gather the full flat center — the elastic-membership entry
        point (docs/ROBUSTNESS.md). Same fan-out/retry/attempt-id shape
        as :meth:`fetch`, but the JOIN envelope also registers this
        process's push-identity epoch with the server's membership
        view: a fresh process on a reused rank is recorded as a
        "replace" (clean dedup slot, dead flag cleared), a reconnecting
        preempted one as a "rejoin" — instead of being mistaken for a
        replay of its predecessor."""
        attempts: dict[int, Optional[int]] = {}
        for rank in self.ranks:
            try:
                attempts[rank] = self._send_join(rank)
            except (ConnectionError, OSError):
                attempts[rank] = None  # the retry path re-sends
        out = np.empty(self.param_size, np.float32)
        for rank, (start, end) in zip(self.ranks, self.rank_bounds):
            out[start:end] = self._await_param(
                rank, attempts[rank], end - start, resend=self._send_join
            )
        return out

    def push_easgd(self, flat_params: np.ndarray) -> None:
        """Push local params; each server does its elastic center move."""
        self._scatter(TAG_PUSH_EASGD, flat_params)

    def push_delta(self, flat_delta: np.ndarray) -> None:
        """Push an accumulated update (Downpour grad/delta apply)."""
        self._scatter(TAG_PUSH_DELTA, flat_delta)

    def stop(self) -> None:
        """Detach from every server (teardown protocol, SURVEY.md §3(e)).

        Attempts ALL servers even when some sends fail — skipping the
        rest would leave healthy servers waiting for a STOP that never
        comes (until their watchdog fires). Errors are collected and
        re-raised as one aggregate at the end."""
        self._shutdown_heartbeat()
        self._detach_all(TAG_STOP, "STOP")

    def leave(self) -> None:
        """Planned departure (preemption notice): tell every server this
        rank is going away WITHOUT counting as a normal STOP — the
        membership view moves it to ``left`` immediately instead of
        waiting for the watchdog to declare it dead. Same all-servers /
        aggregate-errors contract as :meth:`stop`."""
        self._shutdown_heartbeat()
        self._detach_all(TAG_LEAVE, "LEAVE")

    def _shutdown_heartbeat(self) -> None:
        """Signal and join the heartbeat timer thread; idempotent so
        stop()/leave() can be called more than once (or after each
        other) without a second join on a dead thread."""
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None

    def _detach_all(self, tag: int, what: str) -> None:
        errors: list[tuple[int, BaseException]] = []
        for rank in self.server_ranks:
            try:
                self._send_with_retry(rank, tag, None)
            except Exception as e:
                errors.append((rank, e))
        if errors:
            raise RuntimeError(
                f"{what} failed for server rank(s) "
                f"{[r for r, _ in errors]}: "
                f"{'; '.join(repr(e) for _, e in errors)}"
            ) from errors[0][1]

    def _scatter(self, tag: int, flat: np.ndarray) -> None:
        flat = np.asarray(flat, np.float32)
        if flat.shape != (self.param_size,):
            raise ValueError(
                f"flat vector shape {flat.shape} != ({self.param_size},)"
            )
        # one seq per logical push: every server's chunk shares it, and a
        # send retry re-offers the same (epoch, seq) — the server window
        # turns at-least-once delivery into exactly-once application.
        # Each chunk carries that server's last-fetched center version
        # as its staleness basis (0 = never fetched a versioned reply).
        seq = next(self._push_seq)
        for rank, (start, end) in zip(self.ranks, self.rank_bounds):
            chunk = flat[start:end]
            if self.quant != "off":
                # error feedback: compensate this push with the residual
                # the previous quantized push left behind, then carry the
                # new residual forward — the bias cancels over rounds.
                # The residual is folded in BEFORE send-retry, so a
                # retried (deduplicated) send re-offers identical bytes.
                key = (tag, rank)
                res = self._residual.get(key)
                comp = chunk if res is None else chunk + res
                q = quantize(comp, self.quant)
                self._residual[key] = comp - dequantize(q)
                payload_chunk = q
            else:
                payload_chunk = chunk
            self._send_with_retry(
                rank, tag,
                (
                    self._epoch, seq,
                    self.server_version.get(rank, 0),
                    payload_chunk,
                ),
            )
            self.push_sent[rank] += 1
