"""pclient — worker-side stub for the host-async parameter server.

Reference parity (SURVEY.md §2 comp. 4): the reference's ``pclient`` owned
the worker→server mapping, flattened the model (``getParameters()``), and
exposed async fetch/push used by goptim every τ steps. Same role here: it
splits the flat vector across the server partition (``partition_bounds``),
talks the tag protocol over ``mpit_tpu.transport``, and leaves all actual
training math to the caller — compute stays jit-compiled on device, only
flat numpy chunks cross the transport.

Fault tolerance (docs/ROBUSTNESS.md; the reference would simply hang):

- :meth:`fetch` retries with exponential backoff, and every FETCH carries
  a fresh *attempt id* that the server echoes in its PARAM reply — a
  stale reply belonging to a timed-out earlier attempt (or a
  chaos-duplicated one) is discarded instead of being mis-assembled into
  the wrong chunk slot.
- pushes carry an ``(epoch, seq, basis_version, chunk)`` envelope; the
  server's dedup window applies each (epoch, seq) exactly once, so send
  retries after a connection reset (and duplicated frames) can never
  double-apply. ``basis_version`` echoes the center version stamped
  into the last PARAM reply this client accepted from that server
  (``server_version``), which lets the server journal per-push
  staleness — the training-dynamics plane of docs/OBSERVABILITY.md.
- transient send failures (``ConnectionError``/``OSError``) are retried
  with the same backoff schedule before surfacing to the caller.
- a PARAM reply mangled on the wire (chaos ``corrupt``/``truncate``) is
  validated against the expected partition length and discarded
  (``corrupt_params_dropped``); the attempt's timeout then re-issues the
  FETCH — corruption degrades to the already-handled lost-reply case.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Optional, Sequence

import numpy as np

from mpit_tpu.analysis.runtime import (
    active_checker as _rt_active,
    make_lock,
    note_residual_norm as _rt_residual,
)
from mpit_tpu.parallel.pserver import (
    TAG_FETCH,
    TAG_HEARTBEAT,
    TAG_JOIN,
    TAG_LEAVE,
    TAG_PARAM,
    TAG_PUSH_DELTA,
    TAG_PUSH_EASGD,
    TAG_SHARD_MAP,
    TAG_STOP,
    partition_bounds,
)
from mpit_tpu.transport import RecvTimeout, Transport
from mpit_tpu.transport.wire import (
    QuantArray,
    dequantize,
    quant_mode_from_env,
    quantize,
)

# mpit-analysis: protocol-role[client->server]
# (the client side of the PS wire protocol — MPT008 pairs every send/recv
# here against the dispatch loop in pserver.py)


class PClient:
    """Client stub: fetch / push against a set of sharded pservers.

    ``server_ranks[s]`` owns flat chunk s of a ``param_size`` vector.

    ``heartbeat_interval``: when set, a daemon timer thread sends
    zero-payload HEARTBEATs to every server so the server watchdog
    (``PServer(client_timeout=...)``) doesn't declare this client dead
    during long local compute between exchanges. Stopped by :meth:`stop`.

    Retry knobs: ``timeout`` is the *per-attempt* PARAM wait;
    ``max_retries`` extra attempts follow the first, each preceded by an
    exponential backoff (``backoff_base * 2**k``, capped at
    ``backoff_max``). Worst-case fetch latency per server is therefore
    ``(max_retries + 1) * timeout`` plus the backoff sum.

    Accounting: ``push_sent[rank]`` counts chunks *successfully handed to
    the transport* per server — under fault injection that excludes
    resets (never delivered), so it is exactly the number the server
    should have applied (drops/blackholes excepted); the chaos acceptance
    test pins ``server.counts == client sends`` on it.
    """

    def __init__(
        self,
        transport: Transport,
        server_ranks: Sequence[int],
        param_size: int,
        timeout: Optional[float] = 60.0,
        heartbeat_interval: Optional[float] = None,
        max_retries: int = 3,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
        quant: Optional[str] = None,
        shard_map=None,
    ):
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        self.transport = transport
        self.server_ranks = list(server_ranks)
        self.param_size = int(param_size)
        # consistent-hash routing (docs/ROBUSTNESS.md "Shard ownership &
        # resharding"): with a ShardMap, chunk ownership comes from the
        # ring instead of positional partition_bounds, PARAM replies and
        # push envelopes carry per-shard parts, and a dead server is a
        # repair (reroute + fallback fill) instead of a lost round
        self._shard_map = shard_map
        # chunks repaired across reshards: every shard whose ownership
        # this client rerouted off a dead server (the re-offered chunks
        # land at the new owner next round instead of skipping it)
        self.repaired_chunks = 0
        # per-shard center versions from sharded PARAM replies — the
        # dynamics-plane staleness signal stays attributable per shard
        # even while ownership moves
        self.shard_versions: dict[int, int] = {}
        self._rank_shards: dict[int, list[tuple[int, int, int]]] = {}
        # guards the routing tables (server_ranks/ranks/_rank_chunks/...)
        # that `_repair_dead` rebuilds mid-run while the heartbeat thread
        # (and a supervising caller's stop/leave) iterate them
        self._route_lock = make_lock("PClient._route_lock")
        if shard_map is not None:
            if shard_map.param_size != self.param_size:
                raise ValueError(
                    f"shard_map covers {shard_map.param_size} params, "
                    f"client has {self.param_size}"
                )
            self.bounds = list(shard_map.layout)
            self._rank_chunks: dict[int, list[tuple[int, int]]] = {}
            self.ranks: list[int] = []
            self.rank_bounds: list[tuple[int, int]] = []
            self._build_ring_routing()
        else:
            self.bounds = partition_bounds(
                self.param_size, len(self.server_ranks)
            )
            # coalescing: a rank appearing k times in server_ranks owns k
            # chunks — group them per destination so each round sends ONE
            # message per distinct server (one framed scatter instead of
            # k sends, one FETCH/PARAM round trip instead of k). Adjacent
            # chunks merge into one contiguous slice; non-adjacent ones
            # (the common case under ring assignment) ride the same
            # message as separate slices.
            self.ranks = []
            self._rank_chunks = {}
            for rank, (start, end) in zip(self.server_ranks, self.bounds):
                chunks = self._rank_chunks.setdefault(rank, [])
                if rank not in self.ranks:
                    self.ranks.append(rank)
                if chunks and chunks[-1][1] == start:
                    chunks[-1] = (chunks[-1][0], end)
                else:
                    chunks.append((start, end))
            # bounding hull per rank, kept for observability/back-compat
            # (equals the merged chunk when a rank's slices are adjacent)
            self.rank_bounds = [
                (self._rank_chunks[r][0][0], self._rank_chunks[r][-1][1])
                for r in self.ranks
            ]
        if quant is None:
            quant = quant_mode_from_env()
        elif quant not in ("off", "bf16", "int8"):
            raise ValueError(f"quant must be off|bf16|int8, got {quant!r}")
        self.quant = quant
        # error feedback (EF/EF21 shape): the quantization residual of
        # each push is carried into the next one, so the quantizer's bias
        # cancels over rounds instead of accumulating into the center.
        # Keyed per (tag, rank): EASGD pushes params, Downpour pushes
        # deltas — different quantities, separate residual streams.
        self._residual: dict[tuple[int, int], np.ndarray] = {}
        self.timeout = timeout
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        # identity for the server-side dedup window: a replacement client
        # on a reused rank must not look like replays of its predecessor
        self._epoch = int.from_bytes(os.urandom(8), "big")
        # attempt ids are seeded from the epoch so a replacement process
        # on a reused rank can never match a PARAM reply parked in the
        # transport for its predecessor's attempt — same disjointness
        # the epoch gives the push dedup window, applied to fetches
        self._attempt_ids = itertools.count(((self._epoch & 0xFFFFFF) << 24) + 1)
        self._push_seq = itertools.count(1)
        self.push_sent: dict[int, int] = {r: 0 for r in self.server_ranks}
        # center version last seen per server (stamped into attempt-id'd
        # PARAM replies) — echoed as the fetch basis in push envelopes
        # so the server can attribute per-push staleness
        self.server_version: dict[int, int] = {}
        self.stale_params_dropped = 0
        self.corrupt_params_dropped = 0
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if heartbeat_interval is not None:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                args=(float(heartbeat_interval),),
                daemon=True,
                name="mpit-pclient-heartbeat",
            )
            self._hb_thread.start()

    def _heartbeat_loop(self, interval: float) -> None:
        while not self._hb_stop.wait(interval):
            with self._route_lock:
                targets = list(self.server_ranks)
            for rank in targets:
                try:
                    self.transport.send(rank, TAG_HEARTBEAT, None)
                except Exception:
                    # transient (e.g. a TCP blip mid-reconnect): liveness
                    # resumes next tick — one bad send must NOT silently
                    # kill the heartbeat and get a healthy client declared
                    # dead later. The interval bounds the retry rate; the
                    # thread exits only via stop().
                    pass

    # -- ring routing & repair --------------------------------------------

    def _build_ring_routing(self) -> None:
        """Derive per-server routing from the current shard map: which
        (sid, start, end) slices each live server owns, ascending. Also
        refreshes ``server_ranks``/``ranks`` so heartbeats, STOP/LEAVE
        fan-out, and scatters track the surviving membership."""
        sm = self._shard_map
        shards: dict[int, list[tuple[int, int, int]]] = {}
        for sid, (s, e) in enumerate(sm.layout):
            shards.setdefault(sm.assignment[sid], []).append((sid, s, e))
        with self._route_lock:
            self._rank_shards = {
                r: sorted(v, key=lambda t: t[1]) for r, v in shards.items()
            }
            self.ranks = sorted(self._rank_shards)
            self.server_ranks = list(self.ranks)
            self._rank_chunks = {
                r: [(s, e) for _, s, e in v]
                for r, v in self._rank_shards.items()
            }
            self.rank_bounds = [
                (self._rank_chunks[r][0][0], self._rank_chunks[r][-1][1])
                for r in self.ranks
            ]

    def _repair_dead(self, dead_rank: int) -> None:
        """Partial-scatter repair: reroute ownership off a dead server.

        The ring is deterministic, so every client that observes the
        same death derives the SAME successor view — the announcements
        they fan out to the survivors share a ring version, and the
        servers take the first one and idempotently ignore the rest.
        This client's next scatter re-offers the dead server's chunks
        to their new owners instead of skipping the round."""
        sm = self._shard_map
        if dead_rank not in sm.ring.members or len(sm.ring.members) <= 1:
            return
        new_ring = sm.ring.without(dead_rank)
        new_map = sm.with_ring(new_ring)
        moved = [
            sid
            for sid in range(sm.num_shards)
            if sm.assignment[sid] != new_map.assignment[sid]
        ]
        self._shard_map = new_map
        self._build_ring_routing()
        for r in self.ranks:
            self.push_sent.setdefault(r, 0)
        # quantization residuals are keyed per shard in ring mode, so
        # they survive the reroute; versions for moved shards restart at
        # the new owner's counter on the next fetch
        announce = (new_ring.version, list(new_ring.members))
        for r in list(self.ranks):
            try:
                self._send_with_retry(r, TAG_SHARD_MAP, announce)
            except (ConnectionError, OSError):
                # unreachable survivor: its own clients' repair rounds
                # (or ours, next fetch) re-announce the same view
                pass
        self.repaired_chunks += len(moved)
        self._journal(
            "reshard_repair", dead=dead_rank, view=new_ring.version,
            moved=len(moved),
        )

    def _journal(self, ev: str, **fields) -> None:
        """Dynamics-plane journal record via the transport's obs tracer
        (no-op unless obs-wrapped with journaling on — the same
        disabled-cost contract as the server's `_journal_dynamics`)."""
        tracer = getattr(self.transport, "obs_tracer", None)
        if tracer is None or tracer.journal is None:
            return
        tracer.journal.event(ev, tracer.clock.tick(), **fields)

    # -- retry plumbing ---------------------------------------------------

    def _backoff(self, attempt: int) -> None:
        time.sleep(min(self.backoff_base * (2 ** attempt), self.backoff_max))

    def _send_with_retry(self, rank: int, tag: int, payload) -> None:
        """Send, absorbing up to ``max_retries`` transient transport
        failures with backoff. Safe for at-most-once payloads only when
        the receiver deduplicates (push envelopes) or the message is
        idempotent (FETCH, STOP)."""
        for attempt in range(self.max_retries + 1):
            try:
                self.transport.send(rank, tag, payload)
                return
            except (ConnectionError, OSError):
                if attempt == self.max_retries:
                    raise
                self._backoff(attempt)

    def _send_fetch(self, rank: int) -> int:
        attempt_id = next(self._attempt_ids)
        self.transport.send(rank, TAG_FETCH, attempt_id)
        return attempt_id

    def _send_join(self, rank: int) -> int:
        attempt_id = next(self._attempt_ids)
        self.transport.send(rank, TAG_JOIN, (attempt_id, self._epoch))
        return attempt_id

    def _chunk_ok(self, chunk, expected: int) -> Optional[np.ndarray]:
        """float32 view of a PARAM chunk, or None when the reply is
        malformed (chaos ``corrupt`` replaced the frame, ``truncate`` cut
        the array short, or the shape just doesn't match this server's
        partition). Accepts, beyond a bare ndarray: a quantized
        :class:`QuantArray` (dequantized here) and a multi-chunk reply —
        a list of ndarray/QuantArray parts that concatenate to this
        server's merged partition (a sharded server answering one
        coalesced FETCH with its per-shard chunks in one message)."""
        try:
            if isinstance(chunk, QuantArray):
                arr = dequantize(chunk)
            elif isinstance(chunk, list):
                if not chunk:
                    return None
                arr = np.concatenate([
                    dequantize(p) if isinstance(p, QuantArray)
                    else np.asarray(p, dtype=np.float32)
                    for p in chunk
                ])
            else:
                arr = np.asarray(chunk, dtype=np.float32)
            arr = np.asarray(arr, dtype=np.float32)
        except (TypeError, ValueError):
            return None
        if arr.shape != (expected,):
            return None
        return arr

    def _parts_ok(self, chunk) -> Optional[list]:
        """``[(sid, shard_version, arr)]`` from a sharded PARAM reply,
        or None when malformed. Each part is validated against its
        static layout slot — placement never depends on the sender's
        ring view, so a reply stays interpretable even when ownership
        moved under us (the server replies with everything it owns; we
        take whatever arrives, wherever the layout says it lives)."""
        if not isinstance(chunk, list) or not chunk:
            return None
        out = []
        layout = self._shard_map.layout
        num_shards = self._shard_map.num_shards
        for part in chunk:
            if not (
                isinstance(part, (tuple, list))
                and len(part) == 3
                and isinstance(part[0], int)
            ):
                return None
            sid, ver, arr = part
            if not (0 <= sid < num_shards):
                return None
            try:
                if isinstance(arr, QuantArray):
                    arr = dequantize(arr)
                # wire payloads are host numpy (msgpack-decoded), never
                # device arrays — no host sync happens here
                a = np.asarray(arr, dtype=np.float32)  # mpit-analysis: ignore[MPT005]
            except (TypeError, ValueError):
                return None
            s, e = layout[sid]
            if a.shape != (e - s,):
                return None
            out.append((sid, ver if isinstance(ver, int) else 0, a))
        return out

    def _accept_chunk(self, chunk, expected: Optional[int]):
        """Validate a PARAM body: ``expected=None`` means a sharded
        parts reply, an int the legacy contiguous chunk of that size."""
        if expected is None:
            return self._parts_ok(chunk)
        return self._chunk_ok(chunk, expected)

    def _await_param(
        self, rank: int, attempt_id: Optional[int], expected: int,
        resend=None,
    ) -> np.ndarray:
        """Collect one server's PARAM chunk, retrying the whole
        FETCH→PARAM attempt on timeout or send failure. Replies tagged
        with an attempt id other than the live one are stale — consumed
        and discarded so they can never be assembled into this (or a
        later) fetch. Malformed replies (chaos corrupt/truncate) are
        likewise discarded — the wait continues and the per-attempt
        timeout re-issues the FETCH, so a mangled reply is a retriable
        failure, never a crash or a junk-assembled vector."""
        if resend is None:
            resend = self._send_fetch
        last_exc: Optional[BaseException] = None
        for attempt in range(self.max_retries + 1):
            if attempt > 0:
                self._backoff(attempt - 1)
            if attempt_id is None:  # (re)issue this attempt's request
                try:
                    attempt_id = resend(rank)
                except (ConnectionError, OSError) as e:
                    last_exc = e
                    continue
            deadline = (
                None if self.timeout is None
                else time.monotonic() + self.timeout
            )
            while True:
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    last_exc = RecvTimeout(
                        f"PARAM from server {rank} not received within "
                        f"{self.timeout}s (attempt {attempt + 1})"
                    )
                    break
                try:
                    msg = self.transport.recv(
                        rank, TAG_PARAM, timeout=remaining
                    )
                except RecvTimeout as e:
                    last_exc = e
                    break
                payload = msg.payload
                if isinstance(payload, tuple) and len(payload) == 3:
                    # versioned reply (attempt_id, version, chunk) — the
                    # only shape today's server emits for id'd fetches
                    got_id, version, chunk = payload
                    if got_id != attempt_id:
                        self.stale_params_dropped += 1
                        continue  # a timed-out attempt's late reply
                    arr = self._accept_chunk(chunk, expected)
                    if arr is None:
                        # mangled on the wire: keep waiting; the timeout
                        # re-fetches (the server won't resend on its own)
                        self.corrupt_params_dropped += 1
                        continue
                    if isinstance(version, int):
                        # basis for this client's next push envelopes; a
                        # chaos-mangled non-int version just leaves the
                        # previous basis in place (staleness degrades to
                        # an overestimate, never a crash)
                        self.server_version[rank] = version
                    return arr
                if isinstance(payload, tuple) and len(payload) == 2:
                    # pre-version (attempt_id, chunk) reply — kept for
                    # hand-rolled protocol tests and mixed-version runs
                    got_id, chunk = payload
                    if got_id != attempt_id:
                        self.stale_params_dropped += 1
                        continue
                    arr = self._accept_chunk(chunk, expected)
                    if arr is None:
                        self.corrupt_params_dropped += 1
                        continue
                    return arr
                arr = self._accept_chunk(payload, expected)  # legacy un-id'd
                if arr is None:
                    self.corrupt_params_dropped += 1
                    continue
                return arr
            attempt_id = None  # attempt dead: the next one re-sends
        raise RecvTimeout(
            f"fetch from server {rank} failed after "
            f"{self.max_retries + 1} attempts"
        ) from last_exc

    # -- protocol ---------------------------------------------------------

    def fetch(self, fallback: Optional[np.ndarray] = None) -> np.ndarray:
        """Gather the full flat center from all servers (async fan-out:
        request every chunk before waiting on any — the reference's
        ``async_fetch_param`` shape, SURVEY.md §3(b)); per-server
        retry-with-backoff on timeout, attempt-id'd against stale
        replies.

        ``fallback`` (ring mode): the client's local flat params. When a
        server is declared dead mid-fetch, its shards are rerouted on
        the ring (partial-scatter repair) and any still-unsatisfied
        slice is filled from ``fallback`` for THIS round only — the next
        round fetches it from the new owner. Without a fallback a dead
        server raises, as in legacy mode."""
        return self._gather(self._send_fetch, fallback)

    def join(self, fallback: Optional[np.ndarray] = None) -> np.ndarray:
        """Announce this client's (rank, epoch) to every server and
        gather the full flat center — the elastic-membership entry
        point (docs/ROBUSTNESS.md). Same fan-out/retry/attempt-id shape
        as :meth:`fetch`, but the JOIN envelope also registers this
        process's push-identity epoch with the server's membership
        view: a fresh process on a reused rank is recorded as a
        "replace" (clean dedup slot, dead flag cleared), a reconnecting
        preempted one as a "rejoin" — instead of being mistaken for a
        replay of its predecessor."""
        return self._gather(self._send_join, fallback)

    def _gather(self, resend, fallback: Optional[np.ndarray]) -> np.ndarray:
        attempts: dict[int, Optional[int]] = {}
        for rank in list(self.ranks):
            try:
                attempts[rank] = resend(rank)
            except (ConnectionError, OSError):
                attempts[rank] = None  # the retry path re-sends
        out = np.empty(self.param_size, np.float32)
        if self._shard_map is None:
            for rank in self.ranks:
                chunks = self._rank_chunks[rank]
                total = sum(e - s for s, e in chunks)
                arr = self._await_param(
                    rank, attempts[rank], total, resend=resend
                )
                # split the coalesced reply back across this rank's
                # slices, ascending — the inverse of the scatter order
                off = 0
                for s, e in chunks:
                    out[s:e] = arr[off:off + (e - s)]
                    off += e - s
            return out
        # ring mode: parts replies carry (sid, version, slice); place by
        # the static layout, then repair around any dead server
        filled: set[int] = set()
        dead: list[int] = []
        for rank in list(self.ranks):
            try:
                parts = self._await_param(
                    rank, attempts.get(rank), None, resend=resend
                )
            except RecvTimeout:
                if fallback is None:
                    raise
                dead.append(rank)
                continue
            for sid, ver, arr in parts:
                s, e = self._shard_map.layout[sid]
                out[s:e] = arr
                filled.add(sid)
                self.shard_versions[sid] = ver
        for rank in dead:
            self._repair_dead(rank)
        missing = [
            sid
            for sid in range(self._shard_map.num_shards)
            if sid not in filled
        ]
        if missing:
            if fallback is None:
                raise RecvTimeout(
                    f"shards {missing} unavailable and no fallback given"
                )
            fb = np.asarray(fallback, np.float32)
            for sid in missing:
                s, e = self._shard_map.layout[sid]
                out[s:e] = fb[s:e]
        return out

    def push_easgd(self, flat_params: np.ndarray) -> None:
        """Push local params; each server does its elastic center move."""
        self._scatter(TAG_PUSH_EASGD, flat_params)

    def push_delta(self, flat_delta: np.ndarray) -> None:
        """Push an accumulated update (Downpour grad/delta apply)."""
        self._scatter(TAG_PUSH_DELTA, flat_delta)

    def stop(self) -> None:
        """Detach from every server (teardown protocol, SURVEY.md §3(e)).

        Attempts ALL servers even when some sends fail — skipping the
        rest would leave healthy servers waiting for a STOP that never
        comes (until their watchdog fires). Errors are collected and
        re-raised as one aggregate at the end."""
        self._shutdown_heartbeat()
        self._detach_all(TAG_STOP, "STOP")

    def leave(self) -> None:
        """Planned departure (preemption notice): tell every server this
        rank is going away WITHOUT counting as a normal STOP — the
        membership view moves it to ``left`` immediately instead of
        waiting for the watchdog to declare it dead. Same all-servers /
        aggregate-errors contract as :meth:`stop`."""
        self._shutdown_heartbeat()
        self._detach_all(TAG_LEAVE, "LEAVE")

    def _shutdown_heartbeat(self) -> None:
        """Signal and join the heartbeat timer thread; idempotent so
        stop()/leave() can be called more than once (or after each
        other) without a second join on a dead thread."""
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
            self._hb_thread = None

    def _detach_all(self, tag: int, what: str) -> None:
        errors: list[tuple[int, BaseException]] = []
        with self._route_lock:
            targets = list(self.server_ranks)
        for rank in targets:
            try:
                self._send_with_retry(rank, tag, None)
            except Exception as e:
                errors.append((rank, e))
        if errors:
            raise RuntimeError(
                f"{what} failed for server rank(s) "
                f"{[r for r, _ in errors]}: "
                f"{'; '.join(repr(e) for _, e in errors)}"
            ) from errors[0][1]

    def _scatter(self, tag: int, flat: np.ndarray) -> None:
        flat = np.asarray(flat, np.float32)
        if flat.shape != (self.param_size,):
            raise ValueError(
                f"flat vector shape {flat.shape} != ({self.param_size},)"
            )
        # one seq per logical push: every server's chunk shares it, and a
        # send retry re-offers the same (epoch, seq) — the server window
        # turns at-least-once delivery into exactly-once application.
        # Each chunk carries that server's last-fetched center version
        # as its staleness basis (0 = never fetched a versioned reply).
        seq = next(self._push_seq)
        # RT104 boundedness probe: one norm per EF-residual update when
        # the numerics sanitizer is armed, zero host work otherwise
        rt_checker = _rt_active()
        rt_numerics = rt_checker is not None and getattr(
            rt_checker, "numerics", False
        )
        if self._shard_map is not None:
            # ring mode: one envelope per live server carrying its
            # (sid, chunk) parts — after a repair the re-offered shards
            # simply route to their new owner under the same seq
            # discipline. Residuals are keyed per shard so error
            # feedback survives ownership moves.
            for rank in list(self.ranks):
                parts = []
                for sid, s, e in self._rank_shards[rank]:
                    chunk = flat[s:e]
                    if self.quant != "off":
                        key = (tag, sid)
                        res = self._residual.get(key)
                        comp = chunk if res is None else chunk + res
                        q = quantize(comp, self.quant)
                        new_res = comp - dequantize(q)
                        self._residual[key] = new_res
                        if rt_numerics:
                            _rt_residual(
                                f"pclient.ef[{tag}:{sid}]",
                                # host numpy, sanitizer-gated — no
                                # device sync happens here
                                float(np.linalg.norm(new_res)),  # mpit-analysis: ignore[MPT005]
                            )
                        parts.append((sid, q))
                    else:
                        parts.append((sid, chunk))
                self._send_with_retry(
                    rank, tag,
                    (
                        self._epoch, seq,
                        self.server_version.get(rank, 0),
                        parts,
                    ),
                )
                self.push_sent[rank] = self.push_sent.get(rank, 0) + 1
            return
        for rank in self.ranks:
            pieces = [flat[s:e] for s, e in self._rank_chunks[rank]]
            chunk = pieces[0] if len(pieces) == 1 else np.concatenate(pieces)
            if self.quant != "off":
                # error feedback: compensate this push with the residual
                # the previous quantized push left behind, then carry the
                # new residual forward — the bias cancels over rounds.
                # The residual is folded in BEFORE send-retry, so a
                # retried (deduplicated) send re-offers identical bytes.
                key = (tag, rank)
                res = self._residual.get(key)
                comp = chunk if res is None else chunk + res
                q = quantize(comp, self.quant)
                new_res = comp - dequantize(q)
                self._residual[key] = new_res
                if rt_numerics:
                    _rt_residual(
                        f"pclient.ef[{tag}:{rank}]",
                        # host numpy, sanitizer-gated — no device sync
                        float(np.linalg.norm(new_res)),  # mpit-analysis: ignore[MPT005]
                    )
                payload_chunk = q
            else:
                payload_chunk = chunk
            self._send_with_retry(
                rank, tag,
                (
                    self._epoch, seq,
                    self.server_version.get(rank, 0),
                    payload_chunk,
                ),
            )
            self.push_sent[rank] += 1
