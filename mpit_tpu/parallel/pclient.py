"""pclient — worker-side stub for the host-async parameter server.

Reference parity (SURVEY.md §2 comp. 4): the reference's ``pclient`` owned
the worker→server mapping, flattened the model (``getParameters()``), and
exposed async fetch/push used by goptim every τ steps. Same role here: it
splits the flat vector across the server partition (``partition_bounds``),
talks the tag protocol over ``mpit_tpu.transport``, and leaves all actual
training math to the caller — compute stays jit-compiled on device, only
flat numpy chunks cross the transport.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence

import numpy as np

from mpit_tpu.parallel.pserver import (
    TAG_FETCH,
    TAG_HEARTBEAT,
    TAG_PARAM,
    TAG_PUSH_DELTA,
    TAG_PUSH_EASGD,
    TAG_STOP,
    partition_bounds,
)
from mpit_tpu.transport import Transport

# mpit-analysis: protocol-role[client->server]
# (the client side of the PS wire protocol — MPT008 pairs every send/recv
# here against the dispatch loop in pserver.py)


class PClient:
    """Client stub: fetch / push against a set of sharded pservers.

    ``server_ranks[s]`` owns flat chunk s of a ``param_size`` vector.

    ``heartbeat_interval``: when set, a daemon timer thread sends
    zero-payload HEARTBEATs to every server so the server watchdog
    (``PServer(client_timeout=...)``) doesn't declare this client dead
    during long local compute between exchanges. Stopped by :meth:`stop`.
    """

    def __init__(
        self,
        transport: Transport,
        server_ranks: Sequence[int],
        param_size: int,
        timeout: Optional[float] = 60.0,
        heartbeat_interval: Optional[float] = None,
    ):
        self.transport = transport
        self.server_ranks = list(server_ranks)
        self.param_size = int(param_size)
        self.bounds = partition_bounds(self.param_size, len(self.server_ranks))
        self.timeout = timeout
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        if heartbeat_interval is not None:
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop,
                args=(float(heartbeat_interval),),
                daemon=True,
                name="mpit-pclient-heartbeat",
            )
            self._hb_thread.start()

    def _heartbeat_loop(self, interval: float) -> None:
        while not self._hb_stop.wait(interval):
            for rank in self.server_ranks:
                try:
                    self.transport.send(rank, TAG_HEARTBEAT, None)
                except Exception:
                    # transient (e.g. a TCP blip mid-reconnect): liveness
                    # resumes next tick — one bad send must NOT silently
                    # kill the heartbeat and get a healthy client declared
                    # dead later. The interval bounds the retry rate; the
                    # thread exits only via stop().
                    pass

    def fetch(self) -> np.ndarray:
        """Gather the full flat center from all servers (async fan-out:
        request every chunk before waiting on any — the reference's
        ``async_fetch_param`` shape, SURVEY.md §3(b))."""
        for rank in self.server_ranks:
            self.transport.send(rank, TAG_FETCH, None)
        out = np.empty(self.param_size, np.float32)
        for rank, (start, end) in zip(self.server_ranks, self.bounds):
            msg = self.transport.recv(rank, TAG_PARAM, timeout=self.timeout)
            out[start:end] = msg.payload
        return out

    def push_easgd(self, flat_params: np.ndarray) -> None:
        """Push local params; each server does its elastic center move."""
        self._scatter(TAG_PUSH_EASGD, flat_params)

    def push_delta(self, flat_delta: np.ndarray) -> None:
        """Push an accumulated update (Downpour grad/delta apply)."""
        self._scatter(TAG_PUSH_DELTA, flat_delta)

    def stop(self) -> None:
        """Detach from every server (teardown protocol, SURVEY.md §3(e))."""
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
        for rank in self.server_ranks:
            self.transport.send(rank, TAG_STOP, None)

    def _scatter(self, tag: int, flat: np.ndarray) -> None:
        flat = np.asarray(flat, np.float32)
        if flat.shape != (self.param_size,):
            raise ValueError(
                f"flat vector shape {flat.shape} != ({self.param_size},)"
            )
        for rank, (start, end) in zip(self.server_ranks, self.bounds):
            self.transport.send(rank, tag, flat[start:end])
