"""Tensor-parallel training via GSPMD sharding annotations.

Beyond-parity extension, and the OTHER TPU-native parallelism style: where
the shard_map trainers spell out every collective, this trainer only
annotates WHERE tensors live — Megatron-style column/row shardings on the
transformer's projection matrices over a ``tp`` mesh axis — and lets XLA's
SPMD partitioner insert the all-reduces (the scaling-book recipe: pick a
mesh, annotate shardings, let the compiler do the rest).

Sharding rules (the Megatron pairing, one all-reduce per block half):

- qkv projection (``Dense_0``): column-sharded ``P(None, "tp")`` — heads
  split across tp, attention computes per-shard with no communication;
- attention output (``Dense_1``): row-sharded ``P("tp", None)`` — XLA
  inserts the psum that merges head shards;
- MLP up (``Dense_2``): column-sharded, bias sharded with it;
- MLP down (``Dense_3``): row-sharded — second psum;
- embeddings, positions, LayerNorms: replicated.

Batch shards over the ``dp`` axis; gradients reduce over dp because the
loss mean spans the global batch (the partitioner derives this too — no
hand-written pmean anywhere in this file).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu.comm.topology import Topology
from mpit_tpu.parallel import common

# (path-suffix substring, leaf name) -> PartitionSpec for the transformer's
# params; first match wins, default replicated. Momentum/optimizer leaves
# reuse the same rules because their tree paths end with the same param
# path (the rules only look at the trailing components).
_TP_RULES = (
    ("Dense_0", "kernel", P(None, "tp")),
    ("Dense_1", "kernel", P("tp", None)),
    ("Dense_2", "kernel", P(None, "tp")),
    ("Dense_2", "bias", P("tp")),
    ("Dense_3", "kernel", P("tp", None)),
    ("Dense_3", "bias", P()),
)


def _path_keys(path) -> list:
    keys = [getattr(p, "key", getattr(p, "name", None)) for p in path]
    return [k for k in keys if isinstance(k, str)]


def _spec_for_path(path) -> "tuple[P, Optional[int]]":
    """(spec, index of the matching rule) — (P(), None) when unmatched."""
    keys = _path_keys(path)
    for i, (module_name, leaf, spec) in enumerate(_TP_RULES):
        # exact segment equality: substring matching would let Dense_10
        # silently take Dense_1's row sharding
        if leaf in keys[-1:] and any(k == module_name for k in keys[:-1]):
            return spec, i
    return P(), None


def _is_block_dense_kernel(keys: list) -> bool:
    """A Dense kernel inside a transformer Block — the leaves tensor
    parallelism exists to shard. One of these matching NO rule means the
    model drifted from the rule table (renamed/added Dense), and
    silently replicating it would quietly lose tp — hard-fail instead."""
    return (
        keys[-1:] == ["kernel"]
        and any(k.startswith("Block") for k in keys[:-1])
        and any("Dense" in k for k in keys[:-1])
    )


def tp_state_specs(state):
    """PartitionSpec pytree for a TrainState under the Megatron rules.

    Strict by construction: every Dense kernel inside a Block must match
    a rule, and every rule must match at least one leaf — renaming or
    adding a layer raises here instead of silently falling back to
    replicated (losing tensor parallelism with no error). Shared by the
    2-D tp trainer and the composed dp×tp×sp trainer.
    """
    matched: set = set()
    unmatched: list = []

    def assign(path, _):
        spec, idx = _spec_for_path(path)
        if idx is not None:
            matched.add(idx)
        else:
            keys = _path_keys(path)
            if _is_block_dense_kernel(keys):
                unmatched.append("/".join(keys))
        return spec

    tree = jax.tree_util.tree_map_with_path(assign, state)
    if unmatched:
        raise ValueError(
            "tensor-parallel rules cover Dense_0..Dense_3 inside each "
            f"Block, but these Dense kernels matched no rule: "
            f"{sorted(set(unmatched))}. The model's block structure "
            "drifted from _TP_RULES — update the rule table rather "
            "than silently replicating these weights."
        )
    missing = set(range(len(_TP_RULES))) - matched
    if missing:
        raise ValueError(
            "tensor-parallel rules matched no parameter at all for: "
            f"{[_TP_RULES[i][:2] for i in sorted(missing)]} — the "
            "model's layer names drifted from _TP_RULES; fix the "
            "table or the model."
        )
    return tree


def check_tp_divisibility(model, tp: int) -> None:
    """d_model / num_heads / d_ff must all split across the tp axis."""
    d_model = getattr(model, "d_model", tp)
    for field, need in (
        ("d_model", d_model),
        ("num_heads", getattr(model, "num_heads", tp)),
        ("d_ff", getattr(model, "d_ff", 0) or 4 * d_model),
    ):
        if need % tp:
            raise ValueError(f"{field}={need} not divisible by tp={tp}")


class TensorParallelTrainer:
    """dp × tp training for :class:`TransformerLM` (dense-attention mode).

    Usage::

        topo = mpit_tpu.init(axis_names=("dp", "tp"), mesh_shape=(2, 4))
        model = TransformerLM(vocab_size=V)        # seq_axis=None: the
        trainer = TensorParallelTrainer(model, optax.sgd(0.1), topo)
        state = trainer.init_state(jax.random.key(0), x[:2])
        state, metrics = trainer.step(state, x_global, y_global)

    The step function contains NO collectives — they come from the
    sharding annotations alone. Requires ``d_model % tp == 0``,
    ``num_heads % tp == 0`` and ``d_ff % tp == 0``.

    Cross-leaf optimizers (``clip_by_global_norm`` etc.) are SAFE here,
    unlike in the shard_map MoE trainer: ``optimizer.update`` runs under
    jit on globally-sharded gradients, so the partitioner inserts the
    cross-device collectives the global norm needs — every replica sees
    the same scalar.
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        topo: Optional[Topology] = None,
        loss_fn: Optional[Callable] = None,
        donate_state: bool = True,
    ):
        self.model = model
        self.optimizer = optimizer
        self.topo = topo if topo is not None else _current_topology()
        mesh = self.topo.mesh
        if len(mesh.axis_names) < 2 or mesh.axis_names[1] != "tp":
            raise ValueError(
                "TensorParallelTrainer needs a mesh whose second axis is "
                "'tp', e.g. mpit_tpu.init(axis_names=('dp','tp'), "
                f"mesh_shape=(B, T)); got axes {mesh.axis_names}"
            )
        if getattr(model, "seq_axis", None) is not None:
            raise ValueError(
                "tensor parallelism uses the dense-attention model "
                "(seq_axis=None); ring attention shards the sequence, "
                "not the weights"
            )
        if getattr(model, "moe_experts", 0):
            raise ValueError(
                "TensorParallelTrainer has no sharding rules for MoE "
                "expert weights (moe_* leaves would silently stay "
                "replicated, losing expert parallelism); use "
                "MoEParallelTrainer for moe_experts > 0"
            )
        check_tp_divisibility(model, int(mesh.shape["tp"]))
        self.batch_axis = mesh.axis_names[0]
        self.loss_fn = (
            loss_fn
            if loss_fn is not None
            else common.default_loss_fn(model.apply)
        )

        def train_step(state: common.TrainState, x, y):
            loss, grads = jax.value_and_grad(self.loss_fn)(state.params, x, y)
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            return (
                common.TrainState(
                    params=params, opt_state=opt_state, step=state.step + 1
                ),
                {"loss": loss},
            )

        # no in_shardings: jit honors the committed shardings of its
        # arguments (init_state/data_sharding place them), and the
        # partitioner propagates from there
        self._step = jax.jit(
            train_step, donate_argnums=(0,) if donate_state else ()
        )

        def eval_step(params, x, y):
            logits = self.model.apply({"params": params}, x)
            correct = jnp.sum(jnp.argmax(logits, -1) == y)
            loss_sum = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).sum()
            return correct, loss_sum

        self._eval = jax.jit(eval_step)

    @property
    def tp_size(self) -> int:
        return int(self.topo.mesh.shape["tp"])

    def state_sharding(self, state):
        """NamedSharding pytree for a TrainState under the Megatron rules
        (strict — see :func:`tp_state_specs`)."""
        mesh = self.topo.mesh
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), tp_state_specs(state),
            is_leaf=lambda v: isinstance(v, P),
        )

    def data_sharding(self) -> NamedSharding:
        """(B, T) token batches shard over dp, sequence replicated."""
        return NamedSharding(self.topo.mesh, P(self.batch_axis, None))

    def init_state(self, rng, sample_x) -> common.TrainState:
        """Replicated init, then leaves committed to their tp shardings
        (XLA re-lays the weights once here, never per step)."""
        variables = self.model.init(rng, jnp.asarray(sample_x))
        state = common.TrainState.create(variables["params"], self.optimizer)
        return jax.device_put(state, self.state_sharding(state))

    def step(self, state, x_global, y_global):
        """One tp-sharded step on a global (B, T) batch."""
        if len(x_global) % int(self.topo.mesh.shape[self.batch_axis]):
            raise ValueError(
                f"global batch {len(x_global)} not divisible by "
                f"dp={self.topo.mesh.shape[self.batch_axis]}"
            )
        sharding = self.data_sharding()
        x = jax.device_put(jnp.asarray(x_global), sharding)
        y = jax.device_put(jnp.asarray(y_global), sharding)
        state, metrics = self._step(state, x, y)
        common.bound_cpu_dispatch(self.topo, metrics)
        return state, metrics

    def evaluate(self, state, x, y, batch: int = 512):
        """Token-level accuracy and mean loss over a (N, T) eval set."""
        group = int(self.topo.mesh.shape[self.batch_axis])
        correct, loss_sum, n = common.batched_count_eval(
            self._eval, state.params, x, y, batch, group
        )
        tokens = n * x.shape[1]
        return correct / tokens, loss_sum / tokens
