"""Shared trainer plumbing: train state, losses, batch sharding."""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax

from mpit_tpu.data.prefetch import prefetch_to_device


@flax.struct.dataclass
class TrainState:
    """Replicated training state (params + optimizer state + step).

    The reference's analogue is the flat parameter vector each pclient held
    plus torch-optim state tables (SURVEY.md §2 comps. 4-5); here state is a
    pytree and flattening is only done where a flat buffer genuinely helps
    (PS transport), not for every update.
    """

    params: Any
    opt_state: Any
    step: jax.Array

    @classmethod
    def create(cls, params, optimizer: optax.GradientTransformation):
        return cls(
            params=params,
            opt_state=optimizer.init(params),
            step=jnp.zeros((), jnp.int32),
        )


def cross_entropy_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.softmax_cross_entropy_with_integer_labels(
        logits, labels
    ).mean()


def default_loss_fn(apply_fn: Callable) -> Callable:
    """(params, x, y) -> scalar loss, for classification models."""

    def loss_fn(params, x, y):
        logits = apply_fn({"params": params}, x)
        return cross_entropy_loss(logits, y)

    return loss_fn


def assert_elementwise_optimizer(
    optimizer: optax.GradientTransformation, context: str
) -> None:
    """Reject optimizers whose per-leaf update depends on OTHER leaves.

    Trainers that run ``optimizer.update`` inside ``shard_map`` on
    device-varying gradients (expert-parallel MoE) silently desynchronize
    replicated leaves under cross-leaf transforms: ``clip_by_global_norm``
    computes a different norm on every device, so the replicated leaves
    receive different updates and the replicas drift — no error, just
    corruption. (Trainers that pmean gradients before the update, and the
    GSPMD tensor-parallel trainer whose update runs under jit where XLA
    inserts the cross-device norm collectives itself, are NOT subject.)

    Detection is behavioral, not by name: probe the optimizer with
    gradient trees differing only in leaf ``b`` — once scaled (large
    magnitudes, so realistic global-norm thresholds trip) and once with
    ``b`` poisoned to NaN (so all-finite gates like
    ``optax.apply_if_finite`` trip) — and reject if leaf ``a``'s update
    changes. Elementwise transforms (sgd, momentum, adam, adamw,
    per-leaf clip, ...) pass bitwise. Best-effort by nature: coupling
    that activates only beyond the probed magnitudes (say a clip
    threshold above 4e8) still slips through, and optimizers the probe
    cannot run (e.g. ``optax.masked`` bound to the real param
    structure) are let through — the hazard stays documented on the
    trainer either way.
    """
    probe = {
        "a": jnp.full((2,), 1e8, jnp.float32),
        "b": jnp.full((2,), 1e8, jnp.float32),
    }
    try:
        st = optimizer.init(probe)
        u1, _ = optimizer.update(dict(probe), st, probe)
        u2, _ = optimizer.update(
            {"a": probe["a"], "b": probe["b"] * 3.0}, st, probe
        )
        u3, _ = optimizer.update(
            {"a": probe["a"], "b": jnp.full((2,), jnp.nan)}, st, probe
        )
    except Exception:
        return
    ua = np.asarray(u1["a"])
    if not (
        np.array_equal(ua, np.asarray(u2["a"]))
        and np.array_equal(ua, np.asarray(u3["a"]))
    ):
        raise ValueError(
            f"{context} requires an ELEMENTWISE optimizer: this one's "
            "update for a leaf depends on other leaves' gradients "
            "(global-norm clipping?), which silently desynchronizes "
            "replicated parameters when the update runs on "
            "device-varying gradients inside shard_map. Use per-leaf "
            "clipping (optax.clip, optax.clip_by_block_rms) instead."
        )


def check_clip_norm(clip_norm):
    """The ONE clip_norm guard (MoE and ZeRO trainer constructors)."""
    if clip_norm is not None and clip_norm <= 0:
        raise ValueError(f"clip_norm={clip_norm} must be > 0")
    return clip_norm


def clip_by_global_norm_in_mesh(
    grads, max_norm: float, axis: str, is_sharded=None
):
    """Global-norm gradient clipping that is CORRECT inside shard_map —
    the safe counterpart to the cross-leaf transforms
    :func:`assert_elementwise_optimizer` rejects.

    The true global norm is assembled mesh-wide: device-varying leaves
    (``is_sharded(path)`` true, e.g. expert shards or ZeRO gradient
    chunks) contribute their local sum-of-squares through a ``psum``
    over ``axis``; replicated leaves are identical everywhere and count
    once outside it. Every device therefore computes the SAME norm and
    the same scale — no replica drift. ``is_sharded=None`` treats every
    leaf as device-varying (the flat-chunk case).

    The scale rule is exactly ``optax.clip_by_global_norm``'s
    (``g * max_norm / norm`` when ``norm > max_norm``, identity
    otherwise), so a sharded run clips bit-for-bit like a dense run of
    the same model under the optax transform — pinned by the trainer
    equivalence tests.

    Returns ``(clipped_grads, global_norm)``.
    """
    leaves = jax.tree_util.tree_leaves_with_path(grads)
    shard_sq = jnp.float32(0.0)
    repl_sq = jnp.float32(0.0)
    for path, g in leaves:
        sq = jnp.sum(jnp.square(g.astype(jnp.float32)))
        if is_sharded is None or is_sharded(path):
            shard_sq = shard_sq + sq
        else:
            repl_sq = repl_sq + sq
    norm = jnp.sqrt(jax.lax.psum(shard_sq, axis) + repl_sq)
    scale = jnp.where(norm > max_norm, max_norm / norm, 1.0)
    return (
        jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads),
        norm,
    )


def check_accum_steps(accum) -> int:
    """The ONE accum_steps guard (sync fold + ZeRO constructor)."""
    if int(accum) != accum or accum < 1:
        raise ValueError(f"accum_steps={accum} must be an integer >= 1")
    return int(accum)


def accumulated_value_and_grad(loss_fn: Callable, accum: int) -> Callable:
    """(params, x, y) -> (loss, grads), processing the batch as ``accum``
    sequential ``lax.scan`` slices whose losses/gradients average —
    exactly the full-batch mean for equal slices (no model here carries
    batch statistics), at 1/accum of the peak activation memory. Used by
    the sync trainer; the ZeRO trainer carries its own fold because its
    accumulator is the reduce-scattered SHARD, not the full pytree
    (parallel/zero.py::scattered_grad). ``accum=1`` is the plain
    ``value_and_grad``. Validates via :func:`check_accum_steps`."""
    accum = check_accum_steps(accum)
    if accum == 1:
        return jax.value_and_grad(loss_fn)

    def value_and_grad(params, x, y):
        xs = x.reshape(accum, x.shape[0] // accum, *x.shape[1:])
        ys = y.reshape(accum, y.shape[0] // accum, *y.shape[1:])

        def fold(carry, xy):
            loss_acc, g_acc = carry
            l, g = jax.value_and_grad(loss_fn)(params, *xy)
            return (
                loss_acc + l,
                jax.tree.map(jnp.add, g_acc, g),
            ), None

        (loss, grads), _ = jax.lax.scan(
            fold,
            (jnp.float32(0.0), jax.tree.map(jnp.zeros_like, params)),
            (xs, ys),
        )
        return loss / accum, jax.tree.map(lambda g: g / accum, grads)

    return value_and_grad


def check_accum_batch(
    global_batch: int, num_workers: int, accum: int
) -> None:
    """Sync-trainer batch check: divisible by W, per-worker shard
    divisible by the accumulation factor."""
    check_global_batch(global_batch, num_workers)
    if (global_batch // num_workers) % accum:
        raise ValueError(
            f"per-worker batch {global_batch // num_workers} not "
            f"divisible by accum_steps={accum}"
        )


def check_global_batch(global_batch: int, num_workers: int) -> int:
    if global_batch % num_workers != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by {num_workers} "
            "workers (SPMD shards must be equal)"
        )
    return global_batch // num_workers


def accuracy(logits: np.ndarray, labels: np.ndarray) -> float:
    return float((np.argmax(logits, -1) == labels).mean())


def synced_fit_loop(
    topo,
    step_fn,
    batches,
    state,
    *,
    sharding,
    check,
    log_tag: str,
    epochs: int = 1,
    log_every: int = 0,
    start_epoch: int = 0,
    skip_steps: int = 0,
    on_step=None,
    prefetch: int = 2,
):
    """The one per-step fit loop shared by the synchronous trainers
    (sync-DP and seq-parallel differ only in sharding, batch check, and
    log tag). Deterministic resume via ``start_epoch``/``skip_steps``
    (epoch index seeds the permutation); ``on_step(steps, state, metrics)``
    after every step; batches staged ``prefetch`` ahead with the step's own
    sharding. Returns (state, last_metrics)."""
    metrics = None
    steps = 0
    # one host fetch up front so log lines can number steps across resume
    # without a per-step device round-trip (the pipeline trainer's state
    # is a dict, not a TrainState)
    step_leaf = state["step"] if isinstance(state, dict) else state.step
    base_step = int(step_leaf) if log_every else 0

    def step_batches(e, to_skip):
        for x, y in batches.epoch(e):
            if to_skip > 0:
                to_skip -= 1
                continue
            check(x)
            yield x, y

    for e in range(start_epoch, epochs):
        to_skip = skip_steps if e == start_epoch else 0
        for x, y in prefetch_to_device(
            step_batches(e, to_skip), sharding, depth=prefetch
        ):
            state, metrics = step_fn(state, x, y)
            bound_cpu_dispatch(topo, metrics)
            steps += 1
            if on_step is not None:
                on_step(steps, state, metrics)
            # gate on the HOST counter: `int(state.step)` every step would
            # force a device round-trip per step
            if log_every and steps % log_every == 0:
                print(
                    f"[{log_tag}] step={base_step + steps} "
                    f"loss={float(metrics['loss']):.4f}"
                )
    return state, metrics


def batched_count_eval(eval_fn, params, x, y, batch: int, group: int):
    """Run a (params, x, y) -> (correct_sum, loss_sum) eval over the set in
    ``group``-divisible batches (truncating the remainder). Returns
    (correct, loss_sum, n_examples_used)."""
    batch = (min(batch, len(x)) // group) * group or group
    n = (len(x) // batch) * batch
    if n == 0:
        raise ValueError("eval set smaller than one global batch")
    correct = 0
    loss_sum = 0.0
    for i in range(0, n, batch):
        c, l = eval_fn(params, x[i : i + batch], y[i : i + batch])
        correct += int(c)
        loss_sum += float(l)
    return correct, loss_sum, n


def bound_cpu_dispatch(topo, tree) -> None:
    """Serialize step dispatch on the virtual CPU mesh (no-op elsewhere).

    XLA:CPU's cross-module collective rendezvous deadlocks when several
    executions are in flight over the forced host-platform devices: async
    dispatch pipelines step k+1 while k runs, participants from different
    runs tangle on the shared pool, and one of N never arrives — the runtime
    then either hangs or aborts the process (rendezvous.cc "Exiting to
    ensure a consistent program state"). Observed on a 1-core host: an
    8-device psum loop died ~2 of 3 runs; with one execution in flight it
    passed every time. Real accelerator platforms pipeline correctly and
    stay fully async.
    """
    if topo.platform == "cpu" and topo.num_devices > 1:
        jax.block_until_ready(tree)


class RoundTrainer:
    """Shared machinery for τ-round trainers (EASGD, Downpour).

    Subclasses set, in __init__: ``topo``, ``tau``, ``_round`` (jitted round
    step taking (state, x(W,τ,B,...), y(W,τ,B,...))), ``_eval`` (jitted
    (params, x, y) -> summed-correct, or None when model-less), and implement
    ``center_params(state)``.
    """

    topo: Any
    tau: int
    _round: Callable
    _eval: Optional[Callable]

    _log_tag = "round"

    def center_params(self, state):
        raise NotImplementedError

    def round_batches(self, x_round: np.ndarray, y_round: np.ndarray):
        """Reshape τ stacked global batches (τ, W·B, ...) → (W, τ, B, ...)."""
        tau, w = self.tau, self.topo.num_workers
        if x_round.shape[0] != tau:
            raise ValueError(
                f"need {tau} stacked batches, got {x_round.shape[0]}"
            )
        b = check_global_batch(x_round.shape[1], w)
        xr = x_round.reshape(tau, w, b, *x_round.shape[2:]).swapaxes(0, 1)
        yr = y_round.reshape(tau, w, b, *y_round.shape[2:]).swapaxes(0, 1)
        return xr, yr

    def step(self, state, x_round, y_round):
        """One exchange round: τ local steps + the collective. Inputs are τ
        stacked global batches, shape (τ, W·B, ...)."""
        xr, yr = self.round_batches(np.asarray(x_round), np.asarray(y_round))
        state, metrics = self._round(state, xr, yr)
        bound_cpu_dispatch(self.topo, metrics)
        return state, metrics

    def rounds_per_epoch(self, batches) -> int:
        return batches.steps_per_epoch() // self.tau

    def fit(
        self,
        batches,
        state,
        epochs: int = 1,
        log_every: int = 0,
        start_epoch: int = 0,
        skip_rounds: int = 0,
        on_round=None,
        prefetch: int = 2,
    ):
        """Epoch loop grouping minibatches into τ-rounds. Per epoch, a
        trailing group smaller than τ is dropped (SPMD rounds have a fixed
        shape — and *per-epoch* dropping keeps the round↔epoch arithmetic
        exact for checkpoint/resume); raises if that leaves zero full rounds.

        Resume: ``start_epoch``/``skip_rounds`` re-enter the deterministic
        data schedule mid-stream — epoch ``e`` always reuses the same
        permutation (``Batches`` seeds by epoch index), and the first
        ``skip_rounds`` round-groups of ``start_epoch`` are consumed without
        training. ``on_round(rounds_done, state, metrics)`` fires after every
        trained round.

        ``prefetch``: round-groups staged onto the mesh ahead of the running
        step (``device_put`` is async, so transfer overlaps compute); 0 =
        stage synchronously (each staged group holds its full HBM footprint,
        so large-input configs may need 0). Skipped resume rounds are never
        staged."""
        if self.rounds_per_epoch(batches) == 0:
            raise ValueError(
                f"epoch of {batches.steps_per_epoch()} step(s) < "
                f"tau={self.tau}: no full rounds"
            )
        metrics = None
        rounds = 0
        dropped = 0

        def round_groups(e, to_skip):
            nonlocal dropped
            buf_x, buf_y = [], []
            for x, y in batches.epoch(e):
                buf_x.append(x)
                buf_y.append(y)
                if len(buf_x) < self.tau:
                    continue
                if to_skip > 0:
                    to_skip -= 1
                else:
                    yield self.round_batches(
                        np.stack(buf_x), np.stack(buf_y)
                    )
                buf_x, buf_y = [], []
            dropped += len(buf_x)

        sharding = self.topo.worker_sharding()
        for e in range(start_epoch, epochs):
            to_skip = skip_rounds if e == start_epoch else 0
            for xr, yr in prefetch_to_device(
                round_groups(e, to_skip), sharding, depth=prefetch
            ):
                state, metrics = self._round(state, xr, yr)
                bound_cpu_dispatch(self.topo, metrics)
                rounds += 1
                if on_round is not None:
                    on_round(rounds, state, metrics)
                if log_every and rounds % log_every == 0:
                    print(
                        f"[{self._log_tag}] round={rounds} "
                        f"loss={float(metrics['loss']):.4f}"
                    )
        if dropped:
            print(
                f"[{self._log_tag}] dropped {dropped} trailing batch(es) "
                f"across epochs (< tau={self.tau})"
            )
        return state, metrics

    def evaluate(self, state, x, y, batch: int = 1024) -> float:
        """Accuracy of the CENTER variable (the consensus model — what the
        reference's pserver held and reported)."""
        if self._eval is None:
            raise ValueError(
                "evaluate() requires a model; this trainer was built with "
                "model=None (loss-only math mode)"
            )
        w = self.topo.num_workers
        batch = (min(batch, len(x)) // w) * w or w
        n = (len(x) // batch) * batch
        if n == 0:
            raise ValueError(
                f"eval set of {len(x)} smaller than one per-worker sample "
                f"each across {w} workers"
            )
        correct = 0
        center = self.center_params(state)
        for i in range(0, n, batch):
            correct += int(
                self._eval(center, x[i : i + batch], y[i : i + batch])
            )
        return correct / n


def build_count_loss_eval(model, topo) -> Callable:
    """Jitted shard_map eval over the worker axis returning global
    (correct-count sum, loss sum) — the ONE copy shared by the
    replicated-param DP trainers (sync and ZeRO)."""
    import optax
    from jax.sharding import PartitionSpec as P

    axis = topo.worker_axis

    def eval_step(params, x, y):
        logits = model.apply({"params": params}, x)
        correct = jnp.sum(jnp.argmax(logits, -1) == y)
        loss_sum = optax.softmax_cross_entropy_with_integer_labels(
            logits, y
        ).sum()
        return jax.lax.psum(correct, axis), jax.lax.psum(loss_sum, axis)

    return jax.jit(
        jax.shard_map(
            eval_step,
            mesh=topo.mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=(P(), P()),
            check_vma=False,
        )
    )


def build_center_eval(model, topo) -> Optional[Callable]:
    """Jitted shard_map eval returning the summed correct-count across the
    worker axis, or None when model-less."""
    if model is None:
        return None
    from jax.sharding import PartitionSpec as P

    axis = topo.worker_axis

    def eval_step(params, x, y):
        logits = model.apply({"params": params}, x)
        correct = jnp.sum(jnp.argmax(logits, -1) == y)
        return jax.lax.psum(correct, axis)

    return jax.jit(
        jax.shard_map(
            eval_step,
            mesh=topo.mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=P(),
            check_vma=False,
        )
    )
