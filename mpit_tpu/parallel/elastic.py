"""Elastic membership for the parameter-server world.

Tracks which client ranks a :class:`~mpit_tpu.parallel.pserver.PServer`
is serving as clients JOIN, REJOIN, get REPLACED, LEAVE, die, and stop
— replacing the seed-era implicit model where a rank landing in
``dead_clients`` stayed dead forever. The membership view is epoch
bumped: every change increments ``view_epoch``, so journals and
snapshots can order membership transitions without wall clocks.

State machine per rank (driven by :meth:`register` / :meth:`leave` and
the server's watchdog/STOP handling, which mutate the ``dead`` /
``stopped`` sets this object owns):

    unknown ──JOIN──────────────► active          ("join")
    active  ──same-epoch JOIN───► active          ("rejoin": a preempted
                                                   client reconnected)
    active  ──new-epoch JOIN────► active          ("replace": a fresh
                                                   process took the rank;
                                                   dead/stopped cleared)
    active  ──LEAVE─────────────► left            (planned departure)
    active  ──watchdog timeout──► dead            (revivable: any later
                                                   message clears it)

The client's push-identity ``epoch`` (``PClient._epoch``, a random
64-bit value) doubles as the incarnation id here: a replacement process
on a reused rank has a new epoch, which is also what gives it a fresh
``(src, epoch)`` dedup slot on the server — membership and exactly-once
share one notion of identity.

Teardown: the serve loop runs until every *expected* rank is accounted
for (stopped, dead, or left) and at least ``min_quorum`` ranks are —
the same condition as the seed's ``len(stopped | dead) >= num_clients``
when membership never changes, but correct when ranks join or leave
mid-run.

Naming note: :mod:`mpit_tpu.ops.elastic` is unrelated machinery — the
fused EASGD "elastic update" pallas TPU kernel (the algorithm's elastic
*force*, not elastic *membership*). This module is the membership
layer the ROADMAP's elastic item describes.
"""

from __future__ import annotations

from typing import Iterable, Optional


class ElasticMembership:
    """Mutable membership view for one PServer shard.

    The server aliases ``dead_clients`` / ``_stopped`` to the ``dead``
    and ``stopped`` sets owned here, so existing watchdog and STOP
    handling (and the tests and trainers that read those sets) keep
    working unchanged; :meth:`load_state` therefore mutates the sets in
    place and never rebinds them.
    """

    def __init__(self, num_clients: int, client_ranks: Optional[Iterable[int]] = None):
        # the quorum floor: how many clients the run was launched with;
        # a mid-run join can raise the bar via `expected`, never lower it
        self.min_quorum = num_clients
        self.expected: set[int] = set(client_ranks or ())
        self.dead: set[int] = set()
        self.stopped: set[int] = set()
        self.left: set[int] = set()
        self.epochs: dict[int, int] = {}
        self.view_epoch = 0

    def register(self, rank: int, epoch: int) -> str:
        """A JOIN envelope arrived from ``rank`` with push-identity
        ``epoch``; returns the transition kind: ``"join"`` (first
        contact), ``"rejoin"`` (same epoch — a preempted client
        reconnected), or ``"replace"`` (new epoch — a fresh process
        owns the rank now)."""
        prev = self.epochs.get(rank)
        if prev is None:
            kind = "join"
        elif prev == epoch:
            kind = "rejoin"
        else:
            kind = "replace"
        self.expected.add(rank)
        self.epochs[rank] = epoch
        # any register makes the rank active again: it owes a future
        # STOP (or LEAVE/watchdog expiry) before teardown can complete
        self.dead.discard(rank)
        self.left.discard(rank)
        self.stopped.discard(rank)
        self.view_epoch += 1
        return kind

    def leave(self, rank: int) -> None:
        """A LEAVE envelope: planned departure (preemption notice) —
        the rank stops counting toward teardown without waiting for
        the watchdog to declare it dead."""
        self.left.add(rank)
        self.view_epoch += 1

    def teardown_complete(self) -> bool:
        """Every expected rank accounted for, and at least the launch
        quorum of ranks overall — the serve loop's exit condition."""
        accounted = self.stopped | self.dead | self.left
        return (
            len(accounted) >= self.min_quorum
            and self.expected <= accounted
        )

    # -- snapshot round-trip (msgpack-friendly plain types) ---------------

    def state(self) -> dict:
        return {
            "min_quorum": self.min_quorum,
            "expected": sorted(self.expected),
            "dead": sorted(self.dead),
            "stopped": sorted(self.stopped),
            "left": sorted(self.left),
            "epochs": [[r, e] for r, e in sorted(self.epochs.items())],
            "view_epoch": self.view_epoch,
        }

    def load_state(self, state: dict) -> None:
        self.min_quorum = int(state.get("min_quorum", self.min_quorum))
        for name in ("expected", "dead", "stopped", "left"):
            target = getattr(self, name)
            target.clear()
            # msgpack ints, not device scalars: cold restore path
            target.update(int(r) for r in state.get(name, ()))  # mpit-analysis: ignore[MPT005]
        self.epochs.clear()
        self.epochs.update(
            {int(r): int(e) for r, e in state.get("epochs", ())}
        )
        self.view_epoch = int(state.get("view_epoch", 0))
