"""Shared role bodies for the host-async PS protocol.

One implementation of the client training loop, used by BOTH runtimes that
the reference's single Lua codebase served (SURVEY.md §2 comps. 3-6):

- thread mode — :class:`mpit_tpu.parallel.AsyncPSTrainer` (brokered
  in-process transports, the default examples), and
- process mode — ``examples/ptest_proc.py`` under ``python -m
  mpit_tpu.launch -n N`` (one OS process per rank over TCP, the literal
  ``mpirun`` shape).

Keeping the protocol body in one place is what guarantees the two modes
stay protocol-identical.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

import jax
import numpy as np
import optax

from mpit_tpu.obs.core import span as obs_span
from mpit_tpu.obs.live import (
    M_COMPUTE_S,
    M_ELASTIC_DIST,
    M_EXCHANGE_FAILURES,
    M_EXCHANGE_LAT,
    M_EXCHANGE_S,
    M_NORM_RATIO,
    M_PARAM_NORM,
    M_PUSHES,
    M_PUSH_NORM,
    M_REPAIRED_CHUNKS,
    M_ROUNDS,
    M_SAMPLES,
    M_SKIPPED_ROUNDS,
    M_STALE_PARAMS,
    M_STEPS,
    live_registry,
)
from mpit_tpu.parallel import common
from mpit_tpu.parallel.pclient import PClient
from mpit_tpu.transport import RecvTimeout
from mpit_tpu.utils.params import FlatParamSpec, unflatten_params
from mpit_tpu.utils.profiling import force_completion

logger = logging.getLogger("mpit_tpu.parallel.ps_roles")

# mpit-analysis: protocol-role[client->server]
# (shared client-role body for both runtimes; its transport traffic all
# flows through PClient, so MPT008 merges this module into the client
# role's op set)


def make_local_step(
    model, optimizer: optax.GradientTransformation,
    loss_fn: Optional[Callable] = None,
):
    """Jitted ``(params, opt_state, x, y) -> (params, opt_state, loss)`` —
    the client's on-device compute between exchanges."""
    loss_fn = (
        loss_fn if loss_fn is not None else common.default_loss_fn(model.apply)
    )

    def local_step(params, opt_state, x, y):
        loss, g = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = optimizer.update(g, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return jax.jit(local_step)


def _record_dynamics(
    transport,
    reg,
    round_no: int,
    algo: str,
    flat: np.ndarray,
    center: np.ndarray,
    prev_center: Optional[np.ndarray],
    push_vec: Optional[np.ndarray] = None,
    alpha: Optional[float] = None,
) -> None:
    """Per-exchange training-dynamics record (docs/OBSERVABILITY.md
    "dynamics"): elastic distance ‖x_local − x̃‖ — THE quantity the EASGD
    analysis bounds — plus push-delta norm, fetch-delta norm (how far
    the center moved since this client's previous pull), param norm, and
    the update/param norm ratio.

    Every input is host numpy the exchange already materialized (the
    τ-boundary flatten and the fetched center), so this adds ZERO device
    syncs; it lives outside the training loop so MPT005 stays clean, and
    the caller only invokes it when the transport is obs-wrapped — the
    obs-off cost is one attribute check per round (pinned by
    tests/test_dynamics.py).

    ``push_vec`` (downpour) is the pushed delta; for EASGD the push is
    the elastic move itself, so ``alpha`` is passed instead and
    push_norm = alpha·elastic without forming another vector.
    """
    elastic = float(np.linalg.norm(flat - center))
    push_norm = (
        float(np.linalg.norm(push_vec)) if push_vec is not None
        else float(alpha) * elastic
    )
    param_norm = float(np.linalg.norm(flat))
    fetch_delta = (
        0.0 if prev_center is None
        else float(np.linalg.norm(center - prev_center))
    )
    ratio = push_norm / param_norm if param_norm > 0.0 else 0.0
    tracer = getattr(transport, "obs_tracer", None)
    if tracer is not None and tracer.journal is not None:
        tracer.journal.event(
            "dynamics",
            tracer.clock.tick(),
            round=round_no,
            algo=algo,
            elastic=elastic,
            push_norm=push_norm,
            param_norm=param_norm,
            fetch_delta=fetch_delta,
            ratio=ratio,
        )
    reg.set_gauge(M_ELASTIC_DIST, elastic)
    reg.set_gauge(M_PUSH_NORM, push_norm)
    reg.set_gauge(M_PARAM_NORM, param_norm)
    reg.set_gauge(M_NORM_RATIO, ratio)


def client_train_loop(
    client: PClient,
    local_step,
    optimizer: optax.GradientTransformation,
    spec: FlatParamSpec,
    x: np.ndarray,
    y: np.ndarray,
    steps: int,
    batch_size: int,
    tau: int,
    algo: str,
    alpha: float,
    seed: int,
    max_exchange_failures: Optional[int] = None,
    exchange_stats: Optional[dict] = None,
    join: bool = False,
) -> list[float]:
    """The pclient side of SURVEY.md §3(b): τ jit-compiled local steps, then
    push/pull per ``algo`` ("easgd" or "downpour"). Returns per-step losses.
    Does NOT send stop — the caller owns teardown (it may want a final
    ``client.fetch()`` for evaluation first).

    Graceful degradation (docs/ROBUSTNESS.md): with
    ``max_exchange_failures`` set, a failed exchange (timeout after the
    client's retries, or a transport error) logs, SKIPS the round — the
    client keeps training on its local params against the stale center —
    and only escalates once that many *consecutive* rounds have failed
    (any success resets the count). ``None`` keeps fail-fast semantics.
    ``exchange_stats`` (when provided) is filled with
    ``{"skipped_rounds", "exchange_failures", "repaired_chunks"}`` totals
    (``repaired_chunks``: shards rerouted off dead servers by ring-mode
    partial-scatter repair — 0 in legacy flat mode).

    ``join``: announce this client via the elastic-membership JOIN
    envelope for its initial pull instead of a plain fetch — required
    for elastic runs (a respawned replacement process must register its
    fresh push-identity epoch with the server; docs/ROBUSTNESS.md).
    Off by default: non-elastic runs keep their exact fetch counts.

    Loss scalars stay ON DEVICE between exchanges and are host-fetched in
    one batched transfer at each τ boundary (where the param flatten
    already forces completion) — a per-step ``float(loss)`` would stall
    the XLA dispatch pipeline every step and, measured over a remote
    device tunnel, time the round-trip rather than the training.

    Roofline instrumentation (docs/OBSERVABILITY.md): each τ-block of
    local steps runs inside a ``"compute"`` span that ends with
    :func:`force_completion` — proof-of-completion blocking, so the span
    records real device time rather than async dispatch time. The barrier
    is conditional on the span being live (``ctx is not None``): with obs
    off the loop keeps the free-running dispatch pipeline unchanged.
    """
    import jax.numpy as jnp

    from mpit_tpu.utils.params import flatten_params

    rng = np.random.default_rng(seed)
    # live-metrics hook: NULL_REGISTRY unless MPIT_OBS_LIVE armed the
    # transport (docs/OBSERVABILITY.md "live") — publishes below are
    # unconditional, the disabled path is a no-op method call per round
    reg = live_registry(client.transport)
    # obs_span is the no-op NULL_SPAN unless the transport is obs-wrapped
    # (docs/OBSERVABILITY.md) — each span groups one exchange's wire
    # traffic under a single trace on the merged timeline
    with obs_span(client.transport, "initial_fetch"):
        # startup patience: the initial pull races server startup (under
        # a process launcher peers come up seconds apart, and a short
        # MPIT_CONNECT_RETRY_S narrows the transport's own grace). A
        # client that comes up before its servers must wait, not die —
        # unlike mid-run failures, there is no stale center to fall back
        # on yet, so keep re-asking until the deadline
        deadline = time.monotonic() + 60.0
        while True:
            try:
                initial = client.join() if join else client.fetch()
                break
            except (RecvTimeout, ConnectionError, OSError):
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.5)
        params = unflatten_params(spec, jnp.asarray(initial))
    opt_state = optimizer.init(params)
    last_pull = np.asarray(flatten_params(params)[0])
    # training-dynamics plane: armed iff the transport is obs-wrapped —
    # the same zero-cost-when-off contract as the spans above. prev_center
    # remembers the previously fetched center for the fetch-delta norm.
    dyn_on = getattr(client.transport, "obs_tracer", None) is not None
    prev_center: Optional[np.ndarray] = None
    losses: list[float] = []
    pending: list = []
    consecutive_failures = 0
    skipped_rounds = 0
    total_failures = 0

    def flush():
        if pending:
            losses.extend(np.asarray(jnp.stack(pending)).tolist())
            pending.clear()

    done = 0
    round_no = 0
    while done < steps:
        k = min(tau, steps - done)
        t_c = time.perf_counter()
        with obs_span(
            client.transport, "compute", round=round_no + 1, steps=k
        ) as cspan:
            for _ in range(k):
                idx = rng.integers(0, len(x), batch_size)
                params, opt_state, loss = local_step(
                    params, opt_state, x[idx], y[idx]
                )
                pending.append(loss)
            if cspan is not None:
                # span live → pay the sync so compute time is real
                force_completion(params, loss)
        reg.inc(M_STEPS, k)
        reg.inc(M_SAMPLES, k * batch_size)
        reg.inc(M_COMPUTE_S, time.perf_counter() - t_c)
        done += k
        if k < tau:
            break  # steps % tau remainder trains without an exchange
        round_no += 1
        flush()
        # zero-copy wire contract (docs/WIRE.md): the framed transport
        # sends slices of this vector by reference (no serialize copy),
        # and PClient's blocking sends return only once written — so the
        # loop below must never mutate `flat` in place; the post-exchange
        # elastic move builds a NEW array.
        flat = np.asarray(flatten_params(params)[0])
        t_x = time.perf_counter()
        with obs_span(
            client.transport, "exchange",
            round=round_no, algo=algo,
        ):
            try:
                if algo == "easgd":
                    # fetch BEFORE push so the client's elastic move uses
                    # the pre-push center — the paper's update order (both
                    # moves on the old center), and the same order
                    # goptim.easgd_round implements for the collective
                    # path. Push-then-fetch would couple against a center
                    # already moved by this client's own push (an
                    # alpha*(1-alpha) effective move).
                    # The local params ride along as the repair fallback
                    # (ring mode): a dead server's shards are rerouted
                    # and THIS round's gap filled locally instead of
                    # skipping the round (docs/ROBUSTNESS.md).
                    center = client.fetch(fallback=flat)
                    client.push_easgd(flat)
                    if dyn_on:
                        _record_dynamics(
                            client.transport, reg, round_no, algo,
                            flat, center, prev_center, alpha=alpha,
                        )
                        prev_center = center
                    flat = flat - alpha * (flat - center)
                else:
                    delta = flat - last_pull
                    client.push_delta(delta)
                    # the pushed delta now belongs to the server: a fetch
                    # failure below must not get it re-pushed next round
                    prev_pull = last_pull
                    last_pull = flat
                    fetched = client.fetch(fallback=flat)
                    if dyn_on:
                        # elastic here = ‖local − fetched center‖; the
                        # fetch-delta baseline is the previous pull
                        _record_dynamics(
                            client.transport, reg, round_no, algo,
                            flat, fetched, prev_pull, push_vec=delta,
                        )
                    flat = fetched
                    last_pull = flat
            except (RecvTimeout, ConnectionError, OSError) as e:
                total_failures += 1
                consecutive_failures += 1
                reg.inc(M_EXCHANGE_FAILURES)
                if max_exchange_failures is None:
                    raise  # fail-fast semantics (degradation not enabled)
                if consecutive_failures >= max_exchange_failures:
                    raise RuntimeError(
                        f"PS exchange failed {consecutive_failures} "
                        "rounds in a row — escalating instead of "
                        "training further against an unreachable center"
                    ) from e
                skipped_rounds += 1
                reg.inc(M_SKIPPED_ROUNDS)
                reg.inc(M_EXCHANGE_S, time.perf_counter() - t_x)
                logger.warning(
                    "PS exchange failed (%r); skipping round on the "
                    "stale center (%d consecutive failure(s))",
                    e,
                    consecutive_failures,
                )
                continue  # params stay local this round
            consecutive_failures = 0
            dt_x = time.perf_counter() - t_x
            reg.inc(M_ROUNDS)
            reg.inc(M_EXCHANGE_S, dt_x)
            reg.observe(M_EXCHANGE_LAT, dt_x)
            reg.set_gauge(M_PUSHES, sum(client.push_sent.values()))
            reg.set_gauge(M_STALE_PARAMS, client.stale_params_dropped)
            reg.set_gauge(
                M_REPAIRED_CHUNKS, getattr(client, "repaired_chunks", 0)
            )
            params = unflatten_params(spec, jnp.asarray(flat))
    flush()  # flush any remainder losses
    if exchange_stats is not None:
        exchange_stats["skipped_rounds"] = skipped_rounds
        exchange_stats["exchange_failures"] = total_failures
        exchange_stats["repaired_chunks"] = getattr(
            client, "repaired_chunks", 0
        )
    return losses
