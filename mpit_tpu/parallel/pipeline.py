"""Pipeline parallelism: GPipe-style microbatching over a ``pp`` mesh axis.

Beyond-parity extension completing the parallelism suite (dp: all
trainers; sp: ring attention; tp: GSPMD Megatron shardings; pp: here).
The transformer's layer stack shards by STAGE: device ``s`` on the ``pp``
axis holds layers ``[s·L/S, (s+1)·L/S)`` as stacked leaves, activations
flow stage-to-stage with ``lax.ppermute`` (the TPU's neighbor-ICI
primitive), and the batch is cut into microbatches so stages overlap —
the classic schedule: tick ``t`` has stage ``s`` working microbatch
``t−s``, ``M + S − 1`` ticks total, bubble fraction ``(S−1)/(M+S−1)``.

The backward pass is NOT hand-written: ``jax.grad`` transposes the whole
scan-of-ppermute program (the transpose of a ppermute is the reverse
ppermute), so gradients flow backward through the pipeline automatically.

Everything here is pure jax (no flax): the model is a dict of arrays with
the block stack as stacked leaves — exactly the layout pipelining wants —
and the optimizer is a manual SGD+momentum so its state tree mirrors the
param tree (same shard_map specs apply to both).

Boundary ownership keeps replicated params consistent: the embedding's
input side contributes only on stage 0, the final norm and the tied
head's output side only on the last stage (elsewhere their outputs are
masked to zero), so each replicated param's raw gradient is nonzero only
on its owning stage(s) — the tied embedding has two, whose contributions
are complementary; the ``psum`` over pp sums them into the identical
total gradient everywhere before the optimizer touches the replicated
copies.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu.comm.topology import Topology
from mpit_tpu.ops.ring_attention import dense_attention
from mpit_tpu.parallel.common import bound_cpu_dispatch


def _layer_norm(x, scale, bias, eps=1e-6):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps)).astype(x.dtype) * scale + bias


def block_fn(p, h, num_heads: int):
    """One pre-LN transformer block from stacked-leaf params ``p`` (a dict
    of per-layer arrays WITHOUT the leading layer dim)."""
    b, t, d = h.shape
    y = _layer_norm(h, p["ln1_s"], p["ln1_b"])
    qkv = y @ p["qkv_w"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    split = lambda a: a.reshape(b, t, num_heads, d // num_heads)
    att = dense_attention(split(q), split(k), split(v), causal=True)
    h = h + att.reshape(b, t, d) @ p["attn_o"]
    y = _layer_norm(h, p["ln2_s"], p["ln2_b"])
    y = jax.nn.gelu(y @ p["mlp_up"] + p["mlp_up_b"])
    return h + y @ p["mlp_down"] + p["mlp_down_b"]


def init_params(
    rng, vocab_size: int, num_layers: int, d_model: int, d_ff: int,
    max_len: int,
) -> dict:
    """{"blocks": stacked (L, ...) leaves, "rest": embed/pos/final-norm}."""
    k = iter(jax.random.split(rng, 8))
    dist = lambda key, *s: (jax.random.normal(key, s) / np.sqrt(s[-2])
                            ).astype(jnp.float32)
    L, D, F = num_layers, d_model, d_ff
    blocks = {
        "qkv_w": dist(next(k), L, D, 3 * D),
        "attn_o": dist(next(k), L, D, D),
        "mlp_up": dist(next(k), L, D, F),
        "mlp_up_b": jnp.zeros((L, F)),
        "mlp_down": dist(next(k), L, F, D),
        "mlp_down_b": jnp.zeros((L, D)),
        "ln1_s": jnp.ones((L, D)), "ln1_b": jnp.zeros((L, D)),
        "ln2_s": jnp.ones((L, D)), "ln2_b": jnp.zeros((L, D)),
    }
    rest = {
        "embed": jax.random.normal(next(k), (vocab_size, D)) * 0.02,
        "pos": jax.random.normal(next(k), (max_len, D)) * 0.02,
        "lnf_s": jnp.ones((D,)), "lnf_b": jnp.zeros((D,)),
    }
    return {"blocks": blocks, "rest": rest}


def reference_apply(params, x, num_heads: int):
    """Unpipelined ground truth: the same function, all layers in order."""
    h = params["rest"]["embed"][x] + params["rest"]["pos"][: x.shape[1]]
    h = lax.scan(
        lambda c, p: (block_fn(p, c, num_heads), None), h, params["blocks"]
    )[0]
    h = _layer_norm(h, params["rest"]["lnf_s"], params["rest"]["lnf_b"])
    return h @ params["rest"]["embed"].T


class PipelineParallelTrainer:
    """GPipe trainer for the pure-jax transformer LM over a (dp, pp) mesh.

    Usage::

        topo = mpit_tpu.init(axis_names=("dp", "pp"), mesh_shape=(2, 4))
        tr = PipelineParallelTrainer(
            vocab_size=V, num_layers=8, d_model=64, num_heads=4,
            seq_len=T, topo=topo, n_micro=4, lr=0.1, momentum=0.9)
        state = tr.init_state(jax.random.key(0))
        state, metrics = tr.step(state, x_global, y_global)

    Requires ``num_layers % pp == 0`` and the per-dp-shard batch divisible
    by ``n_micro``. Math is schedule-invariant: the same trajectory as the
    unpipelined reference and as any other (dp, pp) factorization
    (tests/test_pipeline_parallel.py).
    """

    def __init__(
        self,
        vocab_size: int,
        num_layers: int,
        d_model: int,
        num_heads: int,
        seq_len: int,
        topo: Optional[Topology] = None,
        d_ff: int = 0,
        n_micro: int = 4,
        lr: float = 0.1,
        momentum: float = 0.9,
    ):
        self.topo = topo if topo is not None else _current_topology()
        mesh = self.topo.mesh
        if len(mesh.axis_names) < 2 or mesh.axis_names[1] != "pp":
            raise ValueError(
                "PipelineParallelTrainer needs a mesh whose second axis is "
                f"'pp'; got axes {mesh.axis_names}"
            )
        self.pp = int(mesh.shape["pp"])
        self.dp = int(mesh.shape[mesh.axis_names[0]])
        if num_layers % self.pp:
            raise ValueError(
                f"num_layers={num_layers} not divisible by pp={self.pp}"
            )
        if d_model % num_heads:
            raise ValueError(
                f"d_model={d_model} not divisible by num_heads={num_heads}"
            )
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.d_model = d_model
        self.num_heads = num_heads
        self.d_ff = d_ff or 4 * d_model
        self.seq_len = seq_len
        self.n_micro = n_micro
        self.lr, self.momentum = lr, momentum
        dp_axis = mesh.axis_names[0]

        spec = {"blocks": P("pp"), "rest": P()}
        heads = num_heads
        M, S = n_micro, self.pp

        def forward(params, x):
            """Loss on this (dp, pp) shard's batch block ``x`` (b, T)."""
            s = lax.axis_index("pp")
            rest = params["rest"]
            b, t = x.shape
            h = rest["embed"][x] + rest["pos"][:t]
            # the pipeline consumes stage 0's embedding only; masking the
            # rest keeps every replicated-param gradient single-owner
            h = jnp.where(s == 0, h, 0.0)
            mb = b // M
            h_mb = h.reshape(M, mb, t, -1)

            def stage(blocks, inp):
                return lax.scan(
                    lambda c, p: (block_fn(p, c, heads), None), inp, blocks
                )[0]

            perm = [(i, (i + 1) % S) for i in range(S)]
            zero = jnp.zeros_like(h_mb[0])

            def tick(prev_out, t_idx):
                recv = lax.ppermute(prev_out, "pp", perm)
                my_mb = lax.dynamic_index_in_dim(
                    h_mb, jnp.clip(t_idx, 0, M - 1), 0, keepdims=False
                )
                inp = jnp.where(s == 0, my_mb, recv)
                out = stage(params["blocks"], inp)
                return out, out

            # the last stage emits microbatch i at tick S-1+i: a STATIC
            # slice of the stacked scan outputs selects exactly the valid
            # window (carrying an output buffer through the scan instead
            # would make backward residuals quadratic in M)
            _, ys = lax.scan(tick, zero, jnp.arange(M + S - 1))
            outbuf = ys[S - 1 : S - 1 + M]
            # only the LAST stage's buffer holds the pipeline output; the
            # head runs there alone so its params have one grad owner too
            h_out = outbuf.reshape(b, t, -1)
            h_out = _layer_norm(h_out, rest["lnf_s"], rest["lnf_b"])
            logits = h_out @ rest["embed"].T
            return jnp.where(s == S - 1, logits, 0.0)

        def loss_fn(params, x, y):
            """LOCAL masked loss — no collective inside: differentiating
            through a psum multiplies every cotangent by the axis size
            (psum transposes to psum), which scaled all grads by pp until
            this was graded locally and reduced afterwards."""
            s = lax.axis_index("pp")
            logits = forward(params, x).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ce = -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()
            return jnp.where(s == S - 1, ce, 0.0)

        def train_step(state, x, y):
            params, mom = state["params"], state["momentum"]
            loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
            # the head stage owns the loss; psum makes it world-visible
            loss = lax.psum(loss, "pp")
            # single-owner replicated grads -> identical everywhere
            grads["rest"] = lax.psum(grads["rest"], "pp")
            grads = lax.pmean(grads, dp_axis)
            loss = lax.pmean(loss, dp_axis)
            mom = jax.tree.map(
                lambda m, g: momentum * m + g, mom, grads
            )
            params = jax.tree.map(
                lambda p, m: p - lr * m, params, mom
            )
            return (
                {"params": params, "momentum": mom,
                 "step": state["step"] + 1},
                {"loss": loss},
            )

        state_spec = {"params": spec, "momentum": spec, "step": P()}
        self._step = jax.jit(
            jax.shard_map(
                train_step,
                mesh=mesh,
                in_specs=(state_spec, P(dp_axis), P(dp_axis)),
                out_specs=(state_spec, P()),
                check_vma=False,
            )
        )

    def init_state(self, rng) -> dict:
        params = init_params(
            rng, self.vocab_size, self.num_layers, self.d_model,
            self.d_ff, self.seq_len,
        )
        state = {
            "params": params,
            "momentum": jax.tree.map(jnp.zeros_like, params),
            "step": jnp.zeros((), jnp.int32),
        }
        mesh = self.topo.mesh

        def group_shardings(tree):
            return {
                "blocks": jax.tree.map(
                    lambda _: NamedSharding(mesh, P("pp")), tree["blocks"]
                ),
                "rest": jax.tree.map(
                    lambda _: NamedSharding(mesh, P()), tree["rest"]
                ),
            }

        shardings = {
            "params": group_shardings(params),
            "momentum": group_shardings(params),
            "step": NamedSharding(mesh, P()),
        }
        return jax.device_put(state, shardings)

    def step(self, state, x_global, y_global):
        """One pipelined step on a global (B, T) batch."""
        b = len(x_global)
        if b % self.dp or (b // self.dp) % self.n_micro:
            raise ValueError(
                f"global batch {b} must split into dp={self.dp} shards of "
                f"a multiple of n_micro={self.n_micro}"
            )
        if x_global.shape[1] > self.seq_len:
            raise ValueError(
                f"sequence of {x_global.shape[1]} exceeds the position "
                f"table (seq_len={self.seq_len})"
            )
        state, metrics = self._step(
            state, jnp.asarray(x_global), jnp.asarray(y_global)
        )
        bound_cpu_dispatch(self.topo, metrics)
        return state, metrics
