"""Pipeline parallelism: GPipe-style microbatching over a ``pp`` mesh axis.

Beyond-parity extension completing the parallelism suite (dp: all
trainers; sp: ring attention; tp: GSPMD Megatron shardings; pp: here).
The transformer's layer stack shards by STAGE: device ``s`` on the ``pp``
axis holds layers ``[s·L/S, (s+1)·L/S)`` as stacked leaves, activations
flow stage-to-stage with ``lax.ppermute`` (the TPU's neighbor-ICI
primitive), and the batch is cut into microbatches so stages overlap —
the classic schedule: tick ``t`` has stage ``s`` working microbatch
``t−s``, ``M + S − 1`` ticks total, bubble fraction ``(S−1)/(M+S−1)``.

The backward pass is NOT hand-written: ``jax.grad`` transposes the whole
scan-of-ppermute program (the transpose of a ppermute is the reverse
ppermute), so gradients flow backward through the pipeline automatically.

The block itself is the ONE definition from
:class:`mpit_tpu.models.transformer.Block` (run in f32): the pipeline
stores its params as stacked leaves — per-layer flax param trees with a
leading layer dim, exactly the layout pipelining wants — initializes them
by vmapping ``Block.init`` over layer keys, and applies them by scanning
``Block.apply``. Only the embedding/position/final-norm/tied-head "rest"
is plain arrays here, and its norm is flax's ``nn.LayerNorm`` applied
functionally. The optimizer is a manual SGD+momentum so its state tree
mirrors the param tree (same shard_map specs apply to both).

Boundary ownership keeps replicated params consistent: the embedding's
input side contributes only on stage 0, the final norm and the tied
head's output side only on the last stage (elsewhere their outputs are
masked to zero), so each replicated param's raw gradient is nonzero only
on its owning stage(s) — the tied embedding has two, whose contributions
are complementary; the ``psum`` over pp sums them into the identical
total gradient everywhere before the optimizer touches the replicated
copies.
"""

from __future__ import annotations

import functools
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu.comm.topology import Topology
from mpit_tpu.models.transformer import Block
from mpit_tpu.parallel.common import (
    assert_elementwise_optimizer,
    bound_cpu_dispatch,
    check_clip_norm,
    clip_by_global_norm_in_mesh,
)


def _is_blocks_leaf(path) -> bool:
    """Stage-sharded leaves live under the top-level ``blocks`` group
    (disjoint layer shards per pp rank); everything else is replicated
    across pp after its psum."""
    head = path[0] if path else None
    return getattr(head, "key", None) == "blocks"


def _block_module(d_model: int, num_heads: int, d_ff: int) -> Block:
    """The shared transformer block, pinned to f32 dense attention."""
    return Block(
        d_model=d_model, num_heads=num_heads, d_ff=d_ff,
        compute_dtype=jnp.float32, seq_axis=None,
    )


def _apply_blocks(block: Block, stacked, h):
    """Scan ``Block.apply`` over stacked (L, ...) flax param leaves."""
    return lax.scan(
        lambda c, p: (block.apply({"params": p}, c), None), h, stacked
    )[0]


def _final_norm(x, scale, bias):
    """flax LayerNorm applied functionally (no second norm definition)."""
    return nn.LayerNorm().apply(
        {"params": {"scale": scale, "bias": bias}}, x
    )


def init_params(
    rng, vocab_size: int, num_layers: int, d_model: int, d_ff: int,
    max_len: int, num_heads: int = 4,
) -> dict:
    """{"blocks": stacked (L, ...) flax Block leaves, "rest":
    embed/pos/final-norm} — blocks initialized by the shared Block's own
    initializers, vmapped over per-layer keys."""
    blk = _block_module(d_model, num_heads, d_ff)
    k_blocks, k_embed, k_pos = jax.random.split(rng, 3)
    dummy = jnp.zeros((1, 1, d_model), jnp.float32)
    blocks = jax.vmap(lambda k: blk.init(k, dummy)["params"])(
        jax.random.split(k_blocks, num_layers)
    )
    rest = {
        "embed": jax.random.normal(k_embed, (vocab_size, d_model)) * 0.02,
        "pos": jax.random.normal(k_pos, (max_len, d_model)) * 0.02,
        "lnf_s": jnp.ones((d_model,)), "lnf_b": jnp.zeros((d_model,)),
    }
    return {"blocks": blocks, "rest": rest}


def schedule_1f1b(n_micro: int, stages: int) -> dict:
    """Static 1F1B timetable for ``n_micro`` microbatches over ``stages``.

    Greedy simulation with the 1F1B priority (run a backward whenever one
    is ready, else the next forward): per (tick, stage) an op code
    (0 idle / 1 fwd / 2 bwd) and microbatch index, plus arrival tables
    saying which microbatch's boundary activation (from stage−1) or
    cotangent (from stage+1) lands at the start of each tick. A unit run
    at tick ``t`` arrives at its neighbor at ``t+1`` (one ppermute hop).

    Properties (asserted by tests): the span is ``2(M+S−1)`` ticks — the
    same bubble as GPipe's forward+transposed-backward — and every stage
    holds at most ``min(S, M)`` microbatches in flight (early stages run
    one ahead of the textbook ``S−s`` because each boundary hop costs a
    ppermute tick), which is the schedule's actual win: saved
    activations stay O(S), not O(M).
    """
    tabs = schedule_pipeline(n_micro, stages, virtual=1)
    # v=1: drop the (all-zero) chunk columns for the original interface
    return {
        "op": tabs["op"],
        "mb": tabs["mb"],
        "arr_act": tabs["arr_act_mb"],
        "arr_ct": tabs["arr_ct_mb"],
        "ticks": tabs["ticks"],
        "max_inflight": tabs["max_inflight"],
    }


# forward-unit orderings tried by the interleaved scheduler; the
# min-span table wins (all are valid — they only reorder ready work)
_F_POLICIES = (
    lambda c, i, S: (i, c),            # microbatch-major
    lambda c, i, S: (c, i),            # chunk-major
    lambda c, i, S: (i // S, c, i),    # Megatron grouping: S-microbatch
                                       # blocks cycling through chunks
)


def schedule_pipeline(n_micro: int, stages: int, virtual: int = 1) -> dict:
    """Static interleaved-1F1B timetable: ``virtual`` chunks per device.

    Global chunk ``c`` (0..v·S) lives on device ``c % S`` as local chunk
    ``c // S`` and holds ``L/(v·S)`` consecutive layers; activations hop
    chunk ``c → c+1``, which is always ONE forward ring hop (cotangents
    the reverse), so the communication pattern is identical to plain
    1F1B — only the timetable changes. Each tick a device runs one unit
    (fwd or bwd of one (chunk, microbatch)); a unit's output arrives at
    its neighbor the next tick.

    The greedy simulation prefers a ready backward, then tries each
    forward ordering in ``_F_POLICIES`` and keeps the shortest-span
    table. Why interleaving wins: a unit is ``1/v`` of a device's
    per-microbatch work, so the (S−1)-deep fill/drain skew costs
    ``(S−1)/v`` device-work units instead of ``S−1`` — the Megatron
    virtual-pipeline argument. ``virtual=1`` reproduces plain 1F1B
    exactly.

    Results are cached per (M, S, v) — treat the tables as read-only.
    With one chunk per device every policy picks the same unit, so v=1
    skips the policy search.
    """
    return _schedule_cached(n_micro, stages, virtual)


@functools.lru_cache(maxsize=64)
def _schedule_cached(n_micro: int, stages: int, virtual: int) -> dict:
    M, S, v = n_micro, stages, virtual
    C = v * S  # total chunks

    def simulate(f_key):
        f_done = [[-1] * M for _ in range(C)]
        b_done = [[-1] * M for _ in range(C)]
        nf = [0] * C
        nb = [0] * C
        inflight_max = [0] * S
        rows = []  # per tick: per device (op, c_local, mb)
        t, total_b = 0, 0
        ring = min(S, M)
        while total_b < C * M:
            if t > 6 * v * (M + S) + 16:
                raise AssertionError("pipeline schedule failed to converge")
            row = []
            for s in range(S):
                chunks = [cl * S + s for cl in range(v)]
                pick = (0, 0, 0)
                b_ready = [
                    (c, nb[c]) for c in chunks
                    if nb[c] < M and (
                        0 <= f_done[c][nb[c]] < t if c == C - 1
                        else 0 <= b_done[c + 1][nb[c]] < t
                    )
                ]
                if b_ready:
                    # drain-first: the highest chunk's backward unblocks
                    # the longest dependency chain
                    c, i = max(b_ready, key=lambda ci: ci[0])
                    pick = (2, c // S, i)
                else:
                    f_ready = [
                        (c, nf[c]) for c in chunks
                        if nf[c] < M and (nf[c] - nb[c]) < ring and (
                            c == 0 or 0 <= f_done[c - 1][nf[c]] < t
                        )
                    ]
                    if f_ready:
                        c, i = min(
                            f_ready, key=lambda ci: f_key(ci[0], ci[1], S)
                        )
                        pick = (1, c // S, i)
                row.append(pick)
            for s, (op, cl, mb) in enumerate(row):
                c = cl * S + s
                if op == 1:
                    f_done[c][mb] = t
                    nf[c] += 1
                    inflight_max[s] = max(
                        inflight_max[s],
                        sum(nf[x] - nb[x] for x in range(s, C, S)),
                    )
                elif op == 2:
                    b_done[c][mb] = t
                    nb[c] += 1
                    total_b += 1
            rows.append(row)
            t += 1
        return t, rows, f_done, b_done, inflight_max

    best = None
    for key in (_F_POLICIES if v > 1 else _F_POLICIES[:1]):
        result = simulate(key)
        if best is None or result[0] < best[0]:
            best = result
    T, rows, f_done, b_done, inflight_max = best

    op = np.zeros((T, S), np.int32)
    chunk = np.zeros((T, S), np.int32)
    mb = np.zeros((T, S), np.int32)
    for t, row in enumerate(rows):
        for s, (o, cl, i) in enumerate(row):
            op[t, s], chunk[t, s], mb[t, s] = o, cl, i
    # arrivals: (local chunk, mb) landing at each (tick, device); -1 none
    arr_act_c = -np.ones((T, S), np.int32)
    arr_act_mb = -np.ones((T, S), np.int32)
    arr_ct_c = -np.ones((T, S), np.int32)
    arr_ct_mb = -np.ones((T, S), np.int32)
    for c in range(C):
        for i in range(M):
            if c + 1 < C and 0 <= f_done[c][i] and f_done[c][i] + 1 < T:
                td, dev = f_done[c][i] + 1, (c + 1) % S
                arr_act_c[td, dev] = (c + 1) // S
                arr_act_mb[td, dev] = i
            if c - 1 >= 0 and 0 <= b_done[c][i] and b_done[c][i] + 1 < T:
                td, dev = b_done[c][i] + 1, (c - 1) % S
                arr_ct_c[td, dev] = (c - 1) // S
                arr_ct_mb[td, dev] = i
    return {
        "op": op,
        "chunk": chunk,
        "mb": mb,
        "arr_act_c": arr_act_c,
        "arr_act_mb": arr_act_mb,
        "arr_ct_c": arr_ct_c,
        "arr_ct_mb": arr_ct_mb,
        "ticks": T,
        "max_inflight": inflight_max,
    }


def reference_apply(params, x, num_heads: int):
    """Unpipelined ground truth: the same function, all layers in order.

    ``d_model``/``d_ff`` are read off the param shapes, so the signature
    matches the old pure-jax one.
    """
    blocks = params["blocks"]
    d_model = blocks["Dense_0"]["kernel"].shape[1]
    d_ff = blocks["Dense_2"]["kernel"].shape[-1]
    blk = _block_module(d_model, num_heads, d_ff)
    h = params["rest"]["embed"][x] + params["rest"]["pos"][: x.shape[1]]
    h = _apply_blocks(blk, blocks, h)
    h = _final_norm(h, params["rest"]["lnf_s"], params["rest"]["lnf_b"])
    return h @ params["rest"]["embed"].T


class PipelineParallelTrainer:
    """GPipe trainer for the pure-jax transformer LM over a (dp, pp) mesh.

    Usage::

        topo = mpit_tpu.init(axis_names=("dp", "pp"), mesh_shape=(2, 4))
        tr = PipelineParallelTrainer(
            vocab_size=V, num_layers=8, d_model=64, num_heads=4,
            seq_len=T, topo=topo, n_micro=4, lr=0.1, momentum=0.9)
        state = tr.init_state(jax.random.key(0))
        state, metrics = tr.step(state, x_global, y_global)

    Requires ``num_layers % pp == 0`` and the per-dp-shard batch divisible
    by ``n_micro``. Math is schedule-invariant: the same trajectory as the
    unpipelined reference and as any other (dp, pp) factorization
    (tests/test_pipeline_parallel.py).
    """

    def __init__(
        self,
        vocab_size: int,
        num_layers: int,
        d_model: int,
        num_heads: int,
        seq_len: int,
        topo: Optional[Topology] = None,
        d_ff: int = 0,
        n_micro: int = 4,
        lr: float = 0.1,
        momentum: float = 0.9,
        schedule: str = "gpipe",
        virtual: int = 2,
        optimizer=None,
        clip_norm: Optional[float] = None,
        donate_state: bool = True,
    ):
        """``optimizer``: an optax GradientTransformation replacing the
        built-in SGD+momentum (``lr``/``momentum`` are then ignored).
        Its update runs on stage-sharded block gradients inside
        shard_map, so it must be ELEMENTWISE — the same behavioral probe
        the MoE/ZeRO trainers use rejects cross-leaf transforms here.
        ``clip_norm``: mesh-correct global-norm clipping (block shards
        psum their sum-of-squares over pp, the replicated rest counts
        once) — works with either optimizer path."""
        self.topo = topo if topo is not None else _current_topology()
        mesh = self.topo.mesh
        if len(mesh.axis_names) < 2 or mesh.axis_names[1] != "pp":
            raise ValueError(
                "PipelineParallelTrainer needs a mesh whose second axis is "
                f"'pp'; got axes {mesh.axis_names}"
            )
        self.pp = int(mesh.shape["pp"])
        self.dp = int(mesh.shape[mesh.axis_names[0]])
        if num_layers % self.pp:
            raise ValueError(
                f"num_layers={num_layers} not divisible by pp={self.pp}"
            )
        if d_model % num_heads:
            raise ValueError(
                f"d_model={d_model} not divisible by num_heads={num_heads}"
            )
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.d_model = d_model
        self.num_heads = num_heads
        self.d_ff = d_ff or 4 * d_model
        self.seq_len = seq_len
        self.n_micro = n_micro
        self.lr, self.momentum = lr, momentum
        self.optimizer = optimizer
        if optimizer is not None:
            assert_elementwise_optimizer(
                optimizer, "PipelineParallelTrainer"
            )
        self.clip_norm = check_clip_norm(clip_norm)
        if schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"schedule={schedule!r} must be 'gpipe', '1f1b', or "
                "'interleaved'"
            )
        self.schedule = schedule
        # virtual chunks per device (Megatron virtual pipeline) — only
        # the interleaved schedule uses more than one
        self.virtual = virtual if schedule == "interleaved" else 1
        if self.virtual < 1:
            raise ValueError(f"virtual={virtual} must be >= 1")
        if num_layers % (self.pp * self.virtual):
            raise ValueError(
                f"num_layers={num_layers} not divisible by "
                f"pp x virtual = {self.pp}x{self.virtual}"
            )
        # storage permutation: stacked layer row r of the (L, ...) leaves
        # must hold the layer device r//K's local chunks cover — under
        # interleaving device s owns chunks {s, s+S, ...}, which are NOT
        # contiguous global layers. Identity for gpipe/1f1b.
        Kc_ = num_layers // (self.pp * self.virtual)
        self._perm = np.array([
            (cl * self.pp + s_) * Kc_ + j
            for s_ in range(self.pp)
            for cl in range(self.virtual)
            for j in range(Kc_)
        ])
        self._inv_perm = np.argsort(self._perm)
        self._permuted = self.virtual > 1
        dp_axis = mesh.axis_names[0]

        spec = {"blocks": P("pp"), "rest": P()}
        blk = _block_module(d_model, num_heads, self.d_ff)
        M, S = n_micro, self.pp

        def forward(params, x):
            """Loss on this (dp, pp) shard's batch block ``x`` (b, T)."""
            s = lax.axis_index("pp")
            rest = params["rest"]
            b, t = x.shape
            h = rest["embed"][x] + rest["pos"][:t]
            # the pipeline consumes stage 0's embedding only; masking the
            # rest keeps every replicated-param gradient single-owner
            h = jnp.where(s == 0, h, 0.0)
            mb = b // M
            h_mb = h.reshape(M, mb, t, -1)

            def stage(blocks, inp):
                return _apply_blocks(blk, blocks, inp)

            perm = [(i, (i + 1) % S) for i in range(S)]
            zero = jnp.zeros_like(h_mb[0])

            def tick(prev_out, t_idx):
                recv = lax.ppermute(prev_out, "pp", perm)
                my_mb = lax.dynamic_index_in_dim(
                    h_mb, jnp.clip(t_idx, 0, M - 1), 0, keepdims=False
                )
                inp = jnp.where(s == 0, my_mb, recv)
                out = stage(params["blocks"], inp)
                return out, out

            # the last stage emits microbatch i at tick S-1+i: a STATIC
            # slice of the stacked scan outputs selects exactly the valid
            # window (carrying an output buffer through the scan instead
            # would make backward residuals quadratic in M)
            _, ys = lax.scan(tick, zero, jnp.arange(M + S - 1))
            outbuf = ys[S - 1 : S - 1 + M]
            # only the LAST stage's buffer holds the pipeline output; the
            # head runs there alone so its params have one grad owner too
            h_out = outbuf.reshape(b, t, -1)
            h_out = _final_norm(h_out, rest["lnf_s"], rest["lnf_b"])
            logits = h_out @ rest["embed"].T
            return jnp.where(s == S - 1, logits, 0.0)

        def loss_fn(params, x, y):
            """LOCAL masked loss — no collective inside: differentiating
            through a psum multiplies every cotangent by the axis size
            (psum transposes to psum), which scaled all grads by pp until
            this was graded locally and reduced afterwards."""
            s = lax.axis_index("pp")
            logits = forward(params, x).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ce = -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()
            return jnp.where(s == S - 1, ce, 0.0)

        K = num_layers // S  # layers per stage (the local block shard)

        v = self.virtual
        Kc = K // v  # layers per chunk

        def loss_and_grads_1f1b(params, x, y):
            """1F1B / interleaved: forwards and backwards explicitly
            scheduled on one tick timeline (schedule_pipeline), instead
            of a forward scan that autodiff transposes afterwards
            (GPipe).

            v=1 (schedule="1f1b"): same 2(M+S−1)-tick span as GPipe, but
            the saved state is an R-slot ring of per-layer block INPUTS
            (backward recomputes each block before transposing it,
            remat-style) — O(S·K) activation memory instead of autodiff
            GPipe's O((M+S−1)·K) per-tick internals.

            v>1 (schedule="interleaved"): each device holds v virtual
            chunks (Megatron virtual pipeline; params stored chunk-
            permuted so P("pp") hands each device its chunks). A tick is
            1/v of a stage's work, so the (S−1)-deep fill/drain skew
            shrinks by v — wins in the bubble-dominated regime (M ≲ S);
            for M ≫ S the extra hop latency per chunk boundary eats the
            gain (measured in schedule_pipeline's simulator, asserted in
            tests).
            """
            tabs = schedule_pipeline(M, S, v)
            t_op = jnp.asarray(tabs["op"])
            t_cl = jnp.asarray(tabs["chunk"])
            t_mb = jnp.asarray(tabs["mb"])
            t_aa_c = jnp.asarray(tabs["arr_act_c"])
            t_aa_m = jnp.asarray(tabs["arr_act_mb"])
            t_ac_c = jnp.asarray(tabs["arr_ct_c"])
            t_ac_m = jnp.asarray(tabs["arr_ct_mb"])
            s = lax.axis_index("pp")
            rest, blocks = params["rest"], params["blocks"]
            # local (K, ...) leaves viewed as v chunks of Kc layers (the
            # storage permutation makes these the right GLOBAL chunks)
            blocks_v = jax.tree.map(
                lambda a: a.reshape(v, Kc, *a.shape[1:]), blocks
            )
            b, t_len = x.shape
            mb = b // M
            # tokens stay int32 (M, mb, t); each fwd/bwd unit embeds its
            # own microbatch, so no O(M) f32 activation buffer exists
            x_mb = x.reshape(M, mb, t_len)
            y_mb = y.reshape(M, mb, t_len)
            perm_fwd = [(i, (i + 1) % S) for i in range(S)]
            perm_bwd = [((i + 1) % S, i) for i in range(S)]

            def head_loss(rest_in, h_out, y_i):
                """Per-microbatch tail: final norm, tied head, CE — the
                full-batch mean is the mean of per-microbatch means."""
                h2 = _final_norm(h_out, rest_in["lnf_s"], rest_in["lnf_b"])
                logits = (h2 @ rest_in["embed"].T).astype(jnp.float32)
                logp = jax.nn.log_softmax(logits, axis=-1)
                ce = -jnp.take_along_axis(logp, y_i[..., None], -1).mean()
                return ce / M

            R = min(S, M)  # ring slots per chunk: the in-flight bound

            def store(buf, cl, idx, val):
                """Predicated ring write: buf[cl, idx % R] = val when
                idx >= 0. Slot reuse is safe by the per-chunk in-flight
                cap: the producer of item i+R cannot have run before
                item i's consumer finished (schedule_pipeline's
                capacity rule, chained chunk-to-chunk)."""
                slot = jnp.remainder(jnp.maximum(idx, 0), R)
                upd = lax.dynamic_update_slice(
                    buf, val[None, None],
                    (jnp.maximum(cl, 0), slot)
                    + (0,) * (buf.ndim - 2),
                )
                return jnp.where(idx >= 0, upd, buf)

            def fetch(buf, cl, idx):
                got = lax.dynamic_slice(
                    buf,
                    (cl, jnp.remainder(idx, R)) + (0,) * (buf.ndim - 2),
                    (1, 1) + buf.shape[2:],
                )
                return got.reshape(buf.shape[2:])

            zero_act = jnp.zeros((mb, t_len, d_model), jnp.float32)
            carry0 = {
                "pf": zero_act,  # last fwd output (sent down-pipe)
                "pb": zero_act,  # last bwd input-cotangent (sent up-pipe)
                # boundary rings — O(v·S), never O(M)
                "act": jnp.zeros((v, R, mb, t_len, d_model), jnp.float32),
                "ct": jnp.zeros((v, R, mb, t_len, d_model), jnp.float32),
                # per-layer chunk inputs + chunk output, R slots per chunk
                "ring": jnp.zeros(
                    (v, R, Kc + 1, mb, t_len, d_model), jnp.float32
                ),
                "gb": jax.tree.map(jnp.zeros_like, blocks_v),
                "gr": jax.tree.map(jnp.zeros_like, rest),
                "loss": jnp.float32(0.0),
            }

            def tick(c, tk):
                recv_a = lax.ppermute(c["pf"], "pp", perm_fwd)
                recv_c = lax.ppermute(c["pb"], "pp", perm_bwd)
                c = {
                    **c,
                    "act": store(
                        c["act"], t_aa_c[tk, s], t_aa_m[tk, s], recv_a
                    ),
                    "ct": store(
                        c["ct"], t_ac_c[tk, s], t_ac_m[tk, s], recv_c
                    ),
                }
                cl = t_cl[tk, s]
                i = t_mb[tk, s]
                blk_c = jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(a, cl, 0, False),
                    blocks_v,
                )

                def fwd(c):
                    # only global chunk 0 (device 0, local chunk 0)
                    # embeds; lax.cond skips the gather elsewhere
                    def embed_in(_):
                        x_i = lax.dynamic_index_in_dim(x_mb, i, 0, False)
                        return rest["embed"][x_i] + rest["pos"][:t_len]

                    inp = lax.cond(
                        (s == 0) & (cl == 0),
                        embed_in,
                        lambda _: fetch(c["act"], cl, i),
                        None,
                    )

                    def f(cc, p):
                        return blk.apply({"params": p}, cc), cc

                    out, saved = lax.scan(f, inp, blk_c)
                    entry = jnp.concatenate([saved, out[None]], 0)
                    ring = store(c["ring"], cl, i, entry)
                    return {**c, "ring": ring, "pf": out}

                def bwd(c):
                    entry = fetch(c["ring"], cl, i)
                    out = entry[Kc]
                    y_i = lax.dynamic_index_in_dim(y_mb, i, 0, False)
                    last = (s == S - 1) & (cl == v - 1)

                    # the head (final norm + tied vocab matmul + CE) and
                    # its vjp run ONLY on the last chunk — lax.cond is
                    # legal here (no collectives inside the branches)
                    def with_head(_):
                        loss_i, head_vjp = jax.vjp(
                            lambda r, o: head_loss(r, o, y_i), rest, out
                        )
                        g_head, ct_last = head_vjp(jnp.float32(1.0))
                        return loss_i, g_head, ct_last

                    def without_head(_):
                        return (
                            jnp.float32(0.0),
                            jax.tree.map(jnp.zeros_like, rest),
                            fetch(c["ct"], cl, i),
                        )

                    loss_i, g_head, ct_out = lax.cond(
                        last, with_head, without_head, None
                    )

                    def bstep(cc, xs):
                        p_j, in_j = xs
                        _, vjp = jax.vjp(
                            lambda p, xx: blk.apply({"params": p}, xx),
                            p_j, in_j,
                        )
                        gp, gx = vjp(cc)
                        return gx, gp

                    # recompute-and-transpose each block, last to first
                    ct_in, g_chunk = lax.scan(
                        bstep, ct_out, (blk_c, entry[:Kc]), reverse=True
                    )
                    # global chunk 0 closes the loop through its
                    # embedding + position lookup immediately (per
                    # microbatch) — no O(M) cotangent buffer
                    x_i = lax.dynamic_index_in_dim(x_mb, i, 0, False)
                    _, evjp = jax.vjp(
                        lambda r: r["embed"][x_i] + r["pos"][:t_len], rest
                    )
                    (g_emb,) = evjp(
                        jnp.where((s == 0) & (cl == 0), ct_in, 0.0)
                    )
                    gb = jax.tree.map(
                        lambda a, g: lax.dynamic_update_index_in_dim(
                            a,
                            lax.dynamic_index_in_dim(a, cl, 0, False) + g,
                            cl, 0,
                        ),
                        c["gb"], g_chunk,
                    )
                    return {
                        **c,
                        "gb": gb,
                        "gr": jax.tree.map(
                            lambda a, gh, ge: a + gh + ge,
                            c["gr"], g_head, g_emb,
                        ),
                        "pb": ct_in,
                        "loss": c["loss"] + loss_i,
                    }

                return lax.switch(
                    t_op[tk, s], [lambda c: c, fwd, bwd], c
                ), None

            c = lax.scan(tick, carry0, jnp.arange(tabs["ticks"]))[0]
            gb = jax.tree.map(
                lambda a: a.reshape(v * Kc, *a.shape[2:]), c["gb"]
            )
            return c["loss"], {"blocks": gb, "rest": c["gr"]}

        if schedule in ("1f1b", "interleaved"):
            loss_and_grads = loss_and_grads_1f1b
        else:
            def loss_and_grads(params, x, y):
                return jax.value_and_grad(loss_fn)(params, x, y)

        opt = self.optimizer
        clip_norm = self.clip_norm

        def _reduced_loss_grads(params, x, y):
            loss, grads = loss_and_grads(params, x, y)
            # the head stage owns the loss; psum makes it world-visible
            loss = lax.psum(loss, "pp")
            # single-owner replicated grads -> identical everywhere
            grads["rest"] = lax.psum(grads["rest"], "pp")
            grads = lax.pmean(grads, dp_axis)
            loss = lax.pmean(loss, dp_axis)
            if clip_norm is not None:
                # blocks are disjoint layer shards per pp rank; rest is
                # replicated over pp (and everything is dp-consistent
                # after the pmean above), so one psum over pp of the
                # block sums-of-squares completes the true global norm
                grads, _ = clip_by_global_norm_in_mesh(
                    grads, clip_norm, "pp", is_sharded=_is_blocks_leaf
                )
            return loss, grads

        def train_step(state, x, y):
            params, mom = state["params"], state["momentum"]
            loss, grads = _reduced_loss_grads(params, x, y)
            mom = jax.tree.map(
                lambda m, g: momentum * m + g, mom, grads
            )
            params = jax.tree.map(
                lambda p, m: p - lr * m, params, mom
            )
            return (
                {"params": params, "momentum": mom,
                 "step": state["step"] + 1},
                {"loss": loss},
            )

        def train_step_optax(state, x, y):
            import optax

            params = state["params"]
            loss, grads = _reduced_loss_grads(params, x, y)
            updates, opt_state = opt.update(
                grads, state["opt_state"], params
            )
            params = optax.apply_updates(params, updates)
            return (
                {"params": params, "opt_state": opt_state,
                 "step": state["step"] + 1},
                {"loss": loss},
            )

        if opt is not None:
            train_step = train_step_optax
            # optimizer state mirrors the param tree in params-shaped
            # SUBTREES (sgd's trace, adam's mu/nu); scalars (count) are
            # replicated. Infer the real structure by shape-only
            # evaluation — nothing materializes — and place the params
            # prefix spec at every params-shaped subtree (shard_map
            # accepts prefix pytrees).
            p_shape = jax.eval_shape(
                functools.partial(
                    init_params,
                    vocab_size=vocab_size, num_layers=num_layers,
                    d_model=d_model, d_ff=self.d_ff, max_len=seq_len,
                    num_heads=num_heads,
                ),
                jax.random.key(0),
            )
            params_td = jax.tree.structure(p_shape)

            def is_params_like(n):
                try:
                    return jax.tree.structure(n) == params_td
                except Exception:
                    return False

            opt_spec = jax.tree.map(
                lambda n: spec if is_params_like(n) else P(),
                jax.eval_shape(opt.init, p_shape),
                is_leaf=is_params_like,
            )
            self._is_params_like = is_params_like
            state_spec = {"params": spec, "opt_state": opt_spec,
                          "step": P()}
        else:
            self._is_params_like = None
            state_spec = {"params": spec, "momentum": spec, "step": P()}
        # state donated like every other trainer (params + opt/momentum
        # update in place; without it each step keeps a second copy of
        # the whole stage-sharded state alive) — donate_state=False for
        # callers that re-step the same state object
        self._step = jax.jit(
            jax.shard_map(
                train_step,
                mesh=mesh,
                in_specs=(state_spec, P(dp_axis), P(dp_axis)),
                out_specs=(state_spec, P()),
                check_vma=False,
            ),
            donate_argnums=(0,) if donate_state else (),
        )
        self._dp_axis = dp_axis

        def eval_step(params, x, y):
            """Global (correct-token count, CE sum).

            Identity-layout schedules (gpipe/1f1b) evaluate through the
            pipelined forward — per-device memory stays O(L/S) layers,
            the reason pipeline parallelism exists; logits live only on
            the last stage, so its counts are masked in and psum-ed.
            The interleaved layout instead all-gathers the stack and
            undoes the chunk permutation (eval pays the gather; the
            pipelined forward assumes contiguous storage)."""
            if self._permuted:
                blocks_full = jax.tree.map(
                    lambda a: lax.all_gather(a, "pp", tiled=True),
                    params["blocks"],
                )
                logits = reference_apply(
                    self._unpermute(
                        {"blocks": blocks_full, "rest": params["rest"]}
                    ),
                    x, num_heads,
                ).astype(jnp.float32)
                correct = jnp.sum(jnp.argmax(logits, -1) == y)
                logp = jax.nn.log_softmax(logits, axis=-1)
                ce_sum = -jnp.take_along_axis(
                    logp, y[..., None], -1
                ).sum()
                return (
                    lax.psum(correct, dp_axis),
                    lax.psum(ce_sum, dp_axis),
                )
            s = lax.axis_index("pp")
            logits = forward(params, x).astype(jnp.float32)
            correct = jnp.sum(jnp.argmax(logits, -1) == y)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ce_sum = -jnp.take_along_axis(logp, y[..., None], -1).sum()
            correct = jnp.where(s == S - 1, correct, 0)
            ce_sum = jnp.where(s == S - 1, ce_sum, 0.0)
            correct = lax.psum(lax.psum(correct, "pp"), dp_axis)
            ce_sum = lax.psum(lax.psum(ce_sum, "pp"), dp_axis)
            return correct, ce_sum

        self._eval = jax.jit(
            jax.shard_map(
                eval_step,
                mesh=mesh,
                in_specs=(spec, P(dp_axis), P(dp_axis)),
                out_specs=(P(), P()),
                check_vma=False,
            )
        )

        # unpipelined per-sample loss on the same params — the bench's
        # analytic FLOP counter traces this (host-side, never compiled);
        # undoes the interleaved storage permutation first
        def _flat_loss(params, x, y):
            logits = reference_apply(
                self._unpermute(params), x, num_heads
            ).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(logp, y[..., None], -1).mean()

        self.loss_fn = _flat_loss

    def _unpermute(self, params: dict) -> dict:
        """Params with blocks in GLOBAL layer order (no-op unless the
        interleaved storage permutation is active)."""
        if not self._permuted:
            return params
        inv = jnp.asarray(self._inv_perm)
        return {
            "blocks": jax.tree.map(lambda a: a[inv], params["blocks"]),
            "rest": params["rest"],
        }

    @property
    def ticks(self) -> int:
        """Pipeline-timeline span of one step, in schedule ticks.

        GPipe: the forward scan is ``M+S−1`` ticks and autodiff appends a
        transposed backward of the same length. 1F1B: one unified
        timeline of ``2(M+S−1)`` ticks carrying both directions — equal
        bubble, O(S) instead of O(M) saved microbatch activations.
        Interleaved: ticks are CHUNK units, each ``1/virtual`` of a
        stage's per-microbatch work — compare ``ticks / virtual`` against
        the other schedules' stage-ticks.
        """
        if self.schedule in ("1f1b", "interleaved"):
            return int(
                schedule_pipeline(
                    self.n_micro, self.pp, self.virtual
                )["ticks"]
            )
        return self.n_micro + self.pp - 1

    def init_state(self, rng, sample_x=None) -> dict:
        """``sample_x`` is accepted (and ignored — shapes come from the
        constructor) so every trainer shares one init_state signature.

        Interleaved: the globally-ordered stacked layers are row-permuted
        into chunk storage order before sharding (checkpoints carry this
        layout — restore with the same schedule/virtual config)."""
        params = init_params(
            rng, self.vocab_size, self.num_layers, self.d_model,
            self.d_ff, self.seq_len, num_heads=self.num_heads,
        )
        if self._permuted:
            perm = jnp.asarray(self._perm)
            params = {
                "blocks": jax.tree.map(
                    lambda a: a[perm], params["blocks"]
                ),
                "rest": params["rest"],
            }
        mesh = self.topo.mesh

        def group_shardings(tree):
            return {
                "blocks": jax.tree.map(
                    lambda _: NamedSharding(mesh, P("pp")), tree["blocks"]
                ),
                "rest": jax.tree.map(
                    lambda _: NamedSharding(mesh, P()), tree["rest"]
                ),
            }

        if self.optimizer is not None:
            opt_state = self.optimizer.init(params)
            state = {
                "params": params,
                "opt_state": opt_state,
                "step": jnp.zeros((), jnp.int32),
            }
            shardings = {
                "params": group_shardings(params),
                "opt_state": jax.tree.map(
                    lambda n: group_shardings(n)
                    if self._is_params_like(n)
                    else NamedSharding(mesh, P()),
                    opt_state, is_leaf=self._is_params_like,
                ),
                "step": NamedSharding(mesh, P()),
            }
            return jax.device_put(state, shardings)
        state = {
            "params": params,
            "momentum": jax.tree.map(jnp.zeros_like, params),
            "step": jnp.zeros((), jnp.int32),
        }
        shardings = {
            "params": group_shardings(params),
            "momentum": group_shardings(params),
            "step": NamedSharding(mesh, P()),
        }
        return jax.device_put(state, shardings)

    def data_sharding(self) -> NamedSharding:
        """(B, T) token batches shard over dp; every pp rank sees the
        full sequence of its dp shard."""
        return NamedSharding(self.topo.mesh, P(self._dp_axis))

    def _check(self, x):
        b = len(x)
        if b % self.dp or (b // self.dp) % self.n_micro:
            raise ValueError(
                f"global batch {b} must split into dp={self.dp} shards of "
                f"a multiple of n_micro={self.n_micro}"
            )
        if x.shape[1] > self.seq_len:
            raise ValueError(
                f"sequence of {x.shape[1]} exceeds the position "
                f"table (seq_len={self.seq_len})"
            )

    def step(self, state, x_global, y_global):
        """One pipelined step on a global (B, T) batch."""
        self._check(x_global)
        state, metrics = self._step(
            state, jnp.asarray(x_global), jnp.asarray(y_global)
        )
        bound_cpu_dispatch(self.topo, metrics)
        return state, metrics

    def fit(
        self,
        batches,
        state,
        epochs: int = 1,
        log_every: int = 0,
        start_epoch: int = 0,
        skip_steps: int = 0,
        on_step=None,
        prefetch: int = 2,
    ):
        """Epoch loop — the shared :func:`common.synced_fit_loop` with
        the dp-only batch sharding."""
        from mpit_tpu.parallel.common import synced_fit_loop

        return synced_fit_loop(
            self.topo, self._step, batches, state,
            sharding=self.data_sharding(),
            check=self._check,
            log_tag=f"pp-{self.schedule}",
            epochs=epochs, log_every=log_every, start_epoch=start_epoch,
            skip_steps=skip_steps, on_step=on_step, prefetch=prefetch,
        )

    def evaluate(self, state, x, y, batch: int = 512):
        """Token-level accuracy and mean loss over a (N, T) eval set."""
        from mpit_tpu.parallel.common import batched_count_eval

        if x.shape[1] > self.seq_len:
            raise ValueError(
                f"sequence of {x.shape[1]} exceeds the position "
                f"table (seq_len={self.seq_len})"
            )
        correct, loss_sum, n = batched_count_eval(
            self._eval, state["params"], x, y, batch,
            self.dp * self.n_micro,
        )
        tokens = n * x.shape[1]
        return correct / tokens, loss_sum / tokens
