"""Pipeline parallelism: GPipe-style microbatching over a ``pp`` mesh axis.

Beyond-parity extension completing the parallelism suite (dp: all
trainers; sp: ring attention; tp: GSPMD Megatron shardings; pp: here).
The transformer's layer stack shards by STAGE: device ``s`` on the ``pp``
axis holds layers ``[s·L/S, (s+1)·L/S)`` as stacked leaves, activations
flow stage-to-stage with ``lax.ppermute`` (the TPU's neighbor-ICI
primitive), and the batch is cut into microbatches so stages overlap —
the classic schedule: tick ``t`` has stage ``s`` working microbatch
``t−s``, ``M + S − 1`` ticks total, bubble fraction ``(S−1)/(M+S−1)``.

The backward pass is NOT hand-written: ``jax.grad`` transposes the whole
scan-of-ppermute program (the transpose of a ppermute is the reverse
ppermute), so gradients flow backward through the pipeline automatically.

The block itself is the ONE definition from
:class:`mpit_tpu.models.transformer.Block` (run in f32): the pipeline
stores its params as stacked leaves — per-layer flax param trees with a
leading layer dim, exactly the layout pipelining wants — initializes them
by vmapping ``Block.init`` over layer keys, and applies them by scanning
``Block.apply``. Only the embedding/position/final-norm/tied-head "rest"
is plain arrays here, and its norm is flax's ``nn.LayerNorm`` applied
functionally. The optimizer is a manual SGD+momentum so its state tree
mirrors the param tree (same shard_map specs apply to both).

Boundary ownership keeps replicated params consistent: the embedding's
input side contributes only on stage 0, the final norm and the tied
head's output side only on the last stage (elsewhere their outputs are
masked to zero), so each replicated param's raw gradient is nonzero only
on its owning stage(s) — the tied embedding has two, whose contributions
are complementary; the ``psum`` over pp sums them into the identical
total gradient everywhere before the optimizer touches the replicated
copies.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu.comm.topology import Topology
from mpit_tpu.models.transformer import Block
from mpit_tpu.parallel.common import bound_cpu_dispatch


def _block_module(d_model: int, num_heads: int, d_ff: int) -> Block:
    """The shared transformer block, pinned to f32 dense attention."""
    return Block(
        d_model=d_model, num_heads=num_heads, d_ff=d_ff,
        compute_dtype=jnp.float32, seq_axis=None,
    )


def _apply_blocks(block: Block, stacked, h):
    """Scan ``Block.apply`` over stacked (L, ...) flax param leaves."""
    return lax.scan(
        lambda c, p: (block.apply({"params": p}, c), None), h, stacked
    )[0]


def _final_norm(x, scale, bias):
    """flax LayerNorm applied functionally (no second norm definition)."""
    return nn.LayerNorm().apply(
        {"params": {"scale": scale, "bias": bias}}, x
    )


def init_params(
    rng, vocab_size: int, num_layers: int, d_model: int, d_ff: int,
    max_len: int, num_heads: int = 4,
) -> dict:
    """{"blocks": stacked (L, ...) flax Block leaves, "rest":
    embed/pos/final-norm} — blocks initialized by the shared Block's own
    initializers, vmapped over per-layer keys."""
    blk = _block_module(d_model, num_heads, d_ff)
    k_blocks, k_embed, k_pos = jax.random.split(rng, 3)
    dummy = jnp.zeros((1, 1, d_model), jnp.float32)
    blocks = jax.vmap(lambda k: blk.init(k, dummy)["params"])(
        jax.random.split(k_blocks, num_layers)
    )
    rest = {
        "embed": jax.random.normal(k_embed, (vocab_size, d_model)) * 0.02,
        "pos": jax.random.normal(k_pos, (max_len, d_model)) * 0.02,
        "lnf_s": jnp.ones((d_model,)), "lnf_b": jnp.zeros((d_model,)),
    }
    return {"blocks": blocks, "rest": rest}


def schedule_1f1b(n_micro: int, stages: int) -> dict:
    """Static 1F1B timetable for ``n_micro`` microbatches over ``stages``.

    Greedy simulation with the 1F1B priority (run a backward whenever one
    is ready, else the next forward): per (tick, stage) an op code
    (0 idle / 1 fwd / 2 bwd) and microbatch index, plus arrival tables
    saying which microbatch's boundary activation (from stage−1) or
    cotangent (from stage+1) lands at the start of each tick. A unit run
    at tick ``t`` arrives at its neighbor at ``t+1`` (one ppermute hop).

    Properties (asserted by tests): the span is ``2(M+S−1)`` ticks — the
    same bubble as GPipe's forward+transposed-backward — and every stage
    holds at most ``min(S, M)`` microbatches in flight (early stages run
    one ahead of the textbook ``S−s`` because each boundary hop costs a
    ppermute tick), which is the schedule's actual win: saved
    activations stay O(S), not O(M).
    """
    M, S = n_micro, stages
    f_done = [[-1] * M for _ in range(S)]
    b_done = [[-1] * M for _ in range(S)]
    nf = [0] * S  # next forward microbatch per stage
    nb = [0] * S  # next backward microbatch per stage (1F1B runs in order)
    inflight_max = [0] * S
    op_rows, mb_rows = [], []
    t, total_b = 0, 0
    while total_b < S * M:
        if t > 4 * (M + S) + 8:
            raise AssertionError("1F1B schedule failed to converge")
        row = []
        for s in range(S):
            op, mb = 0, 0
            bi, fi = nb[s], nf[s]
            b_ready = bi < M and (
                0 <= f_done[s][bi] < t
                if s == S - 1
                else 0 <= b_done[s + 1][bi] < t
            )
            f_ready = fi < M and (fi - nb[s]) < S and (
                s == 0 or 0 <= f_done[s - 1][fi] < t
            )
            if b_ready:
                op, mb = 2, bi
            elif f_ready:
                op, mb = 1, fi
            row.append((op, mb))
        for s, (op, mb) in enumerate(row):  # commit synchronously
            if op == 1:
                f_done[s][mb] = t
                nf[s] += 1
                inflight_max[s] = max(inflight_max[s], nf[s] - nb[s])
            elif op == 2:
                b_done[s][mb] = t
                nb[s] += 1
                total_b += 1
        op_rows.append([op for op, _ in row])
        mb_rows.append([mb for _, mb in row])
        t += 1
    import numpy as np

    T = t
    arr_act = -np.ones((T, S), np.int32)
    arr_ct = -np.ones((T, S), np.int32)
    for s in range(S):
        for i in range(M):
            if s + 1 < S and f_done[s][i] + 1 < T:
                arr_act[f_done[s][i] + 1, s + 1] = i
            if s - 1 >= 0 and b_done[s][i] + 1 < T:
                arr_ct[b_done[s][i] + 1, s - 1] = i
    return {
        "op": np.asarray(op_rows, np.int32),
        "mb": np.asarray(mb_rows, np.int32),
        "arr_act": arr_act,
        "arr_ct": arr_ct,
        "ticks": T,
        "max_inflight": inflight_max,
    }


def reference_apply(params, x, num_heads: int):
    """Unpipelined ground truth: the same function, all layers in order.

    ``d_model``/``d_ff`` are read off the param shapes, so the signature
    matches the old pure-jax one.
    """
    blocks = params["blocks"]
    d_model = blocks["Dense_0"]["kernel"].shape[1]
    d_ff = blocks["Dense_2"]["kernel"].shape[-1]
    blk = _block_module(d_model, num_heads, d_ff)
    h = params["rest"]["embed"][x] + params["rest"]["pos"][: x.shape[1]]
    h = _apply_blocks(blk, blocks, h)
    h = _final_norm(h, params["rest"]["lnf_s"], params["rest"]["lnf_b"])
    return h @ params["rest"]["embed"].T


class PipelineParallelTrainer:
    """GPipe trainer for the pure-jax transformer LM over a (dp, pp) mesh.

    Usage::

        topo = mpit_tpu.init(axis_names=("dp", "pp"), mesh_shape=(2, 4))
        tr = PipelineParallelTrainer(
            vocab_size=V, num_layers=8, d_model=64, num_heads=4,
            seq_len=T, topo=topo, n_micro=4, lr=0.1, momentum=0.9)
        state = tr.init_state(jax.random.key(0))
        state, metrics = tr.step(state, x_global, y_global)

    Requires ``num_layers % pp == 0`` and the per-dp-shard batch divisible
    by ``n_micro``. Math is schedule-invariant: the same trajectory as the
    unpipelined reference and as any other (dp, pp) factorization
    (tests/test_pipeline_parallel.py).
    """

    def __init__(
        self,
        vocab_size: int,
        num_layers: int,
        d_model: int,
        num_heads: int,
        seq_len: int,
        topo: Optional[Topology] = None,
        d_ff: int = 0,
        n_micro: int = 4,
        lr: float = 0.1,
        momentum: float = 0.9,
        schedule: str = "gpipe",
    ):
        self.topo = topo if topo is not None else _current_topology()
        mesh = self.topo.mesh
        if len(mesh.axis_names) < 2 or mesh.axis_names[1] != "pp":
            raise ValueError(
                "PipelineParallelTrainer needs a mesh whose second axis is "
                f"'pp'; got axes {mesh.axis_names}"
            )
        self.pp = int(mesh.shape["pp"])
        self.dp = int(mesh.shape[mesh.axis_names[0]])
        if num_layers % self.pp:
            raise ValueError(
                f"num_layers={num_layers} not divisible by pp={self.pp}"
            )
        if d_model % num_heads:
            raise ValueError(
                f"d_model={d_model} not divisible by num_heads={num_heads}"
            )
        self.vocab_size = vocab_size
        self.num_layers = num_layers
        self.d_model = d_model
        self.num_heads = num_heads
        self.d_ff = d_ff or 4 * d_model
        self.seq_len = seq_len
        self.n_micro = n_micro
        self.lr, self.momentum = lr, momentum
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"schedule={schedule!r} must be 'gpipe' or '1f1b'"
            )
        self.schedule = schedule
        dp_axis = mesh.axis_names[0]

        spec = {"blocks": P("pp"), "rest": P()}
        blk = _block_module(d_model, num_heads, self.d_ff)
        M, S = n_micro, self.pp

        def forward(params, x):
            """Loss on this (dp, pp) shard's batch block ``x`` (b, T)."""
            s = lax.axis_index("pp")
            rest = params["rest"]
            b, t = x.shape
            h = rest["embed"][x] + rest["pos"][:t]
            # the pipeline consumes stage 0's embedding only; masking the
            # rest keeps every replicated-param gradient single-owner
            h = jnp.where(s == 0, h, 0.0)
            mb = b // M
            h_mb = h.reshape(M, mb, t, -1)

            def stage(blocks, inp):
                return _apply_blocks(blk, blocks, inp)

            perm = [(i, (i + 1) % S) for i in range(S)]
            zero = jnp.zeros_like(h_mb[0])

            def tick(prev_out, t_idx):
                recv = lax.ppermute(prev_out, "pp", perm)
                my_mb = lax.dynamic_index_in_dim(
                    h_mb, jnp.clip(t_idx, 0, M - 1), 0, keepdims=False
                )
                inp = jnp.where(s == 0, my_mb, recv)
                out = stage(params["blocks"], inp)
                return out, out

            # the last stage emits microbatch i at tick S-1+i: a STATIC
            # slice of the stacked scan outputs selects exactly the valid
            # window (carrying an output buffer through the scan instead
            # would make backward residuals quadratic in M)
            _, ys = lax.scan(tick, zero, jnp.arange(M + S - 1))
            outbuf = ys[S - 1 : S - 1 + M]
            # only the LAST stage's buffer holds the pipeline output; the
            # head runs there alone so its params have one grad owner too
            h_out = outbuf.reshape(b, t, -1)
            h_out = _final_norm(h_out, rest["lnf_s"], rest["lnf_b"])
            logits = h_out @ rest["embed"].T
            return jnp.where(s == S - 1, logits, 0.0)

        def loss_fn(params, x, y):
            """LOCAL masked loss — no collective inside: differentiating
            through a psum multiplies every cotangent by the axis size
            (psum transposes to psum), which scaled all grads by pp until
            this was graded locally and reduced afterwards."""
            s = lax.axis_index("pp")
            logits = forward(params, x).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ce = -jnp.take_along_axis(logp, y[..., None], axis=-1).mean()
            return jnp.where(s == S - 1, ce, 0.0)

        K = num_layers // S  # layers per stage (the local block shard)

        def loss_and_grads_1f1b(params, x, y):
            """1F1B: forwards and backwards explicitly interleaved on one
            tick timeline (schedule_1f1b), instead of a forward scan that
            autodiff transposes afterwards (GPipe).

            Same span — 2(M+S−1) ticks vs GPipe's (M+S−1) forward plus an
            equally long transposed backward — but the saved state is an
            S-slot ring of per-layer block INPUTS (backward recomputes
            each block before transposing it, remat-style), so peak
            activation memory is O(S·K) block inputs instead of autodiff
            GPipe's O((M+S−1)·K) per-tick internals.
            """
            tabs = schedule_1f1b(M, S)
            t_op = jnp.asarray(tabs["op"])
            t_mb = jnp.asarray(tabs["mb"])
            t_aa = jnp.asarray(tabs["arr_act"])
            t_ac = jnp.asarray(tabs["arr_ct"])
            s = lax.axis_index("pp")
            rest, blocks = params["rest"], params["blocks"]
            b, t_len = x.shape
            mb = b // M
            # tokens stay int32 (M, mb, t); each fwd/bwd unit embeds its
            # own microbatch, so no O(M) f32 activation buffer exists
            x_mb = x.reshape(M, mb, t_len)
            y_mb = y.reshape(M, mb, t_len)
            perm_fwd = [(i, (i + 1) % S) for i in range(S)]
            perm_bwd = [((i + 1) % S, i) for i in range(S)]

            def head_loss(rest_in, h_out, y_i):
                """Per-microbatch tail: final norm, tied head, CE — the
                full-batch mean is the mean of per-microbatch means."""
                h2 = _final_norm(h_out, rest_in["lnf_s"], rest_in["lnf_b"])
                logits = (h2 @ rest_in["embed"].T).astype(jnp.float32)
                logp = jax.nn.log_softmax(logits, axis=-1)
                ce = -jnp.take_along_axis(logp, y_i[..., None], -1).mean()
                return ce / M

            R = min(S, M)  # ring slots: the in-flight bound, never M

            def store(buf, idx, val):
                """Predicated ring write: buf[idx % R] = val when
                idx >= 0. Slot reuse is safe by the in-flight cap: the
                producer of item i+R cannot have run before item i's
                consumer finished (schedule_1f1b's capacity rule)."""
                upd = lax.dynamic_update_index_in_dim(
                    buf, val, jnp.remainder(jnp.maximum(idx, 0), R), 0
                )
                return jnp.where(idx >= 0, upd, buf)

            def fetch(buf, idx):
                return lax.dynamic_index_in_dim(
                    buf, jnp.remainder(idx, R), 0, False
                )

            zero_act = jnp.zeros((mb, t_len, d_model), jnp.float32)
            carry0 = {
                "pf": zero_act,  # last fwd output (sent down-pipe)
                "pb": zero_act,  # last bwd input-cotangent (sent up-pipe)
                # boundary rings — O(S) like everything else in the carry
                "act": jnp.zeros((R, mb, t_len, d_model), jnp.float32),
                "ct": jnp.zeros((R, mb, t_len, d_model), jnp.float32),
                # per-layer block inputs + stage output, R in-flight slots
                "ring": jnp.zeros(
                    (R, K + 1, mb, t_len, d_model), jnp.float32
                ),
                "gb": jax.tree.map(jnp.zeros_like, blocks),
                "gr": jax.tree.map(jnp.zeros_like, rest),
                "loss": jnp.float32(0.0),
            }

            def tick(c, tk):
                recv_a = lax.ppermute(c["pf"], "pp", perm_fwd)
                recv_c = lax.ppermute(c["pb"], "pp", perm_bwd)
                c = {
                    **c,
                    "act": store(c["act"], t_aa[tk, s], recv_a),
                    "ct": store(c["ct"], t_ac[tk, s], recv_c),
                }
                i = t_mb[tk, s]

                def fwd(c):
                    # only stage 0 embeds; lax.cond skips the gather on
                    # the other stages (jnp.where would run it anyway)
                    def embed_in(_):
                        x_i = lax.dynamic_index_in_dim(x_mb, i, 0, False)
                        return rest["embed"][x_i] + rest["pos"][:t_len]

                    inp = lax.cond(
                        s == 0, embed_in, lambda _: fetch(c["act"], i), None
                    )

                    def f(cc, p):
                        return blk.apply({"params": p}, cc), cc

                    out, saved = lax.scan(f, inp, blocks)
                    entry = jnp.concatenate([saved, out[None]], 0)
                    ring = lax.dynamic_update_index_in_dim(
                        c["ring"], entry, jnp.remainder(i, R), 0
                    )
                    return {**c, "ring": ring, "pf": out}

                def bwd(c):
                    entry = lax.dynamic_index_in_dim(
                        c["ring"], jnp.remainder(i, R), 0, False
                    )
                    out = entry[K]
                    y_i = lax.dynamic_index_in_dim(y_mb, i, 0, False)
                    last = s == S - 1

                    # the head (final norm + tied vocab matmul + CE) and
                    # its vjp run ONLY on the last stage — lax.cond is
                    # legal here (no collectives inside the branches)
                    def with_head(_):
                        loss_i, head_vjp = jax.vjp(
                            lambda r, o: head_loss(r, o, y_i), rest, out
                        )
                        g_head, ct_last = head_vjp(jnp.float32(1.0))
                        return loss_i, g_head, ct_last

                    def without_head(_):
                        return (
                            jnp.float32(0.0),
                            jax.tree.map(jnp.zeros_like, rest),
                            fetch(c["ct"], i),
                        )

                    loss_i, g_head, ct_out = lax.cond(
                        last, with_head, without_head, None
                    )

                    def bstep(cc, xs):
                        p_j, in_j = xs
                        _, vjp = jax.vjp(
                            lambda p, xx: blk.apply({"params": p}, xx),
                            p_j, in_j,
                        )
                        gp, gx = vjp(cc)
                        return gx, gp

                    # recompute-and-transpose each block, last to first
                    ct_in, g_blocks = lax.scan(
                        bstep, ct_out, (blocks, entry[:K]), reverse=True
                    )
                    # stage 0 closes the loop through its embedding +
                    # position lookup immediately (per microbatch), so
                    # no O(M) cotangent buffer survives the scan
                    x_i = lax.dynamic_index_in_dim(x_mb, i, 0, False)
                    _, evjp = jax.vjp(
                        lambda r: r["embed"][x_i] + r["pos"][:t_len], rest
                    )
                    (g_emb,) = evjp(jnp.where(s == 0, ct_in, 0.0))
                    return {
                        **c,
                        "gb": jax.tree.map(
                            lambda a, g: a + g, c["gb"], g_blocks
                        ),
                        "gr": jax.tree.map(
                            lambda a, gh, ge: a + gh + ge,
                            c["gr"], g_head, g_emb,
                        ),
                        "pb": ct_in,
                        "loss": c["loss"] + loss_i,
                    }

                return lax.switch(
                    t_op[tk, s], [lambda c: c, fwd, bwd], c
                ), None

            c = lax.scan(tick, carry0, jnp.arange(tabs["ticks"]))[0]
            return c["loss"], {"blocks": c["gb"], "rest": c["gr"]}

        if schedule == "1f1b":
            loss_and_grads = loss_and_grads_1f1b
        else:
            def loss_and_grads(params, x, y):
                return jax.value_and_grad(loss_fn)(params, x, y)

        def train_step(state, x, y):
            params, mom = state["params"], state["momentum"]
            loss, grads = loss_and_grads(params, x, y)
            # the head stage owns the loss; psum makes it world-visible
            loss = lax.psum(loss, "pp")
            # single-owner replicated grads -> identical everywhere
            grads["rest"] = lax.psum(grads["rest"], "pp")
            grads = lax.pmean(grads, dp_axis)
            loss = lax.pmean(loss, dp_axis)
            mom = jax.tree.map(
                lambda m, g: momentum * m + g, mom, grads
            )
            params = jax.tree.map(
                lambda p, m: p - lr * m, params, mom
            )
            return (
                {"params": params, "momentum": mom,
                 "step": state["step"] + 1},
                {"loss": loss},
            )

        state_spec = {"params": spec, "momentum": spec, "step": P()}
        self._step = jax.jit(
            jax.shard_map(
                train_step,
                mesh=mesh,
                in_specs=(state_spec, P(dp_axis), P(dp_axis)),
                out_specs=(state_spec, P()),
                check_vma=False,
            )
        )
        self._dp_axis = dp_axis

        def eval_step(params, x, y):
            """Global (correct-token count, CE sum): the pipelined
            forward's logits exist only on the last stage — other
            stages' zeros are masked OUT of the counts, then psum
            makes the result world-visible."""
            s = lax.axis_index("pp")
            logits = forward(params, x).astype(jnp.float32)
            correct = jnp.sum(jnp.argmax(logits, -1) == y)
            logp = jax.nn.log_softmax(logits, axis=-1)
            ce_sum = -jnp.take_along_axis(logp, y[..., None], -1).sum()
            correct = jnp.where(s == S - 1, correct, 0)
            ce_sum = jnp.where(s == S - 1, ce_sum, 0.0)
            correct = lax.psum(lax.psum(correct, "pp"), dp_axis)
            ce_sum = lax.psum(lax.psum(ce_sum, "pp"), dp_axis)
            return correct, ce_sum

        self._eval = jax.jit(
            jax.shard_map(
                eval_step,
                mesh=mesh,
                in_specs=(spec, P(dp_axis), P(dp_axis)),
                out_specs=(P(), P()),
                check_vma=False,
            )
        )

        # unpipelined per-sample loss on the same params — the bench's
        # analytic FLOP counter traces this (host-side, never compiled)
        def _flat_loss(params, x, y):
            logits = reference_apply(params, x, num_heads).astype(
                jnp.float32
            )
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.take_along_axis(logp, y[..., None], -1).mean()

        self.loss_fn = _flat_loss

    @property
    def ticks(self) -> int:
        """Pipeline-timeline span of one step, in schedule ticks.

        GPipe: the forward scan is ``M+S−1`` ticks and autodiff appends a
        transposed backward of the same length. 1F1B: one unified
        timeline of ``2(M+S−1)`` ticks carrying both directions — equal
        bubble, O(S) instead of O(M) saved microbatch activations.
        """
        if self.schedule == "1f1b":
            return int(schedule_1f1b(self.n_micro, self.pp)["ticks"])
        return self.n_micro + self.pp - 1

    def init_state(self, rng, sample_x=None) -> dict:
        """``sample_x`` is accepted (and ignored — shapes come from the
        constructor) so every trainer shares one init_state signature."""
        params = init_params(
            rng, self.vocab_size, self.num_layers, self.d_model,
            self.d_ff, self.seq_len, num_heads=self.num_heads,
        )
        state = {
            "params": params,
            "momentum": jax.tree.map(jnp.zeros_like, params),
            "step": jnp.zeros((), jnp.int32),
        }
        mesh = self.topo.mesh

        def group_shardings(tree):
            return {
                "blocks": jax.tree.map(
                    lambda _: NamedSharding(mesh, P("pp")), tree["blocks"]
                ),
                "rest": jax.tree.map(
                    lambda _: NamedSharding(mesh, P()), tree["rest"]
                ),
            }

        shardings = {
            "params": group_shardings(params),
            "momentum": group_shardings(params),
            "step": NamedSharding(mesh, P()),
        }
        return jax.device_put(state, shardings)

    def data_sharding(self) -> NamedSharding:
        """(B, T) token batches shard over dp; every pp rank sees the
        full sequence of its dp shard."""
        return NamedSharding(self.topo.mesh, P(self._dp_axis))

    def _check(self, x):
        b = len(x)
        if b % self.dp or (b // self.dp) % self.n_micro:
            raise ValueError(
                f"global batch {b} must split into dp={self.dp} shards of "
                f"a multiple of n_micro={self.n_micro}"
            )
        if x.shape[1] > self.seq_len:
            raise ValueError(
                f"sequence of {x.shape[1]} exceeds the position "
                f"table (seq_len={self.seq_len})"
            )

    def step(self, state, x_global, y_global):
        """One pipelined step on a global (B, T) batch."""
        self._check(x_global)
        state, metrics = self._step(
            state, jnp.asarray(x_global), jnp.asarray(y_global)
        )
        bound_cpu_dispatch(self.topo, metrics)
        return state, metrics

    def fit(
        self,
        batches,
        state,
        epochs: int = 1,
        log_every: int = 0,
        start_epoch: int = 0,
        skip_steps: int = 0,
        on_step=None,
        prefetch: int = 2,
    ):
        """Epoch loop — the shared :func:`common.synced_fit_loop` with
        the dp-only batch sharding."""
        from mpit_tpu.parallel.common import synced_fit_loop

        return synced_fit_loop(
            self.topo, self._step, batches, state,
            sharding=self.data_sharding(),
            check=self._check,
            log_tag=f"pp-{self.schedule}",
            epochs=epochs, log_every=log_every, start_epoch=start_epoch,
            skip_steps=skip_steps, on_step=on_step, prefetch=prefetch,
        )

    def evaluate(self, state, x, y, batch: int = 512):
        """Token-level accuracy and mean loss over a (N, T) eval set."""
        from mpit_tpu.parallel.common import batched_count_eval

        if x.shape[1] > self.seq_len:
            raise ValueError(
                f"sequence of {x.shape[1]} exceeds the position "
                f"table (seq_len={self.seq_len})"
            )
        correct, loss_sum, n = batched_count_eval(
            self._eval, state["params"], x, y, batch,
            self.dp * self.n_micro,
        )
        tokens = n * x.shape[1]
        return correct / tokens, loss_sum / tokens
