"""ZeRO-1 data parallelism: optimizer state sharded 1/W per device.

The memory pillar plain sync DP lacks: ``DataParallelTrainer`` replicates
optimizer state on every device, so Adam costs 2× params per chip no
matter how many chips there are. Here the flat parameter vector is cut
into W contiguous chunks and each device owns ONE chunk's optimizer
state (Rajbhandari et al., ZeRO stage 1 — arXiv:1910.02054):

- forward/backward run exactly as in sync DP (params replicated);
- the gradient average and sharding happen in one ``lax.psum_scatter``
  per step (half of the bandwidth-optimal allreduce, so the step moves
  no more bytes than plain DP's ``pmean``). Under gradient accumulation
  the scatter moves inside the fold — one per slice, same aggregate
  bytes, accum× the collective count — so the PERSISTENT gradient
  state is a 1/W chunk instead of a full param-sized pytree (the
  ZeRO-2 composition; each slice's backward still transiently builds
  one param-sized gradient);
- the optimizer updates only the local chunk (state leaves live sharded
  ``P(axis)`` — 1/W of Adam's mu/nu per device);
- ``lax.all_gather`` reassembles the updated flat vector (the other
  half of the allreduce) and the pytree is re-ravelled.

For ELEMENTWISE optimizers the chunked update equals the full-vector
update exactly — pinned against plain sync DP in tests — and the same
behavioral probe that protects the MoE trainer
(:func:`common.assert_elementwise_optimizer`) rejects cross-leaf
transforms here, where a per-chunk global-norm would silently differ
per device. Flat buffers reuse ``utils/params.flatten_params``
(≡ the reference's ``getParameters()`` view, SURVEY.md §2 comp. 4).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from mpit_tpu import quant as _quant
from mpit_tpu.comm.collectives import quantized_psum_scatter
from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu.comm.topology import Topology
from mpit_tpu.parallel import common
from mpit_tpu.parallel.sync import dp_quant_from_env
from mpit_tpu.utils.params import flatten_params


class ZeroDataParallelTrainer:
    """Sync allreduce DP with ZeRO-1 sharded optimizer state.

    Usage (identical surface to :class:`DataParallelTrainer`)::

        topo = mpit_tpu.init()
        trainer = ZeroDataParallelTrainer(model, optax.adam(1e-3), topo)
        state = trainer.init_state(jax.random.key(0), sample_batch_x)
        state, metrics = trainer.step(state, x_global, y_global)

    ``state.opt_state`` leaves of parameter size live sharded over the
    worker axis; everything else matches plain sync DP.
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        topo: Optional[Topology] = None,
        loss_fn: Optional[Callable] = None,
        donate_state: bool = True,
        accum_steps: int = 1,
        clip_norm: Optional[float] = None,
        quant: Optional[str] = None,
    ):
        """``accum_steps``: gradient accumulation, composable with the
        state sharding — both memory knobs together (activations / accum,
        optimizer state / W). ``clip_norm``: global-norm gradient
        clipping done mesh-correctly on the gradient chunks
        (:func:`common.clip_by_global_norm_in_mesh` — the psum over
        chunk sum-of-squares IS the full-vector norm, so this equals
        ``optax.clip_by_global_norm`` on unsharded sync DP exactly; the
        chain form itself is rejected by the elementwise probe below).
        ``quant`` (default: the ``MPIT_DP_QUANT`` knob): run the
        gradient reduce-scatter through
        :func:`comm.collectives.quantized_psum_scatter` — 1- or 2-byte
        codes on the wire, f32 accumulate. STATELESS (no error feedback
        — the persistent state here is deliberately 1/W-sized, and a
        full-width residual would undo that); the rounding is one
        bounded step per scatter, and the dynamics plane is the
        convergence guardrail (docs/WIRE.md)."""
        self.model = model
        self.optimizer = optimizer
        common.assert_elementwise_optimizer(
            optimizer, "ZeroDataParallelTrainer"
        )
        self.clip_norm = common.check_clip_norm(clip_norm)
        self.quant = dp_quant_from_env() if quant is None else quant
        if self.quant not in _quant.QUANT_MODES:
            raise ValueError(
                f"quant={self.quant!r}: expected one of {_quant.QUANT_MODES}"
            )
        self.topo = topo if topo is not None else _current_topology()
        self.loss_fn = (
            loss_fn
            if loss_fn is not None
            else common.default_loss_fn(model.apply)
        )
        self.accum_steps = accum = common.check_accum_steps(accum_steps)
        axis = self.topo.worker_axis
        mesh = self.topo.mesh
        w = self.topo.num_workers
        self._axis, self._mesh, self._w = axis, mesh, w
        self._donate = donate_state
        self._step = None  # built in init_state (needs the flat size)
        self._eval = common.build_count_loss_eval(model, self.topo)

    def _opt_spec(self, opt_state, padded: int):
        """P(axis) for flat parameter-sized leaves, replicated rest."""
        return jax.tree.map(
            lambda a: P(self._axis)
            if getattr(a, "shape", ()) == (padded,)
            else P(),
            opt_state,
        )

    def _build(self, params_template):
        axis, w = self._axis, self._w
        flat0, spec = flatten_params(params_template)
        n = flat0.size
        padded = -(-n // w) * w
        chunk = padded // w

        # optimizer state is born SHARDED: structure from eval_shape,
        # then a jit with out_shardings computes each leaf directly into
        # its 1/W placement — the full mu/nu never exist on one device
        # (materializing them first would OOM exactly the models ZeRO
        # exists for)
        abstract = jax.eval_shape(
            self.optimizer.init,
            jax.ShapeDtypeStruct((padded,), flat0.dtype),
        )
        opt_spec = self._opt_spec(abstract, padded)
        opt_shardings = jax.tree.map(
            lambda s: NamedSharding(self._mesh, s), opt_spec,
            is_leaf=lambda v: isinstance(v, P),
        )
        opt_state0 = jax.jit(
            lambda: self.optimizer.init(
                jnp.zeros((padded,), flat0.dtype)
            ),
            out_shardings=opt_shardings,
        )()
        state_spec = common.TrainState(
            params=jax.tree.map(lambda _: P(), params_template),
            opt_state=opt_spec,
            step=P(),
        )

        accum = self.accum_steps
        quant_mode = self.quant

        def _scatter(flat_g):
            # mode "off" IS lax.psum_scatter(tiled=True) — the raw path
            # byte-identical to the pre-quant trainer
            return quantized_psum_scatter(
                flat_g, axis_name=axis, mode=quant_mode
            ) / w

        def scattered_grad(params, x, y):
            """Mean-gradient CHUNK for this device.

            accum=1: one grad, one psum_scatter — half of the
            bandwidth-optimal allreduce, no extra bytes vs pmean.
            accum>1: the scatter moves INSIDE the accumulation fold
            (ZeRO-2 composed with accumulation): each slice's gradient
            is reduced-scattered immediately and only the (chunk,)
            accumulator persists across slices — the persistent gradient
            state shrinks from a full param-sized pytree to 1/W of one
            (each slice's backward still materializes one transient
            param-sized gradient), at the cost of one collective per
            slice instead of one per step. Mean of scattered slices ==
            scattered full-batch mean, exactly.
            """
            vg = jax.value_and_grad(self.loss_fn)
            if accum == 1:
                loss, grads = vg(params, x, y)
                flat_g, _ = flatten_params(grads)
                flat_g = jnp.pad(flat_g, (0, padded - n))
                return loss, _scatter(flat_g)
            xs = x.reshape(accum, x.shape[0] // accum, *x.shape[1:])
            ys = y.reshape(accum, y.shape[0] // accum, *y.shape[1:])

            def fold(carry, xy):
                loss_acc, shard_acc = carry
                l, g = vg(params, *xy)
                flat_g, _ = flatten_params(g)
                flat_g = jnp.pad(flat_g, (0, padded - n))
                gs = _scatter(flat_g)
                return (loss_acc + l, shard_acc + gs), None

            (loss, shard), _ = lax.scan(
                fold,
                (jnp.float32(0.0), jnp.zeros((chunk,), flat0.dtype)),
                (xs, ys),
            )
            return loss / accum, shard / accum

        clip_norm = self.clip_norm

        def train_step(state: common.TrainState, x, y):
            loss, g_shard = scattered_grad(state.params, x, y)
            if clip_norm is not None:
                # every device holds a disjoint chunk of the ONE flat
                # mean gradient (padding is zeros), so psum of chunk
                # sums-of-squares is exactly the full-vector norm
                g_shard, _ = common.clip_by_global_norm_in_mesh(
                    g_shard, clip_norm, axis
                )
            flat_p, _ = flatten_params(state.params)
            flat_p = jnp.pad(flat_p, (0, padded - n))
            rank = lax.axis_index(axis)
            p_shard = lax.dynamic_slice(flat_p, (rank * chunk,), (chunk,))
            updates, opt_state = self.optimizer.update(
                g_shard, state.opt_state, p_shard
            )
            new_shard = optax.apply_updates(p_shard, updates)
            # the other half of the allreduce: reassemble the params
            flat_new = lax.all_gather(new_shard, axis, tiled=True)
            params = spec.unravel(flat_new[:n])
            return (
                common.TrainState(
                    params=params, opt_state=opt_state,
                    step=state.step + 1,
                ),
                {"loss": lax.pmean(loss, axis)},
            )

        self._step = jax.jit(
            jax.shard_map(
                train_step,
                mesh=self._mesh,
                in_specs=(state_spec, P(axis), P(axis)),
                out_specs=(state_spec, P()),
                check_vma=False,
            ),
            donate_argnums=(0,) if self._donate else (),
        )
        return opt_state0, opt_spec

    def init_state(self, rng, sample_x) -> common.TrainState:
        """Replicated params; optimizer state born in its 1/W shards
        (never whole on any device — see :meth:`_build`)."""
        variables = self.model.init(rng, jnp.asarray(sample_x))
        params = variables["params"]
        opt_state0, _ = self._build(params)
        replicated = NamedSharding(self._mesh, P())
        return common.TrainState(
            params=jax.device_put(
                params, jax.tree.map(lambda _: replicated, params)
            ),
            opt_state=opt_state0,  # already placed by _build
            step=jax.device_put(jnp.zeros((), jnp.int32), replicated),
        )

    def step(self, state, x_global, y_global):
        """One ZeRO-1 step on a global batch (divisible by W; per-worker
        shard divisible by accum_steps)."""
        common.check_accum_batch(
            len(x_global), self._w, self.accum_steps
        )
        if self._step is None:
            _ = self._build(state.params)
        state, metrics = self._step(state, x_global, y_global)
        common.bound_cpu_dispatch(self.topo, metrics)
        return state, metrics

    def fit(
        self,
        batches,
        state,
        epochs: int = 1,
        log_every: int = 0,
        start_epoch: int = 0,
        skip_steps: int = 0,
        on_step=None,
        prefetch: int = 2,
    ):
        """Epoch loop — the shared :func:`common.synced_fit_loop`."""
        if self._step is None:
            _ = self._build(state.params)
        w, accum = self._w, self.accum_steps
        return common.synced_fit_loop(
            self.topo, self._step, batches, state,
            sharding=self.topo.worker_sharding(),
            check=lambda x: common.check_accum_batch(len(x), w, accum),
            log_tag="zero-dp",
            epochs=epochs, log_every=log_every, start_epoch=start_epoch,
            skip_steps=skip_steps, on_step=on_step, prefetch=prefetch,
        )

    def evaluate(self, state, x, y, batch: int = 1024):
        """Full-dataset eval; returns (accuracy, mean_loss)."""
        correct, loss_sum, n = common.batched_count_eval(
            self._eval, state.params, x, y, batch, self._w
        )
        return correct / n, loss_sum / n
