"""Composed parallelism: one 3-D ``(dp, tp, sp)`` mesh, one step.

The parallelism axes stop being silos here: a single jit-compiled train
step runs data parallelism (batch over ``dp``), Megatron tensor
parallelism (the GSPMD shardings of ``parallel/tensor.py`` over ``tp``),
and exact ring-attention sequence parallelism (``ops/ring_attention.py``
over ``sp``) on the SAME :class:`~mpit_tpu.models.transformer.TransformerLM`.

Design — partial-manual shard_map (the jax 0.9 ``axis_names`` mode):

- the loss/grad region is manual over ``sp`` ONLY: the model runs with
  ``seq_axis="sp"``, so its attention rotates K/V blocks around the sp
  ring with ``lax.ppermute`` and positions are computed from the ring
  rank — exactly the 2-D seq trainer's inner function;
- ``dp`` and ``tp`` stay AUTO inside that same region: the partitioner
  sees batch sharded over dp and weights sharded per the strict Megatron
  rules (:func:`~mpit_tpu.parallel.tensor.tp_state_specs`) and inserts
  the dp batch-mean and tp head/FFN collectives itself — no hand-written
  dp/tp communication anywhere in this file;
- gradients/loss are ``pmean``-ed over ``sp`` manually (the grad-locally
  -then-reduce pattern every shard_map trainer here uses), and the
  optimizer update runs OUTSIDE the manual region under plain GSPMD jit,
  so cross-leaf transforms (global-norm clipping) stay safe exactly as
  in the 2-D tp trainer.

The math is mesh-factorization-invariant: any (dp, tp, sp) split of the
same device count produces the same losses and updated params on the
same global batch (tests/test_composed.py pins this against the 2-D
trainers' trajectories too).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu.comm.topology import Topology
from mpit_tpu.parallel import common
from mpit_tpu.parallel.tensor import check_tp_divisibility, tp_state_specs


class ComposedParallelTrainer:
    """dp × tp × sp training for :class:`TransformerLM`.

    Usage::

        topo = mpit_tpu.init(
            axis_names=("dp", "tp", "sp"), mesh_shape=(2, 2, 2))
        model = TransformerLM(vocab_size=V, seq_axis="sp")
        trainer = ComposedParallelTrainer(model, optax.adam(3e-4), topo)
        state = trainer.init_state(jax.random.key(0), x[:2, :T_local])
        state, metrics = trainer.step(state, x_global, y_global)

    Requires mesh axes named exactly ``("dp", "tp", "sp")``, a model with
    ``seq_axis="sp"``, global batch divisible by dp, sequence length
    divisible by sp, and the tp divisibility rules of the 2-D trainer.
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        topo: Optional[Topology] = None,
        loss_fn: Optional[Callable] = None,
        donate_state: bool = True,
    ):
        self.model = model
        self.optimizer = optimizer
        self.topo = topo if topo is not None else _current_topology()
        mesh = self.topo.mesh
        if tuple(mesh.axis_names) != ("dp", "tp", "sp"):
            raise ValueError(
                "ComposedParallelTrainer needs a mesh with axes "
                "('dp', 'tp', 'sp'), e.g. mpit_tpu.init(axis_names="
                "('dp','tp','sp'), mesh_shape=(D, T, S)); got "
                f"{mesh.axis_names}"
            )
        if getattr(model, "seq_axis", None) != "sp":
            raise ValueError(
                "the composed trainer shards the sequence: construct the "
                "model with seq_axis='sp' "
                f"(got {getattr(model, 'seq_axis', None)!r})"
            )
        if getattr(model, "moe_experts", 0):
            raise ValueError(
                "MoE models are not composed here; use MoEParallelTrainer"
            )
        check_tp_divisibility(model, int(mesh.shape["tp"]))
        self.loss_fn = (
            loss_fn
            if loss_fn is not None
            else common.default_loss_fn(model.apply)
        )

        # manual over sp only: in_specs name sp placements; dp/tp ride
        # the arguments' own (auto) shardings through the region
        grads_fn = jax.shard_map(
            self._local_loss_grads,
            mesh=mesh,
            in_specs=(P(), P(None, "sp"), P(None, "sp")),
            out_specs=(P(), P()),
            axis_names=frozenset({"sp"}),
            check_vma=False,
        )

        def train_step(state: common.TrainState, x, y):
            loss, grads = grads_fn(state.params, x, y)
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            return (
                common.TrainState(
                    params=params, opt_state=opt_state, step=state.step + 1
                ),
                {"loss": loss},
            )

        self._step = jax.jit(
            train_step, donate_argnums=(0,) if donate_state else ()
        )

        def eval_step(params, x, y):
            logits = self.model.apply({"params": params}, x)
            correct = jnp.sum(jnp.argmax(logits, -1) == y)
            loss_sum = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).sum()
            return (
                jax.lax.psum(correct, "sp"),
                jax.lax.psum(loss_sum, "sp"),
            )

        self._eval = jax.jit(
            jax.shard_map(
                eval_step,
                mesh=mesh,
                in_specs=(P(), P(None, "sp"), P(None, "sp")),
                out_specs=(P(), P()),
                axis_names=frozenset({"sp"}),
                check_vma=False,
            )
        )

    def _local_loss_grads(self, params, x, y):
        """Inside the manual-sp region: grad the LOCAL sequence-shard
        loss, reduce over sp afterwards (differentiating through a psum
        scales cotangents by the axis size — the repo-wide pattern)."""
        loss, grads = jax.value_and_grad(self.loss_fn)(params, x, y)
        return (
            jax.lax.pmean(loss, "sp"),
            jax.lax.pmean(grads, "sp"),
        )

    @property
    def dp_size(self) -> int:
        return int(self.topo.mesh.shape["dp"])

    @property
    def tp_size(self) -> int:
        return int(self.topo.mesh.shape["tp"])

    @property
    def sp_size(self) -> int:
        return int(self.topo.mesh.shape["sp"])

    def state_sharding(self, state):
        """Megatron tp shardings (strict), replicated over dp and sp."""
        mesh = self.topo.mesh
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), tp_state_specs(state),
            is_leaf=lambda v: isinstance(v, P),
        )

    def data_sharding(self) -> NamedSharding:
        """(B, T) token batches: batch over dp, sequence over sp."""
        return NamedSharding(self.topo.mesh, P("dp", "sp"))

    def _check(self, x):
        b, t = x.shape[:2]
        if b % self.dp_size or t % self.sp_size:
            raise ValueError(
                f"global batch {b}x{t} not divisible by mesh "
                f"(dp={self.dp_size}, sp={self.sp_size})"
            )

    def init_state(self, rng, sample_x) -> common.TrainState:
        """``sample_x``: a LOCAL-shaped (b, T/sp) token block. Init runs
        on the dense clone (seq_axis=None — shapes are identical), then
        every leaf commits to its tp sharding once."""
        dense = self.model.clone(seq_axis=None)
        variables = dense.init(rng, jnp.asarray(sample_x))
        state = common.TrainState.create(variables["params"], self.optimizer)
        return jax.device_put(state, self.state_sharding(state))

    def step(self, state, x_global, y_global):
        """One composed step on a global (B, T) batch."""
        self._check(x_global)
        sharding = self.data_sharding()
        # device_put straight from host to the sharded layout (asarray
        # first would commit to one device, then reshard device-to-device)
        x = jax.device_put(x_global, sharding)
        y = jax.device_put(y_global, sharding)
        state, metrics = self._step(state, x, y)
        common.bound_cpu_dispatch(self.topo, metrics)
        return state, metrics

    def evaluate(self, state, x, y, batch: int = 512):
        """Token-level accuracy and mean loss over a (N, T) eval set."""
        if x.shape[1] % self.sp_size:
            raise ValueError(
                f"sequence length {x.shape[1]} not divisible by "
                f"sp={self.sp_size}"
            )
        correct, loss_sum, n = common.batched_count_eval(
            self._eval, state.params, x, y, batch, self.dp_size
        )
        tokens = n * x.shape[1]
        return correct / tokens, loss_sum / tokens
