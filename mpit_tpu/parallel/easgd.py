"""EASGD / EAMSGD trainer, collective formulation.

Reference parity: goptim.easgd + pclient/pserver push-pull (SURVEY.md §2
comps. 3-5, §3(b)-(c)). The reference ran one *server process* holding the
center variable and clients that exchanged with it every τ steps over tagged
MPI messages. On TPU that protocol is re-expressed as a symmetric collective
round (SURVEY.md §5, backend item (i)): every worker keeps its own params,
the center is replicated state, and every τ local steps one fused psum
implements the server's entire recv-dispatch loop. The asynchrony the MPI
version got from message interleaving is preserved where it matters
mathematically — clients explore independently between rounds — while the
exchange itself rides ICI inside one jit step (no host, no per-message
round trips). For protocol-level asynchrony (stale pulls), see the
host-async mode in ``mpit_tpu.parallel.pserver``.

Layout: per-worker state is stored with a leading worker axis W sharded over
the mesh ("stacked" layout); inside shard_map each worker sees its slice.
A round step consumes (W, τ, B, ...) batches and runs τ local steps under
``lax.scan`` — so a whole communication period is ONE XLA computation.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu import goptim
from mpit_tpu.comm.topology import Topology
from mpit_tpu.parallel import common


@flax.struct.dataclass
class EASGDState:
    """worker_params/worker_opt have leading worker axis (sharded over dp);
    center is replicated."""

    worker_params: Any
    worker_opt: Any
    center: Any
    round: jax.Array  # replicated scalar: completed exchange rounds


def _stack(tree: Any, w: int) -> Any:
    return jax.tree.map(lambda a: jnp.broadcast_to(a[None], (w, *a.shape)), tree)


def _take0(tree: Any) -> Any:
    return jax.tree.map(lambda a: a[0], tree)


def _put0(tree: Any) -> Any:
    return jax.tree.map(lambda a: a[None], tree)


class EASGDTrainer(common.RoundTrainer):
    """Elastic-averaging SGD over the worker mesh axis.

    Args:
      model: flax module (or None when a custom ``loss_fn`` over raw params
        is supplied together with ``init_params`` — used by the math tests).
      optimizer: the *local* optimizer (EAMSGD = pass momentum here).
      alpha: elastic coupling strength. The paper's stability bound for the
        symmetric round is 0 < α < 1/W for the center move; default follows
        the paper's β/W rule.
      tau: communication period (local steps per exchange round).
      exchange_dtype: compress the exchange collective to this dtype (e.g.
        ``jnp.bfloat16`` halves the bytes the psum moves over ICI/DCN; see
        ``goptim.summed_client_diffs``). None = exact full-precision.
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        topo: Optional[Topology] = None,
        loss_fn: Optional[Callable] = None,
        alpha: Optional[float] = None,
        tau: int = 4,
        donate_state: bool = True,
        use_pallas: bool = False,
        exchange_dtype: Any = None,
    ):
        self.model = model
        self.optimizer = optimizer
        self.use_pallas = bool(use_pallas)
        self.exchange_dtype = exchange_dtype
        self.topo = topo if topo is not None else _current_topology()
        self.tau = int(tau)
        w = self.topo.num_workers
        # β = 0.9 rule from the EASGD paper: α = β / W keeps the center move
        # a convex combination.
        self.alpha = float(alpha) if alpha is not None else 0.9 / w
        self.loss_fn = (
            loss_fn
            if loss_fn is not None
            else common.default_loss_fn(model.apply)
        )
        axis = self.topo.worker_axis
        mesh = self.topo.mesh

        def round_step(state: EASGDState, x, y):
            # per-shard: worker_* enter with leading dim 1
            params = _take0(state.worker_params)
            opt = _take0(state.worker_opt)

            def local_step(carry, batch):
                p, o = carry
                bx, by = batch
                loss, g = jax.value_and_grad(self.loss_fn)(p, bx, by)
                updates, o = self.optimizer.update(g, o, p)
                p = optax.apply_updates(p, updates)
                return (p, o), loss

            (params, opt), losses = jax.lax.scan(
                local_step, (params, opt), (x[0], y[0])
            )
            params, center = goptim.easgd_round(
                params, state.center, self.alpha, axis,
                use_pallas=self.use_pallas,
                compress_dtype=self.exchange_dtype,
            )
            return (
                EASGDState(
                    worker_params=_put0(params),
                    worker_opt=_put0(opt),
                    center=center,
                    round=state.round + 1,
                ),
                {"loss": jnp.mean(jax.lax.pmean(losses, axis))},
            )

        state_specs = EASGDState(
            worker_params=P(axis),
            worker_opt=P(axis),
            center=P(),
            round=P(),
        )
        self._round = jax.jit(
            jax.shard_map(
                round_step,
                mesh=mesh,
                in_specs=(state_specs, P(axis), P(axis)),
                out_specs=(state_specs, P()),
                check_vma=False,
            ),
            donate_argnums=(0,) if donate_state else (),
        )

        self._eval = common.build_center_eval(model, self.topo)
        self._log_tag = "easgd"

    # -- state ------------------------------------------------------------

    def init_state(self, rng, sample_x=None, params: Any = None) -> EASGDState:
        """All workers and the center start from identical params (the
        reference broadcast the initial model the same way, via rank-0
        construction + bcast)."""
        if params is None:
            params = self.model.init(rng, jnp.asarray(sample_x))["params"]
        w = self.topo.num_workers
        state = EASGDState(
            worker_params=_stack(params, w),
            worker_opt=_stack(self.optimizer.init(params), w),
            center=params,
            round=jnp.zeros((), jnp.int32),
        )
        shardings = EASGDState(
            worker_params=jax.tree.map(
                lambda _: self.topo.worker_sharding(), state.worker_params
            ),
            worker_opt=jax.tree.map(
                lambda _: self.topo.worker_sharding(), state.worker_opt
            ),
            center=jax.tree.map(
                lambda _: self.topo.replicated_sharding(), state.center
            ),
            round=self.topo.replicated_sharding(),
        )
        return jax.device_put(state, shardings)

    def center_params(self, state: EASGDState):
        return state.center
