"""Trainers: the TPU-native equivalents of the reference's asyncsgd layer.

- :mod:`mpit_tpu.parallel.sync`     — synchronous allreduce data parallelism
  (SURVEY.md §2 comp. 7, call stack §3(d)).
- :mod:`mpit_tpu.parallel.easgd`    — EASGD/EAMSGD in collective formulation
  (SURVEY.md §2 comp. 5, §5 backend mapping item (i)).
- :mod:`mpit_tpu.parallel.downpour` — Downpour grad-push/param-pull with
  emulated staleness (same mapping).
- :mod:`mpit_tpu.parallel.pserver` / ``pclient`` — host-async
  parameter-server fidelity mode (SURVEY.md §2 comps. 3-4, §5 item (ii)).
- :mod:`mpit_tpu.parallel.seq`      — sequence-parallel training over a 2-D
  (batch × sequence) mesh with ring attention (beyond-parity extension).
- :mod:`mpit_tpu.parallel.tensor`   — GSPMD Megatron tensor parallelism
  (dp × tp; strict sharding rules).
- :mod:`mpit_tpu.parallel.pipeline` — pipeline parallelism (dp × pp;
  GPipe and 1F1B schedules, shared transformer Block).
- :mod:`mpit_tpu.parallel.moe`      — expert-parallel MoE training
  (top-k GShard routing, balance/z losses, all_to_all dispatch).
- :mod:`mpit_tpu.parallel.composed` — one dp × tp × sp step (partial-
  manual shard_map: manual ring-attention sp, GSPMD dp/tp).
"""

from mpit_tpu.parallel.common import TrainState, cross_entropy_loss  # noqa: F401
from mpit_tpu.parallel.sync import DataParallelTrainer  # noqa: F401
from mpit_tpu.parallel.easgd import EASGDTrainer, EASGDState  # noqa: F401
from mpit_tpu.parallel.downpour import DownpourTrainer, DownpourState  # noqa: F401
from mpit_tpu.parallel.pserver import PServer  # noqa: F401
from mpit_tpu.parallel.pclient import PClient  # noqa: F401
from mpit_tpu.parallel.ps_trainer import AsyncPSTrainer  # noqa: F401
from mpit_tpu.parallel.seq import SeqParallelTrainer  # noqa: F401
from mpit_tpu.parallel.tensor import TensorParallelTrainer  # noqa: F401
from mpit_tpu.parallel.pipeline import PipelineParallelTrainer  # noqa: F401
from mpit_tpu.parallel.moe import MoEParallelTrainer  # noqa: F401
from mpit_tpu.parallel.composed import ComposedParallelTrainer  # noqa: F401
from mpit_tpu.parallel.zero import ZeroDataParallelTrainer  # noqa: F401
