"""Downpour-SGD trainer (grad push / param pull, model-averaging flavor).

Reference parity: goptim's ``gdownpour`` (SURVEY.md §2 comp. 5,
BASELINE.json:9 "Downpour-SGD model-averaging"). In the reference, workers
pushed gradients (or params) to parameter servers and pulled fresh params
every τ steps, tolerating staleness from message interleaving. Collective
re-expression (SURVEY.md §5 item (i)): the push is one psum/pmean of the
workers' accumulated updates into the replicated center (the server's apply),
the pull replaces worker params with the center. Protocol staleness is
emulated *exactly and reproducibly* with a center-history ring: workers pull
the center from ``staleness`` rounds ago, which bounds the gradient age the
way a real async PS does on average — and unlike the MPI version, the
staleness is controlled, so its effect on convergence is testable
(SURVEY.md §5 "race detection": property tests replace nondeterminism).

True host-async Downpour (unbounded staleness, per-message ordering) lives in
the host-async PS mode (``mpit_tpu.parallel.pserver`` / ``ps_trainer``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu import goptim
from mpit_tpu.comm.topology import Topology
from mpit_tpu.parallel import common
from mpit_tpu.parallel.easgd import _put0, _stack, _take0


@flax.struct.dataclass
class DownpourState:
    worker_params: Any  # leading worker axis, sharded
    worker_opt: Any  # leading worker axis, sharded
    center: Any  # replicated
    server_opt: Any  # replicated server-side optimizer state
    center_history: Any  # leading axis (staleness+1), replicated; [0] = oldest
    round: jax.Array


class DownpourTrainer(common.RoundTrainer):
    """Downpour: τ local steps, push accumulated grads, pull (stale) center.

    Args:
      optimizer: local worker optimizer.
      server_optimizer: applied to the pushed (averaged) gradient sum at the
        center; defaults to plain SGD with lr=1.0 on the accumulated local
        *updates* — i.e. model averaging, the BASELINE.json:9 flavor.
      tau: push/pull period.
      staleness: rounds of center age workers see on pull (0 = fresh).
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        topo: Optional[Topology] = None,
        loss_fn: Optional[Callable] = None,
        server_optimizer: Optional[optax.GradientTransformation] = None,
        tau: int = 4,
        staleness: int = 0,
        donate_state: bool = True,
    ):
        self.model = model
        self.optimizer = optimizer
        self.topo = topo if topo is not None else _current_topology()
        self.tau = int(tau)
        self.staleness = int(staleness)
        if self.staleness < 0:
            raise ValueError("staleness must be >= 0")
        self.server_optimizer = server_optimizer
        self.loss_fn = (
            loss_fn
            if loss_fn is not None
            else common.default_loss_fn(model.apply)
        )
        axis = self.topo.worker_axis
        mesh = self.topo.mesh

        def round_step(state: DownpourState, x, y):
            params = _take0(state.worker_params)
            opt = _take0(state.worker_opt)
            start = params

            def local_step(carry, batch):
                p, o = carry
                bx, by = batch
                loss, g = jax.value_and_grad(self.loss_fn)(p, bx, by)
                updates, o = self.optimizer.update(g, o, p)
                p = optax.apply_updates(p, updates)
                return (p, o), loss

            (params, opt), losses = jax.lax.scan(
                local_step, (params, opt), (x[0], y[0])
            )
            # push: accumulated local update = params - start
            delta = jax.tree.map(lambda a, b: a - b, params, start)
            if self.server_optimizer is None:
                # model averaging: center += mean_i(delta_i)
                center = goptim.downpour_push(
                    state.center, delta, axis, average=True
                )
                server_opt = state.server_opt
            else:
                # classic: server optimizer consumes -mean(delta) as a grad
                mean_delta = jax.lax.pmean(delta, axis)
                pseudo_grad = jax.tree.map(lambda d: -d, mean_delta)
                updates, server_opt = self.server_optimizer.update(
                    pseudo_grad, state.server_opt, state.center
                )
                center = optax.apply_updates(state.center, updates)

            # staleness ring: append new center, pull the oldest
            history = jax.tree.map(
                lambda h, c: jnp.concatenate([h[1:], c[None]], axis=0),
                state.center_history,
                center,
            )
            pulled = jax.tree.map(lambda h: h[0], history)
            params = goptim.downpour_pull(center, pulled)
            return (
                DownpourState(
                    worker_params=_put0(params),
                    worker_opt=_put0(opt),
                    center=center,
                    server_opt=server_opt,
                    center_history=history,
                    round=state.round + 1,
                ),
                {"loss": jnp.mean(jax.lax.pmean(losses, axis))},
            )

        state_specs = DownpourState(
            worker_params=P(axis),
            worker_opt=P(axis),
            center=P(),
            server_opt=P(),
            center_history=P(),
            round=P(),
        )
        self._round = jax.jit(
            jax.shard_map(
                round_step,
                mesh=mesh,
                in_specs=(state_specs, P(axis), P(axis)),
                out_specs=(state_specs, P()),
                check_vma=False,
            ),
            donate_argnums=(0,) if donate_state else (),
        )

        self._eval = common.build_center_eval(model, self.topo)
        self._log_tag = "downpour"

    def init_state(self, rng, sample_x=None, params: Any = None) -> DownpourState:
        if params is None:
            params = self.model.init(rng, jnp.asarray(sample_x))["params"]
        w = self.topo.num_workers
        server_opt = (
            self.server_optimizer.init(params)
            if self.server_optimizer is not None
            else ()
        )
        history = jax.tree.map(
            lambda a: jnp.broadcast_to(
                a[None], (self.staleness + 1, *a.shape)
            ),
            params,
        )
        state = DownpourState(
            worker_params=_stack(params, w),
            worker_opt=_stack(self.optimizer.init(params), w),
            center=params,
            server_opt=server_opt,
            center_history=history,
            round=jnp.zeros((), jnp.int32),
        )
        rep = self.topo.replicated_sharding()
        shardings = DownpourState(
            worker_params=jax.tree.map(
                lambda _: self.topo.worker_sharding(), state.worker_params
            ),
            worker_opt=jax.tree.map(
                lambda _: self.topo.worker_sharding(), state.worker_opt
            ),
            center=jax.tree.map(lambda _: rep, state.center),
            server_opt=jax.tree.map(lambda _: rep, state.server_opt),
            center_history=jax.tree.map(lambda _: rep, state.center_history),
            round=rep,
        )
        return jax.device_put(state, shardings)

    def center_params(self, state: DownpourState):
        return state.center
