"""Host-async parameter-server trainer: genuine protocol asynchrony.

This is fidelity mode (SURVEY.md §5 backend mapping, item (ii)): the
collective EASGD/Downpour trainers are the fast path (everything fused under
jit over ICI), while this trainer preserves the reference's *runtime
structure* — concurrent pserver/pclient actors exchanging tagged messages
with real interleaving and unbounded staleness (BASELINE.json:7's
"2 pclient + 1 pserver" shape). Clients run their τ local steps as
jit-compiled XLA programs (one compiled function shared by all client
threads — same shapes, one compile; the GIL is released inside XLA so
clients genuinely overlap), and only flat numpy vectors cross the transport.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from mpit_tpu.data.datasets import shard_for_worker
from mpit_tpu.obs.core import (
    ObsConfig,
    arm_faulthandler,
    disarm_faulthandler,
    write_fault_log,
)
from mpit_tpu.obs.core import config_from_env as obs_config_from_env
from mpit_tpu.obs.telemetry import wrap_obs_transports
from mpit_tpu.parallel import common, ps_roles
from mpit_tpu.parallel.pclient import PClient
from mpit_tpu.parallel.pserver import PServer, partition_bounds, spawn_server_thread
from mpit_tpu.transport import Broker
from mpit_tpu.transport.chaos import (
    ChaosConfig,
    FaultLog,
    config_from_env,
    wrap_transports,
)
from mpit_tpu.utils.params import flatten_params, unflatten_params


def _chaos_counts(fault_log: FaultLog, rank: int) -> Callable[[], dict]:
    """Live-snapshot collector: this rank's injected-fault counts by kind
    (faults are attributed to the rank whose send the injector hit)."""

    def counts() -> dict:
        out: dict = {}
        for e in fault_log.events():
            if e.src == rank:
                out[e.kind] = out.get(e.kind, 0) + 1
        return out

    return counts


class AsyncPSTrainer:
    """2-pclient+1-pserver-style async training (counts configurable).

    Transport ranks: ``[0, num_servers)`` are pservers, the rest pclients.

    Args:
      algo: "easgd" (push params, elastic moves on both sides) or
        "downpour" (push accumulated delta, pull-replace).
      alpha: elastic coupling (both server- and client-side move).
      tau: local steps between exchanges.
      transport: "native" (C++ broker, ``mpit_tpu.native``), "inproc"
        (pure-Python broker), "socket" (real TCP loopback: every actor gets
        its own :class:`SocketTransport` on an ephemeral port — actors are
        still threads, but every message crosses a genuine socket with the
        framed wire codec, so the serialize/transfer/deserialize phase
        split and exact byte counters are real; the bench's wire-format
        A/B mode), or "auto" (native when buildable — it is the
        reference-parity message plane, SURVEY.md §2 comp. 1). Tradeoff:
        inproc passes payload *references* (zero copies, fastest per-message
        for huge payloads), native moves real bytes (~memcpy bandwidth) but
        blocks receivers fully off the GIL; end-to-end MNIST PS training
        with 4 clients measured ~17% faster on native. For very large flat
        vectors (ResNet-50-scale) prefer "inproc".
      ckpt_dir: elastic recovery (SURVEY.md §5 do-better over the
        reference's lose-everything semantics): each server persists its
        center chunk to ``ckpt_dir/center_<rank>.npy`` every
        ``ckpt_every`` updates and at teardown; with ``resume`` (the
        default) a fresh ``train()`` whose servers find matching chunks
        restores the center — a killed-and-restarted job continues from
        the last persisted center instead of re-initializing. ``resume=
        False`` deletes stale chunks first (a deliberate fresh start).
        Client rejoin needs no persistence: a replacement client on a
        dead client's rank fetches the live center and its first message
        revives it at the server watchdog (tests/test_failure.py).
      chaos: fault-injection schedule (docs/ROBUSTNESS.md). When set —
        or when any ``MPIT_CHAOS_*`` env knob is — every transport is
        wrapped in a :class:`ChaosTransport` sharing one fault log
        (``stats["chaos_faults"]``); the run must then survive on the
        retry/dedup/degradation machinery below.
      obs: observability config (docs/OBSERVABILITY.md). When set — or
        when any ``MPIT_OBS_*`` env knob is — every transport is wrapped
        in a :class:`~mpit_tpu.obs.telemetry.TelemetryTransport`
        OUTERMOST (over chaos, so telemetry stream indices stay in
        lockstep with the fault schedule's): per-(peer, tag) wire
        counters land in ``stats["telemetry"]``, per-rank journals under
        ``obs.dir`` feed ``python -m mpit_tpu.obs merge``, and when
        chaos is also active the fault log is persisted next to them as
        ``faults.jsonl`` for the timeline overlay. Unset, no wrapper
        exists at all — the measured-zero-overhead contract.
      max_exchange_failures: graceful degradation — a client's failed
        exchange (after PClient's own retries) skips the round on the
        stale center; this many CONSECUTIVE failures escalate to an
        error. ``None`` = fail on the first exchange error.
      fetch_timeout / fetch_retries: forwarded to each PClient — the
        per-attempt PARAM wait and the retry budget for FETCH/PARAM
        and push sends. Chaos tests drop these to sub-second values so
        injected losses resolve quickly.
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        num_clients: int = 2,
        num_servers: int = 1,
        algo: str = "easgd",
        alpha: float = 0.5,
        tau: int = 4,
        server_lr: float = 1.0,
        loss_fn: Optional[Callable] = None,
        transport: str = "auto",
        client_timeout: Optional[float] = None,
        ckpt_dir: Optional[str] = None,
        ckpt_every: Optional[int] = 100,
        resume: bool = True,
        chaos: Optional[ChaosConfig] = None,
        obs: Optional[ObsConfig] = None,
        max_exchange_failures: Optional[int] = 3,
        fetch_timeout: float = 60.0,
        fetch_retries: int = 3,
        ps_shards: Optional[int] = None,
    ):
        if algo not in ("easgd", "downpour"):
            raise ValueError(f"unknown algo {algo!r}")
        if transport not in ("auto", "native", "inproc", "socket"):
            raise ValueError(f"unknown transport {transport!r}")
        self.transport_kind = transport
        # failure detection (SURVEY.md §5 do-better): silence beyond this →
        # the client is declared dead instead of hanging the job forever
        if client_timeout is not None and client_timeout <= 0:
            raise ValueError(
                "client_timeout must be positive (use None to disable)"
            )
        self.client_timeout = client_timeout
        if num_clients < 1 or num_servers < 1:
            raise ValueError("need at least one client and one server")
        self.model = model
        self.optimizer = optimizer
        self.num_clients = num_clients
        self.num_servers = num_servers
        self.algo = algo
        self.alpha = float(alpha)
        self.tau = int(tau)
        self.server_lr = float(server_lr)
        self.loss_fn = (
            loss_fn if loss_fn is not None else common.default_loss_fn(model.apply)
        )
        if ckpt_every is not None and ckpt_every < 1:
            raise ValueError(
                "ckpt_every must be >= 1 (None = persist only at teardown)"
            )
        self.ckpt_dir = ckpt_dir
        self.ckpt_every = None if ckpt_every is None else int(ckpt_every)
        self.resume = bool(resume)
        if max_exchange_failures is not None and max_exchange_failures < 1:
            raise ValueError(
                "max_exchange_failures must be >= 1 (None = fail fast)"
            )
        if fetch_timeout <= 0:
            raise ValueError("fetch_timeout must be positive")
        if fetch_retries < 0:
            raise ValueError("fetch_retries must be >= 0")
        self.chaos = chaos
        self.obs = obs
        # sharded ownership (docs/ROBUSTNESS.md "Shard ownership &
        # resharding"): split the flat vector into this many shards placed
        # on servers by a consistent-hash ring, so clients can reassign a
        # dead server's shards to the survivors mid-run (live resharding)
        # instead of degrading every round that touches its range. None
        # (the default) keeps the legacy one-contiguous-chunk-per-server
        # layout. Env opt-in MPIT_PS_SHARDS serves launcher-driven runs.
        if ps_shards is None:
            import os

            env_shards = int(os.environ.get("MPIT_PS_SHARDS", "0"))
            ps_shards = env_shards if env_shards > 0 else None
        if ps_shards is not None and ps_shards < 1:
            raise ValueError("ps_shards must be >= 1 (None = legacy layout)")
        self.ps_shards = ps_shards
        self.max_exchange_failures = max_exchange_failures
        self.fetch_timeout = float(fetch_timeout)
        self.fetch_retries = int(fetch_retries)
        self.fault_log: Optional[FaultLog] = None
        # one compiled local step shared by all client threads (same shapes,
        # one compile; XLA releases the GIL so clients genuinely overlap)
        self._local_step = ps_roles.make_local_step(
            model, optimizer, self.loss_fn
        )

    def _make_broker(self, size: int):
        if self.transport_kind in ("auto", "native"):
            import mpit_tpu.native as native

            if native.is_available():
                return native.NativeBroker(size)
            if self.transport_kind == "native":
                # surface WHY it is unavailable (explicit request must never
                # silently substitute the Python broker)
                native.ensure_built()
                return native.NativeBroker(size)
        return Broker(size)

    def _make_transports(self, size: int) -> list:
        if self.transport_kind != "socket":
            return self._make_broker(size).transports()
        # real-TCP loopback world: reserve one ephemeral port per rank
        # (bind 0, read, release), then hand every rank the full address
        # table. The release→bind window is racy in principle; in practice
        # the kernel avoids handing a just-released ephemeral port straight
        # back out, and a lost race fails loudly at bind.
        import socket as _socket

        from mpit_tpu.transport.socket_transport import SocketTransport

        probes = []
        addrs: list[tuple[str, int]] = []
        for _ in range(size):
            s = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
            s.bind(("127.0.0.1", 0))
            addrs.append(("127.0.0.1", s.getsockname()[1]))
            probes.append(s)
        for s in probes:
            s.close()
        return [
            SocketTransport(r, size, addresses=addrs) for r in range(size)
        ]

    def train(
        self,
        x: np.ndarray,
        y: np.ndarray,
        steps: int,
        batch_size: int = 64,
        init_rng=None,
        seed: int = 0,
    ):
        """Run the async job; returns (center_params, stats).

        Each client trains on its own contiguous data shard (per-rank split,
        as the reference sharded MNIST by worker id) for ``steps`` local
        steps, exchanging with the servers every ``tau`` steps.
        """
        init_rng = init_rng if init_rng is not None else jax.random.key(seed)
        params0 = self.model.init(init_rng, jnp.asarray(x[:2]))["params"]
        flat0, spec = flatten_params(params0)
        flat0 = np.asarray(flat0, np.float32)

        raw_transports = self._make_transports(
            self.num_servers + self.num_clients
        )
        transports = raw_transports
        # fault injection: explicit config wins, env knobs activate it for
        # launcher-driven runs (MPIT_CHAOS_*; see launch.py's diagnostic)
        chaos_cfg = self.chaos if self.chaos is not None else config_from_env()
        self.fault_log = None
        if chaos_cfg is not None:
            transports, self.fault_log = wrap_transports(transports, chaos_cfg)
        # observability wraps OUTERMOST over chaos: counters see every
        # attempted send (faults included), latency includes injected
        # delay, and the per-(dst, tag) stream index stays in lockstep
        # with the chaos schedule's — the merger's fault-placement key
        obs_cfg = self.obs if self.obs is not None else obs_config_from_env()
        obs_transports: list = []
        if obs_cfg is not None:
            # hung-job forensics (MPIT_OBS_FAULTHANDLER): periodic all-thread
            # stack dumps while the job runs, cancelled at clean teardown
            arm_faulthandler(obs_cfg, "trainer")
            transports = wrap_obs_transports(transports, obs_cfg)
            obs_transports = transports
            if obs_cfg.live and self.fault_log is not None:
                # per-rank chaos fault counts ride the live snapshots: a
                # pull collector sampled at export time (the FaultLog is
                # already thread-safe; no hot-path cost)
                for t in obs_transports:
                    t.obs_registry.add_collector(
                        "chaos", _chaos_counts(self.fault_log, t.rank)
                    )
        server_ranks = list(range(self.num_servers))
        client_ranks = list(
            range(self.num_servers, self.num_servers + self.num_clients)
        )
        bounds = partition_bounds(flat0.size, self.num_servers)
        shard_map = None
        if self.ps_shards is not None:
            from mpit_tpu.comm.topology import HashRing, ShardMap

            # ring placement: every actor derives the same shard→server
            # assignment from the member list alone (blake2b, not Python
            # hash()), so no coordinator hands out the layout
            shard_map = ShardMap(
                HashRing(server_ranks), flat0.size, self.ps_shards
            )

        ckpt_paths = [None] * self.num_servers
        if self.ckpt_dir is not None:
            import os

            os.makedirs(self.ckpt_dir, exist_ok=True)
            ckpt_paths = [
                os.path.join(self.ckpt_dir, f"center_{r}.npy")
                for r in server_ranks
            ]
            if not self.resume:  # deliberate fresh start: drop stale chunks
                for p in ckpt_paths:
                    if os.path.exists(p):
                        os.remove(p)
        def _server_center(r: int, start: int, end: int) -> np.ndarray:
            if shard_map is None:
                return flat0[start:end]
            # sharded: this server's center is the ascending concat of the
            # shards the ring assigns it (possibly non-contiguous in the
            # flat vector, possibly empty when servers outnumber shards)
            pieces = [flat0[s:e] for _, s, e in shard_map.ranges_for(r)]
            if not pieces:
                return np.zeros(0, np.float32)
            return np.concatenate(pieces)

        servers = [
            PServer(
                transports[r],
                _server_center(r, start, end),
                num_clients=self.num_clients,
                alpha=self.alpha,
                server_lr=self.server_lr,
                client_ranks=client_ranks,
                client_timeout=self.client_timeout,
                ckpt_path=path,
                ckpt_every=self.ckpt_every,
                shard_map=shard_map,
            )
            for r, (start, end), path in zip(server_ranks, bounds, ckpt_paths)
        ]
        server_threads = [spawn_server_thread(s) for s in servers]

        losses = [[] for _ in range(self.num_clients)]
        errors: list[BaseException] = []
        clients: list = [None] * self.num_clients
        exchange_stats: list[dict] = [{} for _ in range(self.num_clients)]

        def client_main(c: int):
            client = None
            try:
                tp = transports[self.num_servers + c]
                hb = (
                    self.client_timeout / 3
                    if self.client_timeout is not None
                    else None
                )
                client = PClient(
                    tp, server_ranks, flat0.size, heartbeat_interval=hb,
                    timeout=self.fetch_timeout,
                    max_retries=self.fetch_retries,
                    shard_map=shard_map,
                )
                clients[c] = client
                xs = shard_for_worker(x, c, self.num_clients)
                ys = shard_for_worker(y, c, self.num_clients)
                losses[c] = ps_roles.client_train_loop(
                    client, self._local_step, self.optimizer, spec,
                    xs, ys, steps, batch_size, self.tau, self.algo,
                    self.alpha, seed=seed + 1000 + c,
                    max_exchange_failures=self.max_exchange_failures,
                    exchange_stats=exchange_stats[c],
                )
                client.stop()
            except BaseException as e:  # surface thread failures to caller
                errors.append(e)
                try:
                    if client is not None:
                        # stops the heartbeat thread AND detaches — a leaked
                        # heartbeat would flood the brokers forever
                        client.stop()
                    else:
                        PClient(
                            transports[self.num_servers + c],
                            server_ranks,
                            flat0.size,
                        ).stop()
                except Exception:
                    pass

        client_threads = [
            threading.Thread(target=client_main, args=(c,), daemon=True)
            for c in range(self.num_clients)
        ]
        def teardown_transports():
            # socket mode owns real OS resources (listeners, connections,
            # sender threads) — close them; broker modes die with the run
            if self.transport_kind == "socket":
                for t in raw_transports:
                    try:
                        t.close()
                    except OSError:
                        pass

        for t in client_threads:
            t.start()
        for t in client_threads:
            t.join()
        for t in server_threads:
            t.join(timeout=30)
        server_errors = [s.error for s in servers if s.error is not None]
        if server_errors:
            teardown_transports()
            raise RuntimeError("pserver died during training") from server_errors[0]
        if errors:
            teardown_transports()
            raise errors[0]

        if shard_map is None:
            center_flat = np.concatenate([s.snapshot() for s in servers])
        else:
            # place each server's owned shards back by the STATIC layout
            # (ownership may have moved mid-run; seed values back any shard
            # nobody ended up holding)
            center_flat = np.array(flat0, copy=True)
            for s in servers:
                snap = s.snapshot()
                off = 0
                for _sid, start, end in s.owned_ranges():
                    n = end - start
                    center_flat[start:end] = snap[off:off + n]
                    off += n
        center_params = unflatten_params(spec, jnp.asarray(center_flat))
        stats = {
            "server_counts": [dict(s.counts) for s in servers],
            # True iff every server restored a persisted center chunk —
            # the elastic-recovery signal a resumed job asserts on
            "center_restored": all(s.restored for s in servers),
            # reported as client INDICES (0..num_clients), consistent with
            # "losses" and data sharding — not raw transport ranks
            "dead_clients": sorted(
                r - self.num_servers
                for r in set().union(*(s.dead_clients for s in servers))
            ),
            "mean_final_loss": float(
                np.mean([l[-1] for l in losses if l]) if any(losses) else np.nan
            ),
            "losses": losses,
            # robustness accounting (docs/ROBUSTNESS.md): per-client push
            # sends that reached the transport (== what servers should
            # have applied under dedup), rounds degraded, stale PARAM
            # replies the attempt-id check discarded
            "push_sent": [
                dict(c.push_sent) if c is not None else {} for c in clients
            ],
            "stale_params_dropped": [
                c.stale_params_dropped if c is not None else 0
                for c in clients
            ],
            "skipped_rounds": [
                s.get("skipped_rounds", 0) for s in exchange_stats
            ],
            # sharded repair accounting: per-client count of shards the
            # client re-routed to surviving owners after a server death
            # (0s in legacy mode; see docs/ROBUSTNESS.md)
            "ps_shards": self.ps_shards,
            "repaired_chunks": [
                s.get("repaired_chunks", 0) for s in exchange_stats
            ],
            "exchange_failures": [
                s.get("exchange_failures", 0) for s in exchange_stats
            ],
            # dynamics plane (docs/OBSERVABILITY.md "dynamics"): per-server
            # center version reached, and per-source push-staleness tallies
            # (center updates applied between a client's fetch basis and
            # its push landing) — the in-memory twin of the journal's
            # push_stale records
            "server_versions": [s.version for s in servers],
            "staleness_by_src": [
                {src: dict(st) for src, st in sorted(
                    s.staleness_by_src.items())}
                for s in servers
            ],
        }
        if self.fault_log is not None:
            stats["chaos_faults"] = self.fault_log.counts()
        if obs_transports:
            stats["telemetry"] = [t.summary() for t in obs_transports]
            if obs_cfg.dir is not None and self.fault_log is not None:
                import os

                write_fault_log(
                    self.fault_log.events(),
                    os.path.join(obs_cfg.dir, "faults.jsonl"),
                )
            for t in obs_transports:
                # flush/close journals now — the broker dies with this
                # call, and a merge may run immediately after train()
                t.obs_tracer.close()
                # stop live exporters too (final snapshot hits disk)
                t.close_live()
            if obs_cfg.faulthandler > 0:
                disarm_faulthandler()
        # exact socket-level byte totals (socket mode only): ground truth
        # next to the telemetry summaries' per-(peer,tag) byte counters
        if self.transport_kind == "socket":
            stats["wire_bytes"] = [
                t.wire_byte_counts() for t in raw_transports
            ]
        teardown_transports()
        return center_params, stats

    def evaluate(self, params, x, y, batch: int = 512) -> float:
        apply = jax.jit(lambda p, xb: self.model.apply({"params": p}, xb))
        correct = 0
        n = (len(x) // batch) * batch or len(x)
        for i in range(0, n, batch):
            logits = apply(params, x[i : i + batch])
            correct += int(np.sum(np.argmax(logits, -1) == y[i : i + batch]))
        return correct / n
