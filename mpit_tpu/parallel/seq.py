"""Sequence-parallel training: 2-D (batch × sequence) mesh.

Beyond-parity extension (the reference is data-parallel only — SURVEY.md §2
parallelism ledger): long sequences shard onto their own mesh axis, so a
context that does not fit one device's attention still trains exactly.

Design (the scaling-book recipe): a 2-D ``Mesh(("dp", "sp"))``; tokens
``(B, T)`` shard batch→dp and sequence→sp; params/optimizer state stay
replicated. Inside one jit-compiled shard_map step:

- the model runs with ``seq_axis="sp"`` — its attention is exact ring
  attention (K/V blocks rotate over the sp axis via ``lax.ppermute``,
  ``mpit_tpu.ops.ring_attention``), everything else is position-local;
- the loss is the global per-token mean: local mean + ``pmean`` over BOTH
  axes (equal shard sizes make that exact);
- gradients ``pmean`` over both axes — the one collective pair of the
  step, fused by XLA into the compiled program.

The math is mesh-shape-invariant: (dp=8, sp=1), (dp=2, sp=4) and
(dp=1, sp=8) produce the same losses and the same updated parameters on
the same global batch (tests/test_seq_parallel.py pins this).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu.comm.topology import Topology
from mpit_tpu.parallel import common


class SeqParallelTrainer:
    """Sync trainer over a 2-D (batch_axis, seq_axis) mesh for LMs whose
    model understands ``seq_axis`` (``TransformerLM(seq_axis="sp")``).

    Usage::

        topo = mpit_tpu.init(axis_names=("dp", "sp"), mesh_shape=(2, 4))
        model = TransformerLM(vocab_size=V, seq_axis="sp")
        trainer = SeqParallelTrainer(model, optax.adam(3e-4), topo)
        state = trainer.init_state(jax.random.key(0), x[:per_dp, :])
        state, metrics = trainer.step(state, x_global, y_global)

    ``x_global`` is ``(B, T)`` with ``B`` divisible by the dp extent and
    ``T`` by the sp extent; shards are contiguous blocks (ring order on the
    sequence).
    """

    def __init__(
        self,
        model,
        optimizer: optax.GradientTransformation,
        topo: Optional[Topology] = None,
        loss_fn: Optional[Callable] = None,
        donate_state: bool = True,
    ):
        self.model = model
        self.optimizer = optimizer
        self.topo = topo if topo is not None else _current_topology()
        mesh = self.topo.mesh
        if len(mesh.axis_names) < 2:
            raise ValueError(
                "SeqParallelTrainer needs a 2-D mesh, e.g. "
                "mpit_tpu.init(axis_names=('dp','sp'), mesh_shape=(B, S)); "
                f"got axes {mesh.axis_names}"
            )
        self.batch_axis, self.seq_axis = mesh.axis_names[:2]
        model_axis = getattr(model, "seq_axis", None)
        if model_axis != self.seq_axis:
            raise ValueError(
                f"model.seq_axis={model_axis!r} must name the mesh's "
                f"sequence axis {self.seq_axis!r} (construct the model "
                f"with seq_axis={self.seq_axis!r})"
            )
        # the canonical CE-mean loss works per-token unchanged: logits
        # (b, t, V) vs integer targets (b, t)
        self.loss_fn = (
            loss_fn
            if loss_fn is not None
            else common.default_loss_fn(model.apply)
        )
        axes = (self.batch_axis, self.seq_axis)
        data_spec = P(self.batch_axis, self.seq_axis)

        def train_step(state: common.TrainState, x, y):
            loss, grads = jax.value_and_grad(self.loss_fn)(state.params, x, y)
            grads = jax.lax.pmean(grads, axes)
            loss = jax.lax.pmean(loss, axes)
            updates, opt_state = self.optimizer.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            return (
                common.TrainState(
                    params=params, opt_state=opt_state, step=state.step + 1
                ),
                {"loss": loss},
            )

        self._step = jax.jit(
            jax.shard_map(
                train_step,
                mesh=mesh,
                in_specs=(P(), data_spec, data_spec),
                out_specs=(P(), P()),
                check_vma=False,
            ),
            donate_argnums=(0,) if donate_state else (),
        )

        def eval_step(params, x, y):
            logits = self.model.apply({"params": params}, x)
            correct = jnp.sum(jnp.argmax(logits, -1) == y)
            loss_sum = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).sum()
            return (
                jax.lax.psum(correct, axes),
                jax.lax.psum(loss_sum, axes),
            )

        self._eval = jax.jit(
            jax.shard_map(
                eval_step,
                mesh=mesh,
                in_specs=(P(), data_spec, data_spec),
                out_specs=(P(), P()),
                check_vma=False,
            )
        )

    @property
    def dp_size(self) -> int:
        return int(self.topo.mesh.shape[self.batch_axis])

    @property
    def sp_size(self) -> int:
        return int(self.topo.mesh.shape[self.seq_axis])

    def data_sharding(self) -> NamedSharding:
        """Sharding for global (B, T) token arrays on the 2-D mesh."""
        return NamedSharding(
            self.topo.mesh, P(self.batch_axis, self.seq_axis)
        )

    def _check(self, x):
        b, t = x.shape[:2]
        if b % self.dp_size or t % self.sp_size:
            raise ValueError(
                f"global batch {b}x{t} not divisible by mesh "
                f"(dp={self.dp_size}, sp={self.sp_size})"
            )

    def init_state(self, rng, sample_x) -> common.TrainState:
        """``sample_x``: a LOCAL-shaped (b, T/sp) token block (shapes only).

        Init runs the model OUTSIDE shard_map, so positions/attention see a
        single block — parameter shapes are identical either way.
        """
        dense = self.model
        if getattr(dense, "seq_axis", None) is not None:
            dense = dense.clone(seq_axis=None)
        variables = dense.init(rng, jnp.asarray(sample_x))
        state = common.TrainState.create(variables["params"], self.optimizer)
        return jax.device_put(state, self.topo.replicated_sharding())

    def step(self, state, x_global, y_global):
        """One step on a global (B, T) batch of tokens + shifted targets."""
        self._check(x_global)
        state, metrics = self._step(state, x_global, y_global)
        common.bound_cpu_dispatch(self.topo, metrics)
        return state, metrics

    def fit(
        self,
        batches,
        state,
        epochs: int = 1,
        log_every: int = 0,
        start_epoch: int = 0,
        skip_steps: int = 0,
        on_step=None,
        prefetch: int = 2,
    ):
        """Epoch loop over (tokens, targets) :class:`~mpit_tpu.data.Batches`
        — the shared :func:`common.synced_fit_loop`, staged with the 2-D
        (dp, sp) sharding so no per-step redistribute sneaks in."""
        return common.synced_fit_loop(
            self.topo, self._step, batches, state,
            sharding=self.data_sharding(),
            check=self._check,
            log_tag="seq-sync",
            epochs=epochs, log_every=log_every, start_epoch=start_epoch,
            skip_steps=skip_steps, on_step=on_step, prefetch=prefetch,
        )

    def evaluate(self, state, x, y, batch: int = 512):
        """Token-level accuracy and mean loss over a (N, T) eval set."""
        # only T must divide sp here — batched_count_eval builds
        # dp-divisible batches itself (the eval SET length owes the mesh
        # nothing; caught by driving the PTB preset's 31-window eval set)
        if x.shape[1] % self.sp_size:
            raise ValueError(
                f"sequence length {x.shape[1]} not divisible by "
                f"sp={self.sp_size}"
            )
        correct, loss_sum, n = common.batched_count_eval(
            self._eval, state.params, x, y, batch, self.dp_size
        )
        tokens = n * x.shape[1]
        return correct / tokens, loss_sum / tokens
