"""Compatibility shims for the range of jax releases this package runs on.

The codebase targets the modern public API (``jax.shard_map`` with
``check_vma``). On older jaxlibs (< 0.5) that API lives at
``jax.experimental.shard_map.shard_map`` and spells the replication check
``check_rep``. Installing the alias here — imported from the package
``__init__`` before any trainer module loads — keeps every call site on the
one modern spelling instead of scattering try/except at 15 import sites.
"""

from __future__ import annotations

import functools

import jax


def _install_shard_map_alias() -> None:
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(f, /, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if "axis_names" in kwargs:
            # modern partial-manual mode names the MANUAL axes; the old
            # API names the complement ("auto" axes of the mesh)
            manual = frozenset(kwargs.pop("axis_names"))
            mesh = kwargs.get("mesh", args[0] if args else None)
            kwargs["auto"] = frozenset(mesh.axis_names) - manual
        return _shard_map(f, *args, **kwargs)

    jax.shard_map = shard_map


def _install_axis_size_alias() -> None:
    if hasattr(jax.lax, "axis_size"):
        return
    from jax._src.core import get_axis_env

    def axis_size(axis_name):
        """Static size of a bound mesh axis (product over a tuple of
        names), as the modern ``jax.lax.axis_size`` returns it."""
        env = get_axis_env()
        names = (
            axis_name
            if isinstance(axis_name, (tuple, list))
            else (axis_name,)
        )
        out = 1
        for name in names:
            out *= env.axis_size(name)
        return out

    jax.lax.axis_size = axis_size


_install_shard_map_alias()
_install_axis_size_alias()
