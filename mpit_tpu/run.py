"""Training driver: one function from :class:`TrainConfig` to results.

This is the framework's equivalent of the reference's example-script layer
(SURVEY.md §2 comp. 6) factored into the library, so every BASELINE workload
config is one preset away and the example CLIs stay thin. The loop wires in
everything the reference lacked (SURVEY.md §5): JSONL metrics, step timing,
profiler traces, checkpoint/resume.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

import numpy as np

from mpit_tpu.utils.config import TrainConfig


def _load_dataset(cfg: TrainConfig):
    """(x_train, y_train, x_test, y_test, meta) for the config's dataset;
    ``meta`` carries dataset facts the model needs (e.g. vocab_size)."""
    from mpit_tpu.data import (
        load_cifar10,
        load_imagenet_like,
        load_mnist,
    )

    if cfg.dataset == "mnist":
        return (*load_mnist(synthetic_train=cfg.train_size), {})
    if cfg.dataset == "cifar10":
        return (*load_cifar10(synthetic_train=cfg.train_size), {})
    if cfg.dataset == "imagenet":
        return (
            *load_imagenet_like(
                synthetic_train=cfg.train_size,
                synthetic_test=max(cfg.train_size // 4, 64),
                image_size=cfg.image_size,
            ),
            {},
        )
    if cfg.dataset == "ptb":
        return _ptb_windows(cfg)
    raise ValueError(f"unknown dataset {cfg.dataset!r}")


def _ptb_windows(cfg: TrainConfig):
    """Token stream → (N, T) next-token windows: x=tokens[i:i+T],
    y=tokens[i+1:i+T+1] (the LM objective over fixed-length unrolls)."""
    from mpit_tpu.data import load_ptb

    t_len = cfg.seq_len
    need = (cfg.train_size + 1) * t_len + 1
    train_toks, valid_toks, vocab = load_ptb(
        synthetic_tokens=max(need + need // 8, 20_000)
    )

    def windows(toks: np.ndarray):
        n = (len(toks) - 1) // t_len
        x = toks[: n * t_len].reshape(n, t_len)
        y = toks[1 : n * t_len + 1].reshape(n, t_len)
        return x.astype(np.int32), y.astype(np.int32)

    x_tr, y_tr = windows(train_toks)
    x_va, y_va = windows(valid_toks)
    return (
        x_tr[: cfg.train_size],
        y_tr[: cfg.train_size],
        x_va,
        y_va,
        {"vocab_size": vocab},
    )


def _build_model(cfg: TrainConfig, meta: dict, worker_axis: str = None):
    from mpit_tpu.comm.topology import WORKER_AXIS
    from mpit_tpu.models import REMAT_MODELS, STEM_MODELS, get_model

    if worker_axis is None:
        worker_axis = WORKER_AXIS

    name = cfg.model.lower()  # the registry lowercases; match it
    algo = cfg.resolved_algo()
    if cfg.remat and name not in REMAT_MODELS:
        import warnings

        warnings.warn(
            f"remat is implemented for {REMAT_MODELS} only; model "
            f"{cfg.model!r} runs without it",
            stacklevel=2,
        )
    if cfg.moe_experts and not (name == "transformer" and algo == "moe-sync"):
        import warnings

        warnings.warn(
            f"moe_experts={cfg.moe_experts} only applies with "
            f"model='transformer' and algo='moe-sync'; model={cfg.model!r} "
            f"algo={cfg.algo!r} runs without experts",
            stacklevel=2,
        )
    if cfg.seq_impl != "ring" and algo != "seq-sync":
        import warnings

        warnings.warn(
            f"seq_impl={cfg.seq_impl!r} only applies with algo='seq-sync' "
            f"(no sequence axis exists under algo={cfg.algo!r}); running "
            "plain dense attention",
            stacklevel=2,
        )
    if name == "transformer":
        return get_model(
            cfg.model,
            vocab_size=meta.get("vocab_size", 10_000),
            num_layers=cfg.layers,
            d_model=cfg.d_model,
            num_heads=cfg.heads,
            d_ff=cfg.d_ff,
            max_len=max(cfg.seq_len, 32),
            # seq-sync applies the model inside shard_map with the sequence
            # sharded on the mesh's "sp" axis (ring attention); moe-sync
            # shards experts over the worker axis
            seq_axis="sp" if algo == "seq-sync" else None,
            seq_impl=cfg.seq_impl,
            remat=cfg.remat,
            attn_impl=cfg.attn_impl,
            **(
                {
                    "moe_experts": cfg.moe_experts,
                    "moe_axis": worker_axis,
                    "moe_capacity_factor": cfg.moe_capacity_factor,
                    "moe_top_k": cfg.moe_top_k,
                    "moe_balance_weight": cfg.moe_balance_weight,
                    "moe_zloss_weight": cfg.moe_zloss_weight,
                }
                if algo == "moe-sync"
                else {}
            ),
        )
    if name in ("lstm", "lstm_lm", "ptb_lstm"):
        return get_model(cfg.model, vocab_size=meta.get("vocab_size", 10_000))
    # capability kwargs derive from the registry lists — the ONE source of
    # which model takes which flag
    kwargs = {}
    if name in STEM_MODELS:
        kwargs["stem"] = cfg.stem
    if name in REMAT_MODELS:
        kwargs["remat"] = cfg.remat
    return get_model(cfg.model, **kwargs)


# the per-step (no τ-round) algos — ONE copy; bench.py imports these so
# its mesh/τ handling can never drift from the driver's
SYNC_ALGOS = ("sync", "zero-sync", "seq-sync", "moe-sync", "pp-sync")


def second_axis_for(cfg: TrainConfig) -> dict:
    """algo -> (second mesh-axis name, configured extent) for the 2-D
    mesh algos; the ONE copy bench.py and _world_for share."""
    return {"seq-sync": ("sp", cfg.sp), "pp-sync": ("pp", cfg.pp)}


def build_optimizer(cfg: TrainConfig, total_updates: int):
    """The config's optax optimizer + schedule (the ONE construction the
    driver, PS path, and bench harness share).

    ``total_updates``: optimizer-update count the cosine decays over —
    for τ-round trainers that is LOCAL steps (the local optimizer updates
    every step), for sync trainers it equals the step count.
    """
    import optax

    total = max(int(total_updates), 2)  # optax needs decay_steps > 0
    if cfg.lr_schedule == "constant":
        lr = cfg.lr
    elif cfg.lr_schedule == "cosine":
        lr = optax.cosine_decay_schedule(cfg.lr, total)
    elif cfg.lr_schedule == "warmup-cosine":
        warm = min(cfg.warmup_steps, total - 1)  # strictly < total
        lr = optax.warmup_cosine_decay_schedule(
            0.0, cfg.lr, warm, total
        )
    else:
        raise ValueError(
            f"unknown lr_schedule {cfg.lr_schedule!r}; have: constant, "
            "cosine, warmup-cosine"
        )
    if cfg.optimizer == "sgd":
        opt = optax.sgd(lr, momentum=cfg.momentum)
    elif cfg.optimizer == "adam":
        opt = optax.adam(lr)
    elif cfg.optimizer == "adamw":
        opt = optax.adamw(lr, weight_decay=cfg.weight_decay)
    else:
        raise ValueError(
            f"unknown optimizer {cfg.optimizer!r}; have: sgd, adam, adamw"
        )
    # --clip-norm: chain the optax transform wherever the update sees
    # consistent gradients (sync/seq/tp: reduced before update;
    # easgd/downpour/ps-*: per-worker local updates, so a per-worker
    # clip IS the async semantics). moe-sync/zero-sync updates run on
    # device-varying gradients — their trainers take clip_norm directly
    # (mesh-correct psum'd norm) and their constructors REJECT this
    # chain, so the driver must not install it there. pp-sync is in the
    # same boat: its trainer receives this optimizer and applies it on
    # stage-sharded block gradients inside shard_map (the probe would
    # reject the chain), so it too takes clip_norm= directly.
    if cfg.clip_norm is not None and cfg.resolved_algo() not in (
        "moe-sync", "zero-sync", "pp-sync"
    ):
        opt = optax.chain(optax.clip_by_global_norm(cfg.clip_norm), opt)
    return opt


def build_trainer(cfg: TrainConfig, model, opt, topo):
    """Collective trainer for ``cfg.algo`` (the single algo→trainer mapping;
    the bench harness reuses it so both measure the exact same construction)."""
    from mpit_tpu.parallel import (
        DataParallelTrainer,
        DownpourTrainer,
        EASGDTrainer,
        SeqParallelTrainer,
    )

    if cfg.exchange_dtype not in ("none", "bf16"):
        raise ValueError(
            f"unknown exchange_dtype {cfg.exchange_dtype!r}; have: none, bf16"
        )
    algo = cfg.resolved_algo()
    if cfg.grad_accum > 1 and algo not in ("sync", "zero-sync"):
        import warnings

        warnings.warn(
            f"grad_accum={cfg.grad_accum} applies to algo='sync' and "
            f"'zero-sync' only; algo={cfg.algo!r} runs without "
            "accumulation",
            stacklevel=2,
        )
    if cfg.exchange_dtype != "none" and algo != "easgd":
        import warnings

        warnings.warn(
            f"exchange_dtype={cfg.exchange_dtype!r} only applies to the "
            f"easgd/eamsgd exchange collective; algo={cfg.algo!r} runs "
            "full-precision (flag ignored)",
            stacklevel=2,
        )
    if algo == "easgd":
        import jax.numpy as jnp

        xdtype = jnp.bfloat16 if cfg.exchange_dtype == "bf16" else None
        return EASGDTrainer(model, opt, topo, alpha=cfg.alpha, tau=cfg.tau,
                            exchange_dtype=xdtype)
    if algo == "downpour":
        return DownpourTrainer(model, opt, topo, tau=cfg.tau,
                               staleness=cfg.staleness)
    if algo == "sync":
        return DataParallelTrainer(model, opt, topo,
                                   accum_steps=cfg.grad_accum)
    if algo == "zero-sync":
        from mpit_tpu.parallel import ZeroDataParallelTrainer

        return ZeroDataParallelTrainer(model, opt, topo,
                                       accum_steps=cfg.grad_accum,
                                       clip_norm=cfg.clip_norm)
    if algo == "seq-sync":
        return SeqParallelTrainer(model, opt, topo)
    if algo == "moe-sync":
        from mpit_tpu.parallel import MoEParallelTrainer

        if not cfg.moe_experts:
            raise ValueError(
                "algo='moe-sync' needs --moe-experts > 0 (and model="
                "transformer)"
            )
        return MoEParallelTrainer(model, opt, topo,
                                  clip_norm=cfg.clip_norm)
    if algo == "pp-sync":
        from mpit_tpu.parallel import PipelineParallelTrainer

        if cfg.model.lower() != "transformer":
            raise ValueError(
                "algo='pp-sync' is transformer-only (the pipeline stages "
                f"a transformer layer stack); got model={cfg.model!r}"
            )
        ignored = [
            f for f, on in (
                ("attn_impl", cfg.attn_impl != "xla"),
                ("remat", cfg.remat),
            ) if on
        ]
        if ignored:
            import warnings

            warnings.warn(
                f"pp-sync builds its own f32 dense-attention pipeline "
                f"model; {ignored} do not apply and are ignored",
                stacklevel=2,
            )
        # the pipeline builds its own stacked-leaf params; shapes come
        # off the flax model so one --model transformer config drives
        # every trainer. It takes the SAME optax optimizer run() builds
        # for everyone (elementwise — probe-enforced) and the
        # mesh-correct clip_norm (the optax chain must NOT be installed
        # for pp-sync; build_optimizer excludes it).
        return PipelineParallelTrainer(
            vocab_size=model.vocab_size,
            num_layers=model.num_layers,
            d_model=model.d_model,
            num_heads=model.num_heads,
            seq_len=model.max_len,
            d_ff=model.d_ff,
            topo=topo,
            n_micro=cfg.n_micro,
            optimizer=opt,
            clip_norm=cfg.clip_norm,
            schedule=cfg.pp_schedule,
            virtual=cfg.pp_virtual,
        )
    raise ValueError(f"unknown algo {cfg.algo!r}")


def _world_for(cfg: TrainConfig):
    """The topology ``cfg`` needs, rebuilding the world when the pinned one
    does not fit (seq-sync wants a 2-D dp×sp mesh with the configured sp
    extent; everything else wants an effectively 1-D worker mesh)."""
    import jax

    import mpit_tpu
    # direct from the submodule: the comm package re-exports topology (the
    # function), shadowing the submodule attribute of the same name
    from mpit_tpu.comm.topology import is_initialized
    from mpit_tpu.comm.topology import topology as current_topology

    algo = cfg.resolved_algo()
    second_axis = second_axis_for(cfg)
    if is_initialized():
        cur = current_topology()
        names = cur.mesh.axis_names
        shape = cur.mesh.devices.shape
        if algo in second_axis:
            ax, extent = second_axis[algo]
            fits = names[:2] == ("dp", ax) and shape[1] == extent
        else:
            fits = all(n == 1 for n in shape[1:])
        if fits:
            return cur
        mpit_tpu.finalize()
    if algo in second_axis:
        ax, extent = second_axis[algo]
        n = len(jax.devices())
        if n % extent:
            raise ValueError(
                f"{ax}={extent} does not divide the {n} available devices"
            )
        return mpit_tpu.init(
            axis_names=("dp", ax), mesh_shape=(n // extent, extent)
        )
    return mpit_tpu.init()


def _check_resume_layout(cfg: TrainConfig) -> None:
    """Refuse a resume whose checkpoint was written under a different
    param LAYOUT. The pipeline stores its layer stack chunk-permuted
    under interleaving, and a different pp extent re-shards the stack —
    shapes match either way, so from_bytes would happily load layers in
    the wrong order and train a silently-wrong model."""
    import json as _json
    import os as _os

    from mpit_tpu.utils import latest_checkpoint

    step = latest_checkpoint(cfg.ckpt_dir)
    if step is None:
        return
    meta_path = _os.path.join(cfg.ckpt_dir, f"ckpt_{step:08d}.json")
    if not _os.path.exists(meta_path):
        return
    saved = _json.loads(
        _json.load(open(meta_path)).get("config", "{}")
    )
    if saved.get("algo") != cfg.algo:
        return  # cross-algo restore fails on structure already
    # EVERY resuming trainer checkpoints an optax opt_state whose pytree
    # STRUCTURE depends on: the optimizer (adam's two moments vs sgd's
    # trace), whether the lr is a SCHEDULE (scale_by_schedule carries a
    # count leaf; a constant lr doesn't), and — where build_optimizer
    # chains it — whether clip_norm is set (the chain's state tuple gains
    # an element). from_bytes reports any of these as an opaque structure
    # error, so catch them here for ALL algos, not just pp-sync. Value-
    # only changes (lr, clip threshold, cosine<->warmup-cosine, momentum:
    # optax.sgd builds a TraceState for any non-None float, 0.0 included)
    # are structure-identical and stay resumable.
    clip_chained = cfg.resolved_algo() not in (
        "moe-sync", "zero-sync", "pp-sync"  # these take clip_norm on the
    )  # trainer, outside opt_state (build_optimizer's chain comment)
    structure_of = lambda opt, sched, clip: {
        "optimizer": opt,
        "lr_is_schedule": sched != "constant",
        **({"clip_chained": clip is not None} if clip_chained else {}),
    }
    cur = structure_of(cfg.optimizer, cfg.lr_schedule, cfg.clip_norm)
    # old metadata-less fields: compare only what the checkpoint recorded
    sav = structure_of(
        saved.get("optimizer", cfg.optimizer),
        saved.get("lr_schedule", cfg.lr_schedule),
        saved.get("clip_norm", cfg.clip_norm),
    )
    if sav != cur:
        diff = {k: (sav[k], cur[k]) for k in cur if sav[k] != cur[k]}
        raise ValueError(
            f"resume layout mismatch: checkpoint in {cfg.ckpt_dir!r} was "
            f"written with a different optimizer-state structure "
            f"{diff} (saved, requested) — restore with the original "
            "optimizer/lr_schedule/clip_norm configuration or start fresh"
        )
    if cfg.algo != "pp-sync":
        return
    # state-LAYOUT generation check: the pipeline state moved from
    # {params, momentum, step} (built-in SGD) to {params, opt_state,
    # step} (optax path). The config looks identical across that code
    # change, so peek at the serialized top-level keys and fail clearly
    # instead of deep inside from_bytes.
    from mpit_tpu.utils.checkpoint import _ckpt_path

    try:
        # stream ONLY the top-level map keys — deserializing the full
        # tree here would double resume I/O and spike host memory just
        # to look at three strings
        import msgpack

        with open(_ckpt_path(cfg.ckpt_dir, step), "rb") as f:
            unp = msgpack.Unpacker(f, raw=False)
            keys = set()
            for _ in range(unp.read_map_header()):
                keys.add(unp.unpack())
                unp.skip()
    except Exception:
        keys = None
    if keys is not None and "momentum" in keys and "opt_state" not in keys:
        raise ValueError(
            f"checkpoint step {step} in {cfg.ckpt_dir} stores the "
            "pre-optax pipeline state layout {params, momentum, step}; "
            "the current pp-sync trainer keeps {params, opt_state, "
            "step}. Restart training (or restore with an old build) — "
            "resuming across this layout change is not supported."
        )
    # only interleaving permutes storage: under gpipe/1f1b the stacked
    # layers are globally ordered, so a different pp extent re-shards
    # soundly on restore and a gpipe<->1f1b flip is layout-identical.
    # layers always matters (it changes the array shapes — fail clearly
    # here, not inside from_bytes). Optimizer structure was checked above
    # for every algo.
    fields = ["layers", "pp_schedule"]
    if "interleaved" in (saved.get("pp_schedule"), cfg.pp_schedule):
        fields += ["pp", "pp_virtual"]
    mismatched = {
        f: (saved.get(f), getattr(cfg, f))
        for f in fields
        if f in saved and saved.get(f) != getattr(cfg, f)
    }
    if set(mismatched) == {"pp_schedule"} and "interleaved" not in (
        saved.get("pp_schedule"), cfg.pp_schedule
    ):
        return
    if mismatched:
        raise ValueError(
            f"resume layout mismatch: checkpoint in {cfg.ckpt_dir!r} was "
            f"written with {mismatched} (saved, requested) — the pipeline "
            "param/opt-state layout depends on these; restore with the "
            "original config or start fresh"
        )


def run(cfg: TrainConfig) -> dict:
    """Train per ``cfg``; returns a results dict (acc, loss, throughput...).

    The driver builds the world itself (idempotent when a fitting topology
    exists; a non-fitting pinned mesh — e.g. a leftover 2-D seq-sync mesh —
    is finalized and rebuilt, see :func:`_world_for`).
    """
    import jax

    import mpit_tpu
    from mpit_tpu.data import Batches
    from mpit_tpu.utils import (
        MetricsLogger,
        force_completion,
        latest_checkpoint,
        restore_checkpoint,
        save_checkpoint,
        trace,
    )

    topo = _world_for(cfg)
    x_tr, y_tr, x_te, y_te, meta = _load_dataset(cfg)
    from mpit_tpu.data import cast_input_dtype

    # train inputs only: eval accumulates in float32 regardless, and the
    # staging win is per-step HBM/transfer traffic, which eval doesn't pay
    x_tr = cast_input_dtype(x_tr, cfg.input_dtype)
    is_seq = cfg.dataset == "ptb"
    model = _build_model(cfg, meta, worker_axis=topo.worker_axis)
    # cosine horizon: PS clients count LOCAL steps; everyone else counts
    # fit-loop units x (τ local updates per unit for the round trainers)
    if cfg.algo.startswith("ps-"):
        total_updates = cfg.steps
    else:
        steps_per_epoch = max(
            len(x_tr) // max(cfg.global_batch, 1), 1
        )
        total_updates = cfg.epochs * steps_per_epoch
    opt = build_optimizer(cfg, total_updates)

    log = MetricsLogger(path=cfg.metrics_path, tag=cfg.algo, echo=False)
    results: dict = {"config": cfg.to_json(), "workers": topo.num_workers,
                     "platform": topo.platform}

    if cfg.algo.startswith("ps-"):
        return _run_async_ps(cfg, model, opt, x_tr, y_tr, x_te, y_te,
                             log, results)

    trainer = build_trainer(cfg, model, opt, topo)

    gb = max(cfg.global_batch // topo.num_workers, 1) * topo.num_workers
    state = trainer.init_state(jax.random.key(cfg.seed), x_tr[:2])

    start_unit = 0
    if cfg.resume and cfg.ckpt_dir:
        _check_resume_layout(cfg)
        template = state
        shardings = jax.tree.map(lambda a: a.sharding, template)
        state, step = restore_checkpoint(cfg.ckpt_dir, template,
                                         shardings=shardings)
        if step is not None:
            start_unit = step
            results["resumed_from"] = step

    batches = Batches(x_tr, y_tr, global_batch=gb, seed=cfg.seed)
    is_sync = cfg.resolved_algo() in SYNC_ALGOS
    tau = 1 if is_sync else cfg.tau
    units_per_epoch = batches.steps_per_epoch() // tau
    if units_per_epoch == 0:
        raise ValueError(
            f"epoch of {batches.steps_per_epoch()} step(s) cannot fill one "
            f"{'step' if is_sync else f'round of tau={tau}'}"
        )
    # resume re-enters the SAME deterministic data schedule: unit counters
    # map back to (epoch, offset); cfg.epochs is total, not additional
    start_epoch, skip_units = divmod(start_unit, units_per_epoch)
    unit = start_unit  # steps (sync) or rounds (easgd/downpour)
    metrics = None

    def on_unit(_done, st, m):
        nonlocal unit, metrics
        unit += 1
        metrics = m
        if cfg.log_every and unit % cfg.log_every == 0:
            log.log(unit, loss=m["loss"])
        if cfg.ckpt_dir and cfg.ckpt_every and unit % cfg.ckpt_every == 0:
            save_checkpoint(cfg.ckpt_dir, st, step=unit,
                            metadata={"config": cfg.to_json()})

    t_start = time.perf_counter()
    with trace(cfg.profile_dir):
        if is_sync:
            state, metrics = trainer.fit(
                batches, state, epochs=cfg.epochs, start_epoch=start_epoch,
                skip_steps=skip_units, on_step=on_unit,
                prefetch=cfg.prefetch,
            )
        else:
            state, metrics = trainer.fit(
                batches, state, epochs=cfg.epochs, start_epoch=start_epoch,
                skip_rounds=skip_units, on_round=on_unit,
                prefetch=cfg.prefetch,
            )
        if metrics is not None:
            # completion proof covering BOTH the final state and the last
            # metrics (block_until_ready lies on this platform, and the
            # loss alone would not prove the state update finished)
            force_completion(state, metrics)
    wall = time.perf_counter() - t_start
    trained = unit - start_unit
    samples = trained * tau * gb
    if cfg.ckpt_dir and trained:
        save_checkpoint(cfg.ckpt_dir, state, step=unit,
                        metadata={"config": cfg.to_json()})

    if is_sync:
        acc, eval_loss = trainer.evaluate(state, x_te, y_te)
        results["eval_loss"] = eval_loss
    else:
        acc = trainer.evaluate(state, x_te, y_te)
    if is_seq and cfg.resolved_algo() not in (
        "seq-sync", "moe-sync", "pp-sync"
    ):
        # eval counts correct *tokens* per window; the seq/moe/pp-sync
        # trainers already normalize per token themselves
        acc = acc / cfg.seq_len
    results.update(
        accuracy=acc,
        final_loss=float(metrics["loss"]) if metrics is not None else None,
        trained_units=trained,
        samples=samples,
        wall_s=wall,
        samples_per_sec=samples / wall,
        # per DEVICE, not per worker-axis entry: on seq-sync's 2-D mesh all
        # dp*sp chips execute the step (identical on 1-D meshes)
        samples_per_sec_per_chip=samples / wall / topo.num_devices,
        step_time={"steps": trained,
                   "mean_s": wall / trained if trained else None},
        last_checkpoint=(latest_checkpoint(cfg.ckpt_dir)
                         if cfg.ckpt_dir else None),
    )
    log.close()
    return results


def _run_async_ps(cfg, model, opt, x_tr, y_tr, x_te, y_te, log, results):
    """The reference's literal pclient/pserver shape (BASELINE.json:7).

    Aux-flag support in this mode (round-1 advisor: these used to be silent
    no-ops): ``profile_dir`` traces the whole async run; ``ckpt_dir`` makes
    every server persist its center chunk (elastic recovery — every
    ``ckpt_every`` updates and at teardown) plus the final msgpack center
    checkpoint; ``resume`` restores the persisted chunks so a restarted
    job continues from the last center; ``log_every`` logs the per-step
    client losses post-hoc (there is no global step during the run —
    clients are asynchronous by design). ``grad_accum`` has no meaning
    here and WARNs instead of silently ignoring."""
    import warnings

    from mpit_tpu.parallel import AsyncPSTrainer
    from mpit_tpu.utils import save_checkpoint, trace

    for flag, on in (
        ("grad_accum", cfg.grad_accum > 1),
    ):
        if on:
            warnings.warn(
                f"{flag!r} is not supported with algo={cfg.algo!r} "
                "(async PS clients run their own local steps); ignoring",
                stacklevel=3,
            )
    if cfg.exchange_dtype not in ("none", "bf16"):
        raise ValueError(
            f"unknown exchange_dtype {cfg.exchange_dtype!r}; have: none, bf16"
        )
    if cfg.exchange_dtype != "none":
        warnings.warn(
            "exchange_dtype compresses the collective easgd exchange; the "
            "host-async PS protocol serializes parameters on its own path "
            "and ignores it",
            stacklevel=3,
        )
    ps_algo = cfg.resolved_algo().removeprefix("ps-")
    alpha = cfg.alpha if cfg.alpha is not None else 0.9 / cfg.clients
    trainer = AsyncPSTrainer(
        model, opt,
        num_clients=cfg.clients, num_servers=cfg.servers,
        algo=ps_algo,
        alpha=alpha, tau=cfg.tau,
        transport=cfg.transport,
        client_timeout=cfg.client_timeout,
        ckpt_dir=cfg.ckpt_dir or None,
        # config semantics: ckpt_every=0 means "no periodic writes" —
        # servers then persist only at teardown, never every-100 default
        ckpt_every=cfg.ckpt_every or None,
        resume=cfg.resume,
    )
    per_client = max(cfg.global_batch // cfg.clients, 1)
    t0 = time.perf_counter()
    with trace(cfg.profile_dir):
        center, stats = trainer.train(
            x_tr, y_tr, steps=cfg.steps, batch_size=per_client, seed=cfg.seed
        )
    wall = time.perf_counter() - t0
    acc = trainer.evaluate(center, x_te, y_te)
    if cfg.dataset == "ptb":
        acc = acc / cfg.seq_len
    samples = cfg.steps * per_client * cfg.clients
    if cfg.log_every:
        # stop before the final step — the summary line below logs it
        for s in range(cfg.log_every - 1, cfg.steps - 1, cfg.log_every):
            step_losses = [l[s] for l in stats["losses"] if len(l) > s]
            if step_losses:
                log.log(s + 1, loss=float(np.mean(step_losses)))
    log.log(cfg.steps, loss=stats["mean_final_loss"], accuracy=acc)
    if cfg.ckpt_dir:
        save_checkpoint(
            cfg.ckpt_dir, center, step=cfg.steps,
            metadata={"config": cfg.to_json(), "kind": "ps_center"},
        )
        results["last_checkpoint"] = cfg.steps
    results.update(
        accuracy=acc,
        final_loss=stats["mean_final_loss"],
        server_counts=stats["server_counts"],
        dead_clients=stats["dead_clients"],
        center_restored=stats["center_restored"],
        samples=samples,
        wall_s=wall,
        samples_per_sec=samples / wall,
        clients=cfg.clients,
        servers=cfg.servers,
    )
    log.close()
    return results


def main(argv=None, description: Optional[str] = None) -> None:
    """CLI over every BASELINE workload config (installed as ``mpit-train``;
    ``examples/train.py`` is the same entry run from a checkout, passing its
    usage docstring as ``description``). Prints the results dict as one JSON
    line."""
    cfg = TrainConfig.from_args(
        argv,
        description=description
        or "mpit_tpu training driver — any preset, any flag override "
        "(e.g. --preset mnist-easgd --epochs 10). On the CPU-simulated "
        "mesh, prefix with XLA_FLAGS=--xla_force_host_platform_device_"
        "count=8 JAX_PLATFORMS=cpu.",
    )

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # honor an explicit platform choice even when a sitecustomize
        # pre-registered a hardware backend at interpreter start
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    print(json.dumps(run(cfg), default=repr))
