# mpit-analysis: protocol-role[serving_router->serving_replica]
"""Live weight streaming into serving replicas (router side).

Reuses the PS fetch *shapes* — named ndarray/QuantArray leaves with a
version counter — without the PS machinery: serving is read-only, so
there is no error feedback, no push path, and a missed refresh costs
staleness, not correctness. The publisher answers replica
``WEIGHT_SUB`` subscriptions (and explicit rolling pushes) with one
``WEIGHT_PUSH`` carrying ``(version, names, arrays)``; quantization
(``bf16``/``int8`` per :mod:`mpit_tpu.quant`) amortizes refresh bytes
exactly like the quantized PARAM fetch does for training pulls.

Leaf naming uses the pytree path string; the replica rebuilds against
its OWN treedef (same architecture by construction) and cross-checks
the names, so a publisher/replica model mismatch fails loudly instead
of silently scattering weights.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from mpit_tpu.fleet.replica import TAG_WEIGHT_PUSH
from mpit_tpu.quant import QUANT_MODES, QuantArray, dequantize, quantize


def flatten_named(params) -> tuple:
    """``(names, arrays)`` — one host ndarray per pytree leaf, names from
    the jax key path (deterministic leaf order: the treedef's)."""
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    names = [jax.tree_util.keystr(path) for path, _ in leaves]
    arrays = [np.asarray(leaf) for _, leaf in leaves]
    return names, arrays


def unflatten_like(template, names, arrays):
    """Rebuild a params pytree with ``template``'s structure from a
    ``(names, arrays)`` pair, dequantizing any QuantArray leaves."""
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    want = [jax.tree_util.keystr(path) for path, _ in paths_leaves]
    if list(names) != want:
        diff = next(
            ((a, b) for a, b in zip(names, want) if a != b),
            (len(names), len(want)),
        )
        raise ValueError(
            "weight push names do not match this replica's model "
            f"(first difference: {diff})"
        )
    leaves = [
        dequantize(a) if isinstance(a, QuantArray) else np.asarray(a)
        for a in arrays
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


class StaticWeightSource:
    """A versioned in-memory weight source (checkpoint stand-in).

    ``version`` starts at 1 so a fresh replica (construction-time
    weights = version 0) always has something to pull; :meth:`bump`
    installs new params under the next version — the rolling-refresh
    driver for tests and soaks. A PServer-backed source is the same
    two-method surface (``version``/``current``) over the versioned
    PARAM fetch."""

    def __init__(self, params, version: int = 1):
        if version < 1:
            raise ValueError("version must be >= 1")
        self._params = params
        self.version = int(version)

    def current(self) -> tuple:
        return self.version, self._params

    def bump(self, params) -> int:
        self._params = params
        self.version += 1
        return self.version


class WeightPublisher:
    """Serve versioned weights to replicas over the router's transport.

    ``quant``: ``off``/``bf16``/``int8`` — the wire precision of pushed
    leaves (error feedback deliberately absent: each push is a fresh
    quantization of the source truth, so refresh error never
    accumulates across versions)."""

    def __init__(self, transport, source, quant: str = "off"):
        if quant not in QUANT_MODES:
            raise ValueError(f"quant must be one of {QUANT_MODES}")
        self.transport = transport
        self.source = source
        self.quant = quant
        #: rank -> last version pushed (audit surface for the harness)
        self.pushed: dict[int, int] = {}

    def _encode(self, params) -> tuple:
        names, arrays = flatten_named(params)
        if self.quant != "off":
            arrays = [
                # Each push is a fresh full snapshot, not an accumulating
                # stream — residual state would correct nothing.
                # mpit-analysis: ef-off[serving push is a fresh snapshot]
                quantize(np.asarray(a, np.float32), self.quant)
                for a in arrays
            ]
        return names, arrays

    def publish_to(self, rank: int) -> int:
        """Push the current source version to one replica; returns the
        version pushed."""
        version, params = self.source.current()
        names, arrays = self._encode(params)
        self.transport.send(
            rank, TAG_WEIGHT_PUSH, (int(version), names, arrays)
        )
        self.pushed[rank] = int(version)
        return int(version)

    def on_sub(self, rank: int, have_version: int) -> Optional[int]:
        """Answer one WEIGHT_SUB: push iff the source is newer than what
        the replica reports serving. Returns the pushed version or
        None."""
        if int(have_version) >= self.source.version:
            return None
        return self.publish_to(rank)

    def push_all(self, ranks) -> dict:
        """Rolling refresh: push the current version to every rank, one
        at a time (the one-at-a-time order is what keeps a fleet serving
        through a refresh — at most one replica pays install latency at
        any moment)."""
        return {r: self.publish_to(r) for r in ranks}
