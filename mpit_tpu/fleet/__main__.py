"""Fleet CLI: seeded fleet runs, lifecycle audits, and the soak pin.

    python -m mpit_tpu.fleet run --out /tmp/fleet --replicas 3 \\
        --requests 24 --kill-after 2 --kill-rank 1

drives one workload through the router + N replicas (threads over the
in-process broker by default; ``--procs`` spawns each replica as an OS
process speaking the framed ``SocketTransport``, the deployment shape),
writes the router's lifecycle journal into ``--out``, and prints one
JSON report line. Chain::

    python -m mpit_tpu.fleet audit /tmp/fleet
    python -m mpit_tpu.obs slo /tmp/fleet --gate scripts/fleet_smoke.json

Subcommands:

``run``      one seeded fleet run (kill leg, rolling weight refresh,
             controller) — a pure function of its flags; rerunning a
             failed soak's line replays it.
``replica``  the subprocess entry ``run --procs`` spawns per replica
             rank; world discovery via the ``mpit_tpu.launch`` env
             contract (``MPIT_RANK``/``MPIT_WORLD_SIZE``/
             ``MPIT_TRANSPORT_HOSTS``).
``audit``    replay a run's router journal into the zero-lost verdict
             (exit 1 when any routed request never finished).
``pin``      compare a clean run dir against a chaos run dir: the kill
             may move p99 but must not move p50 (factor gate) and must
             lose nothing — the fleet soak's pass/fail core.

Env knobs (all overridable by flags): ``MPIT_FLEET_POLICY``
(p2c|least), ``MPIT_FLEET_MAX_OUTSTANDING`` (0 = unlimited admission),
``MPIT_FLEET_QUANT`` (off|bf16|int8 weight-push encoding),
``MPIT_FLEET_DETECT_TIMEOUT_S`` (process-mode death-detect patience).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def _build_model(seed: int = 0):
    import jax
    import jax.numpy as jnp

    from mpit_tpu.models.transformer import TransformerLM

    model = TransformerLM(
        vocab_size=17, num_layers=2, d_model=32, num_heads=4,
        max_len=64, compute_dtype=jnp.float32,
    )
    params = model.init(
        jax.random.key(seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _server_factory(out, max_batch, segment, seed=0):
    from mpit_tpu.models import Server
    from mpit_tpu.obs.core import ObsConfig

    model, params = _build_model(seed)

    def factory(rank: int):
        obs = (
            ObsConfig(dir=os.path.join(out, f"rep{rank}"))
            if out else None
        )
        return Server(
            model, params, max_batch=max_batch, segment=segment, obs=obs
        )

    return factory, params


# -- replica: the subprocess entry ------------------------------------------


def _main_replica(argv) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mpit_tpu.fleet replica",
        description="one fleet replica over SocketTransport; world from "
        "MPIT_RANK/MPIT_WORLD_SIZE/MPIT_TRANSPORT_HOSTS",
    )
    p.add_argument("--out", default=None, help="obs base dir (journals "
                   "land in <out>/rep<rank>)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max-batch", type=int, default=2)
    p.add_argument("--segment", type=int, default=4)
    p.add_argument("--router-rank", type=int, default=0)
    ns = p.parse_args(argv)

    rank = int(os.environ["MPIT_RANK"])
    size = int(os.environ["MPIT_WORLD_SIZE"])

    from mpit_tpu.fleet.replica import ReplicaServer
    from mpit_tpu.transport.socket_transport import SocketTransport

    factory, _params = _server_factory(
        ns.out, ns.max_batch, ns.segment, seed=ns.seed
    )
    transport = SocketTransport(rank, size)
    rep = ReplicaServer(factory(rank), transport, router_rank=ns.router_rank)
    rep.subscribe_weights()
    try:
        summary = rep.run()
    finally:
        rep.close()
        transport.close()
    print(json.dumps(summary))
    return 0


# -- run: the fleet driver ---------------------------------------------------


def _proc_harness(out, max_batch, segment, model_seed, **kwargs):
    """A ``FleetHarness`` whose replicas are OS processes: reserved
    ports, ``replica``-subcommand children over ``SocketTransport``,
    SIGKILL as the chaos kill, waitpid as death detection. Defined
    lazily so the in-process path never pays the import."""
    from mpit_tpu.fleet.harness import FleetHarness
    from mpit_tpu.launch import _reserve_ports

    class ProcFleetHarness(FleetHarness):
        def __init__(self):
            super().__init__(lambda rank: None, **kwargs)
            self._procs: dict = {}
            self._stopping = False
            self._detect_timeout_s = float(
                os.environ.get("MPIT_FLEET_DETECT_TIMEOUT_S", "60")
            )

        def _make_world(self, size: int) -> None:
            from mpit_tpu.transport.socket_transport import (
                SocketTransport,
            )

            socks, ports = _reserve_ports(size)
            self._hosts = ",".join(
                f"127.0.0.1:{port}" for port in ports
            )
            self._addrs = [("127.0.0.1", port) for port in ports]
            for s in socks:
                s.close()  # rank 0 binds now, children bind theirs
            self._transports = {
                0: SocketTransport(0, size, addresses=self._addrs)
            }

        def _spawn_replica(self, rank: int) -> None:
            env = dict(os.environ)
            env["MPIT_RANK"] = str(rank)
            env["MPIT_WORLD_SIZE"] = str(len(self._addrs))
            env["MPIT_TRANSPORT_HOSTS"] = self._hosts
            env.setdefault("JAX_PLATFORMS", "cpu")
            cmd = [
                sys.executable, "-m", "mpit_tpu.fleet", "replica",
                "--seed", str(model_seed),
                "--max-batch", str(max_batch),
                "--segment", str(segment),
            ]
            if out:
                cmd += ["--out", out]
            self._procs[rank] = subprocess.Popen(
                cmd, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )

        def _kill_replica(self, rank: int) -> None:
            proc = self._procs.get(rank)
            if proc is not None and proc.poll() is None:
                proc.kill()

        def _replica_dead(self, rank: int) -> bool:
            proc = self._procs.get(rank)
            return (
                proc is not None
                and proc.poll() is not None
                and not self._stopping
            )

        def _join_replicas(self) -> None:
            self._stopping = True
            deadline = time.monotonic() + self._detect_timeout_s
            for proc in self._procs.values():
                try:
                    proc.wait(
                        timeout=max(0.1, deadline - time.monotonic())
                    )
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()

    return ProcFleetHarness()


def _main_run(argv) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mpit_tpu.fleet run",
        description="seeded fleet run: router + N replicas, optional "
        "kill leg / rolling weight refresh / controller; one JSON "
        "report line",
    )
    p.add_argument("--out", required=True,
                   help="router journal dir (created if missing); "
                   "replica journals land in <out>/rep<rank>")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--rate", type=float, default=200.0)
    p.add_argument("--slo-ms", type=float, default=60_000.0)
    p.add_argument("--replicas", type=int, default=3)
    p.add_argument("--spares", type=int, default=0)
    p.add_argument("--policy", default=None,
                   help="p2c|least (default: $MPIT_FLEET_POLICY or p2c)")
    p.add_argument("--max-outstanding", type=int, default=None,
                   help="admission cap (default: "
                   "$MPIT_FLEET_MAX_OUTSTANDING or unlimited)")
    p.add_argument("--kill-after", type=int, default=None,
                   help="kill --kill-rank at this router boundary")
    p.add_argument("--kill-rank", type=int, default=1)
    p.add_argument("--refresh-at", default="",
                   help="comma-separated router boundaries for rolling "
                   "weight refreshes (e.g. 4,8)")
    p.add_argument("--quant", default=None,
                   help="weight-push encoding off|bf16|int8 (default: "
                   "$MPIT_FLEET_QUANT or off)")
    p.add_argument("--controller", action="store_true",
                   help="route deaths through the alert->action control "
                   "plane (spawns into --spares) instead of bare "
                   "mark_dead")
    p.add_argument("--procs", action="store_true",
                   help="replicas as OS processes over SocketTransport "
                   "(default: threads over the in-process broker)")
    p.add_argument("--max-batch", type=int, default=2)
    p.add_argument("--segment", type=int, default=4)
    p.add_argument("--no-warmup", action="store_true",
                   help="skip the unjournaled XLA warmup (in-process "
                   "mode only)")
    ns = p.parse_args(argv)

    from mpit_tpu.fleet import (
        FleetHarness, StaticWeightSource, audit_lifecycle,
    )
    from mpit_tpu.loadgen import LoadSpec, ServeChaos, make_workload
    from mpit_tpu.loadgen.slo import aggregate_paths

    spec = LoadSpec(
        requests=ns.requests, rate=ns.rate, seed=ns.seed, cancel_prob=0.0,
    )
    work = make_workload(spec, 17, max_len=64)
    for r in work:
        r.slo_ms = ns.slo_ms

    chaos = (
        ServeChaos(seed=ns.seed, kill_after=ns.kill_after)
        if ns.kill_after is not None else None
    )
    refresh = tuple(
        int(b) for b in ns.refresh_at.split(",") if b.strip()
    )
    quant = ns.quant or os.environ.get("MPIT_FLEET_QUANT", "off")

    _model, params = _build_model(ns.seed)
    source = StaticWeightSource(params, version=1) if (
        refresh or quant != "off"
    ) else None

    def bump(version):
        import jax

        return jax.tree_util.tree_map(
            lambda a: a + 1e-3 * version, params
        )

    common = dict(
        requests=work,
        n_replicas=ns.replicas,
        spares=ns.spares,
        policy=ns.policy,
        seed=ns.seed,
        obs_dir=ns.out,
        max_outstanding=(
            ns.max_outstanding if ns.max_outstanding is not None
            else int(os.environ.get("MPIT_FLEET_MAX_OUTSTANDING", "0"))
        ),
        chaos=chaos,
        kill_rank=ns.kill_rank,
        source=source,
        quant=quant,
        refresh_boundaries=refresh,
        refresh_params_fn=bump if refresh else None,
        use_controller=ns.controller,
    )
    if ns.procs:
        harness = _proc_harness(
            ns.out, ns.max_batch, ns.segment, ns.seed, **common
        )
    else:
        factory, _p = _server_factory(
            ns.out, ns.max_batch, ns.segment, seed=ns.seed
        )
        if not ns.no_warmup:
            # compile every bucket shape outside the journals, so the
            # kill-vs-clean pin compares scheduling, not XLA compiles
            from mpit_tpu.models import Server

            warm = Server(
                _build_model(ns.seed)[0], params,
                max_batch=ns.max_batch, segment=ns.segment,
            )
            for r in work:
                warm.submit(list(r.prompt), r.max_new)
            warm.drain()
            warm.close()
        harness = FleetHarness(factory, **common)
    rep = harness.run()

    audit = audit_lifecycle([ns.out])
    report = aggregate_paths(
        sorted(
            os.path.join(ns.out, f)
            for f in os.listdir(ns.out)
            if f.startswith("obs_rank") and f.endswith(".jsonl")
        )
    )
    report["replica_count"] = ns.replicas
    report["router_policy"] = (
        ns.policy or os.environ.get("MPIT_FLEET_POLICY", "p2c")
    )
    report["fleet"] = {
        "admitted": audit["admitted"],
        "finished": audit["finished"],
        "redispatched": audit["redispatched"],
        "shed": audit["shed"],
        "lost": audit["lost"],
        "dead_replicas": audit["dead_replicas"],
        "versions_monotonic": audit["versions_monotonic"],
        "ok": audit["ok"],
    }
    report["client"] = {
        "submitted": rep.submitted,
        "killed_ranks": rep.killed_ranks,
        "spawned_ranks": rep.spawned_ranks,
        "redispatched": rep.redispatched,
        "boundaries": rep.boundaries,
        "wall_s": round(rep.wall_s, 4),
    }
    print(json.dumps(report))
    return 0 if audit["ok"] else 1


# -- audit -------------------------------------------------------------------


def _main_audit(argv) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mpit_tpu.fleet audit",
        description="replay a fleet run's router journal into the "
        "zero-lost verdict",
    )
    p.add_argument("paths", nargs="+",
                   help="router journal dir(s) or obs_rank*.jsonl files")
    p.add_argument("--json", action="store_true")
    ns = p.parse_args(argv)

    from mpit_tpu.fleet import audit_lifecycle, format_audit

    audit = audit_lifecycle(ns.paths)
    if ns.json:
        print(json.dumps(audit, indent=2))
    else:
        print(format_audit(audit))
    return 0 if audit["ok"] and audit["versions_monotonic"] else 1


# -- pin: clean-vs-chaos p50/p99 ---------------------------------------------


def _main_pin(argv) -> int:
    p = argparse.ArgumentParser(
        prog="python -m mpit_tpu.fleet pin",
        description="the soak's core claim: a replica kill may move p99 "
        "but must not move p50 (same-seed clean run as the baseline), "
        "and must lose zero admitted requests",
    )
    p.add_argument("clean", help="clean run's router journal dir")
    p.add_argument("chaos", help="kill run's router journal dir")
    p.add_argument("--p50-factor", type=float, default=3.0,
                   help="max allowed chaos-p50 / clean-p50 (default 3.0 "
                   "— generous: CI CPUs are noisy, the LOST gate is the "
                   "sharp one)")
    p.add_argument("--expect-kill", action="store_true",
                   help="additionally require the chaos run to name a "
                   "dead replica and a redispatch (the fault actually "
                   "fired)")
    p.add_argument("--json", action="store_true")
    ns = p.parse_args(argv)

    from mpit_tpu.fleet import audit_lifecycle
    from mpit_tpu.loadgen.slo import aggregate_paths

    def _load(d):
        paths = sorted(
            os.path.join(d, f) for f in os.listdir(d)
            if f.startswith("obs_rank") and f.endswith(".jsonl")
        )
        return audit_lifecycle([d]), aggregate_paths(paths)

    clean_audit, clean_rep = _load(ns.clean)
    chaos_audit, chaos_rep = _load(ns.chaos)
    failures = []
    for name, audit in (("clean", clean_audit), ("chaos", chaos_audit)):
        if not audit["ok"]:
            failures.append(
                f"{name}: lost={audit['lost']} unrouted={audit['unrouted']}"
            )
        if not audit["versions_monotonic"]:
            failures.append(f"{name}: weight version regression")
    p50_clean = clean_rep.get("e2e", {}).get("p50_ms")
    p50_chaos = chaos_rep.get("e2e", {}).get("p50_ms")
    if p50_clean is None or p50_chaos is None:
        failures.append("missing e2e p50 samples")
    elif p50_chaos > p50_clean * ns.p50_factor:
        failures.append(
            f"p50 moved: {p50_chaos}ms > {p50_clean}ms x {ns.p50_factor}"
        )
    if ns.expect_kill:
        if not chaos_audit["dead_replicas"]:
            failures.append("chaos run killed no replica")
        elif not chaos_audit["redispatched"] and chaos_audit["lost"]:
            failures.append("kill orphaned requests without redispatch")
    verdict = {
        "ok": not failures,
        "failures": failures,
        "p50_ms": {"clean": p50_clean, "chaos": p50_chaos},
        "p99_ms": {
            "clean": clean_rep.get("e2e", {}).get("p99_ms"),
            "chaos": chaos_rep.get("e2e", {}).get("p99_ms"),
        },
        "killed": chaos_audit["dead_replicas"],
        "redispatched": chaos_audit["redispatched"],
    }
    if ns.json:
        print(json.dumps(verdict, indent=2))
    else:
        print(
            "pin: p50 clean {clean}ms chaos {chaos}ms; p99 {pc}ms -> "
            "{px}ms; killed {k}; redispatched {r}".format(
                clean=p50_clean, chaos=p50_chaos,
                pc=verdict["p99_ms"]["clean"], px=verdict["p99_ms"]["chaos"],
                k=verdict["killed"], r=verdict["redispatched"],
            )
        )
        for f in failures:
            print(f"  FAIL {f}")
        print("pin: " + ("OK" if not failures else "FAILED"))
    return 0 if not failures else 1


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    cmds = {
        "run": _main_run,
        "replica": _main_replica,
        "audit": _main_audit,
        "pin": _main_pin,
    }
    if argv and argv[0] in cmds:
        return cmds[argv[0]](argv[1:])
    print(
        "usage: python -m mpit_tpu.fleet {run|replica|audit|pin} ...",
        file=sys.stderr,
    )
    return 2


if __name__ == "__main__":
    sys.exit(main())
