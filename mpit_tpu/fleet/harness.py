"""In-process fleet harness: N replica threads + one router loop.

The fleet analogue of :class:`mpit_tpu.loadgen.harness.LoadHarness` —
and the single-host test/soak vehicle for every fleet guarantee. Ranks
are threads over one :class:`~mpit_tpu.transport.inproc.Broker` (rank 0
= router, 1..N = replicas; the multi-process runner in
``fleet/__main__.py`` swaps in ``SocketTransport`` with the same
protocol). The router loop is single-threaded and open-loop: arrivals
come due on the workload's schedule regardless of fleet capacity, so
overload shows up in e2e latency — the measurement — not in silently
throttled offered load.

Chaos: a :class:`~mpit_tpu.loadgen.chaos.ServeChaos` ``kill_after``
boundary kills ``kill_rank`` — the in-process SIGKILL is the replica's
``killed`` flag, which drops any not-yet-sent replies and exits the
dispatch loop, so requests the replica had already consumed become
exactly the orphans redispatch exists for. Death is *detected*, not
assumed: the router loop watches thread liveness (the process-level
runner watches waitpid) and feeds a synthesized ``dead_rank`` alert to
the controller (when armed) or calls ``mark_dead`` directly.

Cancellations are not routed (the fleet wire has no CANCEL lane yet);
run fleet workloads with ``cancel_prob=0``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

from mpit_tpu.fleet.controller import FleetController
from mpit_tpu.fleet.replica import ReplicaServer
from mpit_tpu.fleet.router import Router
from mpit_tpu.fleet.weights import WeightPublisher
from mpit_tpu.transport.inproc import Broker


@dataclasses.dataclass
class FleetReport:
    """Outcome of one fleet run. ``results``: rid → ``{tokens, replica,
    serving_weights_version}``; ``replica_summaries``: each replica
    loop's exit summary; ``controller_log``: the actions taken."""

    results: dict
    submitted: int
    shed: int
    redispatched: int
    killed_ranks: list
    spawned_ranks: list
    boundaries: int
    wall_s: float
    replica_summaries: list
    controller_log: list
    weights_pushed: dict


class FleetHarness:
    """Run one workload against an in-process fleet.

    ``server_factory(rank)``: builds the replica's ``Server`` (give each
    rank its own obs dir — replica journals carry TTFT, the router
    journal carries admission/e2e; never aggregate the two together).
    ``n_replicas``: initial fleet size; ``spares``: extra ranks the
    controller may spawn into. ``source``: a weight source to publish
    from (replicas subscribe at startup); ``refresh_boundaries``: router
    boundaries at which the source is bumped via ``refresh_params_fn``
    and rolled across the fleet. ``chaos``+``kill_rank``: the replica
    kill leg. ``use_controller``: route death through the alert→action
    path (and allow spawn into spares) instead of bare ``mark_dead``."""

    def __init__(
        self,
        server_factory: Callable,
        requests: list,
        n_replicas: int = 3,
        spares: int = 0,
        policy: Optional[str] = None,
        seed: int = 0,
        obs_dir: Optional[str] = None,
        max_outstanding: int = 0,
        chaos=None,
        kill_rank: Optional[int] = None,
        source=None,
        quant: str = "off",
        refresh_boundaries: tuple = (),
        refresh_params_fn: Optional[Callable] = None,
        use_controller: bool = False,
        poll_s: float = 0.005,
        idle_sleep: float = 0.001,
    ):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        self.server_factory = server_factory
        self.requests = sorted(requests, key=lambda r: r.arrival_s)
        self.n_replicas = int(n_replicas)
        self.spares = int(spares)
        self.policy = policy
        self.seed = int(seed)
        self.obs_dir = obs_dir
        self.max_outstanding = int(max_outstanding)
        self.chaos = chaos
        self.kill_rank = kill_rank if kill_rank is not None else 1
        self.source = source
        self.quant = quant
        self.refresh_boundaries = set(refresh_boundaries)
        self.refresh_params_fn = refresh_params_fn
        self.use_controller = use_controller
        self.poll_s = float(poll_s)
        self.idle_sleep = float(idle_sleep)
        self._replicas: dict[int, ReplicaServer] = {}
        self._threads: dict[int, threading.Thread] = {}
        self._summaries: list = []

    # -- replica lifecycle (the process-backed runner in ``__main__``
    # overrides these four hooks; the router loop is shared) -----------------

    def _make_world(self, size: int) -> None:
        """Bind ``self._transports[rank]`` for every rank in the world."""
        self._broker = Broker(size)
        self._transports = self._broker.transports()

    def _replica_dead(self, rank: int) -> bool:
        """A replica that stopped serving without being told to — the
        in-process waitpid is thread liveness."""
        t = self._threads.get(rank)
        rep = self._replicas.get(rank)
        return (
            t is not None
            and not t.is_alive()
            and not (rep is not None and rep.stopped)
        )

    def _join_replicas(self) -> None:
        for t in self._threads.values():
            t.join(timeout=10.0)
        for rep in self._replicas.values():
            rep.close()

    def _spawn_replica(self, rank: int) -> None:
        rep = ReplicaServer(
            self.server_factory(rank),
            self._transports[rank],
            router_rank=0,
            poll_s=self.poll_s,
        )
        self._replicas[rank] = rep
        t = threading.Thread(
            target=lambda: self._summaries.append(rep.run()),
            name=f"mpit-fleet-replica-{rank}",
            daemon=True,
        )
        self._threads[rank] = t
        t.start()
        rep.subscribe_weights()

    def _kill_replica(self, rank: int) -> None:
        rep = self._replicas.get(rank)
        if rep is not None:
            rep.killed = True

    # -- the router loop ---------------------------------------------------

    def run(self) -> FleetReport:
        size = 1 + self.n_replicas + self.spares
        self._make_world(size)
        initial = list(range(1, self.n_replicas + 1))
        all_ranks = list(range(1, size))
        router = Router(
            self._transports[0],
            initial,
            policy=self.policy,
            seed=self.seed,
            max_outstanding=self.max_outstanding,
            obs_dir=self.obs_dir,
        )
        publisher = (
            WeightPublisher(self._transports[0], self.source, self.quant)
            if self.source is not None else None
        )
        controller = (
            FleetController(
                router, all_ranks,
                max_replicas=self.n_replicas,
                spawn=self._spawn_replica,
            )
            if self.use_controller else None
        )
        for rank in initial:
            self._spawn_replica(rank)

        reqs = self.requests
        t0 = time.perf_counter()
        i = 0
        boundary = 0
        killed_ranks: list = []
        while True:
            now = time.perf_counter() - t0
            while i < len(reqs) and reqs[i].arrival_s <= now:
                r = reqs[i]
                r.rid = router.submit(
                    list(r.prompt), r.max_new, slo_ms=r.slo_ms
                )
                i += 1
            if self.chaos is not None and router.alive:
                fault = self.chaos.draw(boundary)
                if fault is not None and fault[0] == "kill":
                    if self.kill_rank in router.alive and (
                        self.kill_rank not in killed_ranks
                    ):
                        killed_ranks.append(self.kill_rank)
                        self._kill_replica(self.kill_rank)
                elif fault is not None and fault[0] == "delay":
                    time.sleep(fault[1])
            # death detection: a replica that exited without a STOP
            for rank in sorted(router.alive):
                if self._replica_dead(rank):
                    alert = {
                        "ev": "alert", "kind": "dead_rank",
                        "rank": rank, "t": time.time(),
                        "detail": "replica loop exited",
                    }
                    if controller is not None:
                        controller.step([alert])
                    else:
                        router.mark_dead(rank)
            if publisher is not None:
                router.poll_weight_subs(publisher)
                if boundary in self.refresh_boundaries:
                    self.refresh_boundaries.discard(boundary)
                    if self.refresh_params_fn is not None:
                        self.source.bump(
                            self.refresh_params_fn(self.source.version + 1)
                        )
                    publisher.push_all(sorted(router.alive))
            # drain every queued reply, then wait briefly for the next
            while router.poll(timeout=0.0) is not None:
                pass
            boundary += 1
            if i >= len(reqs) and router.outstanding == 0:
                break
            if not router.alive and router.outstanding:
                break  # whole fleet dead — the audit names the losses
            if router.outstanding == 0:
                gap = reqs[i].arrival_s - (time.perf_counter() - t0)
                if gap > 0:
                    time.sleep(min(self.idle_sleep, gap))
            else:
                router.poll(timeout=self.poll_s)
        router.stop()
        self._join_replicas()
        router.close()
        return FleetReport(
            results=dict(router.results),
            submitted=i,
            shed=router.shed,
            redispatched=router.redispatched,
            killed_ranks=killed_ranks,
            spawned_ranks=sorted(
                (router.alive | router.dead) - set(initial)
            ),
            boundaries=boundary,
            wall_s=time.perf_counter() - t0,
            replica_summaries=list(self._summaries),
            controller_log=list(controller.log) if controller else [],
            weights_pushed=dict(publisher.pushed) if publisher else {},
        )
