# mpit-analysis: protocol-role[serving_replica->serving_router]
"""Serving replica: a ``Server`` behind a transport dispatch loop.

One replica = one :class:`mpit_tpu.models.serving.Server` owned by one
rank, serving ROUTE requests from the router and absorbing WEIGHT_PUSH
refreshes between scheduling steps. The loop is the pserver dispatch
idiom — a wildcard recv routed by tag comparison — so the protocol-role
model extracts its alphabet and MPT008 pairs it against the router's.

Wire tags 11–15 extend the registry in ``parallel/pserver.py`` (1–10);
the fleet gets its own STOP tag rather than reusing ``TAG_STOP`` so the
wire-schema lock never unions two protocols' payload shapes under one
tag. Payload envelopes (all framed — tuples of scalars, lists and
arrays; MPT017 keeps them off the pickle fallback):

- ``ROUTE``  (router→replica): ``(rid, prompt, max_new, slo_ms)``
- ``REPLY``  (replica→router): ``(rank, rid, tokens, version)`` —
  ``version`` is the replica's serving weights version, the audit stamp
- ``WEIGHT_SUB``  (replica→router): ``(rank, have_version)``
- ``WEIGHT_PUSH`` (router→replica): ``(version, names, arrays)``
- ``FLEET_STOP``  (router→replica): ``0``

Weight installs are **read-only** consumption of the PS fetch shapes:
quantized leaves (bf16/int8 ``QuantArray``) are dequantized on arrival
and swapped into the server between segments — no error feedback,
nothing flows back toward training.
"""

from __future__ import annotations

from mpit_tpu.obs.live import M_FLEET_WEIGHTS_VERSION, live_registry
from mpit_tpu.transport.base import RecvTimeout

# fleet wire tags — continuing the PS registry (parallel/pserver.py owns
# 1–10); the values are part of the wire-schema lock
TAG_ROUTE = 11
TAG_REPLY = 12
TAG_WEIGHT_SUB = 13
TAG_WEIGHT_PUSH = 14
TAG_FLEET_STOP = 15


class ReplicaServer:
    """Own one serving ``Server`` on one transport rank.

    ``transport``: any :class:`mpit_tpu.transport.base.Transport` bound
    to this replica's rank. ``router_rank``: where replies and weight
    subscriptions go. ``serve_every``: scheduling steps run per loop
    turn once work is queued (1 = finest-grained weight-refresh
    interleaving)."""

    def __init__(
        self,
        server,
        transport,
        router_rank: int = 0,
        serve_every: int = 1,
        poll_s: float = 0.02,
    ):
        if serve_every < 1:
            raise ValueError("serve_every must be >= 1")
        self.server = server
        self.transport = transport
        self.rank = transport.rank
        self.router_rank = int(router_rank)
        self.serve_every = int(serve_every)
        self.poll_s = float(poll_s)
        self.killed = False  # chaos hook: a set flag is a SIGKILL
        self.stopped = False
        self._inflight: dict[int, int] = {}  # server rid -> fleet rid
        self._replies = 0

    # -- weight refresh ----------------------------------------------------

    def subscribe_weights(self) -> None:
        """Tell the router's publisher what version this replica serves
        (0 = construction-time weights, never pushed); the publisher
        answers with a WEIGHT_PUSH iff it has something newer."""
        self.transport.send(
            self.router_rank,
            TAG_WEIGHT_SUB,
            (self.rank, int(self.server.weights_version)),
        )

    def _install(self, version: int, names, arrays) -> None:
        # local import: weights.py imports this module for the tag
        # registry; deferring the reverse edge keeps import acyclic
        from mpit_tpu.fleet.weights import unflatten_like

        if int(version) <= self.server.weights_version:
            return  # duplicate/stale push — installs are idempotent
        params = unflatten_like(self.server.params, names, arrays)
        self.server.install_weights(params, version=version)
        live_registry(self.server).set_gauge(
            M_FLEET_WEIGHTS_VERSION, self.server.weights_version
        )

    # -- request lifecycle -------------------------------------------------

    def _admit(self, rid: int, prompt, max_new: int, slo_ms: float) -> None:
        srv_rid = self.server.submit(
            [int(t) for t in prompt],
            int(max_new),
            slo_ms=float(slo_ms) if slo_ms > 0 else None,
        )
        self._inflight[srv_rid] = rid

    def _flush_results(self) -> None:
        for srv_rid, tokens in self.server.results().items():
            rid = self._inflight.pop(srv_rid, None)
            if rid is None:
                continue
            if self.killed:
                # a killed replica's reply dies with it — the router's
                # detect-timeout + redispatch path owns this request now
                continue
            self.transport.send(
                self.router_rank,
                TAG_REPLY,
                (
                    self.rank,
                    rid,
                    [int(t) for t in tokens],
                    int(self.server.weights_version),
                ),
            )
            self._replies += 1

    # -- dispatch loop -----------------------------------------------------

    def run(self) -> dict:
        """Serve until FLEET_STOP (or a chaos kill). Returns a small
        summary for the harness/postmortem."""
        while not self.stopped and not self.killed:
            # drain everything queued before spending time on a segment
            try:
                timeout = 0.0 if self.server.pending else self.poll_s
                msg = self.transport.recv(timeout=timeout)
            except RecvTimeout:
                msg = None
            if self.killed:
                break
            if msg is not None:
                if msg.tag == TAG_ROUTE:
                    rid, prompt, max_new, slo_ms = msg.payload
                    self._admit(rid, prompt, max_new, slo_ms)
                elif msg.tag == TAG_WEIGHT_PUSH:
                    version, names, arrays = msg.payload
                    self._install(version, names, arrays)
                elif msg.tag == TAG_FLEET_STOP:
                    self.stopped = True
                continue
            if self.server.pending:
                for _ in range(self.serve_every):
                    if self.server.pending == 0 or self.killed:
                        break
                    self.server.step()
                self._flush_results()
        self._flush_results()
        return {
            "rank": self.rank,
            "replies": self._replies,
            "weights_version": int(self.server.weights_version),
            "killed": bool(self.killed),
            "abandoned": len(self._inflight),
        }

    def close(self) -> None:
        try:
            self.server.close()
        except Exception:
            pass
