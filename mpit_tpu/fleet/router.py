# mpit-analysis: protocol-role[serving_router->serving_replica]
"""Request router: admission, dispatch policy, lifecycle journal.

One router owns the fleet's front door. Every admitted request is
journaled ``req_enqueue`` → ``req_route`` → (``req_redispatch`` →)* →
``req_finish``: the routing journal is the audit trail the zero-lost
guarantee is *checked against* (:mod:`mpit_tpu.fleet.audit`), not just
telemetry. Shed requests are journaled as ``req_shed`` without an
enqueue, so ``obs slo`` over the router journal counts goodput over
admitted requests only and sheds never look like losses.

Dispatch policies (:func:`choose_replica`, pure and seeded — a failing
run replays its exact routing):

- ``least``: lowest queue depth, ties broken by lowest rank;
- ``p2c``: power-of-two-choices — two seeded candidate draws per rid
  via the shared :func:`~mpit_tpu.transport.chaos._mix` hash, the
  less-loaded of the two wins (ties again by rank). The classic
  load-balancing result: two random probes get within a constant factor
  of least-loaded while only ever reading two gauges.

Load per replica is the router's own outstanding count, optionally
fused with the replica-exported live-plane queue-depth gauges
(:func:`live_loads`) — the gauges see work the router already handed
over, the outstanding count sees work the gauge exporter hasn't
snapshotted yet; the max of the two is the conservative view.

Replica death: the router never blocks on a dead replica — replies are
drained with a timeout, and :meth:`Router.mark_dead` re-dispatches the
dead replica's outstanding requests to survivors (``req_redispatch``).
A late reply from a request that was re-dispatched is dropped by rid
bookkeeping (first finish wins; the journal shows both paths).
"""

from __future__ import annotations

import os
from typing import Optional

from mpit_tpu.fleet.replica import (
    TAG_FLEET_STOP,
    TAG_REPLY,
    TAG_ROUTE,
    TAG_WEIGHT_SUB,
)
from mpit_tpu.obs.live import (
    M_FLEET_OUTSTANDING,
    M_FLEET_REDISPATCHED,
    M_FLEET_REPLICAS,
    M_FLEET_ROUTED,
    M_FLEET_SHED,
    NULL_REGISTRY,
)
from mpit_tpu.transport.base import RecvTimeout
from mpit_tpu.transport.chaos import _mix

#: domain separator: router candidate draws must not collide with wire-
#: or serve-chaos draws made from the same user seed
_FLEET_STREAM = 0xF1EE7

POLICIES = ("least", "p2c")


def choose_replica(policy: str, seed: int, rid: int, loads: dict) -> int:
    """The dispatch decision, as a pure function of ``(policy, seed,
    rid, loads)`` — rank → load for every *alive* candidate. Determinism
    is the replay contract: same inputs, same replica, any process."""
    if not loads:
        raise ValueError("no alive replicas to route to")
    ranks = sorted(loads)
    if policy == "least":
        return min(ranks, key=lambda r: (loads[r], r))
    if policy == "p2c":
        a = ranks[_mix(seed, _FLEET_STREAM, rid, 0) % len(ranks)]
        b = ranks[_mix(seed, _FLEET_STREAM, rid, 1) % len(ranks)]
        return a if (loads[a], a) <= (loads[b], b) else b
    raise ValueError(f"policy must be one of {POLICIES}, got {policy!r}")


def live_loads(live_dir: str, alive) -> dict:
    """Queue-depth view from the replicas' live-plane snapshots: rank →
    ``load.pending`` gauge (0 for ranks that haven't exported yet). The
    router fuses this with its own outstanding counts — see module
    docstring."""
    from mpit_tpu.obs.live import M_LOAD_PENDING, read_snapshots

    snaps = read_snapshots(live_dir)
    out = {}
    for rank in alive:
        gauges = snaps.get(rank, {}).get("gauges", {})
        out[rank] = float(gauges.get(M_LOAD_PENDING, 0.0))
    return out


class _RouterObs:
    """The router's lifecycle journal: the ``_ServeObs`` layout (one
    ``obs_rank<r>.jsonl`` in MetricsLogger format, Lamport-stamped) so
    merge/summary/slo read it unchanged — but *router-plane* events.
    Kept separate from the replica journals on purpose: router rids and
    per-replica server rids are different namespaces, and aggregating
    them together would double-count every request."""

    __slots__ = ("journal", "clock")

    def __init__(self, obs_dir: str, rank: int = 0):
        from mpit_tpu.obs.core import Journal, LogicalClock

        os.makedirs(obs_dir, exist_ok=True)
        self.journal = Journal(
            os.path.join(obs_dir, f"obs_rank{rank}.jsonl"), rank
        )
        self.clock = LogicalClock()

    def event(self, ev: str, **fields) -> None:
        self.journal.event(ev, self.clock.tick(), **fields)

    def close(self) -> None:
        self.journal.close()


class Router:
    """Admission + dispatch over one transport rank.

    ``transport``: the router's rank (replies and weight subscriptions
    arrive here). ``replicas``: the replica ranks initially alive.
    ``policy``/``seed``: the :func:`choose_replica` inputs (env default
    ``MPIT_FLEET_POLICY``). ``max_outstanding``: admission cap across
    the whole fleet — submits past it are shed, journaled, and return
    None (env default ``MPIT_FLEET_MAX_OUTSTANDING``, 0 = unlimited).
    ``obs_dir``: where the lifecycle journal lands (None = no journal).
    ``registry``: a live-plane MetricsRegistry (defaults to the no-op
    null registry)."""

    def __init__(
        self,
        transport,
        replicas,
        policy: Optional[str] = None,
        seed: int = 0,
        max_outstanding: Optional[int] = None,
        obs_dir: Optional[str] = None,
        registry=None,
        live_dir: Optional[str] = None,
    ):
        if policy is None:
            policy = os.environ.get("MPIT_FLEET_POLICY", "p2c")
        if policy not in POLICIES:
            raise ValueError(
                f"policy must be one of {POLICIES}, got {policy!r}"
            )
        if max_outstanding is None:
            max_outstanding = int(
                os.environ.get("MPIT_FLEET_MAX_OUTSTANDING", "0")
            )
        self.transport = transport
        self.alive = set(int(r) for r in replicas)
        self.dead: set = set()
        self.policy = policy
        self.seed = int(seed)
        self.max_outstanding = int(max_outstanding)
        self.live_dir = live_dir
        self._obs = _RouterObs(obs_dir) if obs_dir else None
        self._reg = registry if registry is not None else NULL_REGISTRY
        self._next_rid = 0
        #: rid -> replica rank currently responsible for it
        self.assigned: dict[int, int] = {}
        #: rid -> the submitted request fields (what a redispatch resends)
        self._requests: dict[int, tuple] = {}
        self.results: dict[int, dict] = {}
        self.shed = 0
        self.redispatched = 0
        self._reg.set_gauge(M_FLEET_REPLICAS, len(self.alive))

    # -- admission + dispatch ----------------------------------------------

    @property
    def outstanding(self) -> int:
        return len(self.assigned)

    def _loads(self) -> dict:
        counts = {r: 0 for r in sorted(self.alive)}
        for rank in self.assigned.values():
            if rank in counts:
                counts[rank] += 1
        if self.live_dir:
            for rank, depth in live_loads(self.live_dir, self.alive).items():
                counts[rank] = max(counts[rank], int(depth))
        return counts

    def submit(
        self, prompt, max_new: int, slo_ms: Optional[float] = None
    ) -> Optional[int]:
        """Admit one request and route it; None when shed at admission
        (fleet saturated per ``max_outstanding``)."""
        if (
            self.max_outstanding > 0
            and self.outstanding >= self.max_outstanding
        ):
            self.shed += 1
            self._reg.inc(M_FLEET_SHED)
            if self._obs is not None:
                self._obs.event("req_shed", outstanding=self.outstanding)
            return None
        rid = self._next_rid
        self._next_rid += 1
        prompt = [int(t) for t in prompt]
        slo = float(slo_ms) if slo_ms is not None else 0.0
        self._requests[rid] = (prompt, int(max_new), slo)
        if self._obs is not None:
            self._obs.event(
                "req_enqueue", rid=rid, p_len=len(prompt),
                max_new=int(max_new),
                **({"slo_ms": slo} if slo > 0 else {}),
            )
        replica = choose_replica(self.policy, self.seed, rid, self._loads())
        self._route(rid, replica)
        return rid

    def _route(self, rid: int, replica: int) -> None:
        prompt, max_new, slo = self._requests[rid]
        self.assigned[rid] = replica
        self.transport.send(
            replica, TAG_ROUTE, (rid, prompt, max_new, slo)
        )
        self._reg.inc(M_FLEET_ROUTED)
        self._reg.set_gauge(M_FLEET_OUTSTANDING, self.outstanding)
        if self._obs is not None:
            self._obs.event("req_route", rid=rid, replica=replica)

    def redispatch(self, rid: int, to: int) -> None:
        """Re-route one outstanding request after its assignee died.
        Journaled as ``req_redispatch`` — the explicit not-lost marker
        the lifecycle audit requires between a dead ``req_route`` and
        the eventual ``req_finish``."""
        src = self.assigned.get(rid)
        self.redispatched += 1
        self._reg.inc(M_FLEET_REDISPATCHED)
        if self._obs is not None:
            self._obs.event(
                "req_redispatch",
                rid=rid,
                replica=to,
                **({} if src is None else {"from_replica": src}),
            )
        prompt, max_new, slo = self._requests[rid]
        self.assigned[rid] = to
        self.transport.send(to, TAG_ROUTE, (rid, prompt, max_new, slo))
        self._reg.inc(M_FLEET_ROUTED)

    def mark_dead(self, rank: int) -> list:
        """Retire a replica and re-dispatch everything it still owed.
        Returns the re-dispatched rids (empty when it owed nothing)."""
        rank = int(rank)
        if rank not in self.alive:
            return []
        self.alive.discard(rank)
        self.dead.add(rank)
        self._reg.set_gauge(M_FLEET_REPLICAS, len(self.alive))
        orphans = sorted(
            rid for rid, r in self.assigned.items() if r == rank
        )
        for rid in orphans:
            loads = self._loads()
            if not loads:
                break  # nobody left — the audit will name these lost
            self.redispatch(rid, choose_replica(
                self.policy, self.seed, rid, loads
            ))
        return orphans

    def add_replica(self, rank: int) -> None:
        """Admit a (re)spawned replica into the routing set (the
        controller's spawn path lands here)."""
        rank = int(rank)
        self.dead.discard(rank)
        self.alive.add(rank)
        self._reg.set_gauge(M_FLEET_REPLICAS, len(self.alive))

    # -- reply + subscription intake ---------------------------------------

    def poll(self, timeout: float = 0.0) -> Optional[int]:
        """Consume at most one REPLY; returns its rid (None on timeout).
        A reply for a rid this replica no longer owns (re-dispatched,
        first finish already recorded) is dropped — exactly-once finish
        per rid is the journal invariant."""
        try:
            msg = self.transport.recv(tag=TAG_REPLY, timeout=timeout)
        except RecvTimeout:
            return None
        rank, rid, tokens, version = msg.payload
        if rid not in self.assigned:
            return None  # late duplicate from a superseded dispatch
        if self.assigned.get(rid) != rank and rank in self.dead:
            return None  # zombie reply from a retired replica
        del self.assigned[rid]
        self.results[rid] = {
            "tokens": [int(t) for t in tokens],
            "replica": int(rank),
            "serving_weights_version": int(version),
        }
        self._reg.set_gauge(M_FLEET_OUTSTANDING, self.outstanding)
        if self._obs is not None:
            _p, max_new, _slo = self._requests.get(rid, ([], 0, 0.0))
            self._obs.event(
                "req_finish",
                rid=rid,
                gen=max(0, len(tokens) - len(_p)),
                reason="fleet",
                replica=int(rank),
                serving_weights_version=int(version),
            )
        return rid

    def poll_weight_subs(self, publisher) -> int:
        """Drain queued WEIGHT_SUBs into the publisher; returns how many
        were answered with a push."""
        pushed = 0
        while True:
            try:
                msg = self.transport.recv(tag=TAG_WEIGHT_SUB, timeout=0.0)
            except RecvTimeout:
                return pushed
            rank, have_version = msg.payload
            if publisher.on_sub(int(rank), int(have_version)) is not None:
                pushed += 1

    # -- teardown ----------------------------------------------------------

    def stop(self) -> None:
        """FLEET_STOP to every live replica (dead ones get nothing — the
        tag would park in a mailbox nobody drains)."""
        for rank in sorted(self.alive):
            self.transport.send(rank, TAG_FLEET_STOP, 0)

    def close(self) -> None:
        if self._obs is not None:
            self._obs.close()
