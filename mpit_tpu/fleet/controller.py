"""Self-driving control plane: alerts + live snapshots → fleet actions.

The observability plane graduating from report to control signal. The
policy core (:func:`decide`) is a pure function from ``(alerts,
snapshots, alive, limits)`` to a list of :class:`Action` — testable
without processes, replayable from any soak's ``alerts.jsonl`` — and
:class:`FleetController` is the thin loop that executes those actions
through supervisor callbacks (spawn/retire a replica process, flip the
router's admission cap).

Policy (deliberately small; every rule cites the alert that justifies
it):

- ``dead_rank`` on a replica → retire it from routing (re-dispatching
  its orphans) and spawn a replacement, fleet size permitting;
- ``slo_burn`` anywhere → spawn one additional replica if below
  ``max_replicas``, else shed: halve the admission window so queueing
  stops compounding the burn;
- ``straggler`` on a replica → no kill (stragglers recover; killing on
  p50-vs-peers noise would flap) — the action is ``shed`` only when the
  straggler is also the *only* replica;
- no active alerts and load comfortably under capacity → ``unshed``
  (restore the admission cap), and retire the newest spare replica when
  the fleet has been idle past the scale-down watermark.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

ACTION_KINDS = ("spawn", "retire", "shed", "unshed")


@dataclasses.dataclass(frozen=True)
class Action:
    """One control decision. ``rank`` is the subject replica for
    spawn/retire (the new rank to bring up, the dead rank to drop);
    ``reason`` names the alert kind (or watermark) that justified it —
    every action in the journal is attributable."""

    kind: str
    rank: int = -1
    reason: str = ""

    def __post_init__(self):
        if self.kind not in ACTION_KINDS:
            raise ValueError(
                f"kind must be one of {ACTION_KINDS}, got {self.kind!r}"
            )


def decide(
    alerts,
    alive,
    all_ranks,
    max_replicas: int,
    outstanding: int = 0,
    max_outstanding: int = 0,
    dead=(),
) -> list:
    """The policy core. ``alerts``: alert records (``kind``/``rank``)
    newly fired this tick. ``alive``: replica ranks currently routed
    to. ``all_ranks``: the rank pool replicas may occupy (spawns pick
    the lowest free one). ``dead``: ranks already lost — a replacement
    never reuses a dead rank's slot (its transport may still hold the
    corpse's undelivered traffic). Pure — same inputs, same actions."""
    alive = set(alive)
    dead = set(dead)
    actions: list = []
    spawned: set = set()

    def _free_rank() -> Optional[int]:
        for r in sorted(all_ranks):
            if r not in alive and r not in spawned and r not in dead:
                return r
        return None

    for rec in alerts:
        kind = rec.get("kind")
        rank = rec.get("rank", -1)
        if kind == "dead_rank" and rank in alive:
            actions.append(Action("retire", rank=rank, reason="dead_rank"))
            alive.discard(rank)
            dead.add(rank)
            repl = _free_rank()
            if repl is not None and len(alive) + len(spawned) < max_replicas:
                spawned.add(repl)
                actions.append(
                    Action("spawn", rank=repl, reason="dead_rank")
                )
        elif kind == "slo_burn":
            repl = _free_rank()
            if repl is not None and len(alive) + len(spawned) < max_replicas:
                spawned.add(repl)
                actions.append(
                    Action("spawn", rank=repl, reason="slo_burn")
                )
            else:
                actions.append(Action("shed", reason="slo_burn"))
        elif kind == "straggler" and len(alive) <= 1:
            actions.append(Action("shed", reason="straggler"))
    if not alerts and max_outstanding > 0 and outstanding * 2 <= max_outstanding:
        actions.append(Action("unshed", reason="idle"))
    return actions


class FleetController:
    """Execute :func:`decide` against a live fleet.

    ``spawn``/``retire``: supervisor callbacks (rank → None) — process
    launch in the multi-process runner, thread start in the in-process
    harness. ``router``: gains/loses replicas via ``add_replica``/
    ``mark_dead`` and has its admission cap halved/restored on
    shed/unshed. Alert records come from the engine's ``on_fire`` hook
    or :func:`mpit_tpu.obs.alerts.read_alerts` over the soak's alert
    file — both produce the same dicts."""

    def __init__(
        self,
        router,
        all_ranks,
        max_replicas: int,
        spawn: Optional[Callable] = None,
        retire: Optional[Callable] = None,
    ):
        self.router = router
        self.all_ranks = tuple(sorted(int(r) for r in all_ranks))
        self.max_replicas = int(max_replicas)
        self._spawn = spawn
        self._retire = retire
        self._base_cap = int(getattr(router, "max_outstanding", 0))
        #: every action taken, in order — the controller's own audit log
        self.log: list = []

    def step(self, alerts) -> list:
        """One control tick over newly-fired alert records; returns the
        actions executed."""
        actions = decide(
            alerts,
            self.router.alive,
            self.all_ranks,
            self.max_replicas,
            outstanding=self.router.outstanding,
            max_outstanding=self.router.max_outstanding,
            dead=self.router.dead,
        )
        for act in actions:
            self._apply(act)
            self.log.append(act)
        return actions

    def _apply(self, act: Action) -> None:
        if act.kind == "retire":
            if self._retire is not None:
                self._retire(act.rank)
            self.router.mark_dead(act.rank)
        elif act.kind == "spawn":
            if self._spawn is not None:
                self._spawn(act.rank)
            self.router.add_replica(act.rank)
        elif act.kind == "shed":
            cap = self.router.max_outstanding
            if cap > 0:
                self.router.max_outstanding = max(1, cap // 2)
            else:
                # unlimited admission + an SLO burn: impose a cap at the
                # current outstanding level — stop the queue growing
                self.router.max_outstanding = max(
                    1, self.router.outstanding
                )
        elif act.kind == "unshed":
            if self._base_cap != self.router.max_outstanding:
                self.router.max_outstanding = self._base_cap
