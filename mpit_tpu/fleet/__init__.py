"""Serving fleet: request router + replica set + self-driving control.

The deployment half of "serving under traffic" (the measurement half is
:mod:`mpit_tpu.loadgen`): a **router** admits loadgen requests and
dispatches them to N **replica** processes — each a
:class:`mpit_tpu.models.serving.Server` behind a transport dispatch
loop — by least-loaded or power-of-two-choices over the queue-depth
each replica reports, journaling every request's routing lifecycle
(``req_route``/``req_redispatch``) so a kill-time audit can prove no
admitted request was lost. Replicas pull versioned weights from a
:class:`~mpit_tpu.fleet.weights.WeightPublisher` (quantized bf16/int8
over the same wire the PS PARAM path uses — error feedback stays OFF,
serving is read-only) and stamp every reply with the
``serving_weights_version`` they decoded with, making rolling refreshes
auditable. A **controller** closes the loop: it consumes the alert
stream (``slo_burn``/``dead_rank``/``straggler``) and live snapshots to
spawn/retire replicas and shed load at admission.

Wire tags 11–15 (``TAG_ROUTE``..``TAG_FLEET_STOP``) live in
:mod:`~mpit_tpu.fleet.replica`; both roles carry protocol-role markers,
so MPT008 pairs their alphabets, the wire-schema lock pins their payload
shapes (MPT016–018), and ``analysis mcheck`` explores the ``fleet-route``
model (MPT019: no admitted request both lost and unacked under a single
replica kill). docs/SERVING.md has the walkthrough.
"""

from mpit_tpu.fleet.audit import audit_lifecycle, format_audit
from mpit_tpu.fleet.controller import Action, FleetController, decide
from mpit_tpu.fleet.harness import FleetHarness, FleetReport
from mpit_tpu.fleet.replica import (
    TAG_FLEET_STOP,
    TAG_REPLY,
    TAG_ROUTE,
    TAG_WEIGHT_PUSH,
    TAG_WEIGHT_SUB,
    ReplicaServer,
)
from mpit_tpu.fleet.router import Router, choose_replica
from mpit_tpu.fleet.weights import (
    StaticWeightSource,
    WeightPublisher,
    flatten_named,
    unflatten_like,
)

__all__ = [
    "TAG_ROUTE",
    "TAG_REPLY",
    "TAG_WEIGHT_SUB",
    "TAG_WEIGHT_PUSH",
    "TAG_FLEET_STOP",
    "ReplicaServer",
    "Router",
    "choose_replica",
    "StaticWeightSource",
    "WeightPublisher",
    "flatten_named",
    "unflatten_like",
    "FleetHarness",
    "FleetReport",
    "Action",
    "FleetController",
    "decide",
    "audit_lifecycle",
    "format_audit",
]
