"""Journal-verified fleet guarantees: zero-lost routing, monotonic
weight versions, kill postmortem.

The router's lifecycle journal is written to be *checked*, not just
read: every admitted request leaves ``req_enqueue`` + ``req_route``
records, every recovery leaves ``req_redispatch``, every completion
``req_finish``. :func:`audit_lifecycle` replays those records into the
invariant the fleet soak gates on — **every routed request reaches
``req_finish``, either directly or through an explicit
``req_redispatch`` chain** — and reports the violations by rid, so a
failing soak names its lost requests instead of a percentage.

Stdlib-only (journals are JSONL) — auditable anywhere, no jax.
"""

from __future__ import annotations

from typing import Iterable

from mpit_tpu.obs.merge import expand_journal_paths, read_journal

#: router-journal lifecycle events, in the order a healthy rid sees them
LIFECYCLE_EVENTS = (
    "req_enqueue",
    "req_route",
    "req_redispatch",
    "req_finish",
    "req_shed",
)


def audit_lifecycle(paths: Iterable[str]) -> dict:
    """Audit one fleet run's ROUTER journal(s).

    Returns::

        {
          "admitted": n,            # req_enqueue records
          "routed": n,              # rids with >= 1 req_route
          "finished": n,            # rids with a req_finish
          "redispatched": n,        # rids that needed >= 1 redispatch
          "shed": n,                # admission rejections (not losses)
          "lost": [rid, ...],       # routed but never finished — THE bug
          "unrouted": [rid, ...],   # admitted but never routed
          "replicas_finished": {replica: count},
          "versions_by_replica": {replica: [version, ...]},  # reply order
          "versions_monotonic": bool,
          "dead_replicas": [rank, ...],   # named by redispatch records
          "ok": bool,               # no lost, no unrouted
        }
    """
    enqueued: set = set()
    routed: set = set()
    finished: set = set()
    redispatched: set = set()
    shed = 0
    dead: set = set()
    by_replica_finished: dict = {}
    versions: dict = {}
    for path in expand_journal_paths(list(paths)):
        for rec in read_journal(path):
            ev = rec.get("ev")
            rid = rec.get("rid")
            if ev == "req_enqueue":
                enqueued.add(rid)
            elif ev == "req_route":
                routed.add(rid)
            elif ev == "req_redispatch":
                redispatched.add(rid)
                routed.add(rid)
                if "from_replica" in rec:
                    dead.add(rec["from_replica"])
            elif ev == "req_finish":
                finished.add(rid)
                replica = rec.get("replica")
                if replica is not None:
                    by_replica_finished[replica] = (
                        by_replica_finished.get(replica, 0) + 1
                    )
                    if "serving_weights_version" in rec:
                        versions.setdefault(replica, []).append(
                            rec["serving_weights_version"]
                        )
            elif ev == "req_shed":
                shed += 1
    lost = sorted(routed - finished)
    unrouted = sorted(enqueued - routed)
    monotonic = all(
        all(a <= b for a, b in zip(vs, vs[1:]))
        for vs in versions.values()
    )
    return {
        "admitted": len(enqueued),
        "routed": len(routed),
        "finished": len(finished),
        "redispatched": len(redispatched),
        "shed": shed,
        "lost": lost,
        "unrouted": unrouted,
        "replicas_finished": {
            int(k): v for k, v in sorted(by_replica_finished.items())
        },
        "versions_by_replica": {
            int(k): v for k, v in sorted(versions.items())
        },
        "versions_monotonic": monotonic,
        "dead_replicas": sorted(dead),
        "ok": not lost and not unrouted,
    }


def format_audit(audit: dict) -> str:
    """One human-readable block (the soak's postmortem paragraph)."""
    lines = [
        f"admitted={audit['admitted']} routed={audit['routed']} "
        f"finished={audit['finished']} "
        f"redispatched={audit['redispatched']} shed={audit['shed']}",
    ]
    if audit["dead_replicas"]:
        outcome = (
            f"{audit['redispatched']} request(s) re-dispatched, none lost"
            if audit["ok"] else f"{len(audit['lost'])} request(s) LOST"
        )
        lines.append(
            "killed replica(s): "
            + ", ".join(str(r) for r in audit["dead_replicas"])
            + " — " + outcome
        )
    for replica, count in audit["replicas_finished"].items():
        vs = audit["versions_by_replica"].get(replica, [])
        span = f" versions {vs[0]}..{vs[-1]}" if vs else ""
        lines.append(f"  replica {replica}: {count} finished{span}")
    if not audit["versions_monotonic"]:
        lines.append("  VERSION REGRESSION: a replica's stamped "
                     "serving_weights_version moved backward")
    if audit["lost"]:
        lines.append(f"  LOST rids: {audit['lost']}")
    if audit["unrouted"]:
        lines.append(f"  UNROUTED rids: {audit['unrouted']}")
    lines.append("audit: " + ("OK" if audit["ok"] else "FAILED"))
    return "\n".join(lines)
