"""VGG-small for CIFAR-10 (BASELINE.json:8 — the reference's config 2 trained
a small VGG-style torch-nn convnet with sync allreduce DP).

bfloat16 compute / float32 params; NHWC; 3×3 conv stacks with max-pool,
GroupNorm instead of BatchNorm — no mutable batch statistics, so the module
stays a pure params->logits function (jit/shard_map-friendly, and immune to
the cross-replica BN-stats question sync DP would otherwise raise).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class VGGSmall(nn.Module):
    num_classes: int = 10
    widths: Sequence[int] = (64, 128, 256)
    convs_per_block: int = 2
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.compute_dtype)
        for width in self.widths:
            for _ in range(self.convs_per_block):
                x = nn.Conv(
                    width, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.compute_dtype,
                )(x)
                x = nn.GroupNorm(num_groups=32, dtype=self.compute_dtype)(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        # flatten, as in classic VGG: the spatial arrangement carries class
        # evidence that a global average pool would integrate away
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512, dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)
