"""Autoregressive decoding for :class:`TransformerLM`.

The reference is a training harness with no sampling path; users of a
trained LM still expect one. Two recipes, same sampling semantics:

- :func:`generate` — the exact fixed-buffer recipe: each step re-runs
  the full forward on a FIXED ``(1, max_len)`` token buffer, so jit
  compiles exactly once, and causal attention guarantees the logits at
  the current position are unaffected by whatever garbage sits beyond
  it (pinned by a test that varies the suffix). Cost is O(T²·d) per
  token — fine for demos and spot-checks, and the only recipe that
  slides the window past ``max_len`` (positions shift; documented
  truncation, not an error).
- :func:`generate_fast` — the serving recipe: ``decode=True`` clones
  the model into cached-attention chunk steps (K/V cache in the
  ``cache`` collection, ``TransformerLM.decode``) and the whole request
  runs inside one jit: the PROMPT enters the cache as a single
  matmul-bound chunk (:func:`_prefill_decode_scan`, ``head=False`` so
  only one row per batch row pays the vocab projection), then each
  GENERATED token is a ``lax.scan`` tick — no per-token host
  round-trips, one device fetch at the end. Cache position clocks are
  PER ROW, so mixed-length batches prefill every row's entire prompt
  in the same dense pass and every tick is pure sampling — the one
  kernel serves equal and unequal prompts alike. Prefill/scan lengths
  and batch rows are bucketed to powers of two so compiles stay
  logarithmic. Greedy output is pinned equal to :func:`generate`'s;
  sampled output is pinned equal at the same seed (every kernel
  indexes the same per-generated-token key stream).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnums=0)
def _apply(model, params, x):
    """Module-level jit keyed on the (hashable) flax module: repeated
    generate() calls with the same model hit one compile cache entry
    instead of retracing per call."""
    return model.apply({"params": params}, x)


def generate(
    model,
    params,
    prompt: Sequence[int],
    steps: int,
    temperature: float = 0.0,
    seed: int = 0,
    rng: Optional[jax.Array] = None,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    min_p: Optional[float] = None,
) -> list:
    """Continue ``prompt`` by ``steps`` tokens; returns prompt + new.

    ``temperature=0``: greedy argmax (deterministic). ``>0``: softmax
    sampling at that temperature, reproducible from ``seed`` (or pass an
    explicit ``rng`` key), optionally restricted to the ``top_k``
    highest-scoring tokens, the ``top_p`` probability nucleus, and/or
    the ``min_p`` band (tokens at least min_p times as probable as the
    best). Filter order is fixed: temperature scales first, then
    **top-k → min-p → top-p** (see :func:`_filter_logits`). Because
    top-p's cumulative mass is computed over the distribution
    renormalized AFTER the top-k/min-p masks, combining ``top_p`` with
    ``min_p`` diverges from HuggingFace-style warper pipelines (which
    evaluate each filter on the distribution as earlier warpers left
    it, with min-p ordered differently): the nucleus here can admit
    tokens an HF pipeline at the same settings would drop, and vice
    versa. Each filter alone matches the standard definition. ``model``
    must be the dense single-device configuration (``seq_axis=None``).
    """
    _validate(model, prompt, temperature, top_k, top_p, min_p=min_p)
    length = model.max_len
    buf = jnp.zeros((1, length), jnp.int32)
    buf = buf.at[0, : len(prompt)].set(jnp.asarray(prompt, jnp.int32))
    pos = len(prompt)
    if rng is None:
        rng = jax.random.key(seed)
    keys = jax.random.split(rng, max(steps, 1))
    toks = [int(t) for t in prompt]
    for i in range(steps):
        if pos >= length:  # slide the window (positions shift — see doc)
            buf = jnp.roll(buf, -1, axis=1)
            pos = length - 1
        logits = _apply(model, params, buf)[0, pos - 1]
        if temperature > 0:
            scaled = _filter_logits(
                logits / temperature, top_k, top_p, min_p
            )
            nxt = jax.random.categorical(keys[i], scaled)
        else:
            nxt = jnp.argmax(logits)
        buf = buf.at[0, pos].set(nxt)
        toks.append(int(nxt))
        pos += 1
    return toks


def _validate(
    model, prompt, temperature, top_k=None, top_p=None, eos_id=None,
    min_p=None,
):
    """Shared argument checks for every decoding entry point."""
    if eos_id is not None and not 0 <= eos_id < model.vocab_size:
        raise ValueError(
            f"eos_id={eos_id} outside [0, vocab_size={model.vocab_size})"
        )
    if getattr(model, "seq_axis", None) is not None:
        raise ValueError(
            "generation runs the dense model; clone(seq_axis=None) first"
        )
    max_len = getattr(model, "max_len", None)  # RNN LMs have no cap
    if len(prompt) < 1 or (max_len is not None and len(prompt) > max_len):
        raise ValueError(
            f"prompt of {len(prompt)} tokens must be in [1, "
            f"max_len={max_len}]"
        )
    if temperature < 0:
        raise ValueError(f"temperature={temperature} must be >= 0")
    if top_k is not None and not 1 <= top_k <= model.vocab_size:
        raise ValueError(
            f"top_k={top_k} must be in [1, vocab_size={model.vocab_size}]"
        )
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p={top_p} must be in (0, 1]")
    if min_p is not None and not 0.0 < min_p <= 1.0:
        raise ValueError(f"min_p={min_p} must be in (0, 1]")
    if (
        (top_k is not None or top_p is not None or min_p is not None)
        and temperature == 0
    ):
        raise ValueError(
            "top_k/top_p/min_p shape the SAMPLING distribution; "
            "temperature=0 is greedy argmax, which they cannot affect — "
            "set temperature > 0"
        )
    bad = [t for t in prompt if not 0 <= int(t) < model.vocab_size]
    if bad:
        raise ValueError(
            f"prompt tokens {bad} outside [0, vocab_size="
            f"{model.vocab_size}) — XLA would silently clamp the "
            "embedding lookup"
        )


def _filter_logits(logits, top_k, top_p, min_p=None):
    """Mask logits outside the top-k set / the top-p nucleus / the
    min-p band to -inf (jit-safe, static shapes). The ONE filter both
    recipes share — what makes their sampled streams comparable at a
    fixed seed.

    top-p keeps the smallest prefix of probability-sorted tokens whose
    cumulative mass reaches ``top_p`` (the token that crosses the
    threshold is kept — standard nucleus rule), so at least one token
    always survives; ties at the top-k boundary keep every token equal
    to the k-th value (strictly-less masking). min-p keeps tokens whose
    probability is at least ``min_p`` times the maximum's — computed in
    logit space (``l >= l_max + log(min_p)``, softmax-free), on the
    post-temperature distribution like the other filters; the argmax
    always survives, and a traced ``min_p=0`` is exactly "keep all"
    (``log 0 = -inf``).
    """
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][-1]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if min_p is not None:
        # threshold vs the UNFILTERED max (the max survives every mask)
        floor = jnp.max(logits) + jnp.log(min_p)
        logits = jnp.where(logits < floor, -jnp.inf, logits)
    if top_p is not None:
        order = jnp.argsort(logits)[::-1]  # descending
        probs = jax.nn.softmax(logits[order])
        # mass STRICTLY BEFORE each token; the crossing token stays
        before = jnp.cumsum(probs) - probs
        keep_sorted = before < top_p
        keep = jnp.zeros_like(keep_sorted).at[order].set(keep_sorted)
        logits = jnp.where(keep, logits, -jnp.inf)
    return logits


@functools.lru_cache(maxsize=32)
def _cache_shapes(dec, batch):
    """Shape inference for a decode-mode model's ``cache`` collection —
    host-side ShapeDtypeStructs only, so caching them pins no device
    memory (and no parameter initialization ever executes). ``batch`` is
    the decode batch (1 for generate_fast, beam width for beam_search)."""
    return jax.eval_shape(
        dec.init, jax.random.key(0), jnp.zeros((batch, 1), jnp.int32)
    )["cache"]


def _zero_cache(dec, batch=1, sharding_fn=None):
    """Fresh all-zeros cache per call: the arrays die with the request
    instead of being pinned in an lru slot (zeros are cheap; the traced
    init shape inference is the part worth caching). ``sharding_fn``
    (leaf ShapeDtypeStruct -> Sharding, generate_tp's head split): each
    leaf is BORN in its placement — a transient full cache on one
    device would defeat exactly the too-big-for-one-chip case."""
    return jax.tree.map(
        lambda s: jnp.zeros(
            s.shape, s.dtype,
            device=None if sharding_fn is None else sharding_fn(s),
        ),
        _cache_shapes(dec, batch),
    )


def generate_fast(
    model,
    params,
    prompt: Sequence[int],
    steps: int,
    temperature: float = 0.0,
    seed: int = 0,
    rng: Optional[jax.Array] = None,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    weights_dtype=None,
    eos_id: Optional[int] = None,
    min_p: Optional[float] = None,
) -> list:
    """KV-cached generation: continue ``prompt`` by ``steps`` tokens.

    Same sampling semantics as :func:`generate` (greedy at
    ``temperature=0``, else softmax sampling keyed per generated token,
    with the same fixed **top-k → min-p → top-p** filter order and the
    same HF divergence when ``top_p`` and ``min_p`` combine — see
    :func:`generate`), but compiled as one program — the serving path (the N=1 row of the
    chunked-prefill kernel: one dense pass for the prompt, one scan
    tick per generated token). Narrower model support than
    :func:`generate`, which handles anything dense ``apply`` can run:

    - no window sliding — ``len(prompt) + steps`` must fit in
      ``model.max_len``;
    - MoE models are rejected (``generate`` runs them via the
      dense-reference FFN; the cache path does not);
    - ``attn_impl`` is overridden to the cached XLA path, so for a
      flash-attention model the greedy-equality pin versus
      :func:`generate` holds only up to that kernel's numerics.
    """
    _validate(model, prompt, temperature, top_k, top_p, eos_id, min_p)
    if steps <= 0:
        return [int(t) for t in prompt]  # prompt length already validated
    if rng is None:
        rng = jax.random.key(seed)
    if weights_dtype is not None:
        params = cast_weights(params, weights_dtype)
    return _truncate_at_eos(
        _generate_rows(
            model, params, [prompt], steps, temperature, [rng],
            top_k, top_p, min_p=min_p,
        )[0],
        len(prompt), eos_id,
    )


def _bucket(n, cap):
    """The ONE power-of-two bucket rule every decode dimension uses
    (scan/prefill/generation lengths, batch rows): smallest power of two
    >= n, capped at ``cap`` so cache writes and positional gathers stay
    in bounds (enlarging past the cap would clamp silently — don't)."""
    b = 1
    while b < n:
        b *= 2
    return min(b, cap)


def _decode_setup(model, prompt, steps):
    """Shared serving setup: the overflow contract (ONE copy) and the
    decode-mode clone."""
    total = len(prompt) + steps
    if total > model.max_len:
        raise ValueError(
            f"prompt+steps = {total} exceeds max_len={model.max_len}; "
            "the KV cache cannot slide — use generate() for overflow"
        )
    return model.clone(
        decode=True, remat=False, seq_axis=None, attn_impl="xla"
    )


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _beam_scan(
    model, pre_bucket, gen_len, beam, eos_id,
    params, cache1, pre_buf, p_len, limit,
):
    """Fixed-budget beam search with chunked prefill, as ONE program.

    The prompt runs ONCE at batch 1 (``head=False`` dense chunk — the
    same prefill recipe as :func:`_prefill_decode_scan`); the filled
    cache then broadcasts across the beam batch dimension (every beam
    shares the prompt by definition), and only EXPANSIONS tick. Each
    survivor-selection step REORDERS the caches by parent beam with a
    plain gather (the standard recipe — cheap relative to the matmuls).
    Expansion 0 scores candidates from the prefill logits with beam 0
    alone live ([0, -inf, ...]), picking the ``beam`` best distinct
    continuations — the textbook initialization.

    ``eos_id`` (static; None = fixed-length): a finished beam's only
    allowed continuation is another ``eos_id`` at zero cost, freezing
    its score while the budget runs out. ``limit`` (traced, = steps):
    bucket-overrun expansions at or past the budget freeze EVERYTHING —
    parents, scores, done — so the final ranking reflects exactly
    ``steps`` expansions (a beam ranking must be frozen, not just
    ignored). Bucket-overrun cache writes may clamp at the max_len
    boundary: safe because they strictly follow the last kept expansion
    and the cache dies with this call.

    Returns ``(gen_tokens (beam, gen_len), scores (beam,))`` — the
    caller prepends the prompt and argmaxes over scores.
    """
    vocab = model.vocab_size

    def gather_beams(tree, parents):
        return jax.tree.map(
            lambda a: a[parents]
            if getattr(a, "ndim", 0) >= 1 and a.shape[0] == beam
            else a,
            tree,
        )

    def expand(logp, scores, done):
        """Score (beam, vocab) candidates and pick the survivors."""
        cand = scores[:, None] + logp
        if eos_id is not None:
            # finished beams may only emit eos again, at zero cost
            pad_row = jnp.full((vocab,), -jnp.inf).at[eos_id].set(0.0)
            cand = jnp.where(
                done[:, None], scores[:, None] + pad_row[None, :], cand
            )
        top_scores, top_idx = jax.lax.top_k(cand.reshape(-1), beam)
        return top_scores, top_idx // vocab, (
            top_idx % vocab
        ).astype(jnp.int32)

    # --- prefill at batch 1, broadcast the cache across the beams
    hidden, mut = model.clone(head=False).apply(
        {"params": params, "cache": cache1}, pre_buf, mutable=["cache"]
    )
    cache = _fix_cache_indices(mut["cache"], p_len)
    cache = jax.tree.map(
        lambda a: jnp.repeat(a, beam, axis=0)
        if getattr(a, "ndim", 0) >= 1 and a.shape[0] == 1
        else a,
        cache,
    )
    logp0 = jax.nn.log_softmax(
        model.head_logits(params, hidden[:, p_len - 1])[0].astype(
            jnp.float32
        )
    )
    scores0 = jnp.full((beam,), -jnp.inf).at[0].set(0.0)
    done0 = jnp.zeros((beam,), bool)
    scores, parents, chosen = expand(
        jnp.broadcast_to(logp0, (beam, vocab)), scores0, done0
    )
    # no cache gather here: every row is still the identical broadcast
    # prefill cache, so gathering by parents is a value-level no-op XLA
    # cannot elide (it would copy the whole beam-wide K/V cache)
    toks = jnp.zeros((beam, gen_len), jnp.int32).at[:, 0].set(chosen)
    done = (
        (chosen == eos_id) if eos_id is not None else done0
    )

    def step(carry, e):
        cache, toks, scores, done, prev = carry
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            prev[:, None],
            mutable=["cache"],
        )
        cache = mut["cache"]
        logp = jax.nn.log_softmax(
            logits[:, 0].astype(jnp.float32), axis=-1
        )
        new_scores, parents, chosen = expand(logp, scores, done)
        frozen = e >= limit  # the steps budget is spent
        parents = jnp.where(frozen, jnp.arange(beam), parents)
        chosen = jnp.where(frozen, prev, chosen)
        scores = jnp.where(frozen, scores, new_scores)
        cache = gather_beams(cache, parents)
        toks = toks[parents].at[:, e].set(chosen)
        if eos_id is not None:
            done = jnp.where(
                frozen, done, done[parents] | (chosen == eos_id)
            )
        return (cache, toks, scores, done, chosen), None

    if gen_len > 1:
        (cache, toks, scores, done, _), _ = jax.lax.scan(
            step, (cache, toks, scores, done, chosen),
            jnp.arange(1, gen_len),
        )
    return toks, scores


def beam_search(
    model,
    params,
    prompt: Sequence[int],
    steps: int,
    beam_size: int = 4,
    eos_id: Optional[int] = None,
    weights_dtype=None,
) -> "tuple[list, float]":
    """Beam-search decoding over the KV-cached model: the highest
    log-probability continuation of ``prompt`` found with ``beam_size``
    beams and a fixed budget of ``steps`` expansions.

    Returns ``(tokens, score)`` — the best sequence (prompt included,
    truncated just past the first ``eos_id`` beyond the prompt when one
    was emitted) and its total log-probability (raw sum; no length
    penalty). ``beam_size=1`` is exactly greedy :func:`generate_fast`.
    Same model restrictions as :func:`generate_fast` (no MoE, fits in
    ``max_len``); with ``beam_size`` large enough to hold every partial
    hypothesis the search is exhaustive — pinned against brute-force
    enumeration in tests.
    """
    _validate(model, prompt, 0.0, eos_id=eos_id)
    if beam_size < 1:
        raise ValueError(f"beam_size={beam_size} must be >= 1")
    # beam_size > vocab_size is deliberately LEGAL: exhaustive search over
    # k steps needs beam_size >= vocab**(k-1) (the brute-force equivalence
    # test runs beam 25 over vocab 5). Surplus beams sit at -inf only
    # transiently — after step s there are vocab**s finite hypotheses, so
    # they fill in as the frontier widens and the final argmax never picks
    # a -inf row while any finite hypothesis exists.
    if steps <= 0:
        return [int(t) for t in prompt], 0.0
    if weights_dtype is not None:
        params = cast_weights(params, weights_dtype)
    dec = _decode_setup(model, prompt, steps)
    p0 = len(prompt)
    pre_bucket = _bucket(p0, model.max_len)
    gen_bucket = _bucket(steps, model.max_len)
    pre_buf = jnp.zeros((1, pre_bucket), jnp.int32)
    pre_buf = pre_buf.at[0, :p0].set(jnp.asarray(prompt, jnp.int32))
    toks, scores = _beam_scan(
        dec, pre_bucket, gen_bucket, beam_size, eos_id,
        params, _zero_cache(dec, 1), pre_buf,
        jnp.asarray(p0, jnp.int32),
        jnp.asarray(steps, jnp.int32),
    )
    best = int(jnp.argmax(scores))
    seq = [int(t) for t in prompt] + [
        int(t) for t in jax.device_get(toks[best, :steps])
    ]
    return _truncate_at_eos(seq, len(prompt), eos_id), float(scores[best])


def _prefill_chunk(
    model, params, cache0, pre_buf, p_lens, clock0=0, with_head=True
):
    """The ONE padded-prefill recipe (shared by the batch decode kernel,
    the Server's admission prefill, and the speculative decoder): run
    the prompt buffer as a dense ``head=False`` chunk, undo the padded
    rows' counter over-advance (:func:`_fix_cache_indices`, vector
    ``p_lens`` — per-row clocks land at each row's OWN prompt length),
    and project each row's last PROMPT hidden state through the vocab
    head — never materializing (N, pre_bucket, V) f32 logits.

    ``clock0`` (scalar): the position ``cache0`` is already filled to —
    0 for a fresh cache; the prefix length when ``cache0`` is a
    prefix-cache template (the Server's shared-prefix admission). The
    chunk appends at the cache's own per-row clocks either way; clock0
    only enters the counter fix-up (global position = clock0 + local
    length) — ``p_lens`` stays LOCAL to this chunk, including the
    last-hidden gather.

    Returns ``(cache, last_logits)`` — last_logits is (N, V), the
    distribution for each row's first generated token; ``with_head=
    False`` skips the vocab projection and returns ``(cache, None)``
    for callers that only want the filled cache (prefix templates, the
    speculative draft's admission)."""
    hidden, mut = model.clone(head=False).apply(
        {"params": params, "cache": cache0}, pre_buf, mutable=["cache"]
    )
    cache = _fix_cache_indices(mut["cache"], clock0 + p_lens)
    if not with_head:
        return cache, None
    h_last = jax.vmap(lambda h, n: h[n - 1])(hidden, p_lens)  # (N, d)
    return cache, model.head_logits(params, h_last)


def _fix_cache_indices(cache, p_len):
    """Rewrite every position-counter leaf (per-block ``cache_index``,
    the LM's ``pos_index``) to ``p_len`` after a PADDED prefill chunk:
    the chunk ran at the bucket length, so the counters over-advanced
    and the slots in ``[p_len, bucket)`` hold padding garbage. Decode
    resumes at ``p_len`` and overwrites slot ``i`` in the same tick
    whose mask first exposes it (``j <= i``), so the garbage is never
    attended — pinned by the fast==slow equality tests.

    Counter leaves are per-row (B,); ``p_len`` may be a scalar (every
    row at the same position) or a (B,) vector (per-row prefill — each
    row's clock lands at ITS OWN prompt length)."""
    import jax.tree_util as jtu

    def fix(path, leaf):
        name = getattr(path[-1], "key", None) if path else None
        if name in ("cache_index", "pos_index"):
            return jnp.broadcast_to(
                jnp.asarray(p_len, leaf.dtype), leaf.shape
            )
        return leaf

    return jtu.tree_map_with_path(fix, cache)


def _sample_rows(
    logits, row_keys, greedy, top_k, use_top_p, temp, top_p, min_p=None,
):
    """The ONE sampling rule both decode kernels share: greedy argmax,
    or temperature scale -> :func:`_filter_logits` -> categorical, per
    row of ``logits`` (N, V) with ``row_keys`` (N,). A change here is a
    change to BOTH kernels — which is what keeps the prefill==tick
    parity pinnable.

    ``temp``/``top_p``/``min_p`` may be scalars (every row the same
    rule — the batch entry points) or (N,) vectors (per-row rules — the
    serving path's per-request overrides). Row n's math is identical
    either way, which is what keeps a mixed-rule Server row bit-equal
    to its solo call. ``min_p=None`` omits the min-p mask entirely
    (kernels without the knob compile the exact program they always
    did)."""
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    n = logits.shape[0]
    temps = jnp.broadcast_to(jnp.asarray(temp, jnp.float32), (n,))
    tops = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (n,))
    if min_p is None:
        scaled = jax.vmap(
            lambda l, t, p: _filter_logits(
                l / t, top_k, p if use_top_p else None
            )
        )(logits, temps, tops)
    else:
        mps = jnp.broadcast_to(jnp.asarray(min_p, jnp.float32), (n,))
        scaled = jax.vmap(
            lambda l, t, p, mp: _filter_logits(
                l / t, top_k, p if use_top_p else None, mp
            )
        )(logits, temps, tops, mps)
    return jax.vmap(jax.random.categorical)(
        row_keys, scaled
    ).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5, 6))
def _prefill_decode_scan(
    model, pre_bucket, gen_len, greedy, top_k, use_top_p, use_min_p,
    params, cache0, pre_buf, p_lens, keys, temp, top_p, min_p,
):
    """Chunked-prefill decoding, per-row clocks: EVERY row's ENTIRE
    prompt enters the cache in one dense pass (matmul-bound — one chunk
    instead of p_len latency-bound ticks), each row's position counters
    land at its OWN ``p_lens[n]``, and then every scan tick is pure
    sampling for every row — ticks == gen_len, the minimum any shared
    program can spend, for equal AND mixed prompt lengths alike (the
    equal-length batch is just the all-rows-equal special case).

    ``pre_buf`` is (N, pre_bucket) — prompts left-aligned, padding
    arbitrary; padded rows' cache writes and counter over-advance are
    undone by :func:`_fix_cache_indices` (vector ``p_lens``). The
    prefill pass runs the model with ``head=False`` and projects ONE
    hidden row per batch row through the vocab head (each row's own
    ``p_lens[n]-1`` position) — never materializing (N, pre_bucket, V)
    f32 logits. Token j of every row is sampled with ``keys[:, j]`` —
    the per-generated-token stream contract that pins every batched row
    equal to its solo :func:`generate_fast` call. ``keys`` is
    pre-padded to exactly ``gen_len`` columns by the caller.

    Bucket-overrun ticks (t >= a row's remaining budget) may clamp
    their cache writes and position gathers at the max_len boundary:
    safe because (a) they strictly FOLLOW the last kept sample in the
    sequential scan, and (b) the cache dies with this call — nothing
    ever reads it after the scan. Reusing the returned cache would
    break invariant (b).
    """
    cache, last = _prefill_chunk(model, params, cache0, pre_buf, p_lens)

    mp = min_p if use_min_p else None
    tok0 = _sample_rows(
        last, keys[:, 0], greedy, top_k, use_top_p, temp, top_p, mp
    )

    def step(carry, t):
        cache, prev = carry
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            prev[:, None],
            mutable=["cache"],
        )
        nxt = _sample_rows(
            logits[:, 0], keys[:, t + 1], greedy, top_k, use_top_p,
            temp, top_p, mp,
        )
        return (mut["cache"], nxt), nxt

    if gen_len > 1:
        (_, _), rest = jax.lax.scan(
            step, (cache, tok0), jnp.arange(gen_len - 1)
        )
        rest = rest.swapaxes(0, 1)  # (N, gen_len-1)
        return jnp.concatenate([tok0[:, None], rest], axis=1)
    return tok0[:, None]


def generate_batch(
    model,
    params,
    prompts: "Sequence[Sequence[int]]",
    steps: int,
    temperature: float = 0.0,
    seed: int = 0,
    rng: Optional[jax.Array] = None,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    weights_dtype=None,
    eos_id: Optional[int] = None,
    min_p: Optional[float] = None,
) -> "list[list]":
    """Continue N prompts by ``steps`` tokens each, in ONE compiled
    decode scan over a (N, ...) K/V cache — the batched serving path.

    Row ``n`` is pinned exactly equal to
    ``generate_fast(..., prompts[n], rng=fold_in(rng, n))``: per-row
    cache clocks prefill every row's ENTIRE prompt in one dense pass
    (equal or mixed lengths), each row draws from its own key stream,
    and the scan spends exactly bucket(steps) sampling ticks. Same
    model restrictions as :func:`generate_fast`.
    """
    return _batch_impl(
        model, params, prompts, steps, temperature, seed, rng,
        top_k, top_p, weights_dtype=weights_dtype, eos_id=eos_id,
        min_p=min_p,
    )


def cast_weights(params, dtype):
    """Cast floating-point param leaves for serving (int leaves pass
    through). Decode is HBM-bandwidth-bound, so bf16 weights halve the
    at-rest param memory AND the bytes the scan streams per token —
    guaranteed by construction here (done once, outside the compiled
    scan), rather than hoped for from XLA hoisting the per-step
    compute-dtype cast out of the loop. For a float32-compute model
    this changes numerics (weights quantized to bf16); for the default
    bf16-compute models the kernel already computed in bf16 and only
    the storage changes."""
    return jax.tree.map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(a.dtype, jnp.floating) else a,
        params,
    )


def _truncate_at_eos(seq, p_len, eos_id):
    """Cut a generated row just past the first eos beyond the prompt —
    the same rule beam_search applies (the ONE truncation convention)."""
    if eos_id is None:
        return seq
    for i in range(p_len, len(seq)):
        if seq[i] == eos_id:
            return seq[: i + 1]
    return seq


def _batch_impl(
    model, params, prompts, steps, temperature, seed, rng, top_k, top_p,
    cache_sharding_fn=None, params_placer=None, weights_dtype=None,
    eos_id=None, min_p=None,
):
    """The ONE prologue generate_batch and generate_tp share: validation,
    trivial early returns, the per-row rng derivation (fold_in — the
    half of the pinned-parity contract that lives outside the kernel),
    then :func:`_generate_rows`. ``params_placer`` (generate_tp's
    Megatron device_put) runs only AFTER validation passes — a rejected
    request must not pay a whole-model transfer."""
    if len(prompts) == 0:
        return []
    for p in prompts:
        _validate(model, p, temperature, top_k, top_p, eos_id, min_p)
    if steps <= 0:
        return [[int(t) for t in p] for p in prompts]
    if weights_dtype is not None:
        params = cast_weights(params, weights_dtype)
    if params_placer is not None:
        params = params_placer(params)
    if rng is None:
        rng = jax.random.key(seed)
    # one fold_in+split dispatch for all rows, not N
    rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
        jnp.arange(len(prompts))
    )
    rows = _generate_rows(
        model, params, prompts, steps, temperature, rngs, top_k, top_p,
        cache_sharding_fn=cache_sharding_fn, min_p=min_p,
    )
    return [
        _truncate_at_eos(r, len(p), eos_id)
        for r, p in zip(rows, prompts)
    ]


def _generate_rows(
    model, params, prompts, steps, temperature, rngs, top_k, top_p,
    cache_sharding_fn=None, min_p=None,
):
    """The ONE wrapper both serving entry points share: bucket the
    prefill and generation lengths (power-of-two, capped at max_len)
    AND the row count (power-of-two — every distinct N would otherwise
    compile its own program; pad rows are dummy prompts whose outputs
    are sliced away), build the token buffer host-side in one transfer,
    split each row's key stream from its own rng (values identical to a
    per-row ``split(rng_n, steps)``), pad keys to the bucket, run the
    kernel, and slice each row to its own prompt+steps.

    ONE kernel for every batch shape (:func:`_prefill_decode_scan`):
    per-row cache clocks let each row's ENTIRE prompt prefill in the
    single dense pass — equal and mixed lengths alike — so the scan
    spends exactly bucket(steps) latency-bound ticks, all of them
    sampling."""
    n = len(prompts)
    dec = _decode_setup(model, max(prompts, key=len), steps)
    nb, pre_bucket, gen_bucket, pre_buf, p_lens, keys = _prep_rows(
        prompts, steps, rngs, model.max_len
    )
    gen = _prefill_decode_scan(
        dec, pre_bucket, gen_bucket, temperature == 0.0, top_k,
        top_p is not None, min_p is not None,
        params, _zero_cache(dec, nb, sharding_fn=cache_sharding_fn),
        pre_buf, p_lens, keys,
        jnp.asarray(max(temperature, 1e-9), jnp.float32),
        jnp.asarray(1.0 if top_p is None else top_p, jnp.float32),
        jnp.asarray(0.0 if min_p is None else min_p, jnp.float32),
    )
    host = jax.device_get(gen)
    return [
        [int(t) for t in prompts[i]] + [int(t) for t in host[i, :steps]]
        for i in range(n)
    ]


def _prep_rows(prompts, steps, rngs, max_len_cap):
    """The batching prep every decode family shares (transformer KV
    kernel AND the LSTM carry kernel — rnn_sampling imports this): the
    power-of-two buckets, the left-aligned prompt buffer, per-row true
    lengths (pad rows are DISCARDED 1-token dummies), and the per-row
    key streams (``split(rng_n, steps)``) padded to the generation
    bucket by repeating the last key (only discarded bucket-overrun
    ticks ever index the padding). The invariants here ARE the
    batch==solo parity contract; keep them in one place.

    ``rngs=None`` (the greedy speculative path): skip the key streams
    and return ``keys=None`` — everything else is identical."""
    import numpy as np

    if isinstance(rngs, (list, tuple)):
        rngs = jnp.stack(list(rngs))
    n = len(prompts)
    nb = _bucket(n, 1 << 30)  # rows have no cap — pad rows are sliced away
    if rngs is not None and nb > n:
        # pad rows reuse row 0's rng; outputs are discarded
        rngs = jnp.concatenate(
            [rngs, jnp.repeat(rngs[:1], nb - n, axis=0)]
        )
    pre_bucket = _bucket(max(len(q) for q in prompts), max_len_cap)
    gen_bucket = _bucket(steps, max_len_cap)
    keys = None
    if rngs is not None:
        keys = jax.vmap(
            lambda k: jax.random.split(k, max(steps, 1))
        )(rngs)
        if keys.shape[1] < gen_bucket:
            keys = jnp.concatenate(
                [keys,
                 jnp.repeat(
                     keys[:, -1:], gen_bucket - keys.shape[1], axis=1
                 )],
                axis=1,
            )
    pre_host = np.zeros((nb, pre_bucket), np.int32)
    for i, q in enumerate(prompts):
        pre_host[i, : len(q)] = q
    p_lens = np.ones((nb,), np.int32)
    p_lens[:n] = [len(q) for q in prompts]
    return (
        nb, pre_bucket, gen_bucket, jnp.asarray(pre_host),
        jnp.asarray(p_lens), keys,
    )


def generate_tp(
    model,
    params,
    prompts: "Sequence[Sequence[int]]",
    steps: int,
    topo=None,
    temperature: float = 0.0,
    seed: int = 0,
    rng: Optional[jax.Array] = None,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    weights_dtype=None,
    eos_id: Optional[int] = None,
    min_p: Optional[float] = None,
) -> "list[list]":
    """Tensor-parallel batched decode: the SAME compiled kernel as
    :func:`generate_batch`, partitioned by GSPMD across a mesh with a
    ``tp`` axis — Megatron serving for models too large (or too slow)
    for one chip.

    No decode-specific collectives are written anywhere: params commit
    to the strict Megatron shardings
    (:func:`mpit_tpu.parallel.tensor.tp_state_specs` — column/row split
    Dense kernels), the K/V caches commit head-sharded over ``tp``, and
    XLA's partitioner inserts the per-token psums when it compiles
    :func:`_prefill_decode_scan` for the committed layouts. Same kernel,
    same key streams as :func:`generate_batch` — token-identical up to
    partitioned-reduction numerics (row-sharded matmuls accumulate via
    psum in a different order, so a near-tie argmax can flip in the
    last ulps on real hardware; exact equality is pinned on the virtual
    CPU mesh).

    ``topo``: a topology whose mesh has a ``tp`` axis (e.g.
    ``mpit_tpu.init(axis_names=("dp", "tp"), mesh_shape=(1, T))``);
    defaults to the current one. ``num_heads`` (and d_model/d_ff) must
    divide by the tp extent. Pre-sharded params (a tp trainer's
    ``state.params``) pass through unchanged; replicated or host params
    are placed once here.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from mpit_tpu.comm.topology import topology as _current_topology
    from mpit_tpu.parallel.tensor import (
        check_tp_divisibility,
        tp_state_specs,
    )

    topo = topo if topo is not None else _current_topology()
    mesh = topo.mesh
    if "tp" not in mesh.axis_names:
        raise ValueError(
            f"generate_tp needs a mesh with a 'tp' axis; got "
            f"{mesh.axis_names}"
        )
    check_tp_divisibility(model, int(mesh.shape["tp"]))

    def place_params(p):
        return jax.device_put(
            p,
            jax.tree.map(
                lambda s: NamedSharding(mesh, s), tp_state_specs(p),
                is_leaf=lambda v: isinstance(v, P),
            ),
        )

    # cached K/V are (batch, decode_len, heads, head_dim): heads ride tp,
    # matching the qkv column split so cache writes stay local; the
    # index/position scalars replicate. Each cache leaf is BORN in this
    # placement (see _zero_cache) — never whole on one device.
    def cache_sharding(leaf):
        spec = P(None, None, "tp", None) if len(leaf.shape) == 4 else P()
        return NamedSharding(mesh, spec)

    return _batch_impl(
        model, params, prompts, steps, temperature, seed, rng,
        top_k, top_p, cache_sharding_fn=cache_sharding,
        params_placer=place_params, weights_dtype=weights_dtype,
        eos_id=eos_id, min_p=min_p,
    )
