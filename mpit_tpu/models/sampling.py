"""Autoregressive decoding for :class:`TransformerLM` — eval utility.

The reference is a training harness with no sampling path; users of a
trained LM still expect one. This is the exact, compile-once recipe —
NOT a serving path (no KV cache): each step re-runs the full forward on
a FIXED ``(1, max_len)`` token buffer, so jit compiles exactly once, and
causal attention guarantees the logits at the current position are
unaffected by whatever garbage sits beyond it (pinned by a test that
varies the suffix). Cost is O(T²·d) per token — fine for demos and eval
perplexity spot-checks, deliberately not optimized further until a use
case needs it.

When the context outgrows ``max_len`` the window slides: absolute
positions shift, so generation continues from the model's view of the
last ``max_len − 1`` tokens (documented truncation, not an error).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnums=0)
def _apply(model, params, x):
    """Module-level jit keyed on the (hashable) flax module: repeated
    generate() calls with the same model hit one compile cache entry
    instead of retracing per call."""
    return model.apply({"params": params}, x)


def generate(
    model,
    params,
    prompt: Sequence[int],
    steps: int,
    temperature: float = 0.0,
    seed: int = 0,
    rng: Optional[jax.Array] = None,
) -> list:
    """Continue ``prompt`` by ``steps`` tokens; returns prompt + new.

    ``temperature=0``: greedy argmax (deterministic). ``>0``: softmax
    sampling at that temperature, reproducible from ``seed`` (or pass an
    explicit ``rng`` key). ``model`` must be the dense single-device
    configuration (``seq_axis=None``).
    """
    if getattr(model, "seq_axis", None) is not None:
        raise ValueError(
            "generate() runs the dense model; clone(seq_axis=None) first"
        )
    if not 0 < len(prompt) <= model.max_len:
        raise ValueError(
            f"prompt of {len(prompt)} tokens must be in [1, "
            f"max_len={model.max_len}]"
        )
    if temperature < 0:
        raise ValueError(f"temperature={temperature} must be >= 0")
    bad = [t for t in prompt if not 0 <= int(t) < model.vocab_size]
    if bad:
        raise ValueError(
            f"prompt tokens {bad} outside [0, vocab_size="
            f"{model.vocab_size}) — XLA would silently clamp the "
            "embedding lookup"
        )
    length = model.max_len
    buf = jnp.zeros((1, length), jnp.int32)
    buf = buf.at[0, : len(prompt)].set(jnp.asarray(prompt, jnp.int32))
    pos = len(prompt)
    if rng is None:
        rng = jax.random.key(seed)
    keys = jax.random.split(rng, max(steps, 1))
    toks = [int(t) for t in prompt]
    for i in range(steps):
        if pos >= length:  # slide the window (positions shift — see doc)
            buf = jnp.roll(buf, -1, axis=1)
            pos = length - 1
        logits = _apply(model, params, buf)[0, pos - 1]
        if temperature > 0:
            nxt = jax.random.categorical(keys[i], logits / temperature)
        else:
            nxt = jnp.argmax(logits)
        buf = buf.at[0, pos].set(nxt)
        toks.append(int(nxt))
        pos += 1
    return toks
