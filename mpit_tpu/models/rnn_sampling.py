"""Autoregressive decoding for :class:`~mpit_tpu.models.lstm.LSTMLM`.

The RNN analogue of the transformer serving path
(:mod:`mpit_tpu.models.sampling`): the reference's PTB LSTM (BASELINE
config 5) is a headline training family, and a trained LM deserves a
sampling tier. Same architecture as the transformer kernel, with the
carry replacing the KV cache:

- the PROMPT enters in ONE compiled ``nn.RNN`` pass (matmul-bound; the
  per-layer carries land at each row's OWN prompt length via
  ``seq_lengths`` — the RNN-native equivalent of per-row cache clocks,
  so mixed-length batches prefill fully too);
- each GENERATED token is a one-step carry update inside a ``lax.scan``
  — O(H²) per token, no re-reading the history;
- prompt/generation lengths and batch rows bucket to powers of two
  (compiles stay logarithmic), token j of every row samples with key j
  of that row's own stream (``fold_in(rng, row)``) — the same
  per-generated-token contract that pins every batched row equal to a
  solo call, and both pinned equal to the full-forward slow reference.

Shares the sampling rule (:func:`sampling._sample_rows`), filters,
validation, eos truncation, and ``weights_dtype`` with the transformer
path — one convention across model families.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from mpit_tpu.models import sampling


def _rnn_prefill(model, params, cache0, pre_buf, p_lens, with_head=True):
    """The ONE RNN padded-prefill recipe (the carry analogue of
    :func:`sampling._prefill_chunk`, shared by the batch kernel and the
    RNNServer's admission/template prefills): the prompt buffer through
    one ``nn.RNN`` pass with ``seq_lengths`` freezing each row's carry
    at its OWN true length, then the vocab head on each row's last true
    position only. ``with_head=False`` skips the projection and returns
    ``(cache, None)`` (prefix templates)."""
    hidden, mut = model.clone(head=False).apply(
        {"params": params, "cache": cache0}, pre_buf,
        seq_lengths=p_lens, mutable=["cache"],
    )
    if not with_head:
        return mut["cache"], None
    h_last = jax.vmap(lambda h, n: h[n - 1])(hidden, p_lens)  # (N, H)
    return mut["cache"], model.head_logits(params, h_last)  # (N, V)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5, 6))
def _rnn_prefill_decode_scan(
    model, pre_bucket, gen_len, greedy, top_k, use_top_p, use_min_p,
    params, cache0, pre_buf, p_lens, keys, temp, top_p, min_p,
):
    """One program: prompt pass (carries frozen at each row's own
    length), head on each row's last prompt position only, then
    ``gen_len`` one-token ticks — every tick pure sampling for every
    row."""
    cache, last = _rnn_prefill(model, params, cache0, pre_buf, p_lens)
    mp = min_p if use_min_p else None
    tok0 = sampling._sample_rows(
        last, keys[:, 0], greedy, top_k, use_top_p, temp, top_p, mp
    )

    def step(carry, t):
        cache, prev = carry
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            prev[:, None],
            mutable=["cache"],
        )
        nxt = sampling._sample_rows(
            logits[:, 0], keys[:, t + 1], greedy, top_k, use_top_p,
            temp, top_p, mp,
        )
        return (mut["cache"], nxt), nxt

    if gen_len > 1:
        (_, _), rest = jax.lax.scan(
            step, (cache, tok0), jnp.arange(gen_len - 1)
        )
        rest = rest.swapaxes(0, 1)
        return jnp.concatenate([tok0[:, None], rest], axis=1)
    return tok0[:, None]


def generate_rnn(
    model,
    params,
    prompts,
    steps: int,
    temperature: float = 0.0,
    seed: int = 0,
    rng: Optional[jax.Array] = None,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    weights_dtype=None,
    eos_id: Optional[int] = None,
    min_p: Optional[float] = None,
):
    """Continue prompt(s) by ``steps`` tokens with a carry-decode LSTM.

    ``prompts`` is either one prompt (a flat sequence of ints — returns
    one token list, the :func:`sampling.generate_fast` shape) or a list
    of prompts (returns a list of rows, the
    :func:`sampling.generate_batch` shape; row n pinned equal to its
    solo call at ``fold_in(rng, n)``). Unlike the transformer there is
    no ``max_len`` — an RNN carry has no positional horizon.

    Empty-input contract — this DIVERGES from the transformer path:
    :func:`sampling.generate_batch` maps ``[] -> []``, but here a flat
    empty ``prompts`` is ambiguous between "empty batch" and "one empty
    prompt", so the empty *list* ``[]`` is treated as one empty prompt
    and rejected by the shared validator (the same ``ValueError``
    ``generate``/``generate_fast`` raise on an empty prompt) — a caller
    bug cannot silently come back as ``[]``. The one unambiguous
    spelling of "empty batch" is the empty *tuple* ``prompts=()``,
    which returns ``[]``.
    """
    if isinstance(prompts, tuple) and len(prompts) == 0:
        return []  # explicit empty batch: the () spelling documented above
    solo = len(prompts) == 0 or not hasattr(prompts[0], "__len__")
    batch = [prompts] if solo else list(prompts)
    for q in batch:
        sampling._validate(
            model, q, temperature, top_k, top_p, eos_id, min_p
        )
    if steps <= 0:
        rows = [[int(t) for t in q] for q in batch]
        return rows[0] if solo else rows
    if weights_dtype is not None:
        params = sampling.cast_weights(params, weights_dtype)
    if rng is None:
        rng = jax.random.key(seed)
    if solo:
        rngs = rng[None] if hasattr(rng, "ndim") else jnp.stack([rng])
    else:
        rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
            jnp.arange(len(batch))
        )

    n = len(batch)
    # the shared prep (buckets, prompt buffer, pad rows, key streams) —
    # the SAME parity invariants as the transformer path, one copy.
    # RNNs have no positional horizon, so the length cap is unbounded.
    nb, pre_bucket, gen_bucket, pre_buf, p_lens, keys = (
        sampling._prep_rows(batch, steps, rngs, 1 << 30)
    )
    dec = model.clone(decode=True)
    gen = _rnn_prefill_decode_scan(
        dec, pre_bucket, gen_bucket, temperature == 0.0, top_k,
        top_p is not None, min_p is not None,
        params, sampling._zero_cache(dec, nb), pre_buf, p_lens, keys,
        jnp.asarray(max(temperature, 1e-9), jnp.float32),
        jnp.asarray(1.0 if top_p is None else top_p, jnp.float32),
        jnp.asarray(0.0 if min_p is None else min_p, jnp.float32),
    )
    host = jax.device_get(gen)
    rows = [
        sampling._truncate_at_eos(
            [int(t) for t in batch[i]] + [int(t) for t in host[i, :steps]],
            len(batch[i]), eos_id,
        )
        for i in range(n)
    ]
    return rows[0] if solo else rows
