"""LeNet-5-style MNIST model (BASELINE.json:7 — the reference `ptest.lua`
example trained a LeNet-style torch-nn model; SURVEY.md §2 comp. 6).

TPU notes: bfloat16 activations keep the convs on the MXU; params stay
float32 (master copy) and logits are cast back to float32 for a stable
softmax. NHWC layout throughout (XLA:TPU's native conv layout).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class LeNet(nn.Module):
    num_classes: int = 10
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.compute_dtype)
        x = nn.Conv(32, (5, 5), padding="SAME", dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME", dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)
