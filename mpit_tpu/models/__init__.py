"""Model zoo for the BASELINE workload configs.

Reference parity: the reference built models with torch ``nn`` inside its
example scripts (SURVEY.md §2 comps. 6, 8). Here they are flax modules,
bfloat16 compute / float32 params by default (MXU-friendly), one per
BASELINE.json config:

- :class:`LeNet`      — MNIST async-SGD (config 1)
- :class:`VGGSmall`   — CIFAR-10 sync DP (config 2)
- :class:`AlexNet`    — ImageNet Downpour (config 3)
- :class:`ResNet50`   — ImageNet sync allreduce stress (config 4)
- :class:`LSTMLM`     — PTB EASGD (config 5)
"""

from mpit_tpu.models.lenet import LeNet  # noqa: F401
from mpit_tpu.models.mlp import MLP  # noqa: F401
from mpit_tpu.models.sampling import (  # noqa: F401
    beam_search,
    generate,
    generate_batch,
    generate_fast,
    generate_tp,
)
from mpit_tpu.models.rnn_sampling import generate_rnn  # noqa: F401
from mpit_tpu.models.serving import RNNServer, Server  # noqa: F401
from mpit_tpu.models.speculative import (  # noqa: F401
    generate_speculative,
    generate_speculative_batch,
)

_REGISTRY = {"lenet": LeNet, "mlp": MLP}

# registry names (and aliases) whose model takes a stem= choice
# (conv | space_to_depth — mpit_tpu/ops/stem.py); the ONE list consumers
# (run driver, bench, sweep script) gate stem flags on
STEM_MODELS = ("resnet50", "resnet", "alexnet")

# registry names whose model takes a remat= flag (block rematerialization,
# jax.checkpoint via nn.remat) — same single-list contract as STEM_MODELS
REMAT_MODELS = ("resnet50", "resnet", "transformer")


def get_model(name: str, **kwargs):
    """Construct a model by registry name (lazily imported to keep startup
    light)."""
    global _REGISTRY
    name = name.lower()
    if name not in _REGISTRY:
        if name in ("vgg", "vgg_small", "vggsmall"):
            from mpit_tpu.models.vgg import VGGSmall

            _REGISTRY[name] = VGGSmall
        elif name == "alexnet":
            from mpit_tpu.models.alexnet import AlexNet

            _REGISTRY[name] = AlexNet
        elif name in ("resnet50", "resnet"):
            from mpit_tpu.models.resnet import ResNet50

            _REGISTRY[name] = ResNet50
        elif name == "transformer":
            from mpit_tpu.models.transformer import TransformerLM

            _REGISTRY[name] = TransformerLM
        elif name in ("lstm", "lstm_lm", "ptb_lstm"):
            from mpit_tpu.models.lstm import LSTMLM

            _REGISTRY[name] = LSTMLM
        else:
            raise ValueError(f"unknown model: {name!r}")
    return _REGISTRY[name](**kwargs)
