"""Speculative decoding: a small draft LM proposes, the target verifies.

Beyond-parity serving tier (the reference trains models and cannot
sample at all; this accelerates the sampling tier the framework already
has). Greedy speculative decoding with an EXACTNESS guarantee: the
output is token-identical to :func:`~mpit_tpu.models.sampling.
generate_fast`'s greedy decode of the target model alone, for ANY draft
model — a bad draft only costs speed, never correctness. That contract
is what makes the feature testable without hardware: the parity pin
runs on the CPU mesh (tests/test_speculative.py).

Why it is fast on TPU: plain decode is HBM-bound — every generated
token re-reads all target weights for one token's worth of FLOPs.
Here the target consumes the draft's k proposals (plus the pending
token) as ONE (k+1)-token chunk through the SAME cached-attention
kernel the chunked prefill uses (`transformer.py::_cached_attention`:
a T-token chunk appends at each row's clock and masks causally), so
one weight read scores k+1 positions. Accepted tokens advance the
clock; a rejection rewinds both caches by resetting the per-row
position counters (`sampling._fix_cache_indices`) — stale K/V beyond
the clock is overwritten by the next chunk before any mask exposes it,
the same invariant the padded prefill relies on.

The whole loop — draft scan, target chunk, acceptance, rewind — is one
jitted ``lax.while_loop``: zero host round-trips per token, one
compiled program per (prompt-bucket, steps-bucket, k).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from mpit_tpu.models import sampling


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _spec_loop(
    tgt, dft, k, pre_bucket, gen_bucket,
    t_params, d_params, t_cache, d_cache, pre_buf, p_len,
):
    """The compiled speculative loop (batch 1, greedy).

    Invariant at the top of each iteration: both caches hold exactly
    ``pos`` tokens' K/V (their counters say ``pos``), ``prev`` is the
    last accepted token — not yet fed to either model — and
    ``out[:n]`` holds the n tokens generated so far (so ``pos`` counts
    the prompt plus the first n-1 generated tokens).
    Each iteration emits m ∈ [1, k+1] tokens: the a accepted draft
    proposals, then one target token (the correction, or the bonus
    token the (k+1)-th chunk position yields when all k are accepted).
    """
    # prompt prefill, both models — the shared padded-prefill recipe
    # (sampling._prefill_chunk: dense chunk, counters fixed to the true
    # length, one head projection); the draft's prefill logits are
    # irrelevant, only its filled cache matters
    t_cache, t_last = sampling._prefill_chunk(
        tgt, t_params, t_cache, pre_buf, p_len
    )
    d_cache, _ = sampling._prefill_chunk(
        dft, d_params, d_cache, pre_buf, p_len
    )
    tok0 = jnp.argmax(t_last[0], -1).astype(jnp.int32)

    out0 = jnp.zeros((gen_bucket + k + 1,), jnp.int32)
    out0 = out0.at[0].set(tok0)

    def draft_step(carry, _):
        cache, prev = carry
        logits, mut = dft.apply(
            {"params": d_params, "cache": cache},
            prev[None, None], mutable=["cache"],
        )
        nxt = jnp.argmax(logits[0, 0], -1).astype(jnp.int32)
        return (mut["cache"], nxt), nxt

    def body(carry):
        t_cache, d_cache, prev, pos, n, it, out = carry
        # draft proposes k tokens; one extra feed of d_k keeps the
        # draft cache one step ahead so the bonus-token path below
        # leaves it holding everything before the new prev
        (d_cache, last_d), d = jax.lax.scan(
            draft_step, (d_cache, prev), None, length=k
        )
        (d_cache, _), _ = draft_step((d_cache, last_d), None)
        # target scores the (k+1)-chunk [prev, d_1..d_k] in one pass
        chunk = jnp.concatenate([prev[None], d])[None]  # (1, k+1)
        t_logits, t_mut = tgt.apply(
            {"params": t_params, "cache": t_cache},
            chunk, mutable=["cache"],
        )
        t_cache = t_mut["cache"]
        t = jnp.argmax(t_logits[0], -1).astype(jnp.int32)  # (k+1,)
        # a = accepted proposals; emitted tokens are exactly t[:a+1]
        # (t_i == d_i for i < a; t_a is the correction/bonus)
        match = jnp.cumprod((d == t[:k]).astype(jnp.int32))
        a = jnp.sum(match)
        m = a + 1
        out = jax.lax.dynamic_update_slice(out, t, (n,))
        # rewind both clocks to pos + m: everything before the new
        # prev (= t[a], written into out at n + m - 1) is accepted
        new_pos = pos + m
        t_cache = sampling._fix_cache_indices(t_cache, new_pos)
        d_cache = sampling._fix_cache_indices(d_cache, new_pos)
        return (t_cache, d_cache, t[a], new_pos, n + m, it + 1, out)

    def cond(carry):
        return carry[4] < gen_bucket

    _, _, _, _, n, iters, out = jax.lax.while_loop(
        cond, body,
        (t_cache, d_cache, tok0, p_len[0],
         jnp.asarray(1, jnp.int32), jnp.asarray(0, jnp.int32), out0),
    )
    return out, n, iters


def generate_speculative(
    model,
    params,
    draft_model,
    draft_params,
    prompt,
    steps: int,
    k: int = 4,
    eos_id: Optional[int] = None,
    weights_dtype=None,
    return_stats: bool = False,
):
    """Greedy-decode ``steps`` tokens from the target ``model``, with
    ``draft_model`` proposing ``k`` tokens per verification chunk.

    Output == ``generate_fast(model, params, prompt, steps,
    eos_id=eos_id)`` token for token, for ANY draft (the exactness
    contract; pinned in tests). Requirements: both models dense LMs
    over the same vocab; ``len(prompt) + steps + k`` within BOTH
    models' ``max_len`` (the last verification chunk may overhang by up
    to k slots before the overrun is discarded).

    ``return_stats``: also return ``{"iterations", "mean_emitted"}`` —
    verification chunks run and tokens emitted per chunk (in [1, k+1];
    the draft's usefulness, measured).
    """
    sampling._validate(model, prompt, 0.0, None, None, eos_id)
    sampling._validate(draft_model, prompt, 0.0, None, None, None)
    if draft_model.vocab_size != model.vocab_size:
        raise ValueError(
            f"draft vocab {draft_model.vocab_size} != target vocab "
            f"{model.vocab_size}"
        )
    if k < 1:
        raise ValueError(f"k={k} must be >= 1")
    if steps <= 0:
        seq0 = [int(t) for t in prompt]
        return (seq0, {"iterations": 0, "mean_emitted": 0.0}) \
            if return_stats else seq0
    p0 = len(prompt)
    for m, name in ((model, "target"), (draft_model, "draft")):
        if p0 + steps + k > m.max_len:
            raise ValueError(
                f"prompt+steps+k = {p0 + steps + k} exceeds the {name} "
                f"model's max_len={m.max_len} (the verification chunk "
                "needs k slots of headroom)"
            )
    if weights_dtype is not None:
        params = sampling.cast_weights(params, weights_dtype)
        draft_params = sampling.cast_weights(draft_params, weights_dtype)
    tgt = model.clone(decode=True, remat=False, seq_axis=None,
                      attn_impl="xla")
    dft = draft_model.clone(decode=True, remat=False, seq_axis=None,
                            attn_impl="xla")
    pre_bucket = sampling._bucket(p0, model.max_len)
    gen_bucket = sampling._bucket(steps, model.max_len)
    pre_buf = jnp.zeros((1, pre_bucket), jnp.int32)
    pre_buf = pre_buf.at[0, :p0].set(jnp.asarray(prompt, jnp.int32))
    out, n, iters = _spec_loop(
        tgt, dft, k, pre_bucket, gen_bucket,
        params, draft_params,
        sampling._zero_cache(tgt, 1), sampling._zero_cache(dft, 1),
        pre_buf, jnp.asarray([p0], jnp.int32),
    )
    seq = [int(t) for t in prompt] + [
        int(t) for t in jax.device_get(out[:steps])
    ]
    seq = sampling._truncate_at_eos(seq, p0, eos_id)
    if return_stats:
        it = int(iters)
        return seq, {
            "iterations": it,
            # n counts tok0 (from the prefill) plus every chunk's
            # emissions; per-chunk usefulness excludes tok0
            "mean_emitted": (int(n) - 1) / it if it else 0.0,
        }
    return seq
