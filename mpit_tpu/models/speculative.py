"""Speculative decoding: a small draft LM proposes, the target verifies.

Beyond-parity serving tier (the reference trains models and cannot
sample at all; this accelerates the sampling tier the framework already
has). Greedy speculative decoding with an EXACTNESS guarantee: the
output is token-identical to :func:`~mpit_tpu.models.sampling.
generate_fast`'s greedy decode of the target model alone, for ANY draft
model — a bad draft only costs speed, never correctness. That contract
is what makes the feature testable without hardware: the parity pin
runs on the CPU mesh (tests/test_speculative.py).

Why it is fast on TPU: plain decode is HBM-bound — every generated
token re-reads all target weights for one token's worth of FLOPs.
Here the target consumes the draft's k proposals (plus the pending
token) as ONE (k+1)-token chunk through the SAME cached-attention
kernel the chunked prefill uses (`transformer.py::_cached_attention`:
a T-token chunk appends at each row's clock and masks causally), so
one weight read scores k+1 positions. Accepted tokens advance the
clock; a rejection rewinds both caches by resetting the per-row
position counters (`sampling._fix_cache_indices`) — stale K/V beyond
the clock is overwritten by the next chunk before any mask exposes it,
the same invariant the padded prefill relies on.

The whole loop — draft scan, target chunk, acceptance, rewind — is one
jitted ``lax.while_loop``: zero host round-trips per token, one
compiled program per (prompt-bucket, steps-bucket, k).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from mpit_tpu.models import sampling


def _spec_round(
    tgt, dft, k, t_params, d_params, t_cache, d_cache, prev, pos, active,
):
    """ONE speculative round over nb rows — the primitive both the
    standalone loop and the serving spec-segment share (a change to the
    acceptance/rewind math lands here once).

    Per ACTIVE row: the draft proposes k tokens (plus one extra feed so
    its cache stays a step ahead for the bonus-token path), the target
    scores the (k+1)-chunk [prev, d_1..d_k] in one pass, the row
    accepts a leading proposals and emits m = a+1 tokens (= t[:, :m]),
    and both caches' per-row clocks rewind to pos + m. Inactive rows
    emit m = 0 and keep their prev/clock (their chunk writes repeat the
    same discarded slots).

    Returns ``(t_cache, d_cache, new_prev, new_pos, t, a, m)`` where
    ``t`` is (nb, k+1) — each row's emitted tokens are its first m
    entries."""
    nb = prev.shape[0]

    def draft_step(carry, _):
        cache, p = carry
        logits, mut = dft.apply(
            {"params": d_params, "cache": cache},
            p[:, None], mutable=["cache"],
        )
        nxt = jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
        return (mut["cache"], nxt), nxt

    (d_cache, last_d), d = jax.lax.scan(
        draft_step, (d_cache, prev), None, length=k
    )
    (d_cache, _), _ = draft_step((d_cache, last_d), None)
    d = d.swapaxes(0, 1)  # (nb, k)
    chunk = jnp.concatenate([prev[:, None], d], axis=1)
    t_logits, t_mut = tgt.apply(
        {"params": t_params, "cache": t_cache},
        chunk, mutable=["cache"],
    )
    t_cache = t_mut["cache"]
    t = jnp.argmax(t_logits, -1).astype(jnp.int32)  # (nb, k+1)
    # a[r] = accepted proposals; row r emits exactly t[r, :a+1]
    # (t_i == d_i for i < a; t_a is the correction/bonus)
    match = jnp.cumprod((d == t[:, :k]).astype(jnp.int32), axis=1)
    a = jnp.sum(match, axis=1)
    m = jnp.where(active, a + 1, 0)
    new_pos = pos + m
    t_cache = sampling._fix_cache_indices(t_cache, new_pos)
    d_cache = sampling._fix_cache_indices(d_cache, new_pos)
    new_prev = jnp.where(active, t[jnp.arange(nb), a], prev)
    return t_cache, d_cache, new_prev, new_pos, t, a, m


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _spec_loop(
    tgt, dft, k, pre_bucket, gen_bucket,
    limit, t_params, d_params, t_cache, d_cache, pre_buf, p_lens,
):
    """The compiled speculative loop (N rows, greedy — every per-row
    quantity rides the per-row cache clocks).

    Invariant at the top of each iteration, PER ROW r: both caches hold
    exactly ``pos[r]`` tokens' K/V for row r (the per-row counters say
    so), ``prev[r]`` is row r's last accepted token — not yet fed to
    either model — and ``out[r, :n[r]]`` holds its generated tokens.
    Each iteration emits m[r] ∈ [1, k+1] tokens per ACTIVE row: the
    a[r] accepted draft proposals, then one target token (correction,
    or the bonus token the (k+1)-th chunk position yields when all k
    are accepted). Rows that reached their budget freeze (m = 0): they
    keep riding the batch — their rewound clocks make every later
    chunk rewrite the same discarded slots — while the loop runs until
    EVERY row is done. The budget is the TRACED ``limit`` (= the
    caller's ``steps``), not the static ``gen_bucket`` shape: rows
    freeze at ``n >= steps``, so a steps=5 request in a gen_bucket=8
    program stops after 5 tokens instead of decoding 3 more that the
    caller slices off — and the one compiled program still serves
    every steps value in the bucket. Row independence (each row's
    outputs depend
    only on its own tokens and clock) is what keeps a row's result
    identical whatever the other rows do — the same property the
    serving batch==solo tests pin.
    """
    nb = pre_buf.shape[0]
    t_cache, t_last = sampling._prefill_chunk(
        tgt, t_params, t_cache, pre_buf, p_lens
    )
    d_cache, _ = sampling._prefill_chunk(
        dft, d_params, d_cache, pre_buf, p_lens
    )
    tok0 = jnp.argmax(t_last, -1).astype(jnp.int32)  # (nb,)

    out0 = jnp.zeros((nb, gen_bucket + k + 1), jnp.int32)
    out0 = out0.at[:, 0].set(tok0)

    def body(carry):
        t_cache, d_cache, prev, pos, n, it, out = carry
        active = n < limit  # (nb,)
        t_cache, d_cache, new_prev, new_pos, t, a, m = _spec_round(
            tgt, dft, k, t_params, d_params,
            t_cache, d_cache, prev, pos, active,
        )
        # each row writes its chunk at its OWN cursor; frozen rows'
        # writes clamp into the discard margin past gen_bucket
        out = jax.vmap(
            lambda row, tr, nr: jax.lax.dynamic_update_slice(
                row, tr, (nr,)
            )
        )(out, t, jnp.where(active, n, gen_bucket))
        return (
            t_cache, d_cache, new_prev, new_pos, n + m, it + 1, out
        )

    def cond(carry):
        return jnp.any(carry[4] < limit)

    _, _, _, _, n, iters, out = jax.lax.while_loop(
        cond, body,
        (t_cache, d_cache, tok0, p_lens,
         jnp.ones((nb,), jnp.int32), jnp.asarray(0, jnp.int32), out0),
    )
    return out, n, iters


def generate_speculative(
    model,
    params,
    draft_model,
    draft_params,
    prompt,
    steps: int,
    k: int = 4,
    eos_id: Optional[int] = None,
    weights_dtype=None,
    return_stats: bool = False,
):
    """Greedy-decode ``steps`` tokens from the target ``model``, with
    ``draft_model`` proposing ``k`` tokens per verification chunk.

    Output == ``generate_fast(model, params, prompt, steps,
    eos_id=eos_id)`` token for token, for ANY draft (the exactness
    contract; pinned in tests). Requirements: both models dense LMs
    over the same vocab; ``len(prompt) + steps + k`` within BOTH
    models' ``max_len`` (the last verification chunk may overhang by up
    to k slots before the overrun is discarded).

    ``return_stats``: also return ``{"iterations", "mean_emitted"}`` —
    verification chunks run and tokens emitted per chunk (in [1, k+1];
    the draft's usefulness, measured).
    """
    rows, stats = _run_spec(
        model, params, draft_model, draft_params, [prompt], steps, k,
        eos_id, weights_dtype,
    )
    return (rows[0], stats) if return_stats else rows[0]


def generate_speculative_batch(
    model,
    params,
    draft_model,
    draft_params,
    prompts,
    steps: int,
    k: int = 4,
    eos_id: Optional[int] = None,
    weights_dtype=None,
):
    """N prompts through ONE compiled speculative loop — each row
    accepts at its own rate on its own clock (rows that finish freeze
    and ride along), and row n is pinned equal to its solo
    :func:`generate_speculative` call, hence to the target-only greedy
    decode. Row counts and lengths bucket to powers of two; pad rows
    mirror row 0 and are discarded."""
    if len(prompts) == 0:
        return []
    rows, _ = _run_spec(
        model, params, draft_model, draft_params, list(prompts), steps,
        k, eos_id, weights_dtype,
    )
    return rows


def _run_spec(
    model, params, draft_model, draft_params, prompts, steps, k,
    eos_id, weights_dtype,
):
    """Shared prologue + kernel call for the solo and batch entries."""
    for q in prompts:
        sampling._validate(model, q, 0.0, None, None, eos_id)
        sampling._validate(draft_model, q, 0.0, None, None, None)
    if draft_model.vocab_size != model.vocab_size:
        raise ValueError(
            f"draft vocab {draft_model.vocab_size} != target vocab "
            f"{model.vocab_size}"
        )
    if k < 1:
        raise ValueError(f"k={k} must be >= 1")
    if steps <= 0:
        rows = [[int(t) for t in q] for q in prompts]
        return rows, {"iterations": 0, "mean_emitted": 0.0}
    longest = max(len(q) for q in prompts)
    for m, name in ((model, "target"), (draft_model, "draft")):
        if longest + steps + k > m.max_len:
            raise ValueError(
                f"prompt+steps+k = {longest + steps + k} exceeds the "
                f"{name} model's max_len={m.max_len} (the verification "
                "chunk needs k slots of headroom)"
            )
    if weights_dtype is not None:
        params = sampling.cast_weights(params, weights_dtype)
        draft_params = sampling.cast_weights(draft_params, weights_dtype)
    tgt = model.clone(decode=True, remat=False, seq_axis=None,
                      attn_impl="xla")
    dft = draft_model.clone(decode=True, remat=False, seq_axis=None,
                            attn_impl="xla")
    n_real = len(prompts)
    # the shared row-batching prep (greedy: no key streams). Buckets
    # cap at the SMALLER of the two max_lens — both caches consume the
    # same prompt buffer, so the draft's cache must fit it too
    nb, pre_bucket, gen_bucket, pre_buf, p_lens, _ = sampling._prep_rows(
        prompts, steps, None, min(model.max_len, draft_model.max_len)
    )
    out, n, iters = _spec_loop(
        tgt, dft, k, pre_bucket, gen_bucket,
        jnp.asarray(steps, jnp.int32), params, draft_params,
        sampling._zero_cache(tgt, nb), sampling._zero_cache(dft, nb),
        pre_buf, p_lens,
    )
    host = jax.device_get(out)
    rows = [
        sampling._truncate_at_eos(
            [int(t) for t in prompts[i]]
            + [int(t) for t in host[i, :steps]],
            len(prompts[i]), eos_id,
        )
        for i in range(n_real)
    ]
    it = int(iters)
    total = int(jax.device_get(n).sum()) if it else 0
    stats = {
        "iterations": it,
        # n counts each row's tok0 (from the prefill) plus every
        # chunk's emissions; per-chunk usefulness excludes tok0. For
        # nb rows the denominator is chunk-ROWS (it * nb) — pad and
        # frozen rows drag the batch average down honestly (they ran
        # the compute).
        "mean_emitted": (total - nb) / (it * nb) if it else 0.0,
    }
    return rows, stats
