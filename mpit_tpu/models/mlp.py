"""Small MLP classifier.

Not a reference config — exists (a) as the cheap-to-compile model the e2e
tests train (SURVEY.md §4's multi-device tests need fast XLA compiles on the
simulated CPU mesh), and (b) as the minimal example model for docs.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    num_classes: int = 10
    hidden: Sequence[int] = (128,)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.compute_dtype)
        x = x.reshape((x.shape[0], -1))
        for h in self.hidden:
            x = nn.Dense(h, dtype=self.compute_dtype)(x)
            x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)
