"""LSTM language model for the PTB EASGD config (BASELINE.json:11 —
reference config 5: "PTB LSTM language model EASGD (small frequent async
updates, non-vision)").

Embedding → stacked LSTM (``nn.RNN`` = lax.scan over the sequence, so the
whole unroll is one compiled loop — no per-timestep dispatch) → tied-size
projection to the vocab. Takes (B, T) int tokens, returns (B, T, V) float32
logits for next-token prediction; compute in bfloat16 (the matmul-heavy
gates ride the MXU), params float32.
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
from jax import lax


class LSTMLM(nn.Module):
    vocab_size: int = 10_000
    embed_dim: int = 256
    hidden: int = 512
    num_layers: int = 2
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens):
        x = nn.Embed(
            self.vocab_size, self.embed_dim, dtype=self.compute_dtype
        )(tokens)
        for _ in range(self.num_layers):
            x = nn.RNN(
                nn.OptimizedLSTMCell(self.hidden, dtype=self.compute_dtype)
            )(x)
        # vocab head: operands stay in compute_dtype (MXU fast path) but
        # ACCUMULATE in f32 — the large-vocab logits never get quantized
        # to bf16 on the way out (the plain Dense+astype recipe computed
        # a bf16 output first). Param tree unchanged: same Dense module,
        # only its dot_general carries preferred_element_type.
        logits = nn.Dense(
            self.vocab_size, dtype=self.compute_dtype,
            dot_general=functools.partial(
                lax.dot_general, preferred_element_type=jnp.float32
            ),
        )(x)
        return logits.astype(jnp.float32)
