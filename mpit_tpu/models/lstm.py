"""LSTM language model for the PTB EASGD config (BASELINE.json:11 —
reference config 5: "PTB LSTM language model EASGD (small frequent async
updates, non-vision)").

Embedding → stacked LSTM (``nn.RNN`` = lax.scan over the sequence, so the
whole unroll is one compiled loop — no per-timestep dispatch) → tied-size
projection to the vocab. Takes (B, T) int tokens, returns (B, T, V) float32
logits for next-token prediction; compute in bfloat16 (the matmul-heavy
gates ride the MXU), params float32.

Serving: ``decode=True`` is the RNN analogue of the transformer's KV-cache
mode — the per-layer LSTM carries persist in the ``cache`` variable
collection, so the prompt enters in ONE compiled RNN pass (per-row
``seq_lengths``: each row's carry freezes at its own prompt length — the
RNN-native equivalent of per-row cache clocks) and each generated token is
a single-step call. Params are IDENTICAL between modes (the cells and the
head are the same submodules), so a trained checkpoint serves directly —
:func:`mpit_tpu.models.rnn_sampling.generate_rnn`.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


class LSTMLM(nn.Module):
    vocab_size: int = 10_000
    embed_dim: int = 256
    hidden: int = 512
    num_layers: int = 2
    compute_dtype: Any = jnp.bfloat16
    # serving mode: carries live in the "cache" collection and survive
    # across calls (prefill chunk, then one-token ticks)
    decode: bool = False
    # head=False returns the top layer's hidden states (B, T, H) — the
    # decode prefill projects ONE row per batch row through the vocab
    # head (head_logits) instead of materializing (B, T, V) f32 logits
    head: bool = True
    # vocab-head OPERAND dtype override (None -> compute_dtype);
    # accumulation is always f32 — see TransformerLM.head_dtype
    head_dtype: Any = None

    @property
    def _head_operand_dtype(self):
        """One resolution rule, shared by ``_head`` and ``head_logits``
        (same contract as TransformerLM._head_operand_dtype)."""
        return (
            self.compute_dtype if self.head_dtype is None
            else self.head_dtype
        )

    @nn.compact
    def __call__(self, tokens, seq_lengths: Optional[jax.Array] = None):
        """``seq_lengths`` (decode prefill only): per-row true prompt
        lengths — carries freeze beyond each row's own length, so a
        padded (B, bucket) prompt buffer yields the carry of the TRUE
        prompt per row."""
        if seq_lengths is not None and not self.decode:
            raise ValueError("seq_lengths is a decode-mode argument")
        dt = self.compute_dtype
        x = nn.Embed(self.vocab_size, self.embed_dim, dtype=dt)(tokens)
        for i in range(self.num_layers):
            cell = nn.OptimizedLSTMCell(self.hidden, dtype=dt)
            if not self.decode:
                x = nn.RNN(cell)(x)
                continue
            # decode: resume from the stored carry; create-before-mutate
            # like the transformer cache (init must not leak a post-step
            # carry into the initial state)
            ready = self.has_variable("cache", f"carry_{i}")
            var = self.variable(
                "cache", f"carry_{i}",
                lambda: cell.initialize_carry(
                    jax.random.key(0), x[:, 0].shape
                ),
            )
            carry, x = nn.RNN(cell)(
                x, initial_carry=var.value, return_carry=True,
                seq_lengths=seq_lengths,
            )
            if ready:
                var.value = carry
        if not self.head:
            return x
        return self._head(x)

    def _head(self, x):
        # vocab head: operands stay in the head operand dtype (default
        # compute_dtype — the MXU fast path; head_dtype overrides) but
        # ACCUMULATE in f32 — the large-vocab logits never get quantized
        # to bf16 on the way out (the plain Dense+astype recipe computed
        # a bf16 output first). Param tree unchanged: same Dense module,
        # only its dot_general carries preferred_element_type.
        hdt = self._head_operand_dtype
        logits = nn.Dense(
            self.vocab_size, dtype=hdt,
            dot_general=functools.partial(
                lax.dot_general, preferred_element_type=jnp.float32
            ),
        )(x)
        return logits.astype(jnp.float32)

    def head_logits(self, params, h):
        """The vocab head applied to (B, H) hidden rows — the SAME
        projection ``__call__`` ends with (head-operand-dtype operands,
        f32 accumulation), for decode prefill callers that ran
        ``head=False`` and kept only each row's last prompt position."""
        dt = self._head_operand_dtype
        kernel = params["Dense_0"]["kernel"].astype(dt)
        # bias quantized to the head operand dtype BEFORE the add —
        # exactly what flax Dense's promote_dtype does in _head, so
        # prefill logits match the tick path bit for bit (a f32 bias
        # here would shift near-tie argmaxes on the default bf16 model)
        bias = params["Dense_0"]["bias"].astype(dt)
        out = lax.dot_general(
            h.astype(dt), kernel, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return out + bias
