"""AlexNet for the ImageNet Downpour config (BASELINE.json:9 — reference
config 3: "ImageNet AlexNet Downpour-SGD model-averaging, 16 workers / 4
pservers").

Classic 5-conv/3-dense topology, NHWC, bfloat16 compute. LRN is replaced by
GroupNorm (LRN is a 2012 artifact with poor TPU lowering; norm choice does
not affect the throughput benchmark this config exists for).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class AlexNet(nn.Module):
    """``stem="conv"`` is the textbook 11×11/4; ``stem="space_to_depth"``
    computes the same function over 4×4 space-to-depth input
    (``mpit_tpu.ops.stem`` — contraction 363 → 768, no MXU-hostile
    3-channel conv; same 11×11×3×64 parameter shape, different flax param
    name, so checkpoints do not interchange between stems)."""

    num_classes: int = 1000
    compute_dtype: Any = jnp.bfloat16
    stem: str = "conv"

    @nn.compact
    def __call__(self, x):
        from mpit_tpu.ops.stem import stem_conv

        dt = self.compute_dtype
        x = x.astype(dt)
        x = stem_conv(
            self, x, features=64, kernel=11, stride=4, padding=2,
            stem=self.stem, dt=dt, use_bias=True,
        )
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(192, (5, 5), padding=(2, 2), dtype=dt)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = nn.Conv(384, (3, 3), padding=(1, 1), dtype=dt)(x)
        x = nn.relu(x)
        x = nn.Conv(256, (3, 3), padding=(1, 1), dtype=dt)(x)
        x = nn.relu(x)
        x = nn.Conv(256, (3, 3), padding=(1, 1), dtype=dt)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(4096, dtype=dt)(x)
        x = nn.relu(x)
        x = nn.Dense(4096, dtype=dt)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=dt)(x)
        return x.astype(jnp.float32)
