"""Continuous-batching serving loop over the KV-cached decode kernels.

The reference trains models but cannot sample from them at all; this is
the beyond-parity serving tier above :func:`~mpit_tpu.models.sampling.
generate_batch`: a scheduler that keeps a decode batch full while
requests arrive and finish at different times.

Design (TPU-first, resident cache — enabled by the per-row cache clocks
in :class:`~mpit_tpu.models.transformer.Block`):

- The K/V cache is RESIDENT on device: one (NB, ...) cache tree lives
  across the server's whole life, one slot per decode row. Decoding
  advances in fixed **segments** of ticks — each segment is ONE XLA
  program over the whole batch (donated cache in/out, no host copies) —
  and the host intervenes only at segment boundaries.
- At a boundary the server retires finished rows (budget exhausted or
  ``eos_id`` emitted) and **admits** queued requests into freed slots:
  a batch-1 chunked prefill builds the newcomer's cache rows and
  counters (its per-row clock lands at its own prompt length), which
  are written in place into the resident tree. In-flight rows are
  UNTOUCHED — admission costs one prompt prefill for the newcomer and
  nothing for anyone else. Free slots keep ticking garbage (discarded;
  their clamped cache writes can never be attended by occupied rows,
  whose masks stop at their own clocks).
- **Exact parity**: every request's result is bit-equal to its solo
  ``generate_fast(prompt, max_new, rng=request_rng)`` call. Sampling
  keys are pre-split per request (``split(rng, max_new)``); generated
  token j is always drawn with stream key j — token 0 at admission
  (from the prefill logits), the rest inside segments — no matter how
  segments, slots, and batch composition fell. Pinned in
  tests/test_serving.py, greedy and sampled.

Row independence (each row's outputs depend only on its own tokens and
clock — the property the batch==solo tests pin) is what makes
retirement and admission invisible to the surviving rows.

Observability (``Server(obs=ObsConfig(dir=...))``): every request's
lifecycle — ``req_enqueue`` → ``req_admit`` → ``req_first_token`` →
segment ticks → ``req_finish``/``req_cancel`` — plus per-boundary
``prefill``/``segment`` records (duration, batch occupancy, queue
depth) journals through the :mod:`mpit_tpu.obs` Journal; ``python -m
mpit_tpu.obs slo`` aggregates the journals into TTFT/TPOT/e2e
percentiles and goodput (docs/SERVING.md). With obs off every hook is
one ``is None`` check — the load harness pins the null path under 2%
of drain wall-clock (tests/test_loadgen.py).
"""

from __future__ import annotations

import functools
import os
import time
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from mpit_tpu.models import sampling
from mpit_tpu.obs.live import (
    M_E2E,
    M_OCCUPIED,
    M_REQ_CANCELLED,
    M_REQ_FINISHED,
    M_REQ_SUBMITTED,
    M_SEGMENTS,
    M_SERVE_FAULTS,
    M_SLO_MISSES,
    M_TOKENS,
    M_TTFT,
    M_WAITING,
)


class _ServeObs:
    """Per-server request-lifecycle recorder: one rank-0 obs Journal
    (serving is single-process) in the standard ``obs_rank*.jsonl``
    layout, so merge/summary/slo all read a load run unchanged. Built
    only when obs is armed — the disabled Server carries ``None`` and
    every instrumentation site stays a bare identity check.

    With ``ObsConfig.live`` armed, the same lifecycle events also feed a
    live :class:`mpit_tpu.obs.live.MetricsRegistry` (role ``"serve"``)
    snapshotted to ``<dir>/live/rank_0.json`` — submitted/finished/
    cancelled counters, TTFT/e2e rolling histograms, SLO-miss counts
    (against each request's own ``slo_ms``), and waiting/occupied gauges
    per segment. That is the SLO-burn signal the online alert engine and
    a future replica router read while traffic is flowing."""

    __slots__ = ("journal", "clock", "registry", "_live", "_open_reqs")

    def __init__(self, config):
        from mpit_tpu.obs.core import Journal, LogicalClock

        if not getattr(config, "dir", None):
            raise ValueError(
                "serving obs needs a journal directory: pass "
                "ObsConfig(dir=...) (counters-only mode has nothing to "
                "record request lifecycles into)"
            )
        os.makedirs(config.dir, exist_ok=True)
        box = None
        if getattr(config, "blackbox", False):
            from mpit_tpu.obs.blackbox import BlackBox

            box = BlackBox(
                config.dir, 0,
                max_records=getattr(config, "blackbox_records", 2048),
                max_seconds=getattr(config, "blackbox_seconds", 30.0),
            )
        self.journal = Journal(
            os.path.join(config.dir, "obs_rank0.jsonl"), 0,
            max_records=getattr(config, "max_records", None),
            mode="ring" if getattr(config, "ring", False) else "cap",
            blackbox=box,
        )
        self.clock = LogicalClock()
        self.registry = None
        self._live = None
        self._open_reqs: dict = {}  # rid -> (t_enqueue, slo_ms)
        if getattr(config, "live", False):
            from mpit_tpu.obs.live import LiveExporter, MetricsRegistry

            self.registry = MetricsRegistry(0, role="serve")
            self._live = LiveExporter(
                self.registry,
                os.path.join(config.dir, "live"),
                interval_s=getattr(config, "live_interval", 1.0),
            )

    def event(self, ev: str, **fields) -> None:
        self._lifecycle(ev, fields)
        self.journal.event(ev, self.clock.tick(), **fields)
        if self.registry is not None:
            self._publish(ev, fields)

    def _lifecycle(self, ev: str, fields: dict) -> None:
        """Tag lifecycle records with the latencies this recorder already
        measures (monotonic, enqueue → first token / finish):
        ``req_first_token`` gains ``ttft_ms``, ``req_finish`` gains
        ``e2e_ms`` + ``slo_miss`` (vs the request's own ``slo_ms``). The
        tags land in the JOURNAL record itself — a black-box dump or a
        capped journal is then post-mortem-able on its face, without
        replaying the whole request stream to re-derive latencies."""
        now = time.monotonic()
        if ev == "req_enqueue":
            self._open_reqs[fields.get("rid")] = (now, fields.get("slo_ms"))
        elif ev == "req_first_token":
            open_rec = self._open_reqs.get(fields.get("rid"))
            if open_rec is not None:
                fields["ttft_ms"] = round((now - open_rec[0]) * 1e3, 3)
        elif ev == "req_finish":
            open_rec = self._open_reqs.pop(fields.get("rid"), None)
            if open_rec is not None:
                e2e_ms = (now - open_rec[0]) * 1e3
                fields["e2e_ms"] = round(e2e_ms, 3)
                slo_ms = open_rec[1]
                if slo_ms is not None:
                    fields["slo_miss"] = bool(e2e_ms > slo_ms)
        elif ev == "req_cancel":
            self._open_reqs.pop(fields.get("rid"), None)

    def _publish(self, ev: str, fields: dict) -> None:
        """Fold one journal event into the live registry, reusing the
        latencies :meth:`_lifecycle` already stamped into the record —
        the live plane must not depend on the journal surviving or
        being re-read."""
        reg = self.registry
        if ev == "req_enqueue":
            reg.inc(M_REQ_SUBMITTED)
        elif ev == "req_first_token":
            if "ttft_ms" in fields:
                reg.observe(M_TTFT, fields["ttft_ms"] / 1e3)
        elif ev == "req_finish":
            reg.inc(M_REQ_FINISHED)
            reg.inc(M_TOKENS, float(fields.get("gen", 0)))
            if "e2e_ms" in fields:
                reg.observe(M_E2E, fields["e2e_ms"] / 1e3)
                if fields.get("slo_miss"):
                    reg.inc(M_SLO_MISSES)
        elif ev == "req_cancel":
            reg.inc(M_REQ_CANCELLED)
        elif ev == "segment":
            reg.inc(M_SEGMENTS)
            if "waiting" in fields:
                reg.set_gauge(M_WAITING, fields["waiting"])
            if "occupied" in fields:
                reg.set_gauge(M_OCCUPIED, fields["occupied"])
        elif ev == "serve_fault":
            reg.inc(M_SERVE_FAULTS)

    def close(self) -> None:
        self.journal.close()
        if self._live is not None:
            self._live.close()


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _prefill_rows(
    model, pre_bucket, greedy, top_k, use_top_p,
    params, cache0, pre_buf, p_lens, keys0, temp, top_p, clock0,
):
    """Admission: a GROUP of same-bucket prompts through the dense
    chunked prefill as ONE kernel (K rows) — returns their cache rows
    (each row's counter at its OWN global position, per-row clocks) and
    each row's first sampled token (that request's stream key 0 — the
    same key the batch kernel would have used). A burst of K arrivals
    costs one prefill call, not K (pinned in tests/test_serving.py).

    ``clock0``: 0 for a fresh cache; the prefix length when ``cache0``
    rows are copies of the server's prefix-cache template (admission
    then pays only the SUFFIX prompt's FLOPs)."""
    cache, last = sampling._prefill_chunk(
        model, params, cache0, pre_buf, p_lens, clock0
    )
    tok0 = sampling._sample_rows(
        last, keys0, greedy, top_k, use_top_p, temp, top_p
    )
    return cache, tok0


@functools.partial(jax.jit, static_argnums=(0, 1))
def _prefill_prefix(model, pre_bucket, params, cache0, pre_buf, p_len):
    """Cache-only prefill (no vocab projection — the logits would be
    discarded): the prefix-cache TEMPLATE (batch 1, once) and the
    speculative draft's admission rows (kb rows, every boundary) both
    use it; the first sampled token always comes from a TARGET
    prefill."""
    cache, _ = sampling._prefill_chunk(
        model, params, cache0, pre_buf, p_len, with_head=False
    )
    return cache


@functools.partial(jax.jit, static_argnums=(0,))
def _tile_rows(kb, tpl):
    """The batch-1 template repeated into a kb-row cache tree (the
    starting cache for a prefix-server admission group)."""
    return jax.tree.map(
        lambda x: jnp.repeat(x, kb, axis=0), tpl
    )


@functools.partial(
    jax.jit, static_argnums=(0, 1, 2, 3), donate_argnums=(6, 7, 8)
)
def _serve_spec_segment(
    tgt, dft, k, r_cap,
    t_params, d_params, t_cache, d_cache, prev, pos0, rounds,
):
    """``rounds`` speculative rounds over the whole resident batch as
    one program (the spec-server analogue of :func:`_serve_segment`'s
    tick scan): each round every row drafts k tokens, verifies the
    (k+1)-chunk through the target, accepts per row, and rewinds its
    own clock (`speculative._spec_round` — the shared primitive).
    Target cache, draft cache, and prev are DONATED residents.

    ``rounds`` is TRACED (``lax.fori_loop``) so the host can cap it per
    boundary — by the max_len frontier and the largest remaining budget
    — without a recompile per value; ``r_cap`` (static) only sizes the
    out buffer. ``pos0``: each row's cached-token count (len(known)-1;
    free slots pass 0 — the round resets their garbage clocks, which
    keeps them from ever drifting into the clamp zone).

    Returns per row its emitted tokens (first ``n[r]`` entries of
    ``out[r]``) — every row emits ``rounds <= n[r] <= rounds*(k+1)``
    tokens; the host takes what each request's budget needs."""
    from mpit_tpu.models.speculative import _spec_round

    nb = prev.shape[0]
    out0 = jnp.zeros((nb, r_cap * (k + 1)), jnp.int32)
    active = jnp.ones((nb,), bool)

    def round_body(_j, carry):
        t_cache, d_cache, prev, pos, n, out = carry
        t_cache, d_cache, prev, pos, t, _a, m = _spec_round(
            tgt, dft, k, t_params, d_params,
            t_cache, d_cache, prev, pos, active,
        )
        out = jax.vmap(
            lambda row, tr, nr: jax.lax.dynamic_update_slice(
                row, tr, (nr,)
            )
        )(out, t, n)
        return (t_cache, d_cache, prev, pos, n + m, out)

    t_cache, d_cache, prev, _pos, n, out = jax.lax.fori_loop(
        0, rounds, round_body,
        (t_cache, d_cache, prev, pos0, jnp.zeros((nb,), jnp.int32), out0),
    )
    return t_cache, d_cache, prev, out, n


@functools.partial(jax.jit, donate_argnums=(0,))
def _insert_rows(big, rows, slots):
    """Scatter K prefilled cache rows into slots ``slots`` of the
    resident (NB, ...) tree — every leaf is batch-leading, index
    counters included (the resident tree is DONATED: admission writes
    in place, no full-cache copy). Pad rows repeat row 0's inputs AND
    slot, so their duplicate-index writes carry bit-identical values
    (prefill is deterministic) and are harmless under scatter's
    unspecified write order."""
    return jax.tree.map(
        lambda b, r: b.at[slots].set(r.astype(b.dtype)), big, rows
    )


@functools.partial(
    jax.jit, static_argnums=(0, 1, 2, 3, 4), donate_argnums=(6, 7)
)
def _serve_segment(
    model, seg, greedy, top_k, use_top_p,
    params, cache, prev, keys, temp, top_p,
):
    """``seg`` decode ticks over the whole resident batch as one
    program: every tick feeds each slot its previous sample and draws
    its next from that slot's key column. The cache and prev-token
    buffers are DONATED — the segment updates them in place, no
    per-segment reallocation or host round-trips."""

    def step(carry, t):
        cache, prev = carry
        logits, mut = model.apply(
            {"params": params, "cache": cache},
            prev[:, None],
            mutable=["cache"],
        )
        nxt = sampling._sample_rows(
            logits[:, 0], keys[:, t], greedy, top_k, use_top_p,
            temp, top_p,
        )
        return (mut["cache"], nxt), nxt

    (cache, prev), toks = jax.lax.scan(
        step, (cache, prev), jnp.arange(seg)
    )
    return cache, prev, toks.swapaxes(0, 1)  # (NB, seg)


class Server:
    """Continuous-batching decode server for one model + params.

    Args:
      model: a dense ``TransformerLM`` (same restrictions as
        :func:`~mpit_tpu.models.sampling.generate_fast`).
      params: trained parameters. With ``weights_dtype="bf16"`` they are
        cast ONCE here (serving is HBM-bound; see ``cast_weights``).
      max_batch: decode-slot count; queued requests wait for a free slot.
      segment: ticks per kernel call between scheduling points. Large
        segments amortize dispatch; small segments admit/retire sooner.
        Speculative servers ignore it — their boundary granularity is
        ``spec_rounds`` draft-verify rounds instead of ticks.
      temperature/top_k/top_p/eos_id: the default sampling rule and,
        for the STATIC halves (greedy vs sampling, top-k, nucleus
        on/off), the server's compiled-in mode. temperature/top_p
        VALUES are traced per row, so :meth:`submit` can override them
        per request without recompiling; changing mode or top_k needs a
        different Server.
      prefix: optional shared prompt prefix (a system prompt). It
        prefills ONCE into a batch-1 cache template (lazily, at first
        admission); every request implicitly starts with it — results
        include it and equal ``generate_fast(prefix + prompt, ...)`` —
        and admission pays only the request's OWN prompt's FLOPs (the
        template rows are copied, not recomputed).
      draft_model/draft_params: enable SPECULATIVE serving (greedy
        servers only — the exactness contract needs target-argmax
        verification): a resident draft cache rides beside the
        target's, each scheduling round runs ``spec_rounds``-capped
        batched draft-verify rounds (``spec_k`` proposals per round,
        per-row acceptance — `speculative._spec_round`), and every
        result stays bit-equal to its solo greedy call. Requests need
        ``prompt + max_new + spec_k <= max_len`` (chunk headroom).
      obs: optional :class:`~mpit_tpu.obs.ObsConfig` with ``dir`` set —
        journals every request's lifecycle (enqueue/admit/first-token/
        finish/cancel) plus per-boundary prefill/segment records into
        ``<dir>/obs_rank0.jsonl`` for ``python -m mpit_tpu.obs slo``.
        ``None`` (the default) keeps serving uninstrumented: every hook
        site is one ``is None`` check, nothing else.
    """

    def __init__(
        self,
        model,
        params,
        max_batch: int = 8,
        segment: int = 32,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        eos_id: Optional[int] = None,
        weights_dtype=None,
        seed: int = 0,
        prefix=None,
        draft_model=None,
        draft_params=None,
        spec_k: int = 4,
        spec_rounds: int = 4,
        obs=None,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if segment < 1:
            raise ValueError("segment must be >= 1")
        if prefix is not None and len(prefix) == 0:
            prefix = None
        if prefix is not None:
            sampling._validate(model, prefix, 0.0, None, None, None)
        if draft_model is not None:
            if getattr(model, "max_len", None) is None:
                raise ValueError(
                    "speculative serving needs a transformer-style "
                    "target (chunk verification scores k+1 positions "
                    "in parallel; a recurrence cannot)"
                )
            # speculative serving is the greedy tier (the exactness
            # contract needs target-argmax verification)
            if temperature != 0.0 or top_k is not None or top_p is not None:
                raise ValueError(
                    "speculative serving (draft_model=...) is greedy: "
                    "temperature must be 0 and top_k/top_p None"
                )
            if prefix is not None:
                raise ValueError(
                    "draft_model and prefix cannot combine yet — the "
                    "draft cache has no prefix template"
                )
            if draft_model.vocab_size != model.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.vocab_size} != target "
                    f"vocab {model.vocab_size}"
                )
            if draft_model.max_len < model.max_len:
                raise ValueError(
                    "draft max_len must cover the target's (both caches "
                    f"hold the same sequence): {draft_model.max_len} < "
                    f"{model.max_len}"
                )
            if spec_k < 1 or spec_rounds < 1:
                raise ValueError("spec_k and spec_rounds must be >= 1")
        self.model = model
        self._weights_dtype = weights_dtype
        self.params = (
            sampling.cast_weights(params, jnp.bfloat16)
            if weights_dtype in ("bf16", jnp.bfloat16) else params
        )
        # serving-weights provenance: 0 = construction-time weights;
        # bumped by install_weights (the fleet's rolling-refresh path)
        # and stamped into every fleet REPLY for the version audit
        self._weights_version = 0
        self.max_batch = int(max_batch)
        self.segment = int(segment)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self._rng = jax.random.key(seed)
        self._poisoned: Optional[BaseException] = None
        self._next_id = 0
        self._waiting: deque[dict] = deque()
        self._results: dict[int, list[int]] = {}
        self.segments_run = 0
        # None for carry-decode RNNs: their state has no positional
        # horizon, so every frontier/bucket cap below degrades to "no cap"
        # (draft_model on a horizon-free target was rejected above)
        self._max_len = getattr(model, "max_len", None)
        # resident decode state: one slot per row of the bucketed batch
        self._dec = self._decode_clone(model)
        self._nb = sampling._bucket(self.max_batch, 1 << 30)
        self._slots: list = [None] * self._nb
        self._cache = None  # built lazily at first admission
        self._prev = None
        # shared-prefix (prompt-cache) serving: the prefix prefills ONCE
        # into a batch-1 template at first admission; every admission
        # then starts from template copies and pays only its SUFFIX
        self.prefix = (
            [int(t) for t in prefix] if prefix is not None else None
        )
        self._template = None
        self._greedy = self.temperature == 0.0
        # speculative serving: resident DRAFT cache beside the target's
        self.spec_k = int(spec_k)
        self.spec_rounds = int(spec_rounds)
        self._dft = (
            draft_model.clone(
                decode=True, remat=False, seq_axis=None, attn_impl="xla"
            ) if draft_model is not None else None
        )
        self._d_params = (
            sampling.cast_weights(draft_params, jnp.bfloat16)
            if draft_params is not None
            and weights_dtype in ("bf16", jnp.bfloat16)
            else draft_params
        )
        self._d_cache = None
        self._obs = _ServeObs(obs) if obs is not None else None

    # ---- model-family hooks (the RNN server overrides these three) ----

    def _decode_clone(self, model):
        return model.clone(
            decode=True, remat=False, seq_axis=None, attn_impl="xla"
        )

    def _prefill_call(
        self, pre_bucket, cache0, pre_buf, p_lens, keys0, temps, tops, pfx
    ):
        """The admission prefill kernel: (cache rows, first tokens)."""
        return _prefill_rows(
            self._dec, pre_bucket, self._greedy, self.top_k,
            self.top_p is not None,
            self.params, cache0, pre_buf, p_lens, keys0, temps, tops,
            jnp.asarray(pfx, jnp.int32),
        )

    def _template_call(self, pb, buf, p_len):
        """The one-time prefix-template prefill (cache only)."""
        return _prefill_prefix(
            self._dec, pb, self.params,
            sampling._zero_cache(self._dec, 1), buf, p_len,
        )

    def _len_cap(self, pfx=0) -> int:
        """Bucket cap for prompt chunks: the cache headroom above the
        prefix clock, or effectively unbounded for horizon-free RNNs."""
        return (self._max_len - pfx) if self._max_len else (1 << 30)

    # ----------------------------------------------------- weight refresh

    @property
    def weights_version(self) -> int:
        """The version stamp of the weights currently serving (0 =
        construction-time weights, never refreshed)."""
        return self._weights_version

    def install_weights(self, params, version: Optional[int] = None) -> int:
        """Swap in a new weight pytree between scheduling steps (the
        fleet's rolling-refresh path). The same ``weights_dtype`` cast
        as construction applies, so a refreshed server serves at the
        precision it advertised. In-flight requests finish their
        remaining segments under the NEW weights — acceptable for
        serving (each segment reads ``self.params`` afresh) and exactly
        what a rolling fleet refresh means; callers needing per-request
        weight pinning must drain first.

        ``version``: the source's version counter (must move forward);
        None auto-increments. Returns the installed version."""
        if version is None:
            version = self._weights_version + 1
        version = int(version)
        if version <= self._weights_version:
            raise ValueError(
                f"weights version must advance: {version} <= "
                f"{self._weights_version} (rolling refreshes are "
                "monotonic — the audit trail depends on it)"
            )
        self._check_poisoned()
        self.params = (
            sampling.cast_weights(params, jnp.bfloat16)
            if self._weights_dtype in ("bf16", jnp.bfloat16) else params
        )
        self._weights_version = version
        if self._obs is not None:
            self._obs.event("weights_install", version=version)
        return version

    # ------------------------------------------------------------- intake

    def submit(
        self, prompt, max_new_tokens: int, rng=None, seed=None,
        temperature=None, top_p=None, slo_ms=None,
    ) -> int:
        """Queue a request; returns its id. The request's sampling stream
        is fixed HERE (``rng``, or ``fold_in(server_rng, id)`` — matching
        ``generate_batch``'s per-row derivation), so results are
        reproducible regardless of scheduling.

        ``temperature``/``top_p`` override the server defaults for THIS
        request only (the values are traced, so mixed rules share one
        compiled program; each row stays bit-equal to its solo call at
        its own rule). The server's MODE is fixed at construction —
        greedy vs sampling, top-k on/off, nucleus on/off are compiled
        in — so a greedy server rejects temperature overrides and
        ``top_p`` needs nucleus enabled at construction.

        ``slo_ms``: THIS request's end-to-end deadline, journaled at
        enqueue when obs is armed — ``obs slo``'s goodput counts the
        requests that finished within their own deadline. Purely
        declarative: the scheduler never reads it."""
        if temperature is not None:
            if self._greedy:
                raise ValueError(
                    "per-request temperature needs a sampling server "
                    "(constructed with temperature > 0); greedy is a "
                    "server-level mode"
                )
            if temperature <= 0:
                raise ValueError(
                    f"per-request temperature={temperature} must be > 0"
                )
        if top_p is not None and self.top_p is None:
            raise ValueError(
                "per-request top_p needs nucleus sampling enabled at "
                "construction (top_p=...)"
            )
        # the ONE resolution of this request's effective rule — what is
        # validated here is exactly what the kernels later execute
        eff_temp = (
            self.temperature if temperature is None else temperature
        )
        eff_tp = self.top_p if top_p is None else top_p
        sampling._validate(
            self.model, prompt, eff_temp, self.top_k, eff_tp, self.eos_id,
        )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if slo_ms is not None and slo_ms <= 0:
            raise ValueError(f"slo_ms={slo_ms} must be > 0")
        pfx = len(self.prefix) if self.prefix else 0
        if (
            self._max_len is not None
            and pfx + len(prompt) + max_new_tokens > self._max_len
        ):
            raise ValueError(
                f"prefix ({pfx}) + prompt ({len(prompt)}) + "
                f"max_new_tokens ({max_new_tokens}) exceeds "
                f"max_len={self._max_len} "
                "(the cached decode cannot slide)"
            )
        if (
            self._dft is not None
            and len(prompt) + max_new_tokens + self.spec_k
            > self._max_len
        ):
            raise ValueError(
                f"prompt + max_new_tokens + spec_k = "
                f"{len(prompt) + max_new_tokens + self.spec_k} exceeds "
                f"max_len={self._max_len} (the verification chunk "
                "needs spec_k slots of headroom)"
            )
        self._check_poisoned()
        rid = self._next_id
        self._next_id += 1
        if rng is None:
            rng = (
                jax.random.key(seed) if seed is not None
                else jax.random.fold_in(self._rng, rid)
            )
        self._waiting.append({
            "id": rid,
            # full accepted sequence, prefix included — results equal
            # generate_fast(prefix + prompt, ...) token for token
            "known": (self.prefix or []) + [int(t) for t in prompt],
            "max_new": int(max_new_tokens),
            "gen": 0,
            # per-request rule values (server defaults when not given)
            "temp": max(eff_temp, 1e-9),
            "tp": 1.0 if eff_tp is None else eff_tp,
            # the request's ENTIRE stream, split once: generated token j
            # draws key j — solo-call parity under any scheduling
            "stream": jax.random.split(rng, max_new_tokens),
        })
        if self._obs is not None:
            self._obs.event(
                "req_enqueue", rid=rid, p_len=len(prompt) + pfx,
                max_new=int(max_new_tokens),
                **({} if slo_ms is None else {"slo_ms": float(slo_ms)}),
            )
        return rid

    def cancel(self, request_id: int) -> bool:
        """Abandon a request: drop it from the queue, or free its slot
        mid-flight (tokens generated so far are discarded; the freed
        slot admits the next waiter at the coming boundary). Returns
        whether anything was cancelled — False for ids already
        finished (their results stay in :meth:`results`) or unknown."""
        for i, r in enumerate(self._waiting):
            if r["id"] == request_id:
                del self._waiting[i]
                if self._obs is not None:
                    self._obs.event(
                        "req_cancel", rid=request_id, where="queued"
                    )
                return True
        for slot, r in enumerate(self._slots):
            if r is not None and r["id"] == request_id:
                self._slots[slot] = None
                if self._obs is not None:
                    self._obs.event(
                        "req_cancel", rid=request_id, where="slot",
                        gen=r["gen"],
                    )
                return True
        return False

    # ---------------------------------------------------------- scheduling

    def _check_poisoned(self) -> None:
        """The resident cache/prev buffers are DONATED into the segment
        and admission kernels; if such a call raised (or was interrupted
        mid-flight), the donated buffers are invalidated while
        ``self._cache``/``self._prev`` still point at them. Rather than
        letting a later step fail with an opaque 'array has been
        deleted', the first failure marks the server poisoned and every
        subsequent call reports it clearly. In-flight requests are lost
        (build a new Server and resubmit; prompts are host-side), but
        ALREADY-completed results are plain host ints — they stay
        retrievable via :meth:`results`."""
        if self._poisoned is not None:
            raise RuntimeError(
                "Server is poisoned: a donated-buffer kernel failed or "
                "was interrupted, invalidating the resident decode "
                "state. Completed results remain available via "
                "results(); build a new Server to resubmit the rest."
            ) from self._poisoned

    def results(self) -> dict:
        """Pop every COMPLETED request's tokens ({id: tokens}) without
        running anything — works on a poisoned server too (finished
        results are host-side and unaffected by lost device state)."""
        out, self._results = self._results, {}
        return out

    @property
    def pending(self) -> int:
        occupied = sum(1 for s in self._slots if s is not None)
        return len(self._waiting) + occupied

    def _occupied(self):
        return [s for s in self._slots if s is not None]

    def _admit_group(self, grp: list) -> None:
        """Prefill a SAME-BUCKET group of newcomers [(request, slot)]
        as one K-row kernel call and scatter their cache rows + first
        tokens into the resident tree; in-flight slots are untouched.
        K buckets to a power of two (compiles stay log-bounded in the
        burst size); pad rows repeat row 0's inputs and slot, so the
        scatter rewrites row 0's slot with identical data.

        With a server ``prefix``, each row's prefill covers only its
        SUFFIX (the part after the shared prefix): the group's starting
        cache is kb copies of the prefix template (built once, lazily)
        and the chunk appends at the prefix clock — admission pays
        suffix FLOPs, not prefix+suffix."""
        t_pre = time.perf_counter() if self._obs is not None else 0.0
        if self._cache is None:
            self._cache = sampling._zero_cache(self._dec, self._nb)
            self._prev = jnp.zeros((self._nb,), jnp.int32)
        pfx = len(self.prefix) if self.prefix else 0
        if self.prefix and self._template is None:
            pb = sampling._bucket(pfx, self._len_cap())
            buf = np.zeros((1, pb), np.int32)
            buf[0, :pfx] = self.prefix
            self._template = self._template_call(
                pb, jnp.asarray(buf), jnp.asarray([pfx], jnp.int32)
            )
        k = len(grp)
        kb = sampling._bucket(k, 1 << 30)
        # the suffix bucket must fit ABOVE the prefix clock: a chunk
        # appended at position pfx may span at most max_len - pfx slots
        # (a larger bucket would clamp the K/V write start, silently
        # corrupting the prefix rows)
        pre_bucket = sampling._bucket(
            max(len(r["known"]) - pfx for r, _ in grp),
            self._len_cap(pfx),
        )
        pre_buf = np.zeros((kb, pre_bucket), np.int32)
        p_lens = np.zeros((kb,), np.int32)
        slots = np.zeros((kb,), np.int32)
        temps = np.ones((kb,), np.float32)
        tops = np.ones((kb,), np.float32)
        keys0 = []
        for i, (r, slot) in enumerate(grp):
            p = r["known"][pfx:]  # the suffix (everything new)
            pre_buf[i, : len(p)] = p
            p_lens[i] = len(p)
            slots[i] = slot
            temps[i] = r["temp"]
            tops[i] = r["tp"]
            keys0.append(r["stream"][0])
        for i in range(k, kb):  # pad rows mirror row 0 exactly
            pre_buf[i] = pre_buf[0]
            p_lens[i] = p_lens[0]
            slots[i] = slots[0]
            temps[i] = temps[0]
            tops[i] = tops[0]
            keys0.append(grp[0][0]["stream"][0])
        cache0 = (
            _tile_rows(kb, self._template) if self.prefix
            else sampling._zero_cache(self._dec, kb)
        )
        rows, tok0 = self._prefill_call(
            pre_bucket, cache0,
            jnp.asarray(pre_buf), jnp.asarray(p_lens),
            jnp.stack(keys0), jnp.asarray(temps), jnp.asarray(tops),
            pfx,
        )
        self._cache = _insert_rows(self._cache, rows, jnp.asarray(slots))
        if self._dft is not None:
            # the DRAFT cache prefills the same prompts (its logits are
            # never sampled — only its filled rows matter) and scatters
            # into the resident draft tree at the same slots
            if self._d_cache is None:
                self._d_cache = sampling._zero_cache(self._dft, self._nb)
            d_rows = _prefill_prefix(
                self._dft, pre_bucket, self._d_params,
                sampling._zero_cache(self._dft, kb),
                jnp.asarray(pre_buf), jnp.asarray(p_lens),
            )
            self._d_cache = _insert_rows(
                self._d_cache, d_rows, jnp.asarray(slots)
            )
        self._prev = self._prev.at[jnp.asarray(slots[:k])].set(
            tok0[:k].astype(jnp.int32)
        )
        host0 = jax.device_get(tok0[:k])
        o = self._obs
        if o is not None:
            # the device_get above is proof of completion: the prefill
            # duration is real kernel+fetch time, not dispatch time
            o.event(
                "prefill", k=k, bucket=pre_bucket,
                dur=time.perf_counter() - t_pre,
            )
        for i, (r, slot) in enumerate(grp):
            t0 = int(host0[i])
            r["known"].append(t0)
            r["gen"] = 1
            done_eos = self.eos_id is not None and t0 == self.eos_id
            if o is not None:
                o.event("req_admit", rid=r["id"], slot=slot)
                o.event("req_first_token", rid=r["id"])
            if done_eos or r["gen"] >= r["max_new"]:
                self._results[r["id"]] = r["known"]  # done at admission
                if o is not None:
                    o.event(
                        "req_finish", rid=r["id"], gen=r["gen"],
                        reason="eos" if done_eos else "budget",
                    )
            else:
                self._slots[slot] = r

    def step(self) -> None:
        """One scheduling round: admit into free slots, run one segment,
        retire finished rows. Any failure mid-round poisons the server
        (see :meth:`_check_poisoned`) — donated resident buffers may be
        gone, so there is no safe partial state to continue from."""
        self._check_poisoned()
        try:
            self._step_inner()
        except BaseException as e:
            self._poisoned = e
            raise

    def _step_inner(self) -> None:
        # admission: pop FIFO waiters into free slots, then batch the
        # kernel work by prompt bucket — K same-bucket arrivals cost
        # ONE prefill call (the per-row clocks make the group kernel
        # identical to K solo prefills, row by row)
        free = [
            s for s in range(min(self._nb, self.max_batch))
            if self._slots[s] is None
        ]
        groups: dict[int, list] = {}
        pfx = len(self.prefix) if self.prefix else 0
        for slot in free:
            if not self._waiting:
                break
            r = self._waiting.popleft()
            # grouped by SUFFIX bucket — the part admission prefills
            # (same max_len - pfx cap as _admit_group's chunk)
            b = sampling._bucket(
                len(r["known"]) - pfx, self._len_cap(pfx)
            )
            groups.setdefault(b, []).append((r, slot))
        for grp in groups.values():
            self._admit_group(grp)
        occ = self._occupied()
        if not occ:
            return
        if self._dft is not None:
            self._spec_step(occ)
            return
        # a row at the max_len frontier caps the segment for everyone —
        # transient: such a row's budget ends within those ticks. Round
        # DOWN to a power of two so compiled programs stay log-bounded.
        # (horizon-free RNNs have no frontier)
        frontier = (
            min(self._max_len - len(r["known"]) for r in occ)
            if self._max_len is not None else 1 << 30
        )
        # ...and the LARGEST remaining budget caps it too (rounded UP to
        # a power of two): when every occupied row needs <= n more
        # tokens, ticks past bucket(n) are pure waste — the drain tail
        # used to burn a full `segment` of them per round
        need = max(r["max_new"] - r["gen"] for r in occ)
        cap = min(
            self.segment,
            1 << (frontier.bit_length() - 1),
            1 << max(need - 1, 0).bit_length(),
        )
        seg = 1 << (cap.bit_length() - 1)
        dummy = self._stream_slice(occ[0], seg)
        keys = jnp.stack([
            self._stream_slice(r, seg) if r is not None else dummy
            for r in self._slots
        ])
        temps = np.array(
            [1.0 if r is None else r["temp"] for r in self._slots],
            np.float32,
        )
        tops = np.array(
            [1.0 if r is None else r["tp"] for r in self._slots],
            np.float32,
        )
        t_seg = time.perf_counter() if self._obs is not None else 0.0
        self._cache, self._prev, toks = _serve_segment(
            self._dec, seg, self._greedy, self.top_k,
            self.top_p is not None,
            self.params, self._cache, self._prev, keys,
            jnp.asarray(temps), jnp.asarray(tops),
        )
        self.segments_run += 1
        self._harvest(jax.device_get(toks), [seg] * self._nb)
        if self._obs is not None:
            self._segment_event(t_seg, seg, len(occ))

    def _harvest(self, host, avail) -> None:
        """The ONE retirement convention both segment flavors share:
        append up to ``avail[slot]`` harvested tokens per occupied row
        (capped by its remaining budget), retire on eos or budget."""
        for slot, r in enumerate(self._slots):
            if r is None:
                continue
            take = min(int(avail[slot]), r["max_new"] - r["gen"])
            done = False
            for j in range(take):
                tok = int(host[slot, j])
                r["known"].append(tok)
                r["gen"] += 1
                if self.eos_id is not None and tok == self.eos_id:
                    done = True
                    break
            if done or r["gen"] >= r["max_new"]:
                self._results[r["id"]] = r["known"]
                self._slots[slot] = None
                if self._obs is not None:
                    self._obs.event(
                        "req_finish", rid=r["id"], gen=r["gen"],
                        reason="eos" if done else "budget",
                    )

    def _spec_step(self, occ) -> None:
        """One speculative scheduling round: ``rounds`` batched
        draft-verify rounds as one program, then retire. Emitted token
        counts are per row (each row accepts at its own rate); the host
        takes what each budget needs — exactly the tick path's
        retirement rules on a variable-length harvest."""
        k = self.spec_k
        # rounds capped by the configured count, the max_len frontier
        # (a round advances a row's clock by at most k+1), and the
        # largest remaining budget (a round emits at least one token)
        frontier = min(
            (self._max_len - (len(r["known"]) - 1)) // (k + 1)
            for r in occ
        )
        need = max(r["max_new"] - r["gen"] for r in occ)
        rounds = max(1, min(self.spec_rounds, frontier, need))
        pos0 = np.zeros((self._nb,), np.int32)
        for slot, r in enumerate(self._slots):
            if r is not None:
                pos0[slot] = len(r["known"]) - 1
        t_seg = time.perf_counter() if self._obs is not None else 0.0
        self._cache, self._d_cache, self._prev, out, n = (
            _serve_spec_segment(
                self._dec, self._dft, k, self.spec_rounds,
                self.params, self._d_params,
                self._cache, self._d_cache, self._prev,
                jnp.asarray(pos0), jnp.asarray(rounds, jnp.int32),
            )
        )
        self.segments_run += 1
        self._harvest(jax.device_get(out), jax.device_get(n))
        if self._obs is not None:
            self._segment_event(t_seg, rounds, len(occ), spec=True)

    def _segment_event(self, t_begin, seg, occupied, spec=False) -> None:
        """One ``segment`` record per scheduling boundary: duration
        (kernel + harvest fetch — proof of completion), batch occupancy
        entering the segment, and the queue depth left waiting — the
        inputs ``obs slo`` integrates into queue-depth-over-time and
        batch-occupancy. Only called when obs is armed."""
        self._obs.event(
            "segment", seg=int(seg), occupied=occupied,
            nslots=min(self._nb, self.max_batch),
            waiting=len(self._waiting),
            dur=time.perf_counter() - t_begin,
            **({"spec": True} if spec else {}),
        )

    def obs_event(self, ev: str, **fields) -> None:
        """Journal a caller-side event into this server's obs journal —
        a no-op when obs is off. The load harness uses it to place its
        chaos faults (``serve_fault``) on the same timeline as the
        request lifecycles."""
        if self._obs is not None:
            self._obs.event(ev, **fields)

    @property
    def obs_registry(self):
        """The live metrics registry when ``ObsConfig.live`` is armed,
        else None — the :func:`mpit_tpu.obs.live.live_registry` hook's
        contract, so harness-side code publishes through the server the
        same way protocol code publishes through a transport."""
        return self._obs.registry if self._obs is not None else None

    def close(self) -> None:
        """Flush and close the obs journal (idempotent; a no-op when obs
        is off). The journal flushes per record, so an unclosed server
        loses nothing but the ``journal_cap`` footer."""
        if self._obs is not None:
            self._obs.close()

    def _stream_slice(self, r: dict, steps: int):
        """keys [gen, gen+steps) of the request's stream, padded by
        repeating the last key (pad positions are only ever consumed by
        ticks whose samples this server discards — beyond the budget)."""
        s = r["stream"][r["gen"]: r["gen"] + steps]
        if s.shape[0] < steps:
            s = jnp.concatenate(
                [s, jnp.repeat(s[-1:], steps - s.shape[0], axis=0)]
            )
        return s

    def drain(self) -> dict:
        """Run until every submitted request finished; returns
        {id: tokens} (prompt included; truncated just past eos if one was
        emitted — the shared truncation convention). On a poisoned
        server this raises even when nothing appears pending (a failed
        admission loses requests from the queue without occupying a
        slot); use :meth:`results` for the completed work."""
        self._check_poisoned()
        while self._waiting or self._occupied():
            self.step()
        return self.results()


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _rnn_prefill_rows(
    model, pre_bucket, greedy, top_k, use_top_p,
    params, cache0, pre_buf, p_lens, keys0, temp, top_p,
):
    """RNN admission: a group of prompts through the shared RNN prefill
    recipe (`rnn_sampling._rnn_prefill` — carries freeze at each row's
    own length; no counters exist to fix), each row's first token
    sampled from its last true position with its stream key 0.
    Starting carries come from ``cache0`` (zero, or prefix-template
    copies)."""
    from mpit_tpu.models.rnn_sampling import _rnn_prefill

    cache, last = _rnn_prefill(model, params, cache0, pre_buf, p_lens)
    tok0 = sampling._sample_rows(
        last, keys0, greedy, top_k, use_top_p, temp, top_p
    )
    return cache, tok0


@functools.partial(jax.jit, static_argnums=(0, 1))
def _rnn_prefill_template(model, pre_bucket, params, cache0, pre_buf, p_len):
    """Carry-only RNN prefill (no head) for the prefix template."""
    from mpit_tpu.models.rnn_sampling import _rnn_prefill

    cache, _ = _rnn_prefill(
        model, params, cache0, pre_buf, p_len, with_head=False
    )
    return cache


class RNNServer(Server):
    """Continuous batching for the carry-decode RNN family
    (:class:`~mpit_tpu.models.lstm.LSTMLM`): the SAME scheduler as
    :class:`Server` — resident state, segments, grouped burst
    admission, per-request rules, shared-prefix template, cancel,
    poison safety — with the carry tree replacing the KV cache. Three
    differences, all at the model-family hooks: the decode clone is
    plain ``clone(decode=True)``; admission prefills through the
    ``seq_lengths`` path (carries freeze at each row's own prompt
    length — no position counters exist); and there is no ``max_len``
    horizon, so the frontier/bucket caps are unbounded. The per-tick
    segment kernel is the shared :func:`_serve_segment` — an RNN decode
    step is the same (B, 1)-token mutate-the-cache program shape.
    Speculative mode is transformer-only (rejected at construction).

    Parity contract unchanged: every result bit-equal to its solo
    :func:`~mpit_tpu.models.rnn_sampling.generate_rnn` call."""

    def __init__(self, model, params, **kw):
        # fail at construction, not at first admission (where the
        # mismatched prefill would poison the server): KV-cache models
        # carry a max_len horizon, carry-decode RNNs do not
        if getattr(model, "max_len", None) is not None:
            raise ValueError(
                "RNNServer serves carry-decode RNN models (no max_len "
                "horizon); use Server for KV-cache transformer models"
            )
        super().__init__(model, params, **kw)

    def _decode_clone(self, model):
        return model.clone(decode=True)

    def _prefill_call(
        self, pre_bucket, cache0, pre_buf, p_lens, keys0, temps, tops, pfx
    ):
        del pfx  # carries have no clock to offset
        return _rnn_prefill_rows(
            self._dec, pre_bucket, self._greedy, self.top_k,
            self.top_p is not None,
            self.params, cache0, pre_buf, p_lens, keys0, temps, tops,
        )

    def _template_call(self, pb, buf, p_len):
        return _rnn_prefill_template(
            self._dec, pb, self.params,
            sampling._zero_cache(self._dec, 1), buf, p_len,
        )
