"""Continuous-batching serving loop over the KV-cached decode kernels.

The reference trains models but cannot sample from them at all; this is
the beyond-parity serving tier above :func:`~mpit_tpu.models.sampling.
generate_batch`: a scheduler that keeps a decode batch full while
requests arrive and finish at different times.

Design (TPU-first, built ENTIRELY on the existing compiled kernels — no
new model code, no per-row cache clocks):

- Decoding advances in fixed **segments** of ticks. Each segment is one
  call into the shared batched kernel path (``_batch_impl``), so the
  whole segment is one (or two: prefill + scan) XLA program — the host
  only intervenes at segment boundaries.
- At a segment boundary the server retires finished rows (budget
  exhausted or ``eos_id`` emitted) and **admits** queued requests into
  the freed slots. Admission re-enters every in-flight row's KNOWN
  tokens (prompt + generated so far) as that row's "prompt": the mixed-
  length chunked prefill then rebuilds all caches in one matmul-bound
  dense pass. That re-prefill is the price of admission — O(L) extra
  FLOPs per admission event, paid on the MXU-friendly path — and what
  it buys is a decode batch that never runs with dead rows. (True
  in-place admission needs per-row cache clocks, a Block-level change;
  this scheduler is deliberately kernel-reusing instead.)
- **Exact parity**: every request's result is bit-equal to its solo
  ``generate_fast(prompt, max_new, rng=request_rng)`` call. Sampling
  keys are pre-split per request (``split(rng, max_new)``) and each
  segment feeds the kernel the UNUSED SLICE of each row's stream
  (``_batch_impl(key_streams=...)``), so token k of a request is always
  drawn with stream key k no matter how segments and batch compositions
  fell. Greedy is parity-trivial; the key plumbing makes sampled
  serving parity hold too — pinned in tests/test_serving.py.

Row independence (each row's outputs depend only on its own tokens —
the property the batch==solo tests pin) is what makes retirement and
admission invisible to the surviving rows.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp

from mpit_tpu.models import sampling


class Server:
    """Continuous-batching decode server for one model + params.

    Args:
      model: a dense ``TransformerLM`` (same restrictions as
        :func:`~mpit_tpu.models.sampling.generate_fast`).
      params: trained parameters. With ``weights_dtype="bf16"`` they are
        cast ONCE here (serving is HBM-bound; see ``cast_weights``).
      max_batch: decode-slot count; queued requests wait for a free slot.
      segment: ticks per kernel call between scheduling points. Large
        segments amortize dispatch; small segments admit/retire sooner.
      temperature/top_k/top_p/eos_id: the sampling rule, shared by every
        request this server runs (per-request rules would recompile per
        combination; serve different rules from different Servers).
    """

    def __init__(
        self,
        model,
        params,
        max_batch: int = 8,
        segment: int = 32,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        eos_id: Optional[int] = None,
        weights_dtype=None,
        seed: int = 0,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if segment < 1:
            raise ValueError("segment must be >= 1")
        self.model = model
        self.params = (
            sampling.cast_weights(params, jnp.bfloat16)
            if weights_dtype in ("bf16", jnp.bfloat16) else params
        )
        self.max_batch = int(max_batch)
        self.segment = int(segment)
        self.temperature = float(temperature)
        self.top_k = top_k
        self.top_p = top_p
        self.eos_id = eos_id
        self._rng = jax.random.key(seed)
        self._next_id = 0
        self._waiting: deque[dict] = deque()
        self._active: list[dict] = []
        self._results: dict[int, list[int]] = {}
        self.segments_run = 0

    # ------------------------------------------------------------- intake

    def submit(
        self, prompt, max_new_tokens: int, rng=None, seed=None
    ) -> int:
        """Queue a request; returns its id. The request's sampling stream
        is fixed HERE (``rng``, or ``fold_in(server_rng, id)`` — matching
        ``generate_batch``'s per-row derivation), so results are
        reproducible regardless of scheduling."""
        sampling._validate(
            self.model, prompt, self.temperature, self.top_k, self.top_p,
            self.eos_id,
        )
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > self.model.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len={self.model.max_len} "
                "(the cached decode cannot slide)"
            )
        rid = self._next_id
        self._next_id += 1
        if rng is None:
            rng = (
                jax.random.key(seed) if seed is not None
                else jax.random.fold_in(self._rng, rid)
            )
        self._waiting.append({
            "id": rid,
            "known": [int(t) for t in prompt],
            "p0": len(prompt),
            "max_new": int(max_new_tokens),
            "gen": 0,
            # the request's ENTIRE stream, split once: segment k draws
            # keys [gen, gen+steps) from it — solo-call parity
            "stream": jax.random.split(rng, max_new_tokens),
        })
        return rid

    # ---------------------------------------------------------- scheduling

    @property
    def pending(self) -> int:
        return len(self._waiting) + len(self._active)

    def step(self) -> None:
        """One scheduling round: admit into free slots, run one segment,
        retire finished rows."""
        while self._waiting and len(self._active) < self.max_batch:
            self._active.append(self._waiting.popleft())
        if not self._active:
            return
        # a row at the max_len frontier caps the segment for everyone —
        # transient: such a row's budget ends within those ticks
        steps = min(
            self.segment,
            min(self.model.max_len - len(r["known"])
                for r in self._active),
        )
        keys = jnp.stack([
            self._stream_slice(r, steps) for r in self._active
        ])
        rows = sampling._batch_impl(
            self.model, self.params,
            [r["known"] for r in self._active], steps,
            self.temperature, 0, None, self.top_k, self.top_p,
            key_streams=keys,
        )
        self.segments_run += 1
        survivors = []
        for r, row in zip(self._active, rows):
            new = row[len(r["known"]):]
            take = min(len(new), r["max_new"] - r["gen"])
            done = False
            for j in range(take):
                tok = int(new[j])
                r["known"].append(tok)
                r["gen"] += 1
                if self.eos_id is not None and tok == self.eos_id:
                    done = True
                    break
            if done or r["gen"] >= r["max_new"]:
                self._results[r["id"]] = r["known"]
            else:
                survivors.append(r)
        self._active = survivors

    def _stream_slice(self, r: dict, steps: int):
        """keys [gen, gen+steps) of the request's stream, padded by
        repeating the last key (pad positions are only ever consumed by
        ticks whose samples this server discards — beyond the budget)."""
        s = r["stream"][r["gen"]: r["gen"] + steps]
        if s.shape[0] < steps:
            s = jnp.concatenate(
                [s, jnp.repeat(s[-1:], steps - s.shape[0], axis=0)]
            )
        return s

    def drain(self) -> dict:
        """Run until every submitted request finished; returns
        {id: tokens} (prompt included; truncated just past eos if one was
        emitted — the shared truncation convention)."""
        while self._waiting or self._active:
            self.step()
        out, self._results = self._results, {}
        return out
