"""Causal transformer LM — the long-context model family.

Beyond-parity extension (the reference's only sequence model is the PTB
LSTM, SURVEY.md §5): a pre-LN decoder-only transformer whose attention can
run either dense (single-device sequence) or as exact ring attention over a
mesh axis (``seq_axis`` set — the model is then applied INSIDE shard_map
with the sequence dimension sharded onto that axis, and every device holds
``T/W`` positions; ``mpit_tpu.ops.ring_attention``).

The same parameters produce the same function either way: positions are
computed globally from the ring rank, attention is exact, and the loss is a
per-position mean — see tests/test_seq_parallel.py for the bit-level
equivalence checks across mesh shapes.

TPU notes: bf16 compute / f32 params by default, NHD head layout feeding
128-multiple-friendly matmuls; attention accumulates in f32 (the op's
standard recipe).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from mpit_tpu.ops.ring_attention import dense_attention, ring_attention


class Block(nn.Module):
    d_model: int
    num_heads: int
    d_ff: int
    compute_dtype: Any
    seq_axis: Optional[str]

    @nn.compact
    def __call__(self, x):
        dt = self.compute_dtype
        h, d = self.num_heads, self.d_model // self.num_heads
        y = nn.LayerNorm(dtype=dt)(x)
        qkv = nn.Dense(3 * self.d_model, use_bias=False, dtype=dt)(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda a: a.reshape(*a.shape[:2], h, d)
        q, k, v = split(q), split(k), split(v)
        if self.seq_axis is not None:
            att = ring_attention(q, k, v, self.seq_axis, causal=True)
        else:
            att = dense_attention(q, k, v, causal=True)
        att = att.reshape(*att.shape[:2], self.d_model)
        x = x + nn.Dense(self.d_model, use_bias=False, dtype=dt)(att)
        y = nn.LayerNorm(dtype=dt)(x)
        y = nn.Dense(self.d_ff, dtype=dt)(y)
        y = nn.gelu(y)
        x = x + nn.Dense(self.d_model, dtype=dt)(y)
        return x


class TransformerLM(nn.Module):
    """Next-token LM over ``(B, T_local)`` int32 tokens → f32 logits.

    ``seq_axis=None``: ordinary single-sequence model (T_local = T).
    ``seq_axis="sp"``: sequence-parallel — MUST be called inside shard_map
    over a mesh with that axis; tokens are the local contiguous block in
    ring order and positional embeddings are indexed by GLOBAL position
    (ring rank × T_local + local offset).
    """

    vocab_size: int
    num_layers: int = 2
    d_model: int = 128
    num_heads: int = 4
    d_ff: int = 0  # 0 -> 4*d_model
    max_len: int = 1024
    compute_dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None
    # rematerialize each block on the backward pass: activation memory
    # drops from O(layers) to O(1) blocks for ~1/3 more FLOPs — the
    # standard jax.checkpoint trade to fit longer T or bigger B in HBM
    remat: bool = False

    @nn.compact
    def __call__(self, tokens):
        if self.d_model % self.num_heads:
            raise ValueError(
                f"d_model {self.d_model} not divisible by "
                f"num_heads {self.num_heads}"
            )
        dt = self.compute_dtype
        t_local = tokens.shape[1]
        embed = nn.Embed(self.vocab_size, self.d_model, dtype=dt)
        pos_table = self.param(
            "pos_embedding",
            nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
            jnp.float32,
        )
        offset = 0
        total_len = t_local
        if self.seq_axis is not None:
            total_len = t_local * jax.lax.axis_size(self.seq_axis)
            offset = jax.lax.axis_index(self.seq_axis) * t_local
        if total_len > self.max_len:
            raise ValueError(
                f"sequence of {total_len} exceeds max_len={self.max_len}"
            )
        pos = offset + jnp.arange(t_local)
        x = embed(tokens) + pos_table[pos].astype(dt)
        # explicit names: nn.remat renames the wrapped class (Checkpoint
        # Block), which would fork the param tree between remat modes
        block_cls = nn.remat(Block) if self.remat else Block
        for i in range(self.num_layers):
            x = block_cls(
                d_model=self.d_model,
                num_heads=self.num_heads,
                d_ff=self.d_ff or 4 * self.d_model,
                compute_dtype=dt,
                seq_axis=self.seq_axis,
                name=f"Block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=dt)(x)
        # tied output head, genuinely in f32: Embed.attend would promote the
        # query back to compute_dtype, quantizing large-vocab logits to bf16
        table = embed.embedding.astype(jnp.float32)
        return jnp.einsum("btd,vd->btv", x.astype(jnp.float32), table)
