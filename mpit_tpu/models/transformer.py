"""Causal transformer LM — the long-context model family.

Beyond-parity extension (the reference's only sequence model is the PTB
LSTM, SURVEY.md §5): a pre-LN decoder-only transformer whose attention can
run either dense (single-device sequence) or as exact ring attention over a
mesh axis (``seq_axis`` set — the model is then applied INSIDE shard_map
with the sequence dimension sharded onto that axis, and every device holds
``T/W`` positions; ``mpit_tpu.ops.ring_attention``).

The same parameters produce the same function either way: positions are
computed globally from the ring rank, attention is exact, and the loss is a
per-position mean — see tests/test_seq_parallel.py for the bit-level
equivalence checks across mesh shapes.

TPU notes: bf16 compute / f32 params by default, NHD head layout feeding
128-multiple-friendly matmuls; attention accumulates in f32 (the op's
standard recipe).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from mpit_tpu.ops.ring_attention import dense_attention, ring_attention
from mpit_tpu.ops.ulysses import ulysses_attention


class Block(nn.Module):
    d_model: int
    num_heads: int
    d_ff: int
    compute_dtype: Any
    seq_axis: Optional[str]
    moe_experts: int = 0
    moe_axis: Optional[str] = None
    moe_capacity_factor: float = 2.0
    moe_top_k: int = 1
    # single-device attention implementation: "xla" (fused dense),
    # "flash" (pallas kernels both directions on TPU, dense elsewhere),
    # "flash_force" (pallas everywhere — interpret mode off TPU; tests)
    attn_impl: str = "xla"
    # sequence-parallel scheme when seq_axis is set — see TransformerLM
    seq_impl: str = "ring"
    # autoregressive decode mode: the block consumes ONE token per call
    # and attends over a (B, max_len) K/V cache held in the "cache"
    # variable collection (serving path — models/sampling.generate_fast);
    # decode_len sizes the cache (the LM passes its max_len)
    decode: bool = False
    decode_len: int = 0

    @nn.compact
    def __call__(self, x):
        dt = self.compute_dtype
        h, d = self.num_heads, self.d_model // self.num_heads
        y = nn.LayerNorm(dtype=dt)(x)
        qkv = nn.Dense(3 * self.d_model, use_bias=False, dtype=dt)(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda a: a.reshape(*a.shape[:2], h, d)
        q, k, v = split(q), split(k), split(v)
        if self.seq_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"seq_impl={self.seq_impl!r} must be 'ring' or 'ulysses'"
            )
        if self.decode:
            if self.seq_axis is not None or self.moe_experts:
                raise ValueError(
                    "decode mode is single-device dense-FFN only "
                    "(seq_axis=None, moe_experts=0)"
                )
            att = self._cached_attention(q, k, v)
        elif self.seq_axis is not None and self.seq_impl == "ulysses":
            att = ulysses_attention(q, k, v, self.seq_axis, causal=True)
        elif self.seq_axis is not None:
            att = ring_attention(q, k, v, self.seq_axis, causal=True)
        elif self.attn_impl in ("flash", "flash_force"):
            from mpit_tpu.ops.flash_attention import flash_attention

            att = flash_attention(
                q, k, v, causal=True,
                use_pallas=True if self.attn_impl == "flash_force"
                else None,
            )
        else:
            att = dense_attention(q, k, v, causal=True)
        att = att.reshape(*att.shape[:2], self.d_model)
        x = x + nn.Dense(self.d_model, use_bias=False, dtype=dt)(att)
        y = nn.LayerNorm(dtype=dt)(x)
        if self.moe_experts:
            x = x + self._moe(y)
        else:
            y = nn.Dense(self.d_ff, dtype=dt)(y)
            y = nn.gelu(y)
            x = x + nn.Dense(self.d_model, dtype=dt)(y)
        return x

    def _cached_attention(self, q, k, v):
        """Causal attention of a T-token CHUNK over the persistent K/V
        cache (T = 1 per-token decode; T > 1 chunked prefill — the
        prompt lands in the cache as one matmul-bound pass instead of T
        latency-bound ticks).

        The cache lives in the ``cache`` variable collection (flax's
        standard decode recipe): ``cached_key``/``cached_value`` hold the
        first ``cache_index`` positions' keys/values; each call appends
        the chunk's K/V at ``[cache_index, cache_index+T)`` and the
        chunk's query at local row ``r`` (global position
        ``cache_index + r``) attends cache positions ``<= cache_index +
        r`` — exactly the causal rule. Static shapes throughout — the
        cache is allocated at ``decode_len`` and masked, so the whole
        generation loop compiles once per bucket
        (sampling.generate_fast).

        ``cache_index`` is PER ROW, shape (B,): each batch row carries
        its own position clock, so a mixed-length batch prefills every
        row's ENTIRE prompt in one dense pass and ticks from there
        (sampling's batched kernel) — rows no longer share a scalar
        frontier. The K/V append becomes a per-row dynamic_update_slice
        (vmapped) and the causal mask compares against each row's own
        index; with all rows' indices equal this is exactly the old
        shared-clock behavior.

        Numerics match :func:`dense_attention`: f32 scores/softmax/
        accumulation, inputs left in compute dtype for the einsums.
        """
        if self.decode_len <= 0:
            raise ValueError(
                f"decode=True needs decode_len > 0, got {self.decode_len}"
            )
        b, t, h, d = q.shape
        if t > self.decode_len:
            raise ValueError(
                f"chunk of {t} exceeds the {self.decode_len}-slot cache"
            )
        # has_variable BEFORE self.variable: during model.init the cache
        # is created on this very call, and mutating it then would leak
        # a post-step index into the initial cache state
        ready = self.has_variable("cache", "cached_key")
        zeros = nn.initializers.zeros_init()
        ck = self.variable(
            "cache", "cached_key", zeros, None,
            (b, self.decode_len, h, d), k.dtype,
        )
        cv = self.variable(
            "cache", "cached_value", zeros, None,
            (b, self.decode_len, h, d), v.dtype,
        )
        idx = self.variable(
            "cache", "cache_index",
            lambda: jnp.zeros((b,), jnp.int32),
        )
        i = idx.value  # (b,) per-row position clocks
        row_update = jax.vmap(
            lambda cache_row, chunk_row, start:
            jax.lax.dynamic_update_slice(cache_row, chunk_row, (start, 0, 0))
        )
        key_cache = row_update(ck.value, k, i)
        val_cache = row_update(cv.value, v, i)
        if ready:
            ck.value, cv.value = key_cache, val_cache
            idx.value = i + t
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q, key_cache,
            preferred_element_type=jnp.float32,
        ) / (d ** 0.5)
        # row r of batch row n may see cache positions <= i[n] + r
        mask = (
            jnp.arange(self.decode_len)[None, None, :]
            <= i[:, None, None] + jnp.arange(t)[None, :, None]
        )  # (b, t, L)
        s = jnp.where(mask[:, None], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum(
            "bhqk,bkhd->bqhd", p, val_cache,
            preferred_element_type=jnp.float32,
        ).astype(q.dtype)

    def _moe(self, y):
        """GShard MoE FFN replacing the dense MLP (``mpit_tpu.ops.moe``).

        Param names carry the ``moe_`` prefix — the expert-parallel
        trainer's sharding rules key on it (experts shard over
        ``moe_axis``, the router stays replicated). Outside shard_map
        (``moe_axis=None``) the dense reference computes the same
        function on all experts locally.

        Routing-quality stats (balance loss, router z-loss, drop
        fraction) are sown into the ``moe_losses`` collection — a no-op
        unless the caller applies with ``mutable=["moe_losses"]``, so
        plain ``apply`` paths are untouched.
        """
        from mpit_tpu.ops.moe import moe_ffn, moe_ffn_dense_reference

        e, dm, f = self.moe_experts, self.d_model, self.d_ff
        # flax validates declared param shapes on APPLY too, so inside
        # shard_map the expert leaves must be declared with their LOCAL
        # shard shape (axis size is static there); init runs on the dense
        # clone (moe_axis=None) and produces the global (e, ...) leaves
        # that the trainer's P(axis) in-specs then shard to exactly this
        e_l = e
        if self.moe_axis is not None:
            world = jax.lax.axis_size(self.moe_axis)
            if e % world:
                raise ValueError(
                    f"moe_experts={e} not divisible by the {world}-wide "
                    f"{self.moe_axis!r} axis"
                )
            e_l = e // world
        init = nn.initializers.lecun_normal()
        # the expert dim is a BATCH axis for initialization — plain lecun
        # on (E, d_in, d_out) would count E into fan_in and start every
        # expert sqrt(E) too small
        expert_init = nn.initializers.variance_scaling(
            1.0, "fan_in", "truncated_normal", in_axis=-2, out_axis=-1,
            batch_axis=(0,),
        )
        params = {
            "router": self.param("moe_router", init, (dm, e), jnp.float32),
            "w_up": self.param(
                "moe_w_up", expert_init, (e_l, dm, f), jnp.float32
            ),
            "b_up": self.param(
                "moe_b_up", nn.initializers.zeros_init(), (e_l, f),
                jnp.float32,
            ),
            "w_down": self.param(
                "moe_w_down", expert_init, (e_l, f, dm), jnp.float32
            ),
            "b_down": self.param(
                "moe_b_down", nn.initializers.zeros_init(), (e_l, dm),
                jnp.float32,
            ),
        }
        if self.moe_axis is not None:
            out, aux = moe_ffn(
                params, y, axis=self.moe_axis,
                capacity_factor=self.moe_capacity_factor,
                top_k=self.moe_top_k, with_aux=True,
            )
        else:
            out, aux = moe_ffn_dense_reference(
                params, y, capacity_factor=self.moe_capacity_factor,
                top_k=self.moe_top_k, with_aux=True,
            )
        for name, val in aux.items():
            self.sow("moe_losses", name, val)
        return out


def aggregate_moe_losses(collection: dict) -> dict:
    """Mean each sown MoE stat over the blocks that sowed it.

    ``collection`` is the ``moe_losses`` mutable returned by
    ``model.apply(..., mutable=["moe_losses"])``:
    ``{"Block_i": {name: (scalar,), ...}, ...}`` → ``{name: scalar}``.
    """
    per_name: dict = {}
    for block_vals in collection.values():
        for name, vals in block_vals.items():
            per_name.setdefault(name, []).extend(vals)
    return {
        name: sum(vals) / len(vals) for name, vals in per_name.items()
    }


class TransformerLM(nn.Module):
    """Next-token LM over ``(B, T_local)`` int32 tokens → f32 logits.

    ``seq_axis=None``: ordinary single-sequence model (T_local = T).
    ``seq_axis="sp"``: sequence-parallel — MUST be called inside shard_map
    over a mesh with that axis; tokens are the local contiguous block in
    ring order and positional embeddings are indexed by GLOBAL position
    (ring rank × T_local + local offset).
    """

    vocab_size: int
    num_layers: int = 2
    d_model: int = 128
    num_heads: int = 4
    d_ff: int = 0  # 0 -> 4*d_model
    max_len: int = 1024
    compute_dtype: Any = jnp.bfloat16
    seq_axis: Optional[str] = None
    # rematerialize each block on the backward pass: activation memory
    # drops from O(layers) to O(1) blocks for ~1/3 more FLOPs — the
    # standard jax.checkpoint trade to fit longer T or bigger B in HBM
    remat: bool = False
    # mixture-of-experts FFN: moe_experts > 0 replaces every block's MLP
    # with a top-k-routed MoE (ops/moe.py); moe_axis names the mesh axis
    # experts shard over (None = all experts local / dense reference);
    # moe_balance_weight/moe_zloss_weight scale the auxiliary
    # load-balance and router z losses the MoE trainer adds to the CE
    # objective (0.0 = off; the stats are sown either way)
    moe_experts: int = 0
    moe_axis: Optional[str] = None
    moe_capacity_factor: float = 2.0
    moe_top_k: int = 1
    moe_balance_weight: float = 0.0
    moe_zloss_weight: float = 0.0
    # attention tiling for the dense (seq_axis=None) path — see Block
    attn_impl: str = "xla"
    # sequence-parallel scheme when seq_axis is set: "ring" (K/V blocks
    # rotate via ppermute — extreme T, no score matrix) or "ulysses"
    # (all_to_all head<->sequence re-shard around dense attention —
    # moderate T, needs num_heads % axis == 0). Both exact.
    seq_impl: str = "ring"
    # serving path: decode=True turns every block into a cached-attention
    # chunk step (see Block.decode); params are IDENTICAL to the
    # training configuration — only the "cache" collection is added
    decode: bool = False
    # head=False returns the final-norm hidden states (B, T, d_model)
    # instead of logits — chunked prefill projects ONE row through the
    # vocab head (head_logits) rather than materializing (B, T, V) f32
    head: bool = True
    # vocab-head OPERAND dtype override (None -> compute_dtype).
    # Accumulation is always f32 regardless. Exists so the bf16-head
    # quality guard (tests/test_head_dtype.py) can A/B the head in
    # isolation; head_dtype=f32 also serves a bf16 model with a
    # full-precision head when quality comparisons call for it.
    head_dtype: Any = None

    @property
    def _head_operand_dtype(self):
        """The ONE resolution of the head's operand dtype — shared by
        the ``__call__`` head and ``head_logits`` so the prefill==tick
        bit-equality the serving tests pin cannot fork on a rule edit."""
        return (
            self.compute_dtype if self.head_dtype is None
            else self.head_dtype
        )

    @nn.compact
    def __call__(self, tokens):
        if self.d_model % self.num_heads:
            raise ValueError(
                f"d_model {self.d_model} not divisible by "
                f"num_heads {self.num_heads}"
            )
        dt = self.compute_dtype
        t_local = tokens.shape[1]
        # name pinned explicitly (matches the flax auto-name so existing
        # checkpoints/param trees are unchanged): head_logits() reaches the
        # tied table via params["Embed_0"]["embedding"], so reordering or
        # renaming this module must not move that path
        embed = nn.Embed(
            self.vocab_size, self.d_model, dtype=dt, name="Embed_0"
        )
        pos_table = self.param(
            "pos_embedding",
            nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
            jnp.float32,
        )
        offset = 0
        total_len = t_local
        if self.decode:
            if self.seq_axis is not None:
                raise ValueError("decode mode requires seq_axis=None")
            # the LM's own position counter (each block keeps its own
            # cache_index; this one feeds the positional embedding) —
            # same create-before-mutate discipline as Block's cache, and
            # PER ROW like cache_index (each batch row at its own position)
            ready = self.has_variable("cache", "pos_index")
            pidx = self.variable(
                "cache", "pos_index",
                lambda: jnp.zeros((tokens.shape[0],), jnp.int32),
            )
            offset = pidx.value  # (B,)
            if ready:
                pidx.value = offset + t_local
            total_len = 1  # bounds are the caller's contract in decode
        elif self.seq_axis is not None:
            # sequence-parallel: this shard's tokens are the ring-rank'th
            # contiguous block, so positions are GLOBAL offsets
            total_len = t_local * jax.lax.axis_size(self.seq_axis)
            offset = jax.lax.axis_index(self.seq_axis) * t_local
        if total_len > self.max_len:
            raise ValueError(
                f"sequence of {total_len} exceeds max_len={self.max_len}"
            )
        # scalar offset -> (t,) positions; per-row decode offset (B,) ->
        # (B, t) positions — the table gather broadcasts either way
        pos = jnp.asarray(offset)[..., None] + jnp.arange(t_local)
        x = embed(tokens) + pos_table[pos].astype(dt)
        # explicit names: nn.remat renames the wrapped class (Checkpoint
        # Block), which would fork the param tree between remat modes
        block_cls = nn.remat(Block) if self.remat else Block
        for i in range(self.num_layers):
            x = block_cls(
                d_model=self.d_model,
                num_heads=self.num_heads,
                d_ff=self.d_ff or 4 * self.d_model,
                compute_dtype=dt,
                seq_axis=self.seq_axis,
                moe_experts=self.moe_experts,
                moe_axis=self.moe_axis,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_top_k=self.moe_top_k,
                attn_impl=self.attn_impl,
                seq_impl=self.seq_impl,
                decode=self.decode,
                decode_len=self.max_len if self.decode else 0,
                name=f"Block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=dt)(x)
        if not self.head:
            return x
        # tied output head: operands in the head operand dtype (default
        # compute_dtype; head_dtype overrides), ACCUMULATION in f32
        # (preferred_element_type). What must not happen is large-vocab
        # logits quantized to bf16 on output (Embed.attend's behavior);
        # f32 accumulation prevents that while keeping the matmul on the
        # MXU's bf16 fast path — an f32xf32 head at GPT-2-small shapes is
        # ~16% of forward FLOPs running at a fraction of MXU rate, which
        # taxes exactly the MFU-ceiling preset built to prove the
        # framework isn't the bottleneck. For compute_dtype=float32
        # models (the equivalence-test configuration) this is bit-
        # identical to the previous all-f32 head.
        hdt = self._head_operand_dtype
        table = embed.embedding.astype(hdt)
        return jnp.einsum(
            "btd,vd->btv", x.astype(hdt), table,
            preferred_element_type=jnp.float32,
        )

    def head_logits(self, params, h):
        """The tied vocab head applied to (B, d_model) hidden rows —
        the SAME projection ``__call__`` ends with (head-operand-dtype
        operands, f32 accumulation), for callers that ran ``head=False``
        and kept only the rows they need (chunked prefill). The embed
        table's param path is pinned by a test against a full forward."""
        hdt = self._head_operand_dtype
        table = params["Embed_0"]["embedding"].astype(hdt)
        return jnp.einsum(
            "bd,vd->bv", h.astype(hdt), table,
            preferred_element_type=jnp.float32,
        )
