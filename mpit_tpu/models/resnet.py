"""ResNet-50 for the large-tensor collective stress config (BASELINE.json:10
— reference config 4: "ResNet-50 ImageNet sync allreduce").

Bottleneck-v1.5 topology (stride in the 3×3), NHWC, bfloat16 compute /
float32 params. GroupNorm replaces BatchNorm so the module is a pure
function of params — sync DP then needs no cross-replica stats collective
beyond the gradient all-reduce this config exists to stress.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


def space_to_depth_stem(x, kernel, dt):
    """ResNet's 7×7/2 stem via the general s2d-conv reformulation
    (:func:`mpit_tpu.ops.stem.space_to_depth_conv` — see its derivation):
    contraction 147 → 192 over 12 channels, no MXU-hostile 3-channel conv,
    numerically identical to ``nn.Conv(64, (7,7), strides=2,
    padding=(3,3))`` with the same kernel."""
    from mpit_tpu.ops.stem import space_to_depth_conv

    return space_to_depth_conv(x, kernel, stride=2, padding=3, dt=dt)


class Bottleneck(nn.Module):
    features: int
    strides: tuple[int, int] = (1, 1)
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        dt = self.compute_dtype
        conv = partial(nn.Conv, use_bias=False, dtype=dt)
        norm = partial(nn.GroupNorm, num_groups=32, dtype=dt)
        residual = x
        y = conv(self.features, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.features, (3, 3), strides=self.strides, padding="SAME")(y)
        y = nn.relu(norm()(y))
        y = conv(self.features * 4, (1, 1))(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = conv(self.features * 4, (1, 1), strides=self.strides)(
                residual
            )
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet50(nn.Module):
    """``stem="conv"`` is the textbook 7×7/2; ``stem="space_to_depth"``
    computes the same function via :func:`space_to_depth_stem` (MXU-
    friendlier input layout; same 7×7×3×64 parameter shape, different flax
    param name — checkpoints do not interchange between stems)."""

    num_classes: int = 1000
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    compute_dtype: Any = jnp.bfloat16
    stem: str = "conv"
    # rematerialize each bottleneck on backward: the jax.checkpoint
    # memory/FLOPs trade — fits bigger batches at 224px
    remat: bool = False

    @nn.compact
    def __call__(self, x):
        from mpit_tpu.ops.stem import stem_conv

        dt = self.compute_dtype
        x = x.astype(dt)
        x = stem_conv(
            self, x, features=64, kernel=7, stride=2, padding=3,
            stem=self.stem, dt=dt, use_bias=False,
        )
        x = nn.relu(nn.GroupNorm(num_groups=32, dtype=dt)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        # explicit names: nn.remat renames the wrapped class, which would
        # fork the param tree between remat modes
        block_cls = nn.remat(Bottleneck) if self.remat else Bottleneck
        idx = 0
        for stage, blocks in enumerate(self.stage_sizes):
            for block in range(blocks):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = block_cls(
                    features=64 * 2**stage,
                    strides=strides,
                    compute_dtype=dt,
                    name=f"Bottleneck_{idx}",
                )(x)
                idx += 1
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=dt)(x)
        return x.astype(jnp.float32)
