"""Mixture-of-experts FFN with expert parallelism (GShard dispatch).

The last letter of the parallelism suite (dp / sp / tp / pp / ep): experts
shard across an ``ep`` mesh axis — each device owns ``E/ep`` expert FFNs
and a shard of the token batch — and tokens travel to their expert's
device and back with ``lax.all_to_all``, the TPU collective built for
exactly this exchange.

Algorithm (Mesh-TensorFlow / GShard, top-k routing with capacity):

1. router scores each LOCAL token over all ``E`` experts; top-k experts +
   softmax gates per token (k=1 keeps the raw top-1 probability as the
   gate — the Switch rule; k>1 renormalizes the selected gates to sum to
   one — the GShard rule);
2. per (expert, capacity-slot) one-hot **dispatch** mask and gate-weighted
   **combine** tensor are built locally — tokens beyond an expert's
   capacity ``C`` are dropped (the standard overflow rule; capacity_factor
   sizes ``C``). Queueing is choice-major: every token's FIRST choice
   claims its slot before any token's second choice (GShard's priority
   rule — overflow sheds the lower-priority assignments first);
3. ``einsum`` with the dispatch mask packs tokens into an ``(E, C, D)``
   buffer; ``all_to_all`` over ep regroups it so each device holds its own
   experts' slots from EVERY peer: ``(E/ep, ep·C, D)``;
4. the local expert FFNs run batched (one ``vmap`` over local experts —
   a single fat matmul pair on the MXU);
5. the reverse ``all_to_all`` returns processed slots, and the combine
   einsum scatters them back to token positions, gate-scaled.

With ``capacity_factor`` large enough that nothing drops, the result is
EXACTLY ``gate(token) · FFN_{expert(token)}(token)`` — pinned against a
per-token dense reference in tests/test_moe.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def init_moe_params(rng, d_model: int, d_ff: int, num_experts: int) -> dict:
    """Router + stacked expert FFN weights (E on the leading axis —
    shard it ``P("ep")`` for expert parallelism)."""
    k1, k2, k3 = jax.random.split(rng, 3)
    scale = 1.0 / np.sqrt(d_model)
    return {
        "router": jax.random.normal(k1, (d_model, num_experts)) * scale,
        "w_up": jax.random.normal(k2, (num_experts, d_model, d_ff)) * scale,
        "b_up": jnp.zeros((num_experts, d_ff)),
        "w_down": jax.random.normal(k3, (num_experts, d_ff, d_model))
        / np.sqrt(d_ff),
        "b_down": jnp.zeros((num_experts, d_model)),
    }


def _expert_ffn(w_up, b_up, w_down, b_down, x):
    """One expert's FFN — the ONE definition both the sharded path and the
    dense reference run (their equivalence proof depends on it)."""
    return jax.nn.gelu(x @ w_up + b_up) @ w_down + b_down


def _routing(h2, router, num_experts: int, capacity: int, top_k: int = 1):
    """(tokens, D) → dispatch (T, E, C) one-hot, combine (T, E, C), and
    LOCAL routing statistics (for the balance/z losses and drop metric)."""
    if not 1 <= top_k <= num_experts:
        raise ValueError(
            f"top_k={top_k} must be in [1, num_experts={num_experts}]"
        )
    t = h2.shape[0]
    logits = (h2 @ router).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, expert_idx = lax.top_k(probs, top_k)  # (T, k), distinct
    if top_k > 1:
        # GShard: selected gates renormalize to sum to one; the k=1 path
        # keeps the raw probability (Switch) so adding top-k changed no
        # existing top-1 numerics
        gate_vals = gate_vals / gate_vals.sum(-1, keepdims=True)
    # choice-major queueing: flatten (choice, token) so every first
    # choice claims its capacity slot before any second choice
    flat_oh = jax.nn.one_hot(
        expert_idx.T.reshape(-1), num_experts, dtype=jnp.float32
    )  # (k·T, E)
    # position of each assignment within its expert's queue; non-selected
    # columns end up at -1 and never pass the kept mask
    position = jnp.cumsum(flat_oh, axis=0) * flat_oh - 1.0
    kept = (position < capacity) & (flat_oh > 0)
    # exactly one kept column per surviving assignment -> the sum IS its
    # slot; dropped rows sum to 0 but their kept mask zeroes the dispatch
    slot = jnp.where(kept, position, 0.0).sum(-1).astype(jnp.int32)
    pos_oh = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)
    disp_choice = (
        kept.astype(jnp.float32)[:, :, None] * pos_oh[:, None, :]
    ).reshape(top_k, t, num_experts, capacity)
    dispatch = disp_choice.sum(0)
    combine = jnp.einsum("kt,ktec->tec", gate_vals.T, disp_choice)
    stats = {
        # first-choice density (the GShard/Switch balance-loss f term;
        # constant w.r.t. the router — only p carries gradient)
        "f": jax.nn.one_hot(
            expert_idx[:, 0], num_experts, dtype=jnp.float32
        ).mean(0),
        "p": probs.mean(0),
        "z": jnp.mean(
            jax.scipy.special.logsumexp(logits, axis=-1) ** 2
        ),
        "dropped": 1.0 - kept.sum() / (top_k * t),
    }
    return dispatch, combine, stats


def _aux_from_stats(f, p, z, dropped, num_experts: int) -> dict:
    """Balance/z losses from (possibly axis-averaged) routing stats.

    ``balance`` is the Switch/GShard auxiliary load-balance loss
    ``E · Σ_e f_e · p_e`` — exactly 1.0 under perfectly uniform routing,
    larger the more the router concentrates. ``f`` is non-differentiable
    (argmax density), so the gradient pushes ``p`` away from hot experts.
    """
    return {
        "balance": num_experts * jnp.dot(f, p),
        "zloss": z,
        "dropped_frac": dropped,
    }


def moe_ffn(
    params: dict,
    h: jax.Array,
    axis: str = "ep",
    capacity_factor: float = 2.0,
    top_k: int = 1,
    with_aux: bool = False,
) -> "jax.Array | tuple[jax.Array, dict]":
    """Expert-parallel MoE FFN inside ``shard_map``.

    ``h``: the LOCAL (b, t, D) activation block (batch sharded on
    ``axis``). ``params["w_up"]/...`` carry the LOCAL expert shard
    (leading dim E/ep); ``params["router"]`` is replicated and scores all
    E experts. Returns the same shape as ``h`` (plus an aux dict of
    ``balance``/``zloss``/``dropped_frac`` scalars when ``with_aux`` —
    each already ``pmean``-ed over ``axis``, so every device holds the
    GLOBAL value and the losses are exactly mesh-width-invariant).

    Capacity caveat: ``C`` is computed from the LOCAL token count, so the
    per-expert capacity — not just arrival order — depends on the ep
    extent. Under tight ``capacity_factor`` the set of dropped tokens is
    therefore NOT invariant to mesh width; only the ample-capacity
    (no-drop) regime is. The dense reference applies the same per-shard
    rule only when given the same local token count.
    """
    ep = lax.axis_size(axis)
    b, t, d = h.shape
    e_local = params["w_up"].shape[0]
    num_experts = e_local * ep
    if params["router"].shape[1] != num_experts:
        raise ValueError(
            f"router scores {params['router'].shape[1]} experts but the "
            f"local shard x axis implies {num_experts} (= {e_local} local "
            f"x ep={ep}); are the expert weights actually sharded P(ep)?"
        )
    tokens = b * t
    capacity = int(np.ceil(tokens * capacity_factor / num_experts))
    h2 = h.reshape(tokens, d)

    dispatch, combine, stats = _routing(
        h2, params["router"], num_experts, capacity, top_k=top_k
    )
    # pack: (E, C, D) buffer of this device's tokens, by expert and slot
    buf = jnp.einsum("tec,td->ecd", dispatch, h2.astype(jnp.float32))
    # regroup: split E across peers, gather every peer's slots for OUR
    # experts -> (E/ep, ep*C, D)
    buf = lax.all_to_all(
        buf.reshape(ep, e_local, capacity, d), axis, 0, 0, tiled=False
    )
    buf = buf.transpose(1, 0, 2, 3).reshape(e_local, ep * capacity, d)

    out = jax.vmap(_expert_ffn)(
        params["w_up"], params["b_up"], params["w_down"],
        params["b_down"], buf,
    )
    # reverse the exchange: every peer gets its slots back
    out = out.reshape(e_local, ep, capacity, d).transpose(1, 0, 2, 3)
    out = lax.all_to_all(out, axis, 0, 0, tiled=False)
    out = out.reshape(num_experts, capacity, d)
    res = jnp.einsum("tec,ecd->td", combine, out)
    res = res.reshape(b, t, d).astype(h.dtype)
    if not with_aux:
        return res
    # global stats: equal shard sizes make the pmean of local means exact
    # (one pytree pmean -> one fused all-reduce)
    g = lax.pmean(stats, axis)
    aux = _aux_from_stats(
        g["f"], g["p"], g["z"], g["dropped"], num_experts
    )
    return res, aux


def moe_ffn_dense_reference(
    params_full: dict,
    h: jax.Array,
    capacity_factor: float = 2.0,
    top_k: int = 1,
    with_aux: bool = False,
) -> "jax.Array | tuple[jax.Array, dict]":
    """Unsharded ground truth: route each token, run its expert directly.

    ``params_full`` carries ALL experts (leading dim E). Implements the
    identical capacity/overflow rule so the equivalence is exact even when
    tokens drop (given the same local token count — see the capacity
    caveat on :func:`moe_ffn`).
    """
    b, t, d = h.shape
    num_experts = params_full["w_up"].shape[0]
    tokens = b * t
    capacity = int(np.ceil(tokens * capacity_factor / num_experts))
    h2 = h.reshape(tokens, d)
    dispatch, combine, stats = _routing(
        h2, params_full["router"], num_experts, capacity, top_k=top_k
    )
    buf = jnp.einsum("tec,td->ecd", dispatch, h2.astype(jnp.float32))
    out = jax.vmap(_expert_ffn)(
        params_full["w_up"], params_full["b_up"], params_full["w_down"],
        params_full["b_down"], buf,
    )
    res = jnp.einsum("tec,ecd->td", combine, out)
    res = res.reshape(b, t, d).astype(h.dtype)
    if not with_aux:
        return res
    aux = _aux_from_stats(
        stats["f"], stats["p"], stats["z"], stats["dropped"], num_experts
    )
    return res, aux
