"""Mixture-of-experts FFN with expert parallelism (GShard dispatch).

The last letter of the parallelism suite (dp / sp / tp / pp / ep): experts
shard across an ``ep`` mesh axis — each device owns ``E/ep`` expert FFNs
and a shard of the token batch — and tokens travel to their expert's
device and back with ``lax.all_to_all``, the TPU collective built for
exactly this exchange.

Algorithm (Mesh-TensorFlow / GShard, top-1 routing with capacity):

1. router scores each LOCAL token over all ``E`` experts; top-1 expert +
   softmax gate per token;
2. per (expert, capacity-slot) one-hot **dispatch** mask and gate-weighted
   **combine** tensor are built locally — tokens beyond an expert's
   capacity ``C`` are dropped (the standard overflow rule; capacity_factor
   sizes ``C``);
3. ``einsum`` with the dispatch mask packs tokens into an ``(E, C, D)``
   buffer; ``all_to_all`` over ep regroups it so each device holds its own
   experts' slots from EVERY peer: ``(E/ep, ep·C, D)``;
4. the local expert FFNs run batched (one ``vmap`` over local experts —
   a single fat matmul pair on the MXU);
5. the reverse ``all_to_all`` returns processed slots, and the combine
   einsum scatters them back to token positions, gate-scaled.

With ``capacity_factor`` large enough that nothing drops, the result is
EXACTLY ``gate(token) · FFN_{expert(token)}(token)`` — pinned against a
per-token dense reference in tests/test_moe.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def init_moe_params(rng, d_model: int, d_ff: int, num_experts: int) -> dict:
    """Router + stacked expert FFN weights (E on the leading axis —
    shard it ``P("ep")`` for expert parallelism)."""
    k1, k2, k3 = jax.random.split(rng, 3)
    scale = 1.0 / np.sqrt(d_model)
    return {
        "router": jax.random.normal(k1, (d_model, num_experts)) * scale,
        "w_up": jax.random.normal(k2, (num_experts, d_model, d_ff)) * scale,
        "b_up": jnp.zeros((num_experts, d_ff)),
        "w_down": jax.random.normal(k3, (num_experts, d_ff, d_model))
        / np.sqrt(d_ff),
        "b_down": jnp.zeros((num_experts, d_model)),
    }


def _expert_ffn(w_up, b_up, w_down, b_down, x):
    """One expert's FFN — the ONE definition both the sharded path and the
    dense reference run (their equivalence proof depends on it)."""
    return jax.nn.gelu(x @ w_up + b_up) @ w_down + b_down


def _routing(h2, router, num_experts: int, capacity: int):
    """(tokens, D) → dispatch (T, E, C) one-hot and combine (T, E, C)."""
    logits = h2 @ router
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.float32)
    # position of each token within its expert's queue (arrival order);
    # non-selected columns end up at -1 and never pass the kept mask
    position = jnp.cumsum(onehot, axis=0) * onehot - 1.0
    kept = (position < capacity) & (onehot > 0)
    # exactly one kept column per surviving token -> the sum IS its slot;
    # dropped tokens sum to 0 but their kept mask zeroes the dispatch row
    slot = jnp.where(kept, position, 0.0).sum(-1).astype(jnp.int32)
    pos_oh = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)
    dispatch = kept.astype(jnp.float32)[:, :, None] * pos_oh[:, None, :]
    combine = gate[:, None, None] * dispatch
    return dispatch, combine


def moe_ffn(
    params: dict,
    h: jax.Array,
    axis: str = "ep",
    capacity_factor: float = 2.0,
) -> jax.Array:
    """Expert-parallel MoE FFN inside ``shard_map``.

    ``h``: the LOCAL (b, t, D) activation block (batch sharded on
    ``axis``). ``params["w_up"]/...`` carry the LOCAL expert shard
    (leading dim E/ep); ``params["router"]`` is replicated and scores all
    E experts. Returns the same shape as ``h``.
    """
    ep = lax.axis_size(axis)
    b, t, d = h.shape
    e_local = params["w_up"].shape[0]
    num_experts = e_local * ep
    if params["router"].shape[1] != num_experts:
        raise ValueError(
            f"router scores {params['router'].shape[1]} experts but the "
            f"local shard x axis implies {num_experts} (= {e_local} local "
            f"x ep={ep}); are the expert weights actually sharded P(ep)?"
        )
    tokens = b * t
    capacity = int(np.ceil(tokens * capacity_factor / num_experts))
    h2 = h.reshape(tokens, d)

    dispatch, combine = _routing(
        h2, params["router"], num_experts, capacity
    )
    # pack: (E, C, D) buffer of this device's tokens, by expert and slot
    buf = jnp.einsum("tec,td->ecd", dispatch, h2.astype(jnp.float32))
    # regroup: split E across peers, gather every peer's slots for OUR
    # experts -> (E/ep, ep*C, D)
    buf = lax.all_to_all(
        buf.reshape(ep, e_local, capacity, d), axis, 0, 0, tiled=False
    )
    buf = buf.transpose(1, 0, 2, 3).reshape(e_local, ep * capacity, d)

    out = jax.vmap(_expert_ffn)(
        params["w_up"], params["b_up"], params["w_down"],
        params["b_down"], buf,
    )
    # reverse the exchange: every peer gets its slots back
    out = out.reshape(e_local, ep, capacity, d).transpose(1, 0, 2, 3)
    out = lax.all_to_all(out, axis, 0, 0, tiled=False)
    out = out.reshape(num_experts, capacity, d)
    res = jnp.einsum("tec,ecd->td", combine, out)
    return res.reshape(b, t, d).astype(h.dtype)


def moe_ffn_dense_reference(
    params_full: dict, h: jax.Array, capacity_factor: float = 2.0
) -> jax.Array:
    """Unsharded ground truth: route each token, run its expert directly.

    ``params_full`` carries ALL experts (leading dim E). Implements the
    identical capacity/overflow rule so the equivalence is exact even when
    tokens drop.
    """
    b, t, d = h.shape
    num_experts = params_full["w_up"].shape[0]
    tokens = b * t
    capacity = int(np.ceil(tokens * capacity_factor / num_experts))
    h2 = h.reshape(tokens, d)
    dispatch, combine = _routing(
        h2, params_full["router"], num_experts, capacity
    )
    buf = jnp.einsum("tec,td->ecd", dispatch, h2.astype(jnp.float32))
    out = jax.vmap(_expert_ffn)(
        params_full["w_up"], params_full["b_up"], params_full["w_down"],
        params_full["b_down"], buf,
    )
    res = jnp.einsum("tec,ecd->td", combine, out)
    return res.reshape(b, t, d).astype(h.dtype)
