"""Custom TPU ops: pallas kernels + the sharded attention/MoE primitives.

Scope note (honest engineering, not checkbox kernels): this framework's
FLOPs live in model matmuls/convs (MXU via XLA) and its collectives live in
`lax.psum` (ICI via XLA) — both already optimal. The pallas kernels cover
the two places a hand kernel can matter: the EASGD elastic exchange (an
HBM-bandwidth-bound elementwise pass; XLA fuses it well — the kernel pins
the fusion floor and measured SLOWER, so it is flag-gated off) and flash
attention (VMEM-tiled scores for long single-device sequences — opt-in
until its TPU measurement lands). Both are numerically identical to their
XLA paths.
"""

from mpit_tpu.ops.elastic import elastic_update, pallas_supported  # noqa: F401
from mpit_tpu.ops.flash_attention import flash_attention  # noqa: F401
from mpit_tpu.ops.ring_attention import (  # noqa: F401
    dense_attention,
    make_ring_attention,
    ring_attention,
)
from mpit_tpu.ops.moe import (  # noqa: F401
    init_moe_params,
    moe_ffn,
    moe_ffn_dense_reference,
)
from mpit_tpu.ops.ulysses import ulysses_attention  # noqa: F401
