"""Custom TPU kernels (pallas) for the framework's hot elementwise ops.

Scope note (honest engineering, not checkbox kernels): this framework's
FLOPs live in model matmuls/convs (MXU via XLA) and its collectives live in
`lax.psum` (ICI via XLA) — both already optimal. The remaining hot op is the
EASGD elastic exchange: an HBM-bandwidth-bound elementwise pass over every
parameter. XLA fuses it well; the pallas version here exists to (a) pin the
fusion floor — one pass, two outputs, no intermediate materialization —
regardless of what surrounds it in a larger program, and (b) be the seed for
genuinely custom fused ops later. It is numerically identical to the XLA
path (same ops, same order, no reductions) and flag-gated off by default.
"""

from mpit_tpu.ops.elastic import elastic_update, pallas_supported  # noqa: F401
from mpit_tpu.ops.ring_attention import (  # noqa: F401
    dense_attention,
    make_ring_attention,
    ring_attention,
)
from mpit_tpu.ops.moe import (  # noqa: F401
    init_moe_params,
    moe_ffn,
    moe_ffn_dense_reference,
)
