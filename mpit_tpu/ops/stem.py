"""Space-to-depth convolution: MXU-friendly strided stem convs.

A k×k stride-s conv over a 3-channel image contracts only k·k·3 elements,
and the MXU pads the tiny channel dim catastrophically (ResNet's 7×7/2 stem:
147-element contraction at ≈5% utilization; AlexNet's 11×11/4: 363). The
MLPerf-TPU reformulation computes the SAME function over s×s space-to-depth
input: the kernel is zero-padded so every original tap lands on exactly one
s2d tap, the conv becomes stride-1 over s²·C channels, and the contraction
grows by up to s² with no tiny-channel dim.

Derivation (symmetric padding p, stride s, s | H):
  original output(i) taps rows s·i − p … s·i − p + k − 1.
  lo = ceil(p/s) s2d rows of conv padding; the kernel is zero-padded by
  t = s·lo − p on top/left (absorbing the out-of-window taps) and to a
  multiple of s on bottom/right; u = (t+k+pad)/s s2d taps per dim; conv
  padding hi = u − 1 − lo keeps one output per s2d row, and the result is
  sliced to the original output size (for s ∤ (H+2p−k) the s2d grid has one
  extra position).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def space_to_depth_conv(x, kernel, stride: int, padding: int, dt):
    """``conv(x, kernel, stride, padding=(p,p))`` computed s2d-style.

    Numerically identical to the plain strided conv (proven by
    the ``tests/test_models.py`` equivalence tests).
    Requires spatial dims divisible by ``stride`` and ``k > 2·padding``
    (true for every real stem).
    """
    b, h, w, c = x.shape
    kh, kw, kc, out_ch = kernel.shape
    s, p = int(stride), int(padding)
    if kh != kw:
        raise ValueError(f"square kernels only, got {kh}x{kw}")
    if kc != c:
        raise ValueError(f"kernel expects {kc} channels, input has {c}")
    if h % s or w % s:
        raise ValueError(
            f"space-to-depth conv needs spatial dims divisible by "
            f"stride={s}, got {h}x{w}"
        )
    if kh <= 2 * p:
        raise ValueError(f"need kernel {kh} > 2*padding {2 * p}")
    lo = -(-p // s)
    t = s * lo - p
    taps = t + kh
    u = -(-taps // s)
    bpad = s * u - taps
    k = jnp.pad(kernel, ((t, bpad), (t, bpad), (0, 0), (0, 0)))
    k = (
        k.reshape(u, s, u, s, c, out_ch)
        .transpose(0, 2, 1, 3, 4, 5)
        .reshape(u, u, s * s * c, out_ch)
    )
    xs = (
        x.reshape(b, h // s, s, w // s, s, c)
        .transpose(0, 1, 3, 2, 4, 5)
        .reshape(b, h // s, w // s, s * s * c)
    )
    hi = u - 1 - lo
    out = jax.lax.conv_general_dilated(
        xs.astype(dt),
        k.astype(dt),
        window_strides=(1, 1),
        padding=((lo, hi), (lo, hi)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    out_h = (h + 2 * p - kh) // s + 1
    out_w = (w + 2 * p - kw) // s + 1
    return out[:, :out_h, :out_w, :]


def stem_conv(
    module, x, features: int, kernel: int, stride: int, padding: int,
    stem: str, dt, use_bias: bool = False,
):
    """The one strided-stem dispatch shared by stem-capable models
    (resnet50, alexnet): ``stem="conv"`` is the textbook ``nn.Conv``;
    ``stem="space_to_depth"`` computes the same function via
    :func:`space_to_depth_conv` with an identically-shaped kernel parameter
    registered on the CALLING module's scope (param name ``stem_kernel``/
    ``stem_bias`` — checkpoints do not interchange between stems).

    ``module`` is the flax module whose ``@nn.compact`` ``__call__`` is on
    the stack — params and the Conv submodule land in its scope exactly as
    if the dispatch were written inline.
    """
    import flax.linen as nn

    if stem == "space_to_depth":
        k = module.param(
            "stem_kernel",
            nn.initializers.lecun_normal(),
            (kernel, kernel, x.shape[-1], features),
            jnp.float32,
        )
        x = space_to_depth_conv(x, k, stride=stride, padding=padding, dt=dt)
        if use_bias:
            bias = module.param(
                "stem_bias", nn.initializers.zeros_init(), (features,),
                jnp.float32,
            )
            x = x + bias.astype(dt)
        return x
    if stem == "conv":
        return nn.Conv(
            features, (kernel, kernel), strides=(stride, stride),
            padding=(padding, padding), use_bias=use_bias, dtype=dt,
        )(x)
    raise ValueError(f"unknown stem {stem!r}; have: conv, space_to_depth")
