"""Ring attention: exact attention over a sequence sharded across devices.

Beyond-parity TPU extension (the reference predates long-context training
and has no sequence parallelism — SURVEY.md §5; docs/PARITY.md "TPU-first
extensions"). This is the standard ring formulation (Liu et al., "Ring
Attention with Blockwise Transformers", arXiv:2310.01889 — PAPERS.md):

- the sequence axis is sharded onto a mesh axis: every device holds a
  contiguous block of queries, keys, and values;
- K/V blocks rotate around the ring with ``lax.ppermute`` (neighbor
  exchange over ICI — the one point-to-point primitive TPUs are built
  for), W steps for a W-device ring;
- each device folds every visiting block into its local queries' attention
  with the online-softmax (flash) accumulator, so the full T×T score
  matrix never materializes — memory is O(T·T/W²) per device and the
  result is EXACTLY softmax(QKᵀ/√d)V, not an approximation.

Compute/communication overlap: XLA schedules the next ``ppermute``
alongside the current block's einsum; on a real slice each hop is a
neighbor ICI transfer.

All accumulation is float32 regardless of input dtype (bf16 inputs stay
bf16 inside the einsums — MXU-friendly — but scores, the running max, and
the output accumulator are f32, the standard numerically-safe recipe).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _online_block(
    carry_m, carry_l, carry_acc, q, k, v, mask, scale
):
    """Fold one K/V block into the online-softmax accumulator.

    Shapes: q (B,Tq,H,D); k/v (Tk-block versions); scores (B,H,Tq,Tk);
    carry_m / carry_l (B,H,Tq); carry_acc (B,H,Tq,D). ``mask`` is None or
    broadcastable to the score shape; masked positions never contribute
    (exp(-inf)=0) and a row with no unmasked position so far keeps l=0.
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    block_max = jnp.max(s, axis=-1)
    m_new = jnp.maximum(carry_m, block_max)
    # -inf maxes (nothing unmasked yet) would make the exps below nan;
    # substitute 0 — every term they touch is exp(-inf - 0) = 0 anyway
    safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - safe_m[..., None])
    correction = jnp.where(
        jnp.isneginf(carry_m), 0.0, jnp.exp(carry_m - safe_m)
    )
    l_new = carry_l * correction + jnp.sum(p, axis=-1)
    acc_new = carry_acc * correction[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v, preferred_element_type=jnp.float32
    )
    return m_new, l_new, acc_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Exact sequence-parallel attention inside ``shard_map``.

    Args:
      q, k, v: the LOCAL sequence shard, shape ``(B, T_local, H, D)``
        (batch, per-device sequence block, heads, head dim). Shards are
        contiguous blocks in ring order: device ``r`` on ``axis_name``
        holds global positions ``[r·T_local, (r+1)·T_local)``.
      axis_name: mesh axis the sequence is sharded over.
      causal: mask position j from attending to positions > j (global
        positions, computed from the ring rank — a causal LM over the
        full sequence, not per-shard).

    Returns the local shard of ``softmax(QKᵀ/√D)V``, same shape/dtype as
    ``q``. Identical math to dense attention on the gathered sequence
    (see tests/test_ring_attention.py for the equivalence proof).
    """
    if q.ndim != 4:
        raise ValueError(f"expected (B, T, H, D) inputs, got {q.shape}")
    world = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    perm = [(j, (j + 1) % world) for j in range(world)]

    m0 = jnp.full((b, h, t_q), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, t_q), jnp.float32)
    acc0 = jnp.zeros((b, h, t_q, d), jnp.float32)
    q_pos = rank * t_q + jnp.arange(t_q)

    def body(i, carry):
        k_blk, v_blk, m, l, acc = carry
        # after i rotations we hold the block that ORIGINATED at rank - i
        src = (rank - i) % world
        mask = None
        if causal:
            k_pos = src * t_k + jnp.arange(t_k)
            mask = (k_pos[None, :] <= q_pos[:, None])[None, None]
        m, l, acc = _online_block(m, l, acc, q, k_blk, v_blk, mask, scale)
        # rotate even on the last step: every device ends holding its own
        # block again, so the op leaves no net displacement behind
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, acc

    *_unused, l, acc = lax.fori_loop(0, world, body, (k, v, m0, l0, acc0))
    # causal rows always have >= 1 unmasked key (self), so l > 0; the
    # guard still keeps a fully-masked row finite instead of 0/0
    out = acc / jnp.maximum(l, jnp.finfo(jnp.float32).tiny)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def dense_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False
) -> jax.Array:
    """Reference dense attention over the FULL sequence (no sharding) —
    the numerical ground truth ring_attention must match."""
    d = q.shape[-1]
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / (d ** 0.5)
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.arange(t_k)[None, :] <= jnp.arange(t_q)[:, None]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p, v, preferred_element_type=jnp.float32
    ).astype(q.dtype)


def make_ring_attention(
    mesh, axis_name: str, causal: bool = False, jit: bool = True
):
    """Convenience wrapper: a jitted shard_map of :func:`ring_attention`
    over ``mesh`` taking GLOBAL (B, T, H, D) arrays sharded on T.

    The returned callable accepts arrays laid out any way jax can
    redistribute; for zero-copy, pass arrays already sharded
    ``P(None, axis_name)``-style on the sequence axis.
    """
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis_name)

    def _ring(q, k, v):
        return ring_attention(q, k, v, axis_name, causal=causal)

    fn = jax.shard_map(
        _ring, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return jax.jit(fn) if jit else fn
