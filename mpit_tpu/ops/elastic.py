"""Fused EASGD elastic update as a pallas TPU kernel.

The exchange round's elementwise math (goptim.easgd_round, SURVEY.md §3(b-c)):

    new_x = x - α (x - c)            (client move toward center)
    new_c = c + α d                  (center move; d = psum of client diffs)

One kernel, three inputs, two outputs, one pass over HBM — the VPU does the
arithmetic while the bandwidth is the bound. Grid: 1-D over row-blocks of a
(rows, 128)-shaped view (lane dim fixed at 128, float32 sublane tiling;
/opt/skills/guides/pallas_guide.md). α is compile-time static (a config
constant), so it folds into the kernel.

`interpret=True` runs the same kernel on CPU (tests); the public wrapper
falls back to plain XLA elementwise ops when pallas is unusable.

Naming: "elastic" here is EASGD's elastic *force* — the update math.
Elastic *membership* (ranks joining/leaving/preempted mid-run) is
:mod:`mpit_tpu.parallel.elastic`, which shares nothing with this kernel
but the paper's adjective.

Measured (single v5e chip, 25M-element f32 operands, 2026-07): bit-exact
equality with the XLA path; XLA's own fusion was ~2.7x faster per call than
this kernel (grid/dispatch overhead dominates a pure-bandwidth op), which is
why ``use_pallas`` defaults to off everywhere — the kernel documents the
fusion floor and the pallas recipe, it is not the fast path today.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

LANE = 128
BLOCK_ROWS = 512  # 512×128 f32 = 256 KiB per operand block in VMEM


def pallas_supported() -> bool:
    """True when the pallas TPU path can run natively here."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _kernel(alpha, x_ref, c_ref, d_ref, newx_ref, newc_ref):
    x = x_ref[:]
    c = c_ref[:]
    newx_ref[:] = x - alpha * (x - c)
    newc_ref[:] = c + alpha * d_ref[:]


@functools.partial(jax.jit, static_argnames=("alpha", "interpret"))
def _elastic_pallas(x, c, d, alpha: float, interpret: bool):
    from jax.experimental import pallas as pl

    n = x.size
    block = BLOCK_ROWS * LANE
    padded = max(-(-n // block), 1) * block
    rows = padded // LANE

    def prep(a):
        a = a.reshape(-1)
        return jnp.pad(a, (0, padded - n)).reshape(rows, LANE)

    spec = pl.BlockSpec((BLOCK_ROWS, LANE), lambda i: (i, 0))
    out = jax.ShapeDtypeStruct((rows, LANE), x.dtype)
    new_x, new_c = pl.pallas_call(
        functools.partial(_kernel, alpha),
        grid=(rows // BLOCK_ROWS,),
        in_specs=[spec, spec, spec],
        out_specs=[spec, spec],
        out_shape=[out, out],
        interpret=interpret,
    )(prep(x), prep(c), prep(d))
    return (
        new_x.reshape(-1)[:n].reshape(x.shape),
        new_c.reshape(-1)[:n].reshape(x.shape),
    )


def elastic_update(x, center, total_diff, alpha: float, use_pallas=None):
    """Fused elastic pair update; returns ``(new_x, new_center)``.

    Args:
      x, center, total_diff: same-shape arrays (any rank).
      alpha: elastic coupling (static).
      use_pallas: True = require the kernel (interpret-mode off TPU raises
        only if pallas itself is unavailable), False = plain XLA, None =
        kernel on TPU, XLA elsewhere.
    """
    if use_pallas is None:
        use_pallas = pallas_supported()
    if use_pallas:
        interpret = not pallas_supported()
        return _elastic_pallas(
            jnp.asarray(x), jnp.asarray(center), jnp.asarray(total_diff),
            float(alpha), interpret,
        )
    new_x = x - alpha * (x - center)
    new_c = center + alpha * total_diff
    return new_x, new_c
