"""Flash attention as a pallas TPU kernel (single-device sequence).

The within-device counterpart of ``ops/ring_attention.py``: the same
online-softmax recipe, but tiled into VMEM by a pallas kernel so the
(T, T) score matrix never round-trips HBM even on ONE device. XLA's
fusion keeps scores in registers for small T; for long sequences it
materializes (B, H, T, T) scores in HBM — this kernel caps that at a
(block_q, block_k) tile in VMEM.

Kernel structure (the canonical pallas flash shape,
/opt/skills/guides/pallas_guide.md):

- grid ``(B·H, T/block_q, T/block_k)`` — the k-block axis is innermost,
  so for each (head, q-block) the kernel visits k-blocks sequentially,
  carrying the online-softmax state (running max ``m``, normalizer
  ``l``, output accumulator) in VMEM scratch that persists across the
  innermost grid steps;
- scratch initializes at ``j == 0``, the output block writes once at
  the last ``j`` (revisiting one output block across sequential grid
  steps is the standard TPU accumulation pattern);
- causal masking uses GLOBAL positions from the block indices, and a
  fully-masked (block entirely above the diagonal) k-block skips its
  matmuls via ``pl.when``;
- scores/statistics accumulate in f32 regardless of input dtype (bf16
  inputs hit the MXU as bf16 — the recipe shared with ring attention).
  ``m``/``l`` live lane-broadcast in (block_q, 128) scratch (the TPU
  f32 tile's lane width).

`interpret=True` runs the same kernel on CPU (the correctness tests);
the public wrapper falls back to plain XLA dense attention when pallas
cannot run natively and a kernel wasn't explicitly requested. Default
OFF in the model (``attn_impl="xla"``) until the TPU measurement lands —
the elastic-update kernel taught us XLA's fusion can beat a pallas
kernel (ops/elastic.py's 2.7× finding), so the switch stays
evidence-gated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpit_tpu.ops.elastic import pallas_supported
from mpit_tpu.ops.ring_attention import dense_attention

_NEG_INF = float("-inf")
_LANE = 128



def _apply_causal(s, q_off, k_off, q_axis: int):
    """Mask score tile entries where k_pos > q_pos (global positions);
    ``q_axis`` names the tile dimension the query positions vary along
    (0 in the q-major kernels, 1 in the transposed dK/dV kernel). The
    ONE copy of the mask for forward and both backward kernels."""
    q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, s.shape, q_axis)
    k_pos = k_off + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1 - q_axis
    )
    return jnp.where(k_pos <= q_pos, s, _NEG_INF)


def _to2d(a):
    """(B, T, H, D) -> (B·H, T, D), the kernels' layout."""
    b, t, h, d = a.shape
    return a.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from2d(a, b: int, h: int, t: int, d: int):
    return a.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
    *, scale, causal, block_q, block_k, n_k,
):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr[:], _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr[:])
        acc_scr[:] = jnp.zeros_like(acc_scr[:])

    # causal: a k-block strictly above the q-block's last row contributes
    # nothing — skip its matmuls entirely
    needed = (
        j * block_k <= i * block_q + block_q - 1 if causal else j >= 0
    )

    @pl.when(needed)
    def _update():
        q = q_ref[0]  # (block_q, D)
        k = k_ref[0]  # (block_k, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        if causal:
            s = _apply_causal(s, i * block_q, j * block_k, 0)
        m_prev = m_scr[:][:, :1]  # (block_q, 1) of the broadcast store
        l_prev = l_scr[:][:, :1]
        block_max = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        # a still-fully-masked row has m = -inf; exp(s - m) would be nan —
        # substitute 0, every term it touches is exp(-inf - 0) = 0
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - safe_m)
        corr = jnp.where(
            jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - safe_m)
        )
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc_scr[:] * corr + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[:] = acc_new

    @pl.when(j == n_k - 1)
    def _finalize():
        l = l_scr[:][:, :1]
        out = acc_scr[:] / jnp.maximum(l, jnp.finfo(jnp.float32).tiny)
        o_ref[0] = out.astype(o_ref.dtype)
        m = m_scr[:][:, :1]
        # log-sum-exp per query row: P_ij = exp(s_ij - lse_i) in the
        # backward. A row with no unmasked key gets +inf (P row = 0).
        lse = jnp.where(
            l > 0.0, jnp.where(jnp.isneginf(m), 0.0, m) + jnp.log(
                jnp.maximum(l, jnp.finfo(jnp.float32).tiny)
            ),
            jnp.inf,
        )
        lse_ref[0] = lse[:, 0]


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dq_ref, acc_scr,
    *, scale, causal, block_q, block_k, n_k,
):
    """dQ_i = scale · Σ_j dS_ij K_j with dS = P ∘ (dP − D); grid
    (B·H, q-block, k-block-innermost), accumulating in VMEM scratch."""
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr[:])

    needed = (
        j * block_k <= i * block_q + block_q - 1 if causal else j >= 0
    )

    @pl.when(needed)
    def _update():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, None]  # (bq, 1)
        dd = dd_ref[0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = _apply_causal(s, i * block_q, j * block_k, 0)
        p = jnp.exp(s - lse)  # rows with lse=+inf go to 0
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - dd)
        acc_scr[:] += jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(j == n_k - 1)
    def _finalize():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, *, scale, causal, block_q, block_k, n_q,
):
    """dK_j = scale · Σ_i dSᵀ_ji Q_i and dV_j = Σ_i Pᵀ_ji dO_i; grid
    (B·H, k-block, q-block-innermost)."""
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr[:])
        dv_scr[:] = jnp.zeros_like(dv_scr[:])

    # causal: a q-block entirely ABOVE this k-block contributes nothing
    needed = (
        i * block_q + block_q - 1 >= j * block_k if causal else i >= 0
    )

    @pl.when(needed)
    def _update():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][None, :]  # (1, bq)
        dd = dd_ref[0][None, :]
        st = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (bk, bq) = sᵀ
        if causal:
            st = _apply_causal(st, i * block_q, j * block_k, 1)
        pt = jnp.exp(st - lse)
        dv_scr[:] += jax.lax.dot_general(
            pt, do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dpt = jax.lax.dot_general(
            v.astype(jnp.float32), do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dst = pt * (dpt - dd)
        dk_scr[:] += jax.lax.dot_general(
            dst, q.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(i == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret"),
)
def _flash_pallas_bwd(q, k, v, out, lse, ct, causal, block_q, block_k,
                      interpret):
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    q2, k2, v2 = _to2d(q), _to2d(k), _to2d(v)
    do2 = _to2d(ct)
    o2 = _to2d(out)
    # D_i = Σ_d dO_id · O_id — cheap elementwise+reduce, XLA's job
    dd = jnp.sum(
        do2.astype(jnp.float32) * o2.astype(jnp.float32), -1
    )  # (BH, T)
    n_q, n_k = t // block_q, t // block_k

    q_spec = lambda ax: pl.BlockSpec(
        (1, block_q, d), lambda bh, a, b_: (bh, a if ax == 1 else b_, 0)
    )
    row_spec = lambda ax: pl.BlockSpec(
        (1, block_q), lambda bh, a, b_: (bh, a if ax == 1 else b_)
    )
    kv_spec = lambda ax: pl.BlockSpec(
        (1, block_k, d), lambda bh, a, b_: (bh, a if ax == 1 else b_, 0)
    )

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, n_k=n_k,
        ),
        grid=(b * h, n_q, n_k),
        in_specs=[
            q_spec(1), kv_spec(2), kv_spec(2), q_spec(1),
            row_spec(1), row_spec(1),
        ],
        out_specs=q_spec(1),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q2, k2, v2, do2, lse, dd)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, n_q=n_q,
        ),
        grid=(b * h, n_k, n_q),
        in_specs=[
            q_spec(2), kv_spec(1), kv_spec(1), q_spec(2),
            row_spec(2), row_spec(2),
        ],
        out_specs=[kv_spec(1), kv_spec(1)],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q2, k2, v2, do2, lse, dd)

    return (
        _from2d(dq, b, h, t, d),
        _from2d(dk, b, h, t, d),
        _from2d(dv, b, h, t, d),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    """Differentiable flash attention: pallas kernels both directions.

    ``pallas_call`` has no automatic VJP; the backward here is the
    standard FlashAttention recipe — recompute P from the saved
    log-sum-exp, never materializing more than a (block, block) score
    tile: a dQ kernel (q-blocks outer, k-blocks inner) and a fused
    dK/dV kernel (k-blocks outer, q-blocks inner), with the D = rowsum
    (dO ∘ O) vector computed by XLA outside.
    """
    return _flash_pallas(q, k, v, causal, block_q, block_k, interpret)[0]


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_pallas(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, ct):
    q, k, v, out, lse = res
    return _flash_pallas_bwd(
        q, k, v, out, lse, ct, causal, block_q, block_k, interpret
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret"),
)
def _flash_pallas(q, k, v, causal, block_q, block_k, interpret):
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    q2, k2, v2 = _to2d(q), _to2d(k), _to2d(v)
    n_q, n_k = t // block_q, t // block_k

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0))
    kv_spec = pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0))
    out, lse = pl.pallas_call(
        functools.partial(
            _kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, n_k=n_k,
        ),
        grid=(b * h, n_q, n_k),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q), lambda bh, i, j: (bh, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # normalizer l
            pltpu.VMEM((block_q, d), jnp.float32),      # output acc
        ],
        interpret=interpret,
    )(q2, k2, v2)
    return _from2d(out, b, h, t, d), lse


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    use_pallas=None,
) -> jax.Array:
    """Tiled exact attention, ``(B, T, H, D) -> (B, T, H, D)``.

    ``use_pallas``: True = require the kernel (interpret mode off TPU),
    False = XLA dense attention, None = kernel on TPU, XLA elsewhere.

    Fully trainable: the custom VJP runs the standard FlashAttention
    backward as pallas kernels too (P recomputed from the saved
    log-sum-exp; dQ and fused dK/dV passes), so no (T, T) score matrix
    materializes in either direction.
    Falls back to dense whenever ``T`` does not tile cleanly — blocks
    clamp to ``T`` for short sequences, but a clamped block must still
    be sublane-aligned (a multiple of 8) and divide ``T`` — exactness
    and compilable tiles are never traded for the kernel.
    """
    if use_pallas is None:
        use_pallas = pallas_supported()
    t = q.shape[1]
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    tiles = (
        t % block_q == 0 and t % block_k == 0
        and block_q % 8 == 0 and block_k % 8 == 0
    )
    if not use_pallas or not tiles:
        return dense_attention(q, k, v, causal=causal)
    interpret = not pallas_supported()
    return _flash(q, k, v, causal, block_q, block_k, interpret)
