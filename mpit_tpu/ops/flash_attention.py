"""Flash attention as a pallas TPU kernel (single-device sequence).

The within-device counterpart of ``ops/ring_attention.py``: the same
online-softmax recipe, but tiled into VMEM by a pallas kernel so the
(T, T) score matrix never round-trips HBM even on ONE device. XLA's
fusion keeps scores in registers for small T; for long sequences it
materializes (B, H, T, T) scores in HBM — this kernel caps that at a
(block_q, block_k) tile in VMEM.

Kernel structure (the canonical pallas flash shape,
/opt/skills/guides/pallas_guide.md):

- grid ``(B·H, T/block_q, T/block_k)`` — the k-block axis is innermost,
  so for each (head, q-block) the kernel visits k-blocks sequentially,
  carrying the online-softmax state (running max ``m``, normalizer
  ``l``, output accumulator) in VMEM scratch that persists across the
  innermost grid steps;
- scratch initializes at ``j == 0``, the output block writes once at
  the last ``j`` (revisiting one output block across sequential grid
  steps is the standard TPU accumulation pattern);
- causal masking uses GLOBAL positions from the block indices, and a
  fully-masked (block entirely above the diagonal) k-block skips its
  matmuls via ``pl.when``;
- scores/statistics accumulate in f32 regardless of input dtype (bf16
  inputs hit the MXU as bf16 — the recipe shared with ring attention).
  ``m``/``l`` live lane-broadcast in (block_q, 128) scratch (the TPU
  f32 tile's lane width).

`interpret=True` runs the same kernel on CPU (the correctness tests);
the public wrapper falls back to plain XLA dense attention when pallas
cannot run natively and a kernel wasn't explicitly requested. Default
OFF in the model (``attn_impl="xla"``) until the TPU measurement lands —
the elastic-update kernel taught us XLA's fusion can beat a pallas
kernel (ops/elastic.py's 2.7× finding), so the switch stays
evidence-gated.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpit_tpu.ops.elastic import pallas_supported
from mpit_tpu.ops.ring_attention import dense_attention

_NEG_INF = float("-inf")
_LANE = 128


def _kernel(
    q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
    *, scale, causal, block_q, block_k, n_k,
):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr[:], _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr[:])
        acc_scr[:] = jnp.zeros_like(acc_scr[:])

    # causal: a k-block strictly above the q-block's last row contributes
    # nothing — skip its matmuls entirely
    needed = (
        j * block_k <= i * block_q + block_q - 1 if causal else j >= 0
    )

    @pl.when(needed)
    def _update():
        q = q_ref[0]  # (block_q, D)
        k = k_ref[0]  # (block_k, D)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_prev = m_scr[:][:, :1]  # (block_q, 1) of the broadcast store
        l_prev = l_scr[:][:, :1]
        block_max = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        # a still-fully-masked row has m = -inf; exp(s - m) would be nan —
        # substitute 0, every term it touches is exp(-inf - 0) = 0
        safe_m = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - safe_m)
        corr = jnp.where(
            jnp.isneginf(m_prev), 0.0, jnp.exp(m_prev - safe_m)
        )
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc_scr[:] * corr + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[:] = acc_new

    @pl.when(j == n_k - 1)
    def _finalize():
        l = l_scr[:][:, :1]
        out = acc_scr[:] / jnp.maximum(l, jnp.finfo(jnp.float32).tiny)
        o_ref[0] = out.astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    """Differentiable wrapper: pallas forward, exact recompute backward.

    ``pallas_call`` has no automatic VJP, so training through the kernel
    needs one. The backward currently recomputes through
    :func:`dense_attention`'s VJP — mathematically exact (the kernel
    computes the identical function, proven by the equivalence tests),
    but it materializes the (T, T) scores, so flash's memory saving
    applies to the forward/inference path only for now; a pallas
    backward kernel (the standard dq/dk/dv two-pass recipe) is the
    follow-up once a TPU measurement justifies it.
    """
    return _flash_pallas(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, ct):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: dense_attention(q_, k_, v_, causal=causal),
        q, k, v,
    )
    return vjp(ct)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret"),
)
def _flash_pallas(q, k, v, causal, block_q, block_k, interpret):
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    to2d = lambda a: a.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    q2, k2, v2 = to2d(q), to2d(k), to2d(v)
    n_q, n_k = t // block_q, t // block_k

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0))
    kv_spec = pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0))
    out = pl.pallas_call(
        functools.partial(
            _kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, n_k=n_k,
        ),
        grid=(b * h, n_q, n_k),
        in_specs=[q_spec, kv_spec, kv_spec],
        out_specs=pl.BlockSpec(
            (1, block_q, d), lambda bh, i, j: (bh, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # running max m
            pltpu.VMEM((block_q, _LANE), jnp.float32),  # normalizer l
            pltpu.VMEM((block_q, d), jnp.float32),      # output acc
        ],
        interpret=interpret,
    )(q2, k2, v2)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    use_pallas=None,
) -> jax.Array:
    """Tiled exact attention, ``(B, T, H, D) -> (B, T, H, D)``.

    ``use_pallas``: True = require the kernel (interpret mode off TPU),
    False = XLA dense attention, None = kernel on TPU, XLA elsewhere.

    TRAINING CAVEAT: the backward pass is an exact dense-attention
    recompute (``pallas_call`` has no auto-VJP), so under ``jax.grad``
    the (T, T) score matrix still materializes and the forward runs
    twice — the kernel's VMEM tiling pays off for inference/eval today;
    a pallas backward kernel is the follow-up.
    Falls back to dense whenever ``T`` does not tile cleanly — blocks
    clamp to ``T`` for short sequences, but a clamped block must still
    be sublane-aligned (a multiple of 8) and divide ``T`` — exactness
    and compilable tiles are never traded for the kernel.
    """
    if use_pallas is None:
        use_pallas = pallas_supported()
    t = q.shape[1]
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    tiles = (
        t % block_q == 0 and t % block_k == 0
        and block_q % 8 == 0 and block_k % 8 == 0
    )
    if not use_pallas or not tiles:
        return dense_attention(q, k, v, causal=causal)
    interpret = not pallas_supported()
    return _flash(q, k, v, causal, block_q, block_k, interpret)
