"""Ulysses sequence parallelism: all-to-all head↔sequence re-sharding.

The OTHER standard long-context scheme (DeepSpeed-Ulysses, arXiv:
2309.14509), complementing ``ops/ring_attention.py``: instead of rotating
K/V blocks around a ring (W ppermute hops, compute overlapped), Ulysses
re-shards ONCE — an ``all_to_all`` converts the sequence-sharded
``(B, T/P, H, D)`` activations into head-sharded ``(B, T, H/P, D)``,
every device runs plain DENSE attention over the full sequence for its
own heads, and the reverse ``all_to_all`` restores sequence sharding.

Trade-off vs the ring (why both exist): Ulysses moves each element
twice total in two balanced all-to-alls and computes attention with zero
extra softmax bookkeeping, but requires ``H % P == 0`` and holds the
full (T, T) per-head score matrix on one device — so the ring wins for
EXTREME sequence lengths (scores never materialize), Ulysses for
moderate T where the all-to-all is cheaper than W rotation steps. Both
are exact; the tests pin both against the same dense reference.

Positions are global automatically: after the first exchange every
device sees the FULL sequence in ring order, so causal masking needs no
rank offset.
"""

from __future__ import annotations

import jax
from jax import lax

from mpit_tpu.ops.ring_attention import dense_attention


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = False,
) -> jax.Array:
    """Exact sequence-parallel attention inside ``shard_map``.

    Args: the LOCAL sequence shard ``(B, T_local, H, D)`` (contiguous
    blocks in ring order, same contract as
    :func:`~mpit_tpu.ops.ring_attention.ring_attention`); ``H`` must be
    divisible by the axis extent. Returns the local shard of
    ``softmax(QKᵀ/√D)V``, same shape/dtype as ``q``.
    """
    if q.ndim != 4:
        raise ValueError(f"expected (B, T, H, D) inputs, got {q.shape}")
    world = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % world:
        raise ValueError(
            f"ulysses needs num_heads ({h}) divisible by the {world}-wide "
            f"{axis_name!r} axis; use ring attention for more devices "
            "than heads"
        )

    def seq_to_head(a):  # (B, T/P, H, D) -> (B, T, H/P, D)
        return lax.all_to_all(
            a, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qh, kh, vh = seq_to_head(q), seq_to_head(k), seq_to_head(v)
    out = dense_attention(qh, kh, vh, causal=causal)
    # (B, T, H/P, D) -> (B, T/P, H, D)
    return lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=2, tiled=True
    )
