"""Communication core: topology bootstrap + collectives.

TPU-native replacement for the reference's single native component, the Lua/C
MPI binding (SURVEY.md §2 comp. 1 and the native-component ledger): process
bootstrap maps to ``jax.distributed``; rank/size map to TPU-slice discovery;
collectives lower to XLA collectives (``lax.psum`` etc.) over ICI/DCN. The
tagged point-to-point surface (Send/Recv/ANY_SOURCE) lives in
``mpit_tpu.transport`` because it has no XLA analogue.
"""

from mpit_tpu.comm.topology import (  # noqa: F401
    Topology,
    init,
    finalize,
    is_initialized,
    topology,
    rank,
    size,
    process_rank,
    process_count,
)
from mpit_tpu.comm.collectives import (  # noqa: F401
    SUM,
    PROD,
    MAX,
    MIN,
    AVG,
    allreduce,
    allgather,
    bcast,
    barrier,
    device_barrier,
    psum,
    pmean,
    pmax,
    pmin,
    ppermute_ring,
    quantized_allreduce,
    quantized_psum_scatter,
    reduce_scatter,
)
