"""Collectives: the TPU-native ``mpiT.Allreduce / Bcast / Barrier``.

Reference parity (SURVEY.md §2 comp. 1, BASELINE.json:5): mpiT exposed MPI
collectives over flat Torch storages. Here the collectives are XLA
collectives over a mesh axis — they must be called *inside* an SPMD context
(``jax.shard_map`` / ``jit`` over a Mesh) where the worker axis name is bound,
and they lower to ICI all-reduces rather than host-mediated MPI. All
functions are pytree-aware: a whole parameter pytree all-reduces in one call,
matching the reference's flat-tensor usage without requiring flattening.

Host-level process synchronization (``mpiT.Barrier`` outside compute) maps to
``multihost_utils.sync_global_devices``.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

# `from mpit_tpu.comm.topology import ...` (by full module path) rather than
# an attribute import: the package re-exports a `topology()` *function* that
# shadows the submodule attribute of the same name.
from mpit_tpu.comm.topology import topology as _current_topology

# Reduction ops, mirroring mpiT.SUM/PROD/MAX/MIN constants (SURVEY.md §2 L2
# row). AVG is a convenience the reference implemented as SUM + divide
# (SURVEY.md §3(d): "grad /= size").
SUM = "sum"
PROD = "prod"
MAX = "max"
MIN = "min"
AVG = "avg"

_REDUCERS = {
    SUM: lax.psum,
    MAX: lax.pmax,
    MIN: lax.pmin,
}


def _axis(axis_name: Optional[str]) -> str:
    return axis_name if axis_name is not None else _current_topology().worker_axis


def psum(tree: Any, axis_name: Optional[str] = None) -> Any:
    return lax.psum(tree, _axis(axis_name))


def pmean(tree: Any, axis_name: Optional[str] = None) -> Any:
    return lax.pmean(tree, _axis(axis_name))


def pmax(tree: Any, axis_name: Optional[str] = None) -> Any:
    return lax.pmax(tree, _axis(axis_name))


def pmin(tree: Any, axis_name: Optional[str] = None) -> Any:
    return lax.pmin(tree, _axis(axis_name))


def allreduce(tree: Any, op: str = SUM, axis_name: Optional[str] = None) -> Any:
    """``mpiT.Allreduce``: reduce a pytree across the worker axis, all get it.

    XLA has no product collective, so ``op=PROD`` falls back to
    ``all_gather`` + ``prod`` — exact for any sign, but O(W) peak memory per
    leaf; avoid PROD on large leaves.
    """
    axis = _axis(axis_name)
    if op == AVG:
        return lax.pmean(tree, axis)
    if op == PROD:
        return jax.tree.map(
            lambda x: jnp.prod(lax.all_gather(x, axis), axis=0), tree
        )
    try:
        reducer = _REDUCERS[op]
    except KeyError:
        raise ValueError(f"unknown reduction op: {op!r}") from None
    return jax.tree.map(functools.partial(reducer, axis_name=axis), tree)


def allgather(
    tree: Any, axis_name: Optional[str] = None, tiled: bool = False
) -> Any:
    """All-gather each leaf across the worker axis (new leading worker dim,
    or concatenated along axis 0 when ``tiled``)."""
    axis = _axis(axis_name)
    return jax.tree.map(
        lambda x: lax.all_gather(x, axis, tiled=tiled), tree
    )


def bcast(tree: Any, root: int = 0, axis_name: Optional[str] = None) -> Any:
    """``mpiT.Bcast``: every worker receives root's value.

    Implemented as a masked psum — one collective, no gather of W copies:
    ``psum(where(rank == root, x, 0))``. Exact for floats (no reduction
    reordering across distinct values: all non-root contributions are 0).
    """
    axis = _axis(axis_name)
    idx = lax.axis_index(axis)
    world = lax.axis_size(axis)  # static inside shard_map
    if isinstance(root, int) and not 0 <= root < world:
        raise ValueError(
            f"bcast root={root} out of range for worker axis of size {world}"
        )

    def _pick(x):
        x = jnp.asarray(x)
        zero = jnp.zeros_like(x)
        contrib = jnp.where(idx == root, x, zero)
        return lax.psum(contrib, axis)

    return jax.tree.map(_pick, tree)


def device_barrier(axis_name: Optional[str] = None):
    """In-SPMD barrier: a psum of 1 forces a rendezvous on the worker axis.

    SPMD programs are lockstep by construction, so this is rarely needed;
    it exists for ``mpiT.Barrier`` parity inside compiled steps and returns
    the world size (a free ``Comm_size`` check).
    """
    return lax.psum(jnp.ones((), jnp.int32), _axis(axis_name))


def barrier(name: str = "mpit_barrier") -> None:
    """Host-level barrier across processes (``mpiT.Barrier`` outside jit).

    On a single process this is a no-op. Multi-host it blocks until every
    process reaches the same named point.
    """
    if _current_topology().process_count > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def reduce_scatter(
    tree: Any,
    axis_name: Optional[str] = None,
    scatter_dimension: int = 0,
    tiled: bool = True,
) -> Any:
    """Reduce-scatter: sum across workers, each worker keeps its 1/W shard
    (``lax.psum_scatter``). The building block of bandwidth-optimal
    allreduce (reduce_scatter + all_gather) and of sharded-optimizer
    (ZeRO-style) updates; leaves must be divisible by W along
    ``scatter_dimension``."""
    axis = _axis(axis_name)
    return jax.tree.map(
        lambda x: lax.psum_scatter(
            x, axis, scatter_dimension=scatter_dimension, tiled=tiled
        ),
        tree,
    )


def ppermute_ring(
    tree: Any, shift: int = 1, axis_name: Optional[str] = None
) -> Any:
    """Ring neighbor-exchange: each worker sends to ``(rank+shift) % W``.

    The closest XLA analogue to point-to-point Send/Recv (SURVEY.md §7 "hard
    parts": no tagged p2p on TPU). Used by ring-style algorithms; the PS
    protocol instead uses ``mpit_tpu.transport``.
    """
    axis = _axis(axis_name)
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.tree.map(lambda x: lax.ppermute(x, axis, perm), tree)
