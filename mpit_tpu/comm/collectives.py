"""Collectives: the TPU-native ``mpiT.Allreduce / Bcast / Barrier``.

Reference parity (SURVEY.md §2 comp. 1, BASELINE.json:5): mpiT exposed MPI
collectives over flat Torch storages. Here the collectives are XLA
collectives over a mesh axis — they must be called *inside* an SPMD context
(``jax.shard_map`` / ``jit`` over a Mesh) where the worker axis name is bound,
and they lower to ICI all-reduces rather than host-mediated MPI. All
functions are pytree-aware: a whole parameter pytree all-reduces in one call,
matching the reference's flat-tensor usage without requiring flattening.

Host-level process synchronization (``mpiT.Barrier`` outside compute) maps to
``multihost_utils.sync_global_devices``.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

# `from mpit_tpu.comm.topology import ...` (by full module path) rather than
# an attribute import: the package re-exports a `topology()` *function* that
# shadows the submodule attribute of the same name.
from mpit_tpu.comm.topology import topology as _current_topology
from mpit_tpu import quant as _quant

# Reduction ops, mirroring mpiT.SUM/PROD/MAX/MIN constants (SURVEY.md §2 L2
# row). AVG is a convenience the reference implemented as SUM + divide
# (SURVEY.md §3(d): "grad /= size").
SUM = "sum"
PROD = "prod"
MAX = "max"
MIN = "min"
AVG = "avg"


def _pprod(x, axis_name):
    """Product reduction. XLA has no product collective, so this is
    ``all_gather`` + ``prod`` — exact for any sign, but O(W) peak memory
    per leaf; avoid PROD on large leaves."""
    return jnp.prod(lax.all_gather(x, axis_name), axis=0)


# every exported op constant dispatches here (AVG is pmean, handled in
# allreduce directly) — the table and the constants must agree
_REDUCERS = {
    SUM: lax.psum,
    PROD: _pprod,
    MAX: lax.pmax,
    MIN: lax.pmin,
}


def _axis(axis_name: Optional[str]) -> str:
    return axis_name if axis_name is not None else _current_topology().worker_axis


def psum(tree: Any, axis_name: Optional[str] = None) -> Any:
    return lax.psum(tree, _axis(axis_name))


def pmean(tree: Any, axis_name: Optional[str] = None) -> Any:
    return lax.pmean(tree, _axis(axis_name))


def pmax(tree: Any, axis_name: Optional[str] = None) -> Any:
    return lax.pmax(tree, _axis(axis_name))


def pmin(tree: Any, axis_name: Optional[str] = None) -> Any:
    return lax.pmin(tree, _axis(axis_name))


def allreduce(
    tree: Any,
    op: str = SUM,
    axis_name: Optional[str] = None,
    quant: Optional[str] = None,
) -> Any:
    """``mpiT.Allreduce``: reduce a pytree across the worker axis, all get it.

    ``op=PROD`` dispatches to the ``all_gather`` + ``prod`` reducer (XLA
    has no product collective) — exact for any sign, but O(W) peak memory
    per leaf; avoid PROD on large leaves.

    ``quant="bf16"|"int8"`` runs the EQuARX-style quantized scheme
    (:func:`quantized_allreduce`) instead of the raw collective — SUM/AVG
    only, and LOSSY per call: the quantization error is bounded (one
    rounding step per hop) but not fed back at this level. Callers that
    reduce the same stream repeatedly (gradient exchange) should hold an
    error-feedback residual and call :func:`quantized_allreduce`
    directly, as ``parallel/sync.py`` does.
    """
    axis = _axis(axis_name)
    if quant not in (None, "off"):
        if op not in (SUM, AVG):
            raise ValueError(
                f"quantized allreduce supports SUM/AVG, not {op!r}"
            )
        reduced, _, _ = quantized_allreduce(
            tree, axis_name=axis, mode=quant, mean=(op == AVG)
        )
        return reduced
    if op == AVG:
        return lax.pmean(tree, axis)
    try:
        reducer = _REDUCERS[op]
    except KeyError:
        raise ValueError(f"unknown reduction op: {op!r}") from None
    return jax.tree.map(functools.partial(reducer, axis_name=axis), tree)


def _quant_allreduce_leaf(x, axis: str, mode: str, mean: bool, r2=None):
    """One leaf of the quantized allreduce: the bandwidth-optimal
    reduce-scatter + all-gather decomposition with quantized codes on
    both wire hops (EQuARX, PAPERS.md arXiv:2506.17615).

    Per worker: pad the flat leaf to W·chunk, view it as W destination
    rows, quantize each row against its own absmax block scale, and
    ``all_to_all`` the codes — worker k receives every worker's row k,
    dequantizes, and sums in f32 (the accumulate stays full precision;
    only the wire legs are narrow). The reduced chunk is re-quantized
    once and ``all_gather``-ed back.

    Returns ``(reduced, sent_deq, new_r2)``:

    - ``sent_deq`` is THIS worker's dequantized first-hop contribution —
      what the receivers actually summed — so a caller can form the
      level-1 error-feedback residual ``x - sent_deq`` without a second
      quantization pass;
    - ``r2``/``new_r2`` is the level-2 residual on the OWNED reduced
      chunk (shape ``(ceil(n/W),)``): the second hop's rounding,
      compensated into the next round's re-quantization. Chunk ownership
      is stable across calls, so the feedback lands on the same stream.
    """
    w = lax.axis_size(axis)
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    pad = -n % w
    if pad:
        flat = jnp.pad(flat, (0, pad))
    rows = flat.reshape(w, -1)

    codes, scales = _quant.quantize_rows_jnp(rows, mode)
    sent_deq = _quant.dequantize_rows_jnp(codes, scales, mode)
    # first wire hop: row j of every worker travels to worker j
    codes_x = lax.all_to_all(codes, axis, split_axis=0, concat_axis=0)
    if mode == "int8":
        scales_x = lax.all_to_all(scales, axis, split_axis=0, concat_axis=0)
    else:
        scales_x = scales  # bf16 is scale-free; nothing to move
    contrib = _quant.dequantize_rows_jnp(codes_x, scales_x, mode)
    red = jnp.sum(contrib, axis=0)
    if mean:
        red = red / w
    if r2 is not None:
        red = red + jnp.asarray(r2, jnp.float32)

    # second wire hop: one re-quantization of the reduced chunk, gathered
    rcodes, rscale = _quant.quantize_jnp(red, mode)
    new_r2 = red - _quant.dequantize_jnp(rcodes, rscale, mode)
    g_codes = lax.all_gather(rcodes, axis)
    if mode == "int8":
        g_scales = lax.all_gather(rscale, axis).reshape(w, 1)
    else:
        g_scales = None
    out = _quant.dequantize_rows_jnp(g_codes, g_scales, mode).reshape(-1)

    out = out[:n].reshape(shape).astype(dtype)
    sent_deq = sent_deq.reshape(-1)[:n].reshape(shape)
    return out, sent_deq, new_r2


def quantized_allreduce(
    tree: Any,
    axis_name: Optional[str] = None,
    mode: str = "int8",
    mean: bool = False,
    residual: Any = None,
    residual2: Any = None,
) -> tuple:
    """Quantized SUM (or mean) allreduce with two-level error feedback.

    Returns ``(reduced_tree, new_residual_tree, new_residual2_tree)``.
    ``residual`` (same structure as ``tree``, f32 leaves) compensates
    each worker's CONTRIBUTION before the first-hop quantization —
    ``c = x + residual``, new residual ``c - deq(quant(c))`` — the
    standard EF recurrence that keeps the accumulated reduction unbiased
    across repeated calls on one stream (docs/WIRE.md). ``residual2``
    (leaves shaped ``(ceil(leaf_size/W),)``) compensates the second
    hop's re-quantization of this worker's OWNED reduced chunk the same
    way. Pass both back in on the next call; with ``None`` the new
    residuals are still returned (what one call lost), so a caller can
    start the loop without building zero trees."""
    if mode not in ("bf16", "int8"):
        raise ValueError(
            f"quantized allreduce mode {mode!r}: expected 'bf16' or 'int8'"
        )
    axis = _axis(axis_name)
    leaves, treedef = jax.tree.flatten(tree)
    res_leaves = (
        jax.tree.flatten(residual)[0]
        if residual is not None
        else [None] * len(leaves)
    )
    res2_leaves = (
        jax.tree.flatten(residual2)[0]
        if residual2 is not None
        else [None] * len(leaves)
    )
    out, new_res, new_res2 = [], [], []
    for x, r, r2 in zip(leaves, res_leaves, res2_leaves):
        c = jnp.asarray(x, jnp.float32)
        if r is not None:
            c = c + jnp.asarray(r, jnp.float32)
        reduced, sent, nr2 = _quant_allreduce_leaf(c, axis, mode, mean, r2)
        out.append(reduced.astype(jnp.asarray(x).dtype))
        new_res.append(c - sent)
        new_res2.append(nr2)
    return (
        jax.tree.unflatten(treedef, out),
        jax.tree.unflatten(treedef, new_res),
        jax.tree.unflatten(treedef, new_res2),
    )


def quantized_psum_scatter(
    flat: Any, axis_name: Optional[str] = None, mode: str = "int8"
) -> Any:
    """Quantized ``lax.psum_scatter(..., tiled=True)``: the first hop of
    :func:`quantized_allreduce` alone — each worker keeps the f32 sum of
    everyone's quantized chunk k. The ZeRO gradient-scatter hook
    (``parallel/zero.py``): the wire moves 1- or 2-byte codes instead of
    f32, the accumulate stays full precision. STATELESS — no error
    feedback at this level (the rounding is one bounded step per call;
    the dynamics plane is the convergence guardrail)."""
    if mode in (None, "off"):
        return lax.psum_scatter(flat, _axis(axis_name), tiled=True)
    if mode not in ("bf16", "int8"):
        raise ValueError(
            f"quantized psum_scatter mode {mode!r}: "
            "expected 'bf16' or 'int8'"
        )
    axis = _axis(axis_name)
    w = lax.axis_size(axis)
    x = jnp.asarray(flat, jnp.float32)
    rows = x.reshape(w, -1)  # requires W-divisible flats, like tiled=True
    # The ZeRO scatter is stateless by design: each shard owner sees
    # fresh gradients every step, and the dynamics plane is the
    # convergence guardrail (docstring above).
    # mpit-analysis: ef-off[ZeRO scatter is stateless by design]
    codes, scales = _quant.quantize_rows_jnp(rows, mode)
    codes_x = lax.all_to_all(codes, axis, split_axis=0, concat_axis=0)
    if mode == "int8":
        scales = lax.all_to_all(scales, axis, split_axis=0, concat_axis=0)
    contrib = _quant.dequantize_rows_jnp(codes_x, scales, mode)
    return jnp.sum(contrib, axis=0)


def allgather(
    tree: Any, axis_name: Optional[str] = None, tiled: bool = False
) -> Any:
    """All-gather each leaf across the worker axis (new leading worker dim,
    or concatenated along axis 0 when ``tiled``)."""
    axis = _axis(axis_name)
    return jax.tree.map(
        lambda x: lax.all_gather(x, axis, tiled=tiled), tree
    )


def bcast(tree: Any, root: int = 0, axis_name: Optional[str] = None) -> Any:
    """``mpiT.Bcast``: every worker receives root's value.

    Implemented as a masked psum — one collective, no gather of W copies:
    ``psum(where(rank == root, x, 0))``. Exact for floats (no reduction
    reordering across distinct values: all non-root contributions are 0).
    """
    axis = _axis(axis_name)
    idx = lax.axis_index(axis)
    world = lax.axis_size(axis)  # static inside shard_map
    if isinstance(root, int) and not 0 <= root < world:
        raise ValueError(
            f"bcast root={root} out of range for worker axis of size {world}"
        )

    def _pick(x):
        x = jnp.asarray(x)
        zero = jnp.zeros_like(x)
        contrib = jnp.where(idx == root, x, zero)
        return lax.psum(contrib, axis)

    return jax.tree.map(_pick, tree)


def device_barrier(axis_name: Optional[str] = None):
    """In-SPMD barrier: a psum of 1 forces a rendezvous on the worker axis.

    SPMD programs are lockstep by construction, so this is rarely needed;
    it exists for ``mpiT.Barrier`` parity inside compiled steps and returns
    the world size (a free ``Comm_size`` check).
    """
    return lax.psum(jnp.ones((), jnp.int32), _axis(axis_name))


def barrier(name: str = "mpit_barrier") -> None:
    """Host-level barrier across processes (``mpiT.Barrier`` outside jit).

    On a single process this is a no-op. Multi-host it blocks until every
    process reaches the same named point.
    """
    if _current_topology().process_count > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def reduce_scatter(
    tree: Any,
    axis_name: Optional[str] = None,
    scatter_dimension: int = 0,
    tiled: bool = True,
) -> Any:
    """Reduce-scatter: sum across workers, each worker keeps its 1/W shard
    (``lax.psum_scatter``). The building block of bandwidth-optimal
    allreduce (reduce_scatter + all_gather) and of sharded-optimizer
    (ZeRO-style) updates; leaves must be divisible by W along
    ``scatter_dimension``."""
    axis = _axis(axis_name)
    return jax.tree.map(
        lambda x: lax.psum_scatter(
            x, axis, scatter_dimension=scatter_dimension, tiled=tiled
        ),
        tree,
    )


def ppermute_ring(
    tree: Any, shift: int = 1, axis_name: Optional[str] = None
) -> Any:
    """Ring neighbor-exchange: each worker sends to ``(rank+shift) % W``.

    The closest XLA analogue to point-to-point Send/Recv (SURVEY.md §7 "hard
    parts": no tagged p2p on TPU). Used by ring-style algorithms; the PS
    protocol instead uses ``mpit_tpu.transport``.
    """
    axis = _axis(axis_name)
    n = lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.tree.map(lambda x: lax.ppermute(x, axis, perm), tree)
