"""Topology bootstrap: the TPU-native ``mpiT.Init / Comm_rank / Comm_size``.

Reference parity (SURVEY.md §3(a), BASELINE.json:5): ``mpirun`` spawned N Lua
processes which called ``mpiT.Init()`` then discovered ``rank``/``size`` from
``MPI_COMM_WORLD``. Here the "world" is the TPU slice: processes bootstrap via
``jax.distributed`` (when launched multi-host), devices are discovered from
the slice, and the worker axis of the job is a ``jax.sharding.Mesh`` axis —
one *device* per worker, rather than one OS process per worker, because on TPU
the unit of compute is the chip and collectives ride ICI between chips.

Two notions of identity therefore coexist and both are exposed:

- ``process_rank()`` / ``process_count()`` — host-process identity
  (``jax.process_index/count``); the moral equivalent of an MPI rank for
  host-side work (logging, data sharding, the host-async PS transport).
- ``rank()`` / ``size()`` — *worker* identity: position along the mesh's
  worker ("dp") axis. Inside jit/shard_map this is ``lax.axis_index``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence

import jax
import numpy as np

from mpit_tpu.analysis.runtime import make_lock
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Default mesh axis for the data-parallel worker dimension. The reference's
# only parallelism is data parallelism in three flavors (SURVEY.md §2
# parallelism-strategy ledger), so a 1-D mesh is the common case.
WORKER_AXIS = "dp"

_lock = make_lock("topology._lock")
_topology: Optional["Topology"] = None
_distributed_initialized = False


@dataclasses.dataclass(frozen=True)
class Topology:
    """World description produced by :func:`init`.

    Attributes:
      mesh: the global device mesh; axis ``axis_names[0]`` (default ``"dp"``)
        is the worker axis used by the trainers.
      devices: all addressable-or-not global devices, mesh order.
      process_index / process_count: host-process identity.
    """

    mesh: Mesh
    devices: tuple
    process_index: int
    process_count: int
    platform: str

    @property
    def num_workers(self) -> int:
        """Length of the worker axis (what ``size()``/collectives reduce over).

        On a multi-axis mesh this is NOT the total device count — see
        :attr:`num_devices`.
        """
        return int(self.mesh.devices.shape[0])

    @property
    def num_devices(self) -> int:
        return int(np.prod(self.mesh.devices.shape))

    @property
    def worker_axis(self) -> str:
        return self.mesh.axis_names[0]

    @property
    def local_devices(self) -> tuple:
        return tuple(d for d in self.devices if d.process_index == self.process_index)

    def worker_sharding(self, *trailing_axes: Optional[str]) -> NamedSharding:
        """NamedSharding that shards the leading axis across workers."""
        return NamedSharding(
            self.mesh, PartitionSpec(self.worker_axis, *trailing_axes)
        )

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())


def _should_init_distributed() -> bool:
    """Multi-host bootstrap is opt-in via standard jax env vars.

    On a single-host (or axon-tunnelled single chip) calling
    ``jax.distributed.initialize`` without a coordinator either fails or
    hangs, so only do it when the launcher says so — mirroring how the
    reference only had a world when run under ``mpirun`` (SURVEY.md §3(a)).
    """
    if os.environ.get("MPIT_DISTRIBUTED", "").lower() in ("1", "true"):
        return True
    return bool(os.environ.get("JAX_COORDINATOR_ADDRESS"))


def _init_distributed() -> None:
    """Bootstrap ``jax.distributed`` from the launch environment.

    On managed clusters (TPU pods, SLURM) the no-arg form auto-detects.
    Under this repo's own launcher — ``python -m mpit_tpu.launch -n N
    --jax-distributed`` — the world is described by the same env contract
    the PS transport uses (``MPIT_RANK``/``MPIT_WORLD_SIZE``) plus
    ``JAX_COORDINATOR_ADDRESS``, and this jax build does not read
    process-count/id from env, so pass them explicitly.
    """
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = os.environ.get("MPIT_WORLD_SIZE")
    pid = os.environ.get("MPIT_RANK")
    if coord and nproc is not None and pid is not None:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(nproc),
            process_id=int(pid),
        )
    else:
        jax.distributed.initialize()


def init(
    axis_names: Sequence[str] = (WORKER_AXIS,),
    mesh_shape: Optional[Sequence[int]] = None,
    num_workers: Optional[int] = None,
    devices: Optional[Sequence] = None,
) -> Topology:
    """Initialize the world: ``mpiT.Init()`` ≡ topology discovery + mesh.

    Args:
      axis_names: mesh axis names; first is the worker axis.
      mesh_shape: explicit mesh shape (must multiply to #devices used).
      num_workers: use only the first ``num_workers`` devices on a 1-D mesh
        (handy for carving a sub-world, like an MPI sub-communicator).
      devices: explicit device list (tests).

    Idempotent: repeated calls return the existing topology unless
    :func:`finalize` ran in between.
    """
    global _topology, _distributed_initialized
    with _lock:
        if _topology is not None:
            explicit = (
                tuple(axis_names) != (WORKER_AXIS,)
                or mesh_shape is not None
                or num_workers is not None
                or devices is not None
            )
            if explicit:
                raise RuntimeError(
                    "mpit_tpu.init() called with explicit arguments but a "
                    "topology already exists (possibly auto-created); call "
                    "finalize() first to rebuild the world"
                )
            return _topology

        if _should_init_distributed() and not _distributed_initialized:
            _init_distributed()
            _distributed_initialized = True

        devs = list(devices if devices is not None else jax.devices())
        if num_workers is not None:
            if num_workers > len(devs):
                raise ValueError(
                    f"num_workers={num_workers} exceeds available devices "
                    f"({len(devs)})"
                )
            devs = devs[:num_workers]

        if mesh_shape is None:
            mesh_shape = (len(devs),) + (1,) * (len(axis_names) - 1)
        if int(np.prod(mesh_shape)) != len(devs):
            raise ValueError(
                f"mesh_shape {tuple(mesh_shape)} does not cover {len(devs)} devices"
            )
        mesh = Mesh(
            np.asarray(devs, dtype=object).reshape(tuple(mesh_shape)),
            axis_names=tuple(axis_names),
        )
        _topology = Topology(
            mesh=mesh,
            devices=tuple(devs),
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            platform=devs[0].platform if devs else "none",
        )
        return _topology


def finalize() -> None:
    """``mpiT.Finalize()``: drop the world. Safe to call when uninitialized.

    In multi-host mode this also shuts down the ``jax.distributed`` client so
    a later :func:`init` can bootstrap again; single-host it only drops the
    mesh (XLA needs no collective teardown).
    """
    global _topology, _distributed_initialized
    with _lock:
        _topology = None
        if _distributed_initialized:
            jax.distributed.shutdown()
            _distributed_initialized = False


def is_initialized() -> bool:
    return _topology is not None


def topology() -> Topology:
    """The current topology, auto-initializing with defaults if needed."""
    if _topology is None:
        return init()
    return _topology


def process_rank() -> int:
    """Host-process index (≡ MPI rank of the host in multi-host jobs)."""
    return topology().process_index


def process_count() -> int:
    return topology().process_count


def rank():
    """Worker id. Inside jit/shard_map: a traced ``lax.axis_index`` over the
    worker axis. Outside a tracing context this raises — host code should use
    :func:`process_rank` (there is no single "my device" outside SPMD).
    """
    return jax.lax.axis_index(topology().worker_axis)


def size() -> int:
    """Number of workers (devices on the worker axis) — ``mpiT.Comm_size``."""
    return topology().num_workers


# ---------------------------------------------------------------------------
# Consistent-hash shard ring (sharded parameter servers).
#
# Ownership of parameter shards is decided by a consistent-hash ring over the
# live server ranks (docs/ROBUSTNESS.md "Shard ownership & resharding"). The
# ring is deterministic across processes — keys are hashed with blake2b, never
# Python's randomized ``hash()`` — so every client and server derives the same
# assignment from the same member set without coordination. Removing one of N
# members moves only the shards the leaver owned (~1/N of keys); everything
# else stays put, which is what bounds reshard traffic under churn.


def _ring_hash(key: str) -> int:
    import hashlib

    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


class HashRing:
    """Consistent-hash ring over server ranks with a monotonic version.

    ``version`` increments on every membership change (``without`` /
    ``with_member``) and rides the TAG_SHARD_MAP wire envelope so receivers
    can discard stale views. Instances are immutable; membership edits return
    a new ring.
    """

    __slots__ = ("members", "vnodes", "version", "_points")

    def __init__(self, members, vnodes: int = 64, version: int = 0):
        self.members = tuple(sorted(set(int(m) for m in members)))
        if not self.members:
            raise ValueError("HashRing needs at least one member")
        self.vnodes = int(vnodes)
        self.version = int(version)
        pts = []
        for m in self.members:
            for v in range(self.vnodes):
                pts.append((_ring_hash(f"m{m}:v{v}"), m))
        pts.sort()
        self._points = pts

    def owner(self, key) -> int:
        """The member owning ``key`` (first point clockwise of its hash)."""
        import bisect

        h = _ring_hash(f"k{key}")
        i = bisect.bisect_right(self._points, (h, 1 << 62))
        if i == len(self._points):
            i = 0
        return self._points[i][1]

    def without(self, rank: int) -> "HashRing":
        rest = [m for m in self.members if m != rank]
        return HashRing(rest, vnodes=self.vnodes, version=self.version + 1)

    def with_member(self, rank: int) -> "HashRing":
        return HashRing(
            self.members + (int(rank),), vnodes=self.vnodes, version=self.version + 1
        )

    def __eq__(self, other):
        return (
            isinstance(other, HashRing)
            and self.members == other.members
            and self.vnodes == other.vnodes
        )

    def __hash__(self):
        return hash((self.members, self.vnodes))

    def __repr__(self):
        return f"HashRing(members={self.members}, vnodes={self.vnodes}, version={self.version})"


def shard_layout(param_size: int, num_shards: int):
    """Static, contiguous, near-equal split of the flat parameter vector.

    The layout never changes across membership churn — only *ownership* of
    each shard moves. Mirrors ``pserver.partition_bounds`` (kept separate to
    avoid a comm→parallel import cycle).
    """
    if num_shards <= 0:
        raise ValueError("num_shards must be positive")
    base, extra = divmod(param_size, num_shards)
    bounds = []
    start = 0
    for i in range(num_shards):
        size = base + (1 if i < extra else 0)
        bounds.append((start, start + size))
        start += size
    return bounds


class ShardMap:
    """Ring + static layout glue: who owns which slice of the flat params.

    ``assignment[sid]`` is the owning rank of shard ``sid``; the slice bounds
    come from :func:`shard_layout` and are immutable — a reshard moves
    ownership, never the cut points.
    """

    __slots__ = ("ring", "param_size", "num_shards", "layout", "assignment")

    def __init__(self, ring: HashRing, param_size: int, num_shards: int):
        self.ring = ring
        self.param_size = int(param_size)
        self.num_shards = int(num_shards)
        self.layout = shard_layout(self.param_size, self.num_shards)
        self.assignment = tuple(ring.owner(sid) for sid in range(self.num_shards))

    def with_ring(self, ring: HashRing) -> "ShardMap":
        return ShardMap(ring, self.param_size, self.num_shards)

    def ranges_for(self, rank: int):
        """Ascending ``(sid, start, end)`` triples owned by ``rank``."""
        return [
            (sid, s, e)
            for sid, (s, e) in enumerate(self.layout)
            if self.assignment[sid] == rank
        ]

    def owned_size(self, rank: int) -> int:
        return sum(e - s for _, s, e in self.ranges_for(rank))

    def server_ranks(self):
        """Members that own at least one shard, ascending."""
        return sorted(set(self.assignment))

    def shard_size(self, sid: int) -> int:
        s, e = self.layout[sid]
        return e - s


def reshard_schedule(old_map: ShardMap, new_map: ShardMap):
    """The slice exchanges needed to go from ``old_map`` to ``new_map``.

    Returns ascending-shard-id moves ``{"shard", "src", "dst", "size"}``.
    Executed in order, each destination holds at most its old slices plus the
    one incoming slice at any instant (see :func:`schedule_peak_elems`) — the
    no-full-duplicate property from the portable-redistribution literature.
    """
    if old_map.param_size != new_map.param_size or old_map.num_shards != new_map.num_shards:
        raise ValueError("reshard requires identical layout on both sides")
    moves = []
    for sid in range(old_map.num_shards):
        src = old_map.assignment[sid]
        dst = new_map.assignment[sid]
        if src != dst:
            moves.append(
                {"shard": sid, "src": src, "dst": dst, "size": old_map.shard_size(sid)}
            )
    return moves


def schedule_peak_elems(moves, old_map: ShardMap):
    """Per-rank peak resident element count while executing ``moves`` in order.

    A destination materializes the incoming slice while the source still holds
    it (the transfer), then the source frees its copy. The peak for every rank
    must stay ≤ old resident + incoming — never the full model.
    """
    ranks = set(old_map.ring.members)
    for mv in moves:
        ranks.add(mv["src"])
        ranks.add(mv["dst"])
    resident = {r: old_map.owned_size(r) for r in ranks}
    peak = dict(resident)
    for mv in moves:
        src, dst, size = mv["src"], mv["dst"], mv["size"]
        resident[dst] += size
        peak[dst] = max(peak[dst], resident[dst])
        resident[src] -= size
    return peak
